"""Headline benchmark: edges traversed/sec on 2-hop fan-out queries.

Mirrors BASELINE.json's north-star metric: a Freebase-21M-scale synthetic
graph (2M nodes, ~21M edges, skewed degrees), 2-hop traversal from random
seed sets.  The device path — inline-head expansion (ops.expand_inline:
each 32-byte row gather returns metadata AND the first INLINE targets,
with overflow chunks + scatter/prefix-sum slot mapping for long rows),
stability-free sort dedup, one vmapped program for the whole query
batch — is measured against a fully-vectorized NumPy implementation of
the same semantics (the stand-in for the reference's CPU posting-list
walk).
Every query's output materializes on device (per-query checksums, all
verified against numpy), so the edges/s number cannot be faked by XLA
dead-code elimination.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
Environment knobs: BENCH_NODES, BENCH_EDGES, BENCH_SEEDS, BENCH_ITERS,
BENCH_SCALE (shrink everything by a factor: 0.1 -> 200k nodes / 2.1M
edges), BENCH_PROBE_TIMEOUT / BENCH_INIT_RETRIES (backend probe knobs).

Robustness contract (round-1 postmortem: the round artifact was empty
because a wedged TPU turned into an unhandled stack dump): the TPU
backend is probed in a SUBPROCESS with a hard timeout — a wedged chip
hangs inside C++ where no Python-level timeout can fire — with retries
and backoff; if it never comes up we say so in one stderr line and fall
back to XLA-on-CPU so the round still records a real (if unflattering)
number.  A mid-run failure retries once at BENCH_SCALE/8.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

_PROBE = (
    "import jax; d = jax.devices(); import jax.numpy as jnp; "
    "x = jnp.ones((256, 256)); jax.block_until_ready(x @ x); "
    "print(d[0].platform)"
)


def ensure_backend() -> str:
    """Probe the default (TPU) backend out-of-process with a timeout;
    fall back to CPU after retries.  Returns the platform chosen."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # env var alone is not enough: this image's sitecustomize imports
        # jax at interpreter startup, consuming JAX_PLATFORMS before user
        # env can influence it — config.update works until backend init
        import jax

        jax.config.update("jax_platforms", "cpu")
        return "cpu"
    # round-end runs are one-shot: wait out a recovering tunnel (5 probes
    # with exponential backoff ≈ 13 minutes max) before settling for CPU
    retries = int(os.environ.get("BENCH_INIT_RETRIES", 5))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", 120))
    last = ""
    for attempt in range(retries):
        # own process GROUP + file-backed output: the TPU plugin spawns
        # tunnel helpers that inherit pipes — after a timeout kill of the
        # probe alone, communicate() would block on the helper's copy of
        # stdout forever (observed with a wedged chip).  killpg reaps the
        # whole group and files can't block.
        import tempfile

        with tempfile.TemporaryFile("w+") as out, tempfile.TemporaryFile("w+") as err:
            p = subprocess.Popen(
                [sys.executable, "-c", _PROBE],
                stdout=out,
                stderr=err,
                text=True,
                start_new_session=True,
            )
            try:
                rc = p.wait(timeout=probe_timeout)
                out.seek(0)
                err.seek(0)
                if rc == 0:
                    lines = out.read().strip().splitlines()
                    if lines:
                        return lines[-1]
                    last = "probe printed nothing"
                else:
                    last = (err.read().strip().splitlines() or ["rc=%d" % rc])[-1]
            except subprocess.TimeoutExpired:
                import signal

                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    p.kill()  # group signal denied: at least the child dies
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass  # unreaped zombie beats an unbounded hang
                last = f"probe hung >{probe_timeout:.0f}s (backend wedged?)"
        if attempt < retries - 1:
            delay = 5 * (2**attempt)
            print(
                f"# backend probe {attempt + 1}/{retries} failed ({last}); "
                f"retrying in {delay}s",
                file=sys.stderr,
            )
            time.sleep(delay)
    print(
        f"# TPU backend unavailable after {retries} probes ({last}); "
        "falling back to XLA-on-CPU",
        file=sys.stderr,
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    return "cpu"


def build_graph(n_nodes: int, n_edges: int, seed: int = 7):
    """Skewed-degree random digraph (celebrity uids get most edges),
    dense CSR layout: row i == uid i, so no row lookup on the hot path."""
    rng = np.random.default_rng(seed)
    # zipf-ish targets: mix uniform sources with popularity-weighted targets
    src = rng.integers(1, n_nodes + 1, size=n_edges)
    pop = (rng.pareto(1.2, size=n_edges).astype(np.float64) + 1.0)
    dst = (np.clip(pop / pop.max(), 1e-9, 1.0) * (n_nodes - 1)).astype(np.int64) + 1
    half = n_edges // 2
    dst[:half] = rng.integers(1, n_nodes + 1, size=half)
    from dgraph_tpu.models.arena import csr_dense_from_edges

    return csr_dense_from_edges(src, dst, n_nodes)


def np_expand(offsets, dst, rows):
    """Vectorized numpy CSR expansion (the CPU baseline's hot op)."""
    rows = rows[rows >= 0]
    if not len(rows):
        return np.empty(0, dtype=dst.dtype)
    starts = offsets[rows]
    degs = offsets[rows + 1] - starts
    total = int(degs.sum())
    if total == 0:
        return np.empty(0, dtype=dst.dtype)
    cum = np.cumsum(degs)
    within = np.arange(total) - np.repeat(cum - degs, degs)
    return dst[np.repeat(starts, degs) + within]


def np_two_hop(a, h_dst, frontier):
    # dense arena: rows are uids directly (same advantage the device gets)
    out1 = np_expand(a.h_offsets, h_dst, frontier)
    f1 = np.unique(out1)
    out2 = np_expand(a.h_offsets, h_dst, f1)
    chk = np.int32(out2.astype(np.int64).sum() & 0xFFFFFFFF)
    return len(out1) + len(out2), np.unique(out2), chk


def run_bench(scale: float):
    import jax
    import jax.numpy as jnp
    from dgraph_tpu import ops
    from dgraph_tpu.ops.sets import SENT

    n_nodes = max(1024, int(int(os.environ.get("BENCH_NODES", 2_000_000)) * scale))
    n_edges = max(4096, int(int(os.environ.get("BENCH_EDGES", 21_000_000)) * scale))
    n_seeds = max(64, int(int(os.environ.get("BENCH_SEEDS", 4096)) * min(1.0, scale * 4)))
    # 1000-query streams (VERDICT r4 next #1b): one lax.map dispatch
    # serves the whole stream, so the ~70ms fixed dispatch overhead
    # amortizes to noise; compile cost stays at the CHUNK_Q program size
    # (planning + numpy baseline stay ~linear and well inside driver time)
    iters = int(os.environ.get("BENCH_ITERS", 1000))

    t0 = time.time()
    a = build_graph(n_nodes, n_edges)
    h_dst = np.asarray(a.dst)[: a.n_edges]
    try:
        metap, ov_chunks = a.inline_layout_grouped()
        grouped = True
        mask = int(ops.GROUP_MASK)
    except ValueError:  # uid space >= 2^GROUP_BIT: plain inline layout
        metap, ov_chunks = a.inline_layout()
        grouped = False
        mask = SENT  # identity decode
    build_s = time.time() - t0

    deg_of = (a.h_offsets[1:] - a.h_offsets[:-1]).astype(np.int64)
    rng = np.random.default_rng(3)
    frontiers = []
    for _ in range(iters):
        f = np.unique(rng.integers(1, n_nodes + 1, size=n_seeds))
        if grouped:
            # group-order the seed frontier exactly like the device dedup
            # orders hop-1 output: overflow-bearing rows first, ascending
            # — hop 1 then shares the short-slot-map path (ops.skey_encode)
            key = np.asarray(ops.skey_encode(f, deg_of[f] > ops.INLINE))
            f = f[np.argsort(key, kind="stable")]
        frontiers.append(f)

    # plan static overflow-chunk caps from the worst case so one
    # compilation serves all; 1/8-step buckets (bucket_fine) because the
    # whole batch runs as one program — pow2 padding would tax every
    # capacity-proportional cost up to 2×.  pcaps bound the GROUPED
    # productive prefixes (rows with overflow chunks).
    worst1 = worst2 = worstu = wp1 = wp2 = 1
    for f in frontiers:
        c1 = int(a.ov_chunk_degree_of_rows(f).sum())
        f1 = np.unique(np_expand(a.h_offsets, h_dst, f))
        c2 = int(a.ov_chunk_degree_of_rows(f1).sum())
        worst1, worst2 = max(worst1, c1), max(worst2, c2)
        worstu = max(worstu, len(f1))
        wp1 = max(wp1, int((deg_of[f] > ops.INLINE).sum()))
        wp2 = max(wp2, int((deg_of[f1] > ops.INLINE).sum()))
    capo1, capo2 = ops.bucket_fine(worst1), ops.bucket_fine(worst2)
    ucap = ops.bucket_fine(worstu)  # tight row capacity for the deduped frontier
    fcap = ops.bucket(max(len(f) for f in frontiers))
    if grouped:
        pcap1, pcap2 = ops.bucket_fine(wp1), min(ops.bucket_fine(wp2), ucap)
    else:  # ungrouped rows: the slot-map must span every row
        pcap1, pcap2 = fcap, ucap

    # BENCH_PALLAS=1 swaps the overflow slot-map for the Pallas kernel
    # (ops/pallas_slotmap.py — ROOFLINE Path-onward #2); the watch loop
    # A/Bs both and banks the better TPU number.  Grouped layouts only:
    # the kernel's window-max shortcut needs the productive-prefix
    # invariant that skey ordering provides.
    expander = (
        ops.expand_inline_grouped_pallas
        if os.environ.get("BENCH_PALLAS") == "1" and grouped
        else ops.expand_inline_grouped
    )

    # ONE device dispatch for the whole query batch.  Per query the
    # pipeline is the inline-head expansion (ops.expand_inline_grouped):
    # ONE 32-byte row gather serves a row's metadata AND its first INLINE
    # targets (the gather-engine index rate is the measured wall,
    # docs/ROOFLINE.md); only degree>INLINE rows touch overflow chunks.
    # Stored targets are skey-coded, so the dedup sort doubles as the
    # GROUPING pass: overflow-bearing rows land in an ascending prefix
    # and the slot-map scan/scatter chain runs on pcap2 rows, not ucap.
    def one_query(frontier):
        rows0 = ops.frontier_rows(frontier)
        inl1, ov1, t1 = expander(metap, ov_chunks, rows0, capo1, pcap1)
        f1 = ops.sort_unique(
            jnp.concatenate([inl1.reshape(-1), ov1.reshape(-1)])
        )[:ucap]
        rows1 = jnp.where(f1 == SENT, -1, f1 & mask)
        inl2, ov2, t2 = expander(metap, ov_chunks, rows1, capo2, pcap2)
        # checksum over every produced uid (skey-decoded): forces each
        # query's output to actually materialize (otherwise XLA could DCE
        # all but the last query's gathers, and "edges traversed" would
        # be a lie)
        chk = jnp.sum(
            jnp.where(inl2 == SENT, 0, inl2 & mask), dtype=jnp.int32
        ) + jnp.sum(jnp.where(ov2 == SENT, 0, ov2 & mask), dtype=jnp.int32)
        return chk, t1 + t2, (inl2, ov2)

    # one dispatch serves the whole stream: vmap batches CHUNK_Q queries
    # into one program (lockstep ops amortize per-op overhead), lax.map
    # loops sub-batches inside the SAME dispatch — compile cost stays at
    # the 200-query program size while per-dispatch fixed overhead
    # (host round trip + queueing) amortizes over every query
    CHUNK_Q = 200

    @jax.jit
    def run_batch(frontiers_mat):
        def q(frontier):
            chk, t, _out2 = one_query(frontier)
            return chk, t

        if frontiers_mat.shape[0] <= CHUNK_Q:
            return jax.vmap(q)(frontiers_mat)
        g = frontiers_mat.shape[0] // CHUNK_Q
        sub = frontiers_mat[: g * CHUNK_Q].reshape(g, CHUNK_Q, -1)
        chks, counts = jax.lax.map(jax.vmap(q), sub)
        rest = frontiers_mat[g * CHUNK_Q :]
        if rest.shape[0]:
            ct, cc = jax.vmap(q)(rest)
            return (
                jnp.concatenate([chks.reshape(-1), ct]),
                jnp.concatenate([counts.reshape(-1), cc]),
            )
        return chks.reshape(-1), counts.reshape(-1)

    @jax.jit
    def last_query_set(frontier):
        # last query's full result set for the correctness cross-check —
        # a SEPARATE untimed program (keeping every query's outputs as
        # program outputs would pin iters*(ucap*INLINE + capo2*CHUNK)*4
        # bytes of HBM; the per-query checksums already force
        # materialization inside the timed batch)
        _c, _t, (inl2, ov2) = one_query(frontier)
        return ops.sort_unique(jnp.concatenate([inl2.reshape(-1), ov2.reshape(-1)]))

    fmat = jnp.asarray(np.stack([ops.pad_to(f, fcap) for f in frontiers]))

    chks, counts = run_batch(fmat)  # warmup/compile
    np.asarray(counts)

    dev_s = float("inf")
    for _ in range(4):  # best-of-4: the shared chip's load swings runs ~1.5×
        t0 = time.time()
        chks, counts = run_batch(fmat)
        counts = np.asarray(counts)  # sync
        np.asarray(chks)
        dev_s = min(dev_s, time.time() - t0)
    dev_edges = int(counts.sum())
    last_f2 = last_query_set(fmat[-1])

    # best-of-2 for the CPU baseline: the shared host's load swings numpy
    # throughput ~2x between runs; compare against its fastest
    cpu_s = float("inf")
    for _ in range(2):
        t0 = time.time()
        cpu_edges = 0
        cpu_chks = []
        for f in frontiers:
            n, _, c = np_two_hop(a, h_dst, f)
            cpu_edges += n
            cpu_chks.append(c)
        cpu_s = min(cpu_s, time.time() - t0)

    # correctness cross-check: per-query checksums + the last frontier set
    # (device values are skey-coded: decode and re-sort before comparing)
    _, want, _ = np_two_hop(a, h_dst, frontiers[-1])
    got = np.asarray(last_f2)
    got = np.sort(got[got != SENT] & mask)
    assert np.array_equal(got, want), "device 2-hop != numpy reference"
    assert dev_edges == cpu_edges, (dev_edges, cpu_edges)
    assert np.array_equal(np.asarray(chks), np.array(cpu_chks, dtype=np.int32)), (
        "per-query device checksums != numpy"
    )

    dev_eps = dev_edges / dev_s
    cpu_eps = cpu_edges / cpu_s
    print(
        json.dumps(
            {
                "metric": "edges_traversed_per_sec_2hop",
                "value": round(dev_eps, 1),
                "unit": "edges/s",
                "vs_baseline": round(dev_eps / cpu_eps, 3),
                # self-describing record: a wedged-TPU round falls back to
                # XLA-on-CPU (see ensure_backend) and must not read as a
                # TPU measurement
                "platform": jax.devices()[0].platform,
                "pallas_slotmap": os.environ.get("BENCH_PALLAS") == "1",
            }
        )
    )
    print(
        f"# graph: {n_nodes} nodes / {a.n_edges} edges (build {build_s:.1f}s); "
        f"{iters} queries x {n_seeds} seeds; device {dev_s:.2f}s "
        f"({dev_eps/1e6:.1f}M e/s) vs numpy {cpu_s:.2f}s ({cpu_eps/1e6:.1f}M e/s) "
        f"on {jax.devices()[0].platform}; scale={scale:g}",
    )


def main():
    platform = ensure_backend()
    print(f"# backend: {platform}", file=sys.stderr)
    scale = float(os.environ.get("BENCH_SCALE", 1.0))
    try:
        run_bench(scale)
    except AssertionError:
        raise  # correctness failures must never be masked by a retry
    except Exception as e:
        first = str(e).strip().splitlines()
        first = first[0] if first else type(e).__name__
        print(
            f"# bench failed at scale={scale:g} ({type(e).__name__}: {first}); "
            f"retrying once at scale={scale / 8:g}",
            file=sys.stderr,
        )
        run_bench(scale / 8)


if __name__ == "__main__":
    main()
