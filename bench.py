"""Headline benchmark: edges traversed/sec on 2-hop fan-out queries.

Mirrors BASELINE.json's north-star metric: a Freebase-21M-scale synthetic
graph (2M nodes, ~21M edges, skewed degrees), 2-hop traversal from random
seed sets, measured against a fully-vectorized NumPy implementation of
the same semantics (the stand-in for the reference's CPU posting-list
walk).

The device side runs the FUSED BATCHED HOP EXECUTOR (dgraph_tpu/ops/
batch.py): one device program per hop for the whole query batch, in one
of two dedup strategies:

- ``host`` (default off-TPU): each hop is a degree-classed gather
  program — scatter- and sort-free, because XLA-on-CPU's scatter
  (~100ns/update) and sort (~10× numpy) would otherwise dominate — and
  the inter-hop frontier dedup runs as numpy np.unique overlapped with
  the device's async dispatch queue.  2 programs per query batch, not
  one per set-op.
- ``device`` (default on TPU): the whole 2-hop pipeline for a batch of
  queries is ONE jitted program (inline-head expansion + skey-grouped
  sort dedup, the round-5 TPU path); the frontier never leaves HBM.

Every query's output materializes on device (per-query checksums, all
verified against numpy), so the edges/s number cannot be faked by XLA
dead-code elimination.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline",
"fused_hop", "hop_dedup", "serving", ...}.  "serving" is the closed-loop
multi-client A/B (run_serving_bench), three arms over one zipf workload:
the cohort scheduler (DGRAPH_TPU_SCHED=1) vs the serial per-request
path (=0), both cache-off, plus the two-tier query cache arm
(DGRAPH_TPU_CACHE=1, ISSUE 3) reported as "cache_on" with
"cache_qps_ratio" (warm-QPS over the cache-off scheduler arm) and
"tier2_hit_rate" (guarded nonzero) — with QPS, p50/p99 latency, mean
cohort occupancy, flush-reason counts and a cross-arm response-parity
check.
Environment knobs: BENCH_NODES, BENCH_EDGES, BENCH_SEEDS, BENCH_ITERS,
BENCH_SCALE (shrink everything by a factor: 0.1 -> 200k nodes / 2.1M
edges), BENCH_DEDUP (host|device|auto), BENCH_PROBE_BUDGET /
BENCH_PROBE_TIMEOUT / BENCH_INIT_RETRIES (backend probe knobs),
BENCH_SERVE (0 skips the serving A/B) / BENCH_CLIENTS /
BENCH_SERVE_SECONDS / BENCH_SERVE_NODES / BENCH_SERVE_DEG.

Robustness contract (round-1 postmortem: the round artifact was empty
because a wedged TPU turned into an unhandled stack dump): the TPU
backend is probed in a SUBPROCESS with a hard timeout — a wedged chip
hangs inside C++ where no Python-level timeout can fire.  The TOTAL
probe budget is capped (BENCH_PROBE_BUDGET, default 90s — round 5
burned 5×(120s+backoff) ≈ 13 minutes on a wedged chip before falling
back); the outcome is ONE structured ``backend_probe`` json line on
stderr, win or lose.  A mid-run failure retries once at BENCH_SCALE/8.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

_PROBE = (
    "import jax; d = jax.devices(); import jax.numpy as jnp; "
    "x = jnp.ones((256, 256)); jax.block_until_ready(x @ x); "
    "print(d[0].platform)"
)


def _probe_once(timeout_s: float):
    """One out-of-process backend probe.  Returns (platform or None,
    error string).  Own process GROUP + file-backed output: the TPU
    plugin spawns tunnel helpers that inherit pipes — after a timeout
    kill of the probe alone, communicate() would block on the helper's
    copy of stdout forever (observed with a wedged chip)."""
    import tempfile

    with tempfile.TemporaryFile("w+") as out, tempfile.TemporaryFile("w+") as err:
        p = subprocess.Popen(
            [sys.executable, "-c", _PROBE],
            stdout=out,
            stderr=err,
            text=True,
            start_new_session=True,
        )
        try:
            rc = p.wait(timeout=timeout_s)
            out.seek(0)
            err.seek(0)
            if rc == 0:
                lines = out.read().strip().splitlines()
                if lines:
                    return lines[-1], ""
                return None, "probe printed nothing"
            return None, (err.read().strip().splitlines() or ["rc=%d" % rc])[-1]
        except subprocess.TimeoutExpired:
            import signal

            try:
                os.killpg(os.getpgid(p.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                p.kill()  # group signal denied: at least the child dies
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass  # unreaped zombie beats an unbounded hang
            return None, f"probe hung >{timeout_s:.0f}s (backend wedged?)"


def ensure_backend() -> str:
    """Probe the default (TPU) backend out-of-process under a hard TOTAL
    time budget; fall back to CPU when the budget is spent.  Emits ONE
    structured ``backend_probe`` json line on stderr either way and
    returns the platform chosen."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # env var alone is not enough: this image's sitecustomize imports
        # jax at interpreter startup, consuming JAX_PLATFORMS before user
        # env can influence it — config.update works until backend init
        import jax

        jax.config.update("jax_platforms", "cpu")
        return "cpu"
    budget = float(os.environ.get("BENCH_PROBE_BUDGET", 90))
    per_probe = float(os.environ.get("BENCH_PROBE_TIMEOUT", 45))
    max_tries = int(os.environ.get("BENCH_INIT_RETRIES", 3))
    t0 = time.time()
    attempts = 0
    last = ""
    platform = None
    while attempts < max_tries:
        remaining = budget - (time.time() - t0)
        if remaining <= 1:
            break
        attempts += 1
        platform, last = _probe_once(min(per_probe, remaining))
        if platform is not None:
            break
        # short fixed pause: a recovering tunnel sometimes needs a beat,
        # but exponential backoff on a wedged chip just burns the round
        remaining = budget - (time.time() - t0)
        if attempts < max_tries and remaining > 3:
            time.sleep(2)
    record = {
        "backend_probe": {
            "platform": platform or "cpu",
            "outcome": "ok" if platform else "fallback_cpu",
            "attempts": attempts,
            "elapsed_s": round(time.time() - t0, 1),
            "budget_s": budget,
            "last_error": last if platform is None else "",
        }
    }
    print(json.dumps(record), file=sys.stderr)
    if platform is not None:
        return platform
    import jax

    jax.config.update("jax_platforms", "cpu")
    return "cpu"


def build_graph(n_nodes: int, n_edges: int, seed: int = 7):
    """Skewed-degree random digraph (celebrity uids get most edges),
    dense CSR layout: row i == uid i, so no row lookup on the hot path."""
    rng = np.random.default_rng(seed)
    # zipf-ish targets: mix uniform sources with popularity-weighted targets
    src = rng.integers(1, n_nodes + 1, size=n_edges)
    pop = (rng.pareto(1.2, size=n_edges).astype(np.float64) + 1.0)
    dst = (np.clip(pop / pop.max(), 1e-9, 1.0) * (n_nodes - 1)).astype(np.int64) + 1
    half = n_edges // 2
    dst[:half] = rng.integers(1, n_nodes + 1, size=half)
    from dgraph_tpu.models.arena import csr_dense_from_edges

    return csr_dense_from_edges(src, dst, n_nodes)


def np_expand(offsets, dst, rows):
    """Vectorized numpy CSR expansion (the CPU baseline's hot op)."""
    rows = rows[rows >= 0]
    if not len(rows):
        return np.empty(0, dtype=dst.dtype)
    starts = offsets[rows]
    degs = offsets[rows + 1] - starts
    total = int(degs.sum())
    if total == 0:
        return np.empty(0, dtype=dst.dtype)
    cum = np.cumsum(degs)
    within = np.arange(total) - np.repeat(cum - degs, degs)
    return dst[np.repeat(starts, degs) + within]


def np_two_hop(a, h_dst, frontier):
    # dense arena: rows are uids directly (same advantage the device gets)
    out1 = np_expand(a.h_offsets, h_dst, frontier)
    f1 = np.unique(out1)
    out2 = np_expand(a.h_offsets, h_dst, f1)
    chk = np.int32(out2.astype(np.int64).sum() & 0xFFFFFFFF)
    return len(out1) + len(out2), np.unique(out2), chk


def _run_host_dedup(a, h_dst, frontiers):
    """Fused classed-hop pipeline: ONE device program per hop per
    sub-batch, np.unique dedup between hops overlapped with the device's
    async dispatch queue.  Returns (best seconds, edges, chks[int32],
    last query's hop-2 unique set)."""
    import jax
    import jax.numpy as jnp
    from dgraph_tpu import ops
    from dgraph_tpu.ops.sets import SENT

    ce = ops.ClassedExpander(a.offsets, a.dst, a.h_offsets)
    iters = len(frontiers)

    # --- capacity planning (untimed): worst per-class composition over
    # the stream, bucket_fine'd so one compiled program per hop serves
    # every sub-batch ---
    n_cls = ce.n_cls
    c1w = np.ones(n_cls, np.int64)
    c2w = np.ones(n_cls, np.int64)
    h1w = e1w = h2w = e2w = 0
    uniq1 = []
    for f in frontiers:
        c1, h1, e1 = ce.class_counts(f)
        c1w = np.maximum(c1w, c1)
        h1w, e1w = max(h1w, h1), max(e1w, e1)
        f1 = np.unique(np_expand(a.h_offsets, h_dst, f))
        uniq1.append(f1)
        c2, h2, e2 = ce.class_counts(f1)
        c2w = np.maximum(c2w, c2)
        h2w, e2w = max(h2w, h2), max(e2w, e2)
    caps1 = ce.plan_caps(c1w, h1w, e1w)
    caps2 = ce.plan_caps(c2w, h2w, e2w)
    hop1 = ce.program(caps1, "materialize", batched=True)
    hop2 = ce.program(caps2, "checksum", batched=True)

    def stack_partitions(queries, caps):
        """Class-sort each query's rows and write the per-class slices
        straight into stacked [B, cap_c] mats (-1 pad) — the host side
        of one batched hop dispatch."""
        B = len(queries)
        mats = [np.full((B, c), -1, np.int32) for c in caps[:n_cls]]
        mats.append(np.full((B, max(caps[n_cls], 1)), -1, np.int32))
        for j, f in enumerate(queries):
            rs, starts, _deg, _pos = ce.class_sort(f)
            for k in range(n_cls + 1):
                lo, hi = int(starts[k]), int(starts[k + 1])
                if hi > lo:
                    mats[k][j, : hi - lo] = rs[lo:hi]
        return tuple(jnp.asarray(m) for m in mats)

    # --- seed partitions (untimed prep, like frontier padding was) ---
    SB = int(os.environ.get("BENCH_SUBBATCH", 50))
    nb = -(-iters // SB)
    seed_batches = [
        stack_partitions(frontiers[b * SB: (b + 1) * SB], caps1)
        for b in range(nb)
    ]

    def one_pass():
        # dispatch every hop-1 sub-batch up front: jax dispatch is
        # async, so the host's unique+partition work below overlaps the
        # device working through its queue
        futs = [hop1(mb, ()) for mb in seed_batches]
        chks = np.empty(iters, np.int32)
        edges = 0
        for b, (lanes, t1) in enumerate(futs):
            lanes = np.asarray(lanes)  # blocks for THIS sub-batch only
            edges += int(np.asarray(t1).astype(np.int64).sum())
            B = lanes.shape[0]
            uniq = []
            for j in range(B):
                u = np.unique(lanes[j])
                if len(u) and u[-1] == SENT:
                    u = u[:-1]
                uniq.append(u)
            c, t2 = hop2(stack_partitions(uniq, caps2), ())
            chks[b * SB: b * SB + B] = np.asarray(c)
            edges += int(np.asarray(t2).astype(np.int64).sum())
        return edges, chks

    edges, chks = one_pass()  # warmup/compile
    best = float("inf")
    for _ in range(4):  # best-of-4: the shared chip's load swings runs ~1.5×
        t0 = time.time()
        edges, chks = one_pass()
        best = min(best, time.time() - t0)

    # untimed correctness artifact: the last query's full hop-2 set
    last_prog = ce.program(caps2, "materialize")
    pm, _pos = ce.partition(uniq1[-1], caps2)
    lanes, _t = last_prog(tuple(jnp.asarray(m) for m in pm), ())
    lanes = np.asarray(lanes)
    last_set = np.unique(lanes)
    last_set = last_set[last_set != SENT]
    return best, edges, chks, last_set


def _run_device_dedup(a, frontiers, fcap):
    """One jitted program for the WHOLE 2-hop pipeline per query batch
    (inline-head expansion + skey-grouped sort dedup): the TPU path,
    where the sort rides the VPU and the frontier never leaves HBM."""
    import jax
    import jax.numpy as jnp
    from dgraph_tpu import ops
    from dgraph_tpu.ops.sets import SENT

    h_dst = np.asarray(a.dst)[: a.n_edges]
    try:
        metap, ov_chunks = a.inline_layout_grouped()
        grouped = True
        mask = int(ops.GROUP_MASK)
    except ValueError:  # uid space >= 2^GROUP_BIT: plain inline layout
        metap, ov_chunks = a.inline_layout()
        grouped = False
        mask = SENT  # identity decode
    deg_of = (a.h_offsets[1:] - a.h_offsets[:-1]).astype(np.int64)
    if grouped:
        # group-order each seed frontier exactly like the device dedup
        # orders hop-1 output: overflow-bearing rows first, ascending —
        # hop 1 then shares the short-slot-map path (ops.skey_encode)
        gfronts = []
        for f in frontiers:
            key = np.asarray(ops.skey_encode(f, deg_of[f] > ops.INLINE))
            gfronts.append(f[np.argsort(key, kind="stable")])
    else:
        gfronts = frontiers

    worst1 = worst2 = worstu = wp1 = wp2 = 1
    for f in frontiers:
        c1 = int(a.ov_chunk_degree_of_rows(f).sum())
        f1 = np.unique(np_expand(a.h_offsets, h_dst, f))
        c2 = int(a.ov_chunk_degree_of_rows(f1).sum())
        worst1, worst2 = max(worst1, c1), max(worst2, c2)
        worstu = max(worstu, len(f1))
        wp1 = max(wp1, int((deg_of[f] > ops.INLINE).sum()))
        wp2 = max(wp2, int((deg_of[f1] > ops.INLINE).sum()))
    capo1, capo2 = ops.bucket_fine(worst1), ops.bucket_fine(worst2)
    ucap = ops.bucket_fine(worstu)
    if grouped:
        pcap1, pcap2 = ops.bucket_fine(wp1), min(ops.bucket_fine(wp2), ucap)
    else:  # ungrouped rows: the slot-map must span every row
        pcap1, pcap2 = fcap, ucap

    # slot-map backend: the sanctioned knob (DGRAPH_TPU_SLOTMAP, PR 16
    # promotion) or the legacy BENCH_PALLAS=1 the round-5 watch loop
    # still exports.  Selected OUTSIDE the jitted pipeline: the backend
    # is baked into the compiled batch program.
    expander = (
        ops.expand_inline_grouped_pallas
        if grouped
        and (os.environ.get("BENCH_PALLAS") == "1" or ops.use_slotmap_pallas())
        else ops.expand_inline_grouped
    )

    def one_query(frontier):
        rows0 = ops.frontier_rows(frontier)
        inl1, ov1, t1 = expander(metap, ov_chunks, rows0, capo1, pcap1)
        f1 = ops.sort_unique(
            jnp.concatenate([inl1.reshape(-1), ov1.reshape(-1)])
        )[:ucap]
        rows1 = jnp.where(f1 == SENT, -1, f1 & mask)
        inl2, ov2, t2 = expander(metap, ov_chunks, rows1, capo2, pcap2)
        # checksum over every produced uid (skey-decoded): forces each
        # query's output to actually materialize
        chk = jnp.sum(
            jnp.where(inl2 == SENT, 0, inl2 & mask), dtype=jnp.int32
        ) + jnp.sum(jnp.where(ov2 == SENT, 0, ov2 & mask), dtype=jnp.int32)
        return chk, t1 + t2, (inl2, ov2)

    CHUNK_Q = 200

    @jax.jit
    def run_batch(frontiers_mat):
        def q(frontier):
            chk, t, _out2 = one_query(frontier)
            return chk, t

        if frontiers_mat.shape[0] <= CHUNK_Q:
            return jax.vmap(q)(frontiers_mat)
        g = frontiers_mat.shape[0] // CHUNK_Q
        sub = frontiers_mat[: g * CHUNK_Q].reshape(g, CHUNK_Q, -1)
        chks, counts = jax.lax.map(jax.vmap(q), sub)
        rest = frontiers_mat[g * CHUNK_Q:]
        if rest.shape[0]:
            ct, cc = jax.vmap(q)(rest)
            return (
                jnp.concatenate([chks.reshape(-1), ct]),
                jnp.concatenate([counts.reshape(-1), cc]),
            )
        return chks.reshape(-1), counts.reshape(-1)

    @jax.jit
    def last_query_set(frontier):
        _c, _t, (inl2, ov2) = one_query(frontier)
        return ops.sort_unique(jnp.concatenate([inl2.reshape(-1), ov2.reshape(-1)]))

    fmat = jnp.asarray(np.stack([ops.pad_to(f, fcap) for f in gfronts]))
    chks, counts = run_batch(fmat)  # warmup/compile
    np.asarray(counts)
    best = float("inf")
    for _ in range(4):
        t0 = time.time()
        chks, counts = run_batch(fmat)
        counts = np.asarray(counts)  # sync
        np.asarray(chks)
        best = min(best, time.time() - t0)
    edges = int(counts.sum())
    got = np.asarray(last_query_set(fmat[-1]))
    last_set = np.sort(got[got != SENT] & mask)
    last_set = np.unique(last_set)
    return best, edges, np.asarray(chks), last_set


def _serving_store(n_nodes: int, deg: int, seed: int = 13):
    """Small serving graph: one uid predicate 'e' with ~deg out-edges per
    node + a name value per node (gives filters something to chew)."""
    from dgraph_tpu.models import PostingStore

    rng = np.random.default_rng(seed)
    store = PostingStore()
    store.apply_schema("e: uid @count .\nname: string .")
    src = np.repeat(np.arange(1, n_nodes + 1, dtype=np.int64), deg)
    dst = rng.integers(1, n_nodes + 1, size=len(src)).astype(np.int64)
    store.bulk_set_uid_edges("e", src, dst)
    return store


def _serving_mode(
    sched_on: bool, store, variants, clients: int, secs: float,
    cache_on: bool = False,
):
    """One closed-loop run: ``clients`` threads fire queries for ``secs``
    against a fresh DgraphServer (scheduler gated by ``sched_on``, the
    two-tier query cache by ``cache_on``).
    Returns (qps, p50_ms, p99_ms, {query: response}, completed)."""
    import json as _json
    import threading

    os.environ["DGRAPH_TPU_SCHED"] = "1" if sched_on else "0"
    os.environ["DGRAPH_TPU_CACHE"] = "1" if cache_on else "0"
    from dgraph_tpu.serve.server import DgraphServer

    srv = DgraphServer(store)
    srv.start()
    try:
        import http.client

        def mkconn():
            return http.client.HTTPConnection(
                "127.0.0.1", srv.port, timeout=30
            )

        def post_on(conn, q):
            # persistent connection (the server speaks HTTP/1.1
            # keep-alive): no TCP handshake per query
            conn.request("POST", "/query", body=q.encode())
            r = conn.getresponse()
            body = r.read()
            if r.status != 200:
                raise RuntimeError(f"HTTP {r.status}: {body[:200]!r}")
            return _json.loads(body.decode())

        warm = mkconn()
        canon = {}
        for q in variants:  # warmup + canonical responses (untimed)
            out = post_on(warm, q)
            out.pop("server_latency", None)
            canon[q] = out
        warm.close()

        lat_lock = threading.Lock()
        lats: list = []
        errs: list = []
        stop_at = [0.0]

        # zipf query popularity (s = BENCH_SERVE_ZIPF, 0 = uniform):
        # serving traffic has hot queries, and hot queries are what the
        # scheduler's singleflight coalescing dedups — a uniform draw
        # would benchmark a traffic shape real services never see
        s = float(os.environ.get("BENCH_SERVE_ZIPF", 1.1))
        w = 1.0 / np.power(np.arange(1, len(variants) + 1, dtype=np.float64), s)
        probs = w / w.sum()

        def client(cid: int):
            rng = np.random.default_rng(1000 + cid)  # same draw both modes
            my = []
            conn = mkconn()
            try:
                while time.monotonic() < stop_at[0]:
                    q = variants[int(rng.choice(len(variants), p=probs))]
                    t0 = time.monotonic()
                    out = post_on(conn, q)
                    my.append(time.monotonic() - t0)
                    out.pop("server_latency", None)
                    if out != canon[q]:
                        raise AssertionError(f"response diverged for {q!r}")
            except Exception as e:
                errs.append(e)
            finally:
                conn.close()
            with lat_lock:
                lats.extend(my)

        ts = [
            threading.Thread(target=client, args=(c,), daemon=True)
            for c in range(clients)
        ]
        stop_at[0] = time.monotonic() + secs
        t0 = time.monotonic()
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=secs + 60)
        wall = time.monotonic() - t0
        if errs:
            raise errs[0]
        if not lats:
            raise RuntimeError("serving bench made no requests")
        a = np.sort(np.asarray(lats))
        return (
            len(a) / wall,
            float(a[int(0.50 * (len(a) - 1))]) * 1e3,
            float(a[int(0.99 * (len(a) - 1))]) * 1e3,
            canon,
            len(a),
        )
    finally:
        srv.stop()


def run_serving_bench():
    """Closed-loop multi-client serving benchmark (ISSUE 2 + ISSUE 3):
    three arms over the same zipf workload with response-parity checks —
    scheduler on (cache off) vs the serial per-request path (the PR 2
    batching A/B, both cache-off so the ratio still isolates batching),
    plus the two-tier query cache on (ISSUE 3's warm-path A/B: cache_on
    vs the cache-off scheduler arm).  Guards that the cache-on arm's
    tier-2 hit rate is nonzero — a zipf head that never hits means the
    cache is mis-keyed, and the headline ratio would be a lie.
    Returns the dict merged into the headline JSON under "serving"."""
    clients = int(os.environ.get("BENCH_CLIENTS", 32))
    secs = float(os.environ.get("BENCH_SERVE_SECONDS", 4.0))
    n_nodes = int(os.environ.get("BENCH_SERVE_NODES", 20_000))
    deg = int(os.environ.get("BENCH_SERVE_DEG", 16))
    store = _serving_store(n_nodes, deg)

    # 64 same-shape-family 2-hop variants (different seed uids): cohorts
    # coalesce them, and the count leaf keeps responses JSON-light so the
    # measurement stays on traversal, not encoding
    rng = np.random.default_rng(5)
    variants = []
    for _ in range(64):
        seeds = np.unique(rng.integers(1, n_nodes + 1, size=8))
        ul = ", ".join("0x%x" % u for u in seeds)
        variants.append("{ q(func: uid(%s)) { e { c: count(e) } } }" % ul)

    from statistics import median

    from dgraph_tpu.utils.metrics import (
        QCACHE_RESULT_EVENTS,
        SCHED_COHORT_OCCUPANCY,
        SCHED_FLUSHES,
    )

    reps = max(1, int(os.environ.get("BENCH_SERVE_REPS", 2)))
    _occ0, occ_sum0, c0 = SCHED_COHORT_OCCUPANCY.snapshot()
    fl0 = SCHED_FLUSHES.snapshot()
    qc0 = QCACHE_RESULT_EVENTS.snapshot()
    # interleave the modes: the shared host's load swings throughput ~2×
    # between runs (same caveat as the headline bench), so paired runs +
    # medians are the only defensible comparison.  The two sched arms run
    # CACHE-OFF so their ratio still isolates the batching win; the cache
    # arm compares against the cache-off scheduler arm.
    on_runs, off_runs, cache_runs = [], [], []
    canon_on = canon_off = canon_cache = None
    n_on = n_off = n_cache = 0
    for _ in range(reps):
        qps, p50, p99, canon_on, n1 = _serving_mode(
            True, store, variants, clients, secs
        )
        on_runs.append((qps, p50, p99))
        n_on += n1
        qps, p50, p99, canon_off, n2 = _serving_mode(
            False, store, variants, clients, secs
        )
        off_runs.append((qps, p50, p99))
        n_off += n2
        qps, p50, p99, canon_cache, n3 = _serving_mode(
            True, store, variants, clients, secs, cache_on=True
        )
        cache_runs.append((qps, p50, p99))
        n_cache += n3
    _occ1, occ_sum1, c1 = SCHED_COHORT_OCCUPANCY.snapshot()
    fl1 = SCHED_FLUSHES.snapshot()
    qc1 = QCACHE_RESULT_EVENTS.snapshot()
    identical = canon_on == canon_off == canon_cache
    assert identical, "sched/cache arm responses diverged"
    # tier-2 guard: the zipf head MUST hit (nonzero hit rate) or the
    # cache arm measured nothing
    t2_hits = qc1.get("hit", 0) - qc0.get("hit", 0)
    t2_miss = qc1.get("miss", 0) - qc0.get("miss", 0)
    t2_rate = t2_hits / max(t2_hits + t2_miss, 1)
    assert t2_hits > 0, (
        "cache-on serving arm reported a ZERO tier-2 hit rate under the "
        "zipf workload — the result cache never engaged"
    )
    flushes = {k: fl1.get(k, 0) - fl0.get(k, 0) for k in fl1}
    flushes = {k: v for k, v in flushes.items() if v}
    n_flush = max(c1 - c0, 1)
    qps_on = median(r[0] for r in on_runs)
    qps_off = median(r[0] for r in off_runs)
    qps_cache = median(r[0] for r in cache_runs)
    return {
        "clients": clients,
        "seconds": secs,
        "reps": reps,
        "sched_on": {
            "qps": round(qps_on, 1),
            "p50_ms": round(median(r[1] for r in on_runs), 2),
            "p99_ms": round(median(r[2] for r in on_runs), 2),
            "qps_runs": [round(r[0], 1) for r in on_runs],
            "requests": n_on,
        },
        "sched_off": {
            "qps": round(qps_off, 1),
            "p50_ms": round(median(r[1] for r in off_runs), 2),
            "p99_ms": round(median(r[2] for r in off_runs), 2),
            "qps_runs": [round(r[0], 1) for r in off_runs],
            "requests": n_off,
        },
        "cache_on": {
            "qps": round(qps_cache, 1),
            "p50_ms": round(median(r[1] for r in cache_runs), 2),
            "p99_ms": round(median(r[2] for r in cache_runs), 2),
            "qps_runs": [round(r[0], 1) for r in cache_runs],
            "requests": n_cache,
        },
        "qps_ratio": round(qps_on / qps_off, 3) if qps_off else None,
        # ISSUE 3 headline: warm-QPS ratio, cache-on over the cache-off
        # scheduler arm (same sched config, only DGRAPH_TPU_CACHE flips)
        "cache_qps_ratio": round(qps_cache / qps_on, 3) if qps_on else None,
        "tier2_hit_rate": round(t2_rate, 4),
        "cohort_occupancy_mean": round((occ_sum1 - occ_sum0) / n_flush, 2),
        "flush_reasons": flushes,
        "responses_identical": identical,
    }


def _qos_mode(
    qos_on: bool,
    store,
    victim_qs,
    antag_qs,
    v_clients: int,
    a_clients: int,
    secs: float,
    tenants_json: str,
):
    """One closed-loop antagonist/victim run: ``v_clients`` victim
    threads fire light point reads under tenant ``victim`` while
    ``a_clients`` antagonist threads flood heavy traversals under
    tenant ``antagonist``.  ``qos_on`` flips DGRAPH_TPU_QOS — the PR-11
    A/B.  Cache is OFF for both arms (an antagonist whose repeats hit
    the result cache would stress nothing).  Antagonist 429s (quota
    sheds) are counted, not errors — being shed IS the mechanism under
    test.  Returns (victim qps, p50_ms, p99_ms, antag_ok, antag_shed)."""
    import json as _json
    import threading

    # save/restore EVERYTHING this arm pins: a later arm (or the
    # operator's own exports) must not inherit this arm's regime
    saved = {
        k: os.environ.get(k)
        for k in ("DGRAPH_TPU_SCHED", "DGRAPH_TPU_CACHE",
                  "DGRAPH_TPU_QOS", "DGRAPH_TPU_QOS_TENANTS")
    }
    os.environ["DGRAPH_TPU_SCHED"] = "1"
    os.environ["DGRAPH_TPU_CACHE"] = "0"
    os.environ["DGRAPH_TPU_QOS"] = "1" if qos_on else "0"
    os.environ["DGRAPH_TPU_QOS_TENANTS"] = tenants_json
    from dgraph_tpu.serve.server import DgraphServer

    srv = DgraphServer(store)
    srv.start()
    try:
        import http.client

        def post_on(conn, q, tenant):
            conn.request(
                "POST", "/query", body=q.encode(),
                headers={"X-Dgraph-Tenant": tenant},
            )
            r = conn.getresponse()
            body = r.read()
            return r.status, body

        warm = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
        for q in (victim_qs + antag_qs)[:4]:  # compile warmup, untimed
            post_on(warm, q, "warmup")
        warm.close()

        lock = threading.Lock()
        v_lats: list = []
        a_ok = [0]
        a_shed = [0]
        errs: list = []
        stop_at = [0.0]

        def victim(cid: int):
            rng = np.random.default_rng(100 + cid)
            conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=60)
            my = []
            try:
                while time.monotonic() < stop_at[0]:
                    q = victim_qs[int(rng.integers(len(victim_qs)))]
                    t0 = time.monotonic()
                    status, body = post_on(conn, q, "victim")
                    if status != 200:
                        raise RuntimeError(
                            f"victim HTTP {status}: {body[:120]!r}"
                        )
                    _json.loads(body.decode())
                    my.append(time.monotonic() - t0)
            except Exception as e:
                errs.append(e)
            finally:
                conn.close()
            with lock:
                v_lats.extend(my)

        def antagonist(cid: int):
            rng = np.random.default_rng(900 + cid)
            conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=60)
            ok = shed = 0
            try:
                while time.monotonic() < stop_at[0]:
                    q = antag_qs[int(rng.integers(len(antag_qs)))]
                    try:
                        status, _body = post_on(conn, q, "antagonist")
                    except OSError:
                        conn.close()
                        conn = http.client.HTTPConnection(
                            "127.0.0.1", srv.port, timeout=60
                        )
                        continue
                    if status == 200:
                        ok += 1
                    elif status == 429:
                        shed += 1
                        # honor back-pressure minimally: a real client
                        # would sleep Retry-After; the flood sleeps just
                        # enough not to busy-spin the accept loop
                        time.sleep(0.002)
                    else:
                        raise RuntimeError(f"antagonist HTTP {status}")
            except Exception as e:
                errs.append(e)
            finally:
                conn.close()
            with lock:
                a_ok[0] += ok
                a_shed[0] += shed

        ts = [
            threading.Thread(target=victim, args=(c,), daemon=True)
            for c in range(v_clients)
        ] + [
            threading.Thread(target=antagonist, args=(c,), daemon=True)
            for c in range(a_clients)
        ]
        stop_at[0] = time.monotonic() + secs
        t0 = time.monotonic()
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=secs + 120)
        wall = time.monotonic() - t0
        if errs:
            raise errs[0]
        if not v_lats:
            raise RuntimeError("qos bench victim made no requests")
        a = np.sort(np.asarray(v_lats))
        return (
            len(a) / wall,
            float(a[int(0.50 * (len(a) - 1))]) * 1e3,
            float(a[int(0.99 * (len(a) - 1))]) * 1e3,
            a_ok[0],
            a_shed[0],
        )
    finally:
        srv.stop()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def run_qos_bench():
    """Antagonist-isolation benchmark (PR 11's headline robustness
    number).  Three arms over one store:

    - ``victim_solo`` — victim tenant alone, QoS on: the baseline SLO.
    - ``qos_on``      — victim + antagonist flood, QoS on: the
      antagonist is quota-shed (max_queued) and weight-limited, and the
      victim's p99 must stay within ``BENCH_QOS_FACTOR`` (default 3×)
      of its solo p99 — asserted, not just reported.
    - ``qos_off``     — the SAME mix with DGRAPH_TPU_QOS=0: shows the
      leak (victim p99 blowup with no per-tenant machinery).

    Sized by BENCH_QOS_NODES/DEG/SECONDS/VICTIM_CLIENTS/ANTAG_CLIENTS;
    BENCH_QOS_ASSERT=0 downgrades the assertion to reporting (the CI
    smoke keeps it on with a generous factor — a 2-core shared runner
    proves the harness, not the SLO)."""
    n_nodes = int(os.environ.get("BENCH_QOS_NODES", 20_000))
    deg = int(os.environ.get("BENCH_QOS_DEG", 16))
    secs = float(os.environ.get("BENCH_QOS_SECONDS", 3.0))
    v_clients = int(os.environ.get("BENCH_QOS_VICTIM_CLIENTS", 4))
    a_clients = int(os.environ.get("BENCH_QOS_ANTAG_CLIENTS", 16))
    factor = float(os.environ.get("BENCH_QOS_FACTOR", 3.0))
    do_assert = os.environ.get("BENCH_QOS_ASSERT", "1") != "0"
    store = _serving_store(n_nodes, deg)

    rng = np.random.default_rng(17)
    # victim: single-uid point reads with a count leaf — the 1ms-class
    # traffic whose SLO the antagonist must not wreck
    victim_qs = [
        "{ q(func: uid(0x%x)) { c: count(e) } }" % u
        for u in np.unique(rng.integers(1, n_nodes + 1, size=64))
    ]
    # antagonist: wide 2-hop expansions from 64-seed lists — each one
    # orders of magnitude more engine work than a victim read
    antag_qs = []
    for _ in range(128):
        seeds = np.unique(rng.integers(1, n_nodes + 1, size=64))
        ul = ", ".join("0x%x" % u for u in seeds)
        antag_qs.append("{ q(func: uid(%s)) { e { e { c: count(e) } } } }" % ul)

    # the QoS envelope under test: the victim outweighs the antagonist
    # 8:1 for cohort slots, and the antagonist's own queue/inflight
    # quota sheds its flood at admission instead of letting it occupy
    # the global queue
    tenants = json.dumps({
        "victim": {"weight": 8, "priority": "interactive"},
        "antagonist": {
            "weight": 1, "max_queued": 8, "max_inflight": 1,
            "priority": "batch",
        },
    })

    solo_qps, solo_p50, solo_p99, _ok, _shed = _qos_mode(
        True, store, victim_qs, antag_qs, v_clients, 0, secs, tenants
    )
    on_qps, on_p50, on_p99, on_ok, on_shed = _qos_mode(
        True, store, victim_qs, antag_qs, v_clients, a_clients, secs, tenants
    )
    off_qps, off_p50, off_p99, off_ok, off_shed = _qos_mode(
        False, store, victim_qs, antag_qs, v_clients, a_clients, secs, tenants
    )
    # floor: on a noisy shared host a 0.3ms solo p99 would make any
    # ratio meaningless — compare against at least a 5ms baseline
    base = max(solo_p99, 5.0)
    isolation = on_p99 / base
    leak = off_p99 / base
    out = {
        "seconds": secs,
        "victim_clients": v_clients,
        "antagonist_clients": a_clients,
        "tenants": json.loads(tenants),
        "victim_solo": {
            "qps": round(solo_qps, 1), "p50_ms": round(solo_p50, 2),
            "p99_ms": round(solo_p99, 2),
        },
        "qos_on": {
            "victim_qps": round(on_qps, 1),
            "victim_p50_ms": round(on_p50, 2),
            "victim_p99_ms": round(on_p99, 2),
            "antagonist_ok": on_ok,
            "antagonist_shed": on_shed,
        },
        "qos_off": {
            "victim_qps": round(off_qps, 1),
            "victim_p50_ms": round(off_p50, 2),
            "victim_p99_ms": round(off_p99, 2),
            "antagonist_ok": off_ok,
            "antagonist_shed": off_shed,
        },
        # the headline pair: bounded with QoS on, the leak without
        "victim_p99_factor_qos_on": round(isolation, 3),
        "victim_p99_factor_qos_off": round(leak, 3),
        "bound_factor": factor,
        "isolation_holds": bool(isolation <= factor),
    }
    if do_assert:
        assert on_shed > 0, (
            "qos bench: the antagonist was never quota-shed — the "
            "per-tenant admission quota did not engage"
        )
        assert isolation <= factor, (
            f"qos bench: victim p99 under antagonist flood "
            f"({on_p99:.1f}ms) exceeded {factor}x its solo baseline "
            f"({solo_p99:.1f}ms, floored to {base:.1f}ms)"
        )
    return out


def _ivm_mode(
    ivm_on: bool, store, variants, clients: int, secs: float,
    write_rate: float, write_pred: str, cache_on: bool = True,
):
    """One closed-loop read run with a paced writer beside it.

    ``clients`` reader threads fire the zipf variant mix while ONE
    writer toggles edges on ``write_pred`` at ``write_rate``/s (each
    toggle is an add immediately followed by its delete, so the run
    ends at the state it started — what makes the post-quiesce parity
    probe meaningful).  Cache ON both arms; only DGRAPH_TPU_IVM flips:
    the off arm is the store.version-keyed baseline every mutation
    global-invalidates.  Returns (qps, completed, final_responses)."""
    import json as _json
    import threading

    saved = {
        k: os.environ.get(k)
        for k in ("DGRAPH_TPU_SCHED", "DGRAPH_TPU_CACHE", "DGRAPH_TPU_IVM")
    }
    os.environ["DGRAPH_TPU_SCHED"] = "1"
    os.environ["DGRAPH_TPU_CACHE"] = "1" if cache_on else "0"
    os.environ["DGRAPH_TPU_IVM"] = "1" if ivm_on else "0"
    from dgraph_tpu.serve.server import DgraphServer

    srv = DgraphServer(store)
    srv.start()
    try:
        import http.client

        def mkconn():
            return http.client.HTTPConnection(
                "127.0.0.1", srv.port, timeout=30
            )

        def post_on(conn, q):
            conn.request("POST", "/query", body=q.encode())
            r = conn.getresponse()
            body = r.read()
            if r.status != 200:
                raise RuntimeError(f"HTTP {r.status}: {body[:200]!r}")
            return _json.loads(body.decode())

        warm = mkconn()
        for q in variants:
            post_on(warm, q)

        lat_lock = threading.Lock()
        done = [0]
        errs: list = []
        stop_at = [0.0]
        quiesce = threading.Event()

        s = float(os.environ.get("BENCH_SERVE_ZIPF", 1.1))
        w = 1.0 / np.power(
            np.arange(1, len(variants) + 1, dtype=np.float64), s
        )
        probs = w / w.sum()

        def reader(cid: int):
            rng = np.random.default_rng(2000 + cid)  # same draw each arm
            n = 0
            conn = mkconn()
            try:
                while time.monotonic() < stop_at[0]:
                    q = variants[int(rng.choice(len(variants), p=probs))]
                    post_on(conn, q)
                    n += 1
            except Exception as e:
                errs.append(e)
            finally:
                conn.close()
            with lat_lock:
                done[0] += n

        def writer():
            # paced edge toggles: add + revert, one WAL'd mutation each,
            # single-edge journal deltas (the repair path's shape)
            if write_rate <= 0:
                return
            conn = mkconn()
            i = 0
            try:
                while time.monotonic() < stop_at[0]:
                    u = 0x70000 + (i % 97)
                    i += 1
                    post_on(conn, "mutation { set { <0x%x> <%s> <0x%x> . } }"
                            % (u, write_pred, u + 1))
                    post_on(conn, "mutation { delete { <0x%x> <%s> <0x%x> . } }"
                            % (u, write_pred, u + 1))
                    time.sleep(1.0 / write_rate)
            except Exception as e:
                if not quiesce.is_set():
                    errs.append(e)
            finally:
                conn.close()

        ts = [
            threading.Thread(target=reader, args=(c,), daemon=True)
            for c in range(clients)
        ]
        wt = threading.Thread(target=writer, daemon=True)
        stop_at[0] = time.monotonic() + secs
        t0 = time.monotonic()
        for t in ts:
            t.start()
        wt.start()
        for t in ts:
            t.join(timeout=secs + 60)
        wall = time.monotonic() - t0
        quiesce.set()
        wt.join(timeout=secs + 60)
        if errs:
            raise errs[0]
        # post-quiesce probe: the writer reverted every toggle, so a
        # correctly-invalidated (or correctly-REPAIRED) cache must now
        # answer exactly the initial state — through the warm cache
        final = {}
        conn = mkconn()
        for q in variants:
            out = post_on(conn, q)
            out.pop("server_latency", None)
            final[q] = out
        conn.close()
        return done[0] / wall, done[0], final
    finally:
        srv.stop()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _ivm_subscription_demo(store) -> dict:
    """The live-query acceptance probe: a registered subscription gets
    exactly ONE trace-linked push after an affecting mutation, and
    nothing for an unrelated-predicate mutation."""
    import json as _json
    import urllib.request

    from dgraph_tpu import obs

    saved = {
        k: os.environ.get(k)
        for k in ("DGRAPH_TPU_SCHED", "DGRAPH_TPU_CACHE", "DGRAPH_TPU_IVM")
    }
    os.environ["DGRAPH_TPU_SCHED"] = "1"
    os.environ["DGRAPH_TPU_CACHE"] = "1"
    os.environ["DGRAPH_TPU_IVM"] = "1"
    rec = obs.configure(ratio=1.0, seed=11)  # every eval traced
    from dgraph_tpu.serve.server import DgraphServer

    srv = DgraphServer(store)
    srv.start()
    try:
        base = srv.addr

        def post(path, body):
            return urllib.request.urlopen(
                urllib.request.Request(base + path, data=body.encode()),
                timeout=15,
            )

        reg = _json.load(post(
            "/subscribe", "{ s(func: uid(0x1)) { e { c: count(e) } } }"
        ))
        sid = reg["sub_id"]
        sub = srv.subs.get(sid)
        ev0 = sub.next_event(timeout=10)  # the snapshot
        assert ev0 and ev0["kind"] == "snapshot", ev0
        # unrelated predicate: NO push
        post("/query", 'mutation { set { <0x9999> <unrelated_w> "x" . } }')
        quiet = sub.next_event(timeout=1.0)
        assert quiet is None, f"unrelated mutation pushed: {quiet}"
        # affecting predicate: exactly one push, trace-linked
        post("/query", "mutation { set { <0x1> <e> <0x2> . } }")
        ev = sub.next_event(timeout=10)
        assert ev is not None and ev["kind"] == "update", ev
        assert ev["trace_id"], "push was not trace-linked"
        tr = rec.trace(ev["trace_id"])
        assert tr is not None and any(
            s["name"] == "subs.eval" for s in tr["spans"]
        ), "push trace_id does not resolve to a subs.eval trace"
        post("/subscribe/cancel?id=" + sid, "")
        # revert so later arms see the initial graph
        post("/query", "mutation { delete { <0x1> <e> <0x2> . } }")
        return {
            "pushed_seq": ev["seq"],
            "trigger_preds": ev["preds"],
            "trace_linked": True,
            "unrelated_pushed_nothing": True,
        }
    finally:
        srv.stop()
        obs.configure(ratio=0.0)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def run_ivm_bench():
    """Write-rate sweep (ISSUE 12): QPS of the warm two-tier cache as a
    paced writer runs beside the readers — predicate-scoped
    invalidation + delta repair (DGRAPH_TPU_IVM=1, default) against the
    ``store.version``-keyed baseline (=0) where ANY write invalidates
    EVERY cached hop and response.  Writers toggle an UNRELATED
    predicate (the production shape: writes spread across predicates,
    reads concentrate) plus a hot-predicate row that must engage the
    delta-REPAIR path; both assert post-quiesce parity against the
    initial canonical responses, and the subscription demo asserts the
    live-query push contract.  Returns the dict published under "ivm"
    in the headline JSON."""
    from statistics import median

    from dgraph_tpu.utils.metrics import IVM_REPAIRS, QCACHE_RESULT_EVENTS

    clients = int(os.environ.get("BENCH_IVM_CLIENTS", 12))
    secs = float(os.environ.get("BENCH_IVM_SECONDS", 3.0))
    n_nodes = int(os.environ.get("BENCH_IVM_NODES", 8_000))
    deg = int(os.environ.get("BENCH_IVM_DEG", 12))
    rates = [
        float(x)
        for x in os.environ.get("BENCH_IVM_WRITE_RATES", "0,25").split(",")
    ]
    reps = max(1, int(os.environ.get("BENCH_IVM_REPS", 2)))
    store = _serving_store(n_nodes, deg)

    rng = np.random.default_rng(17)
    variants = []
    for _ in range(32):
        seeds = np.unique(rng.integers(1, n_nodes + 1, size=8))
        ul = ", ".join("0x%x" % u for u in seeds)
        variants.append("{ q(func: uid(%s)) { e { c: count(e) } } }" % ul)

    # canonical truth: cache OFF (cache_on=False — the ground truth
    # must come from the cache-less execution path, or a deterministic
    # staleness bug could corrupt canon and probe identically), no
    # writer (the writer always reverts, so every post-quiesce probe
    # must reproduce these bytes)
    _q, _n, canon = _ivm_mode(
        True, store, variants, clients=2, secs=0.3, write_rate=0,
        write_pred="unrelated_w", cache_on=False,
    )

    sweep = []
    for rate in rates:
        on_runs, off_runs = [], []
        for _ in range(reps):
            qps, _n, fin = _ivm_mode(
                True, store, variants, clients, secs, rate, "unrelated_w"
            )
            assert fin == canon, (
                f"IVM-on arm diverged after quiesce at rate {rate}"
            )
            on_runs.append(qps)
            qps, _n, fin = _ivm_mode(
                False, store, variants, clients, secs, rate, "unrelated_w"
            )
            assert fin == canon, (
                f"baseline arm diverged after quiesce at rate {rate}"
            )
            off_runs.append(qps)
        qps_on = median(on_runs)
        qps_off = median(off_runs)
        sweep.append({
            "write_rate": rate,
            "qps_ivm_on": round(qps_on, 1),
            "qps_ivm_off": round(qps_off, 1),
            "ratio": round(qps_on / qps_off, 3) if qps_off else None,
        })

    # hot-predicate row: writes hit the READ predicate, so the win must
    # come from the delta-REPAIR path keeping hop entries warm — assert
    # it actually engaged
    hot_rate = float(os.environ.get("BENCH_IVM_HOT_RATE", "25"))
    rep0 = IVM_REPAIRS.snapshot()
    t2_0 = QCACHE_RESULT_EVENTS.snapshot()
    hot_qps, _n, fin = _ivm_mode(
        True, store, variants, clients, secs, hot_rate, "e"
    )
    assert fin == canon, "hot-write IVM arm diverged after quiesce"
    rep1 = IVM_REPAIRS.snapshot()
    hop_repaired = (
        rep1.get(("hop", "repaired"), 0) - rep0.get(("hop", "repaired"), 0)
    )
    assert hop_repaired > 0, (
        "hot-write arm never engaged the hop repair path"
    )
    t2_1 = QCACHE_RESULT_EVENTS.snapshot()

    nz = [row for row in sweep if row["write_rate"] > 0]
    headline = nz[-1]["ratio"] if nz else None
    return {
        "clients": clients,
        "seconds": secs,
        "reps": reps,
        "qps_vs_write_rate": sweep,
        # the ISSUE 12 headline: warm-cache QPS under writes, scoped
        # invalidation over the global-version baseline
        "write_rate_qps_ratio": headline,
        "hot_write": {
            "write_rate": hot_rate,
            "qps": round(hot_qps, 1),
            "hop_entries_repaired": hop_repaired,
            "tier2_events": {
                k: t2_1.get(k, 0) - t2_0.get(k, 0) for k in t2_1
            },
        },
        "subscription": _ivm_subscription_demo(store),
        "parity_asserted": True,
    }


def _mutation_mode(
    group_commit: bool, clients: int, secs: float, tmp: str,
    fsync_ms: float = 0.0,
):
    """One closed-loop durable-mutation run: ``clients`` threads fire
    single-edge mutations against a fresh DgraphServer over a fresh
    --sync DurableStore (fsync-per-acknowledged-write contract).
    ``group_commit`` flips DGRAPH_TPU_GROUP_COMMIT — the ISSUE 6 A/B:
    per-write fsync inside the write lock vs one shared fsync per convoy
    of concurrent writers.  ``fsync_ms`` > 0 models a production disk by
    arming ``wal.post_flush=delay(ms=...)`` (the failpoint fires inside
    the fsync critical section, so the per-write arm serializes behind
    it while the group-commit convoy shares one delay — same mechanism,
    calibrated medium).  Returns (writes/s, p99_ms, writes, fsyncs)."""
    import json as _json
    import threading

    os.environ["DGRAPH_TPU_GROUP_COMMIT"] = "1" if group_commit else "0"
    os.environ["DGRAPH_TPU_SNAPSHOTTER"] = "0"  # isolate the fsync cost
    from dgraph_tpu.models.wal import DurableStore
    from dgraph_tpu.serve.server import DgraphServer
    from dgraph_tpu.utils.failpoints import fail
    from dgraph_tpu.utils.metrics import (
        GROUP_COMMIT_SYNCS,
        GROUP_COMMIT_WRITES,
    )

    if fsync_ms > 0:
        fail.arm("wal.post_flush", f"delay(ms={fsync_ms:g})")
    store = DurableStore(
        os.path.join(tmp, "gc1" if group_commit else "gc0"),
        sync_writes=True,
    )
    srv = DgraphServer(store)
    srv.start()
    try:
        import http.client

        def post_on(conn, q):
            conn.request("POST", "/query", body=q.encode())
            r = conn.getresponse()
            body = r.read()
            if r.status != 200:
                raise RuntimeError(f"HTTP {r.status}: {body[:200]!r}")
            return _json.loads(body.decode())

        warm = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
        post_on(warm, "mutation { schema { bm: string . } }")
        warm.close()
        w0 = GROUP_COMMIT_WRITES.value()
        s0 = GROUP_COMMIT_SYNCS.value()
        lat_lock = threading.Lock()
        lats: list = []
        errs: list = []
        stop_at = [time.monotonic() + 3600]

        def client(cid: int):
            conn = http.client.HTTPConnection(
                "127.0.0.1", srv.port, timeout=30
            )
            my = []
            uid = (cid + 1) << 24  # disjoint uid ranges per writer
            try:
                while time.monotonic() < stop_at[0]:
                    uid += 1
                    t0 = time.monotonic()
                    post_on(
                        conn,
                        'mutation { set { <0x%x> <bm> "x" . } }' % uid,
                    )
                    my.append(time.monotonic() - t0)
            except Exception as e:
                errs.append(e)
            finally:
                conn.close()
            with lat_lock:
                lats.extend(my)

        ts = [
            threading.Thread(target=client, args=(c,), daemon=True)
            for c in range(clients)
        ]
        stop_at[0] = time.monotonic() + secs
        t0 = time.monotonic()
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=secs + 60)
        wall = time.monotonic() - t0
        if errs:
            raise errs[0]
        if not lats:
            raise RuntimeError("mutation bench made no writes")
        a = np.sort(np.asarray(lats))
        return (
            len(a) / wall,
            float(a[int(0.99 * (len(a) - 1))]) * 1e3,
            GROUP_COMMIT_WRITES.value() - w0,
            GROUP_COMMIT_SYNCS.value() - s0,
        )
    finally:
        srv.stop()
        if fsync_ms > 0:
            fail.disarm("wal.post_flush")
        os.environ.pop("DGRAPH_TPU_GROUP_COMMIT", None)
        os.environ.pop("DGRAPH_TPU_SNAPSHOTTER", None)


def run_mutation_bench():
    """Durable-write A/B (ISSUE 6): --sync mutation throughput with
    concurrent writers, group commit on vs per-write fsync.  Interleaved
    reps + medians, same discipline as the serving bench.  The
    ``fsync_share`` line is the amortization factor the metrics pair
    (dgraph_group_commit_{writes,syncs}_total) exposes in production."""
    import shutil
    import tempfile
    from statistics import median

    clients = int(os.environ.get("BENCH_MUT_CLIENTS", 8))
    secs = float(os.environ.get("BENCH_MUT_SECONDS", 2.0))
    reps = max(1, int(os.environ.get("BENCH_MUT_REPS", 2)))
    # modeled-disk arm: a calibrated fsync latency (EBS/network media
    # run 5-30ms; local NVMe 0.5-3ms).  This CPU container's page-cache
    # fsync is so cheap the exclusive engine section dominates both
    # arms — the modeled arm shows the mechanism at production fsync
    # cost.  0 disables.  (Measured here at 15ms/8 writers: ~2.9x and
    # fsync_share ~2.4, capped by the 2-core host's GIL-contended
    # engine section, not by the commit protocol.)
    fsync_ms = float(os.environ.get("BENCH_MUT_FSYNC_MS", 15.0))
    tmp = tempfile.mkdtemp(prefix="dgraph-bench-mut-")

    def _arm_pair(sub: str, ms: float):
        on_runs, off_runs = [], []
        writes = syncs = 0
        for r in range(reps):
            d = os.path.join(tmp, f"{sub}-r{r}")
            os.makedirs(d, exist_ok=True)
            wps, p99, w, s = _mutation_mode(
                True, clients, secs, d, fsync_ms=ms
            )
            on_runs.append((wps, p99))
            writes += w
            syncs += s
            wps, p99, _w, _s = _mutation_mode(
                False, clients, secs, d, fsync_ms=ms
            )
            off_runs.append((wps, p99))
        wps_on = median(x[0] for x in on_runs)
        wps_off = median(x[0] for x in off_runs)
        return {
            "group_commit": {
                "writes_per_sec": round(wps_on, 1),
                "p99_ms": round(median(x[1] for x in on_runs), 2),
            },
            "per_write_fsync": {
                "writes_per_sec": round(wps_off, 1),
                "p99_ms": round(median(x[1] for x in off_runs), 2),
            },
            # the ISSUE 6 headline: durable writes/s, shared fsync over
            # fsync-per-acknowledged-write, same writer fleet
            "group_commit_ratio": (
                round(wps_on / wps_off, 3) if wps_off else None
            ),
            # >1 = convoys actually shared fsyncs (writes per fsync,
            # group-commit arm only)
            "fsync_share": round(writes / max(syncs, 1), 2),
        }

    try:
        out = {
            "clients": clients,
            "seconds": secs,
            "reps": reps,
            "sync": True,
            "real_disk": _arm_pair("real", 0.0),
        }
        if fsync_ms > 0:
            out["modeled_disk"] = {
                "fsync_ms": fsync_ms,
                **_arm_pair("model", fsync_ms),
            }
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def run_bench(scale: float):
    import jax

    # measured-cost planner: run (or load) the micro-calibration pass up
    # front so every route decision in this run prices from THIS host's
    # rates, and the calibration file is fresh for the next server boot
    from dgraph_tpu.query import planner

    if planner.enabled():
        try:
            planner.boot(measure_now=True)
        except Exception as e:
            print(
                f"# calibration skipped ({type(e).__name__}: {e})",
                file=sys.stderr,
            )
    # a DGRAPH_TPU_PLANNER=0 arm must not mutate planner state: no
    # measurement pass, no calibration-file overwrite — the operator
    # disabled the planner, the bench honors it

    n_nodes = max(1024, int(int(os.environ.get("BENCH_NODES", 2_000_000)) * scale))
    n_edges = max(4096, int(int(os.environ.get("BENCH_EDGES", 21_000_000)) * scale))
    n_seeds = max(64, int(int(os.environ.get("BENCH_SEEDS", 4096)) * min(1.0, scale * 4)))
    iters = int(os.environ.get("BENCH_ITERS", 1000))

    t0 = time.time()
    a = build_graph(n_nodes, n_edges)
    h_dst = np.asarray(a.dst)[: a.n_edges]
    build_s = time.time() - t0

    rng = np.random.default_rng(3)
    frontiers = [
        np.unique(rng.integers(1, n_nodes + 1, size=n_seeds))
        for _ in range(iters)
    ]
    from dgraph_tpu import ops

    fcap = ops.bucket(max(len(f) for f in frontiers))

    platform = jax.devices()[0].platform
    dedup = os.environ.get("BENCH_DEDUP", "auto")
    if dedup == "auto":
        # host-side np.unique between hops wins wherever XLA's sort
        # loses to numpy's (everywhere but TPU, measured ~10×); on TPU
        # the sort rides the VPU and staying device-resident wins
        dedup = "device" if platform == "tpu" else "host"

    if dedup == "host":
        dev_s, dev_edges, chks, last_set = _run_host_dedup(
            a, h_dst, frontiers
        )
    else:
        dev_s, dev_edges, chks, last_set = _run_device_dedup(
            a, frontiers, fcap
        )

    # best-of-2 for the CPU baseline: the shared host's load swings numpy
    # throughput ~2x between runs; compare against its fastest
    cpu_s = float("inf")
    for _ in range(2):
        t0 = time.time()
        cpu_edges = 0
        cpu_chks = []
        for f in frontiers:
            n, _, c = np_two_hop(a, h_dst, f)
            cpu_edges += n
            cpu_chks.append(c)
        cpu_s = min(cpu_s, time.time() - t0)

    # correctness cross-check: per-query checksums + the last frontier set
    _, want, _ = np_two_hop(a, h_dst, frontiers[-1])
    assert np.array_equal(last_set, want), "device 2-hop != numpy reference"
    assert dev_edges == cpu_edges, (dev_edges, cpu_edges)
    assert np.array_equal(chks, np.array(cpu_chks, dtype=np.int32)), (
        "per-query device checksums != numpy"
    )

    dev_eps = dev_edges / dev_s
    cpu_eps = cpu_edges / cpu_s

    serving = None
    if os.environ.get("BENCH_SERVE", "1") != "0":
        # closed-loop multi-client serving mode (cohort scheduler A/B);
        # failures here must not void the headline traversal number
        try:
            serving = run_serving_bench()
        except Exception as e:
            serving = {"error": f"{type(e).__name__}: {e}"}
    durability = None
    if os.environ.get("BENCH_MUT", "1") != "0":
        # durable-mutation A/B (group commit vs per-write fsync); same
        # isolation contract as the serving arm
        try:
            durability = run_mutation_bench()
        except Exception as e:
            durability = {"error": f"{type(e).__name__}: {e}"}
    qos_arm = None
    if os.environ.get("BENCH_QOS", "1") != "0":
        # antagonist/victim isolation A/B (PR 11); same isolation
        # contract — a failed assertion lands in the JSON, the headline
        # traversal number survives
        try:
            qos_arm = run_qos_bench()
        except Exception as e:
            qos_arm = {"error": f"{type(e).__name__}: {e}"}
    ivm_arm = None
    if os.environ.get("BENCH_IVM", "1") != "0":
        # write-rate sweep (ISSUE 12): warm-cache QPS under a paced
        # writer, predicate-scoped invalidation + delta repair vs the
        # store.version-keyed baseline; same isolation contract
        try:
            ivm_arm = run_ivm_bench()
        except Exception as e:
            ivm_arm = {"error": f"{type(e).__name__}: {e}"}
    # planner honesty row: every route decision this process made (the
    # serving arms run in-process) with the measured mispredict rate —
    # future bench rounds show route choice alongside throughput, and a
    # rising mispredict rate means the calibration no longer fits
    cal = planner.calibration_info()
    planner_summary = {
        **planner.mispredict_stats(),
        "decisions_by_route": planner.debug_summary()["counts"],
        "calibration_source": cal["source"],
        "calibrated_dispatch_us": round(cal["rates"]["dispatch_us"], 2),
        "calibrated_device_edge_us": round(
            cal["rates"]["device_edge_us"], 5
        ),
        "calibrated_host_edge_us": round(cal["rates"]["host_edge_us"], 5),
    }
    print(
        json.dumps(
            {
                "metric": "edges_traversed_per_sec_2hop",
                "value": round(dev_eps, 1),
                "unit": "edges/s",
                "vs_baseline": round(dev_eps / cpu_eps, 3),
                # multi-client serving A/B (BENCH_SERVE=0 skips;
                # BENCH_CLIENTS / BENCH_SERVE_SECONDS size it)
                "serving": serving,
                # durable-mutation A/B (BENCH_MUT=0 skips;
                # BENCH_MUT_CLIENTS / BENCH_MUT_SECONDS size it)
                "durability": durability,
                # antagonist/victim multi-tenant QoS A/B (BENCH_QOS=0
                # skips; BENCH_QOS_* size it) — victim p99 bounded with
                # QoS on, the leak shown with QoS off
                "qos": qos_arm,
                # IVM write-rate sweep (BENCH_IVM=0 skips; BENCH_IVM_*
                # size it) — QPS-vs-write-rate curve, scoped
                # invalidation over the global-version baseline, repair
                # engagement + live-query push demo
                "ivm": ivm_arm,
                # measured-cost planner (PR 10): per-route decision
                # counts + mispredict rate + the calibrated rates that
                # drove this run's routing
                "planner": planner_summary,
                # self-describing record: a wedged-TPU round falls back to
                # XLA-on-CPU (see ensure_backend) and must not read as a
                # TPU measurement
                "platform": platform,
                # the batched fused-hop executor (ops/batch.py) served
                # every traversal: one device program per hop (host
                # dedup) or per 2-hop batch (device dedup)
                "fused_hop": True,
                "hop_dedup": dedup,
                "pallas_slotmap": os.environ.get("BENCH_PALLAS") == "1",
            }
        )
    )
    print(
        f"# graph: {n_nodes} nodes / {a.n_edges} edges (build {build_s:.1f}s); "
        f"{iters} queries x {n_seeds} seeds; device {dev_s:.2f}s "
        f"({dev_eps/1e6:.1f}M e/s, {dedup} dedup) vs numpy {cpu_s:.2f}s "
        f"({cpu_eps/1e6:.1f}M e/s) on {platform}; scale={scale:g}",
    )


def main():
    platform = ensure_backend()
    print(f"# backend: {platform}", file=sys.stderr)
    if os.environ.get("BENCH_ONLY") == "qos":
        # standalone qos smoke (CI): the antagonist/victim harness runs
        # without paying for the headline traversal bench — the job
        # exists so the harness itself cannot rot
        print(json.dumps({"qos": run_qos_bench(), "platform": platform}))
        return
    if os.environ.get("BENCH_ONLY") == "ivm":
        # standalone IVM smoke (CI): the write-rate sweep + live-query
        # push demo at tiny sizes — same rot-guard contract as qos
        print(json.dumps({"ivm": run_ivm_bench(), "platform": platform}))
        return
    scale = float(os.environ.get("BENCH_SCALE", 1.0))
    try:
        run_bench(scale)
    except AssertionError:
        raise  # correctness failures must never be masked by a retry
    except Exception as e:
        first = str(e).strip().splitlines()
        first = first[0] if first else type(e).__name__
        print(
            f"# bench failed at scale={scale:g} ({type(e).__name__}: {first}); "
            f"retrying once at scale={scale / 8:g}",
            file=sys.stderr,
        )
        run_bench(scale / 8)


if __name__ == "__main__":
    main()
