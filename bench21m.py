"""21M-quad scale proof: load a Freebase-film-shaped synthetic graph at
the reference's anchor scale through the real mutation path (native
scanner + vectorized bulk apply), then run the two wiki query shapes.

Reference anchors (BASELINE.md): 21M RDF loaded in ~5min (≈73k quads/s,
i7 laptop); 3-hop co-actor query 2-3ms warm / 8-9ms cold; 4-level detail
query 30-35ms warm / 87ms cold; 1.4GB on disk.

Usage: python bench21m.py    (env: B21_QUADS target, default 21_000_000;
B21_CHUNK quads per mutation, default 2_000_000)
Prints one JSON line per metric.  Peak RSS is sampled via resource.
"""

import json
import os
import resource
import time

RESULTS = []


def emit(d: dict) -> None:
    """Record + print a metric, and REWRITE the results file after every
    append — a crash mid-run must not lose hours of accumulated numbers
    (the round-1 empty-artifact postmortem, bench.py docstring)."""
    RESULTS.append(d)
    print(json.dumps(d), flush=True)
    out_path = os.environ.get("B21_OUT", "")
    if out_path:
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"results": RESULTS, "rss_gb": round(rss_gb(), 2)}, f, indent=1)
        os.replace(tmp, out_path)

# B21_HOST_LEVELS=1 reproduces the round-3 tunnel configuration (route
# per-level work to host numpy; only fused chains touch the device).
# The DEFAULT now keeps the engine's standard device routing (262144) —
# the device story is measured, not asserted (VERDICT r3 weak #2): the
# big-fanout shape below runs BOTH ways and records the ratio.
if os.environ.get("B21_HOST_LEVELS") == "1":
    os.environ.setdefault("DGRAPH_TPU_EXPAND_DEVICE_MIN", str(1 << 62))

# engine imports happen INSIDE main() after the backend probe: a module-
# level import that materializes any device value would initialize the
# wedged backend before the CPU fallback can run (the order.py _BIG bug
# class); keeping them lazy makes the probe contract self-contained

# expected quads per director with the zipf generator (measured mean:
# ~88 — bounded-pareto film/perf counts undershoot the uniform 97)
QUADS_PER_DIRECTOR = 88


def rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def main():
    # same wedged-TPU robustness contract as bench.py: probe the backend
    # in a subprocess with a timeout, fall back to CPU so the run still
    # records real numbers
    from bench import ensure_backend

    platform = ensure_backend()
    print(f"# backend: {platform}", flush=True)
    # persistent compile cache (same lever as the server's
    # --compile_cache): repeat runs' cold_ms measures process-restart
    # cold — the reference's anchor semantics — not XLA compile time.
    # B21_COMPILE_CACHE="" disables.
    cache_dir = os.environ.get("B21_COMPILE_CACHE", "scratch/.jitcache")
    if cache_dir:
        import jax as _jax

        try:
            os.makedirs(cache_dir, exist_ok=True)
            _jax.config.update("jax_compilation_cache_dir", cache_dir)
            _jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.5
            )
        except (OSError, AttributeError):
            pass
    global SCHEMA, build, PostingStore, QueryEngine
    from bench_engine import SCHEMA, build
    from dgraph_tpu.models import PostingStore
    from dgraph_tpu.query import QueryEngine

    target = int(os.environ.get("B21_QUADS", 21_000_000))
    chunk_quads = int(os.environ.get("B21_CHUNK", 2_000_000))
    n_directors = target // QUADS_PER_DIRECTOR
    per_chunk = max(1, chunk_quads // QUADS_PER_DIRECTOR)

    st = PostingStore()
    eng = QueryEngine(st)
    eng.run("mutation { schema { %s } }" % SCHEMA)

    total_quads = 0
    gen_s = 0.0
    load_s = 0.0
    done = 0
    while done < n_directors:
        n = min(per_chunk, n_directors - done)
        t0 = time.time()
        # each chunk gets its own uid space via seed offsetting: build()
        # numbers uids from 1, so rebase by string replace would be
        # wrong — instead generate with disjoint uid bases
        rdf = build_chunk(done, n)
        gen_s += time.time() - t0
        t0 = time.time()
        eng.run("mutation { set { %s } }" % rdf)
        load_s += time.time() - t0
        total_quads += rdf.count("\n") + 1
        done += n
        print(
            f"# loaded {done}/{n_directors} directors, {total_quads:,} quads, "
            f"rss {rss_gb():.1f}GB, load {load_s:.0f}s "
            f"({total_quads / max(load_s, 1e-9):,.0f} quads/s)",
            flush=True,
        )

    # vs_baseline fields are only honest at the anchor scale: a smoke run
    # (sub-21M) must not read as a comparison against the reference's
    # full-corpus numbers (VERDICT r4 weak #7) — gate them out below 90%
    full_scale = total_quads >= 0.9 * 21_000_000

    def vs(x: float) -> dict:
        return {"vs_baseline": round(x, 3)} if full_scale else {
            "vs_baseline": None,
            "smoke": f"{total_quads:,} quads < anchor scale; no baseline claim",
        }

    emit({
        "metric": "bulk_load_quads_per_sec",
        "value": round(total_quads / load_s, 1),
        "unit": "quads/s",
        **vs((total_quads / load_s) / 73_000),
        "quads": total_quads,
        "rss_gb": round(rss_gb(), 2),
    })

    # per-query fixed overhead, measured SEPARATELY: a 1-edge query's p50
    # is parse + plan + dispatch, no traversal to speak of.  Small-edge
    # metrics below carry it so their edges/s can be read for what it is
    # (VERDICT r4 weak #7: the hot-actor 3-hop mostly measured dispatch).
    tiny = '{ t(func: uid(0x1)) { name } }'
    eng.run(tiny)
    tms = []
    for _ in range(10):
        t0 = time.time()
        eng.run(tiny)
        tms.append((time.time() - t0) * 1e3)
    tms.sort()
    overhead_ms = tms[len(tms) // 2]
    emit({
        "metric": "engine21m_per_query_overhead",
        "value": round(overhead_ms, 2),
        "unit": "ms",
    })

    # the two wiki shapes.  The 3-hop seeds a MID-TAIL actor — the wiki's
    # anchor is a typical entity; with the zipf corpus a head actor is a
    # different (much heavier) workload, measured separately below.
    co_actor = """
    { me(func: eq(name, "Actor 250000")) {
        ~performance.actor { ~starring {
          name
          starring { performance.actor { name } }
        } }
    } }"""
    # head-of-zipf seed: celebrity fan-out, where the fused device chain
    # engages (its own metric, no wiki anchor to compare against)
    hot_actor = """
    { var(func: eq(name, "Actor 7")) {
        ~performance.actor { ~starring { starring { performance.actor } } }
    } }"""
    eng.run(hot_actor)  # warm
    times = []
    for _ in range(3):
        t0 = time.time()
        eng.run(hot_actor)
        times.append(time.time() - t0)
    emit({
        "metric": "engine21m_3hop_hot_actor",
        "value": round(min(times) * 1e3, 2),
        "unit": "ms",
        "edges": eng.stats["edges"],
        "fused_levels": eng.stats["chain_fused_levels"],
        "chain_reject": eng.stats["chain_reject"],
        # PR 10: the calibrated route decisions (with both cost
        # estimates) that admitted/declined this shape — the fix for the
        # r5 regression where `chain_reject: "fan-out estimate 168342
        # below threshold 262144"` kept this query off the chain scan
        "planner": eng.stats.get("planner", []),
        # traversal rate NET of fixed dispatch overhead; None when the
        # query is too small for the subtraction to mean anything
        "edges_per_sec": round(eng.stats["edges"] / min(times), 1),
        "edges_per_sec_net": (
            round(eng.stats["edges"] / (min(times) - overhead_ms / 1e3), 1)
            if min(times) > 2 * overhead_ms / 1e3
            else None
        ),
        "overhead_ms": round(overhead_ms, 2),
    })
    detail = """
    { dir(func: eq(name, "Director 11")) {
        name
        director.film (orderasc: initial_release_date) {
          name
          initial_release_date
          genre { name }
          starring { performance.actor { name } }
        }
    } }"""
    # big-fanout chain at full scale: level-0 is every director.film edge,
    # so the fused device chain (query/chain.py) engages at its default
    # threshold — THE engine-on-device number (VERDICT r2 #2)
    # var block: the full 3-level traversal executes but the multi-million
    # edge result is not JSON-encoded (no product query returns 1.6M rows;
    # the reference's own encoder runs 235-462ms at just 1-5k descendants)
    fanout = """
    { var(func: has(director.film)) {
        director.film { starring { performance.actor } }
    } }"""
    import jax

    eng.run(fanout)  # warm: arenas, LUTs, jit
    times = []
    for _ in range(3):
        t0 = time.time()
        eng.run(fanout)
        times.append(time.time() - t0)
    chain_s = min(times)
    edges = eng.stats["edges"]
    fused = eng.stats["chain_fused_levels"]
    chain_reject = eng.stats["chain_reject"]
    planner_decs = eng.stats.get("planner", [])
    # the SAME shape with the device paths disabled (chains off, per-level
    # host numpy): the measured device-vs-host comparison the round-3
    # bench only asserted
    saved_thr = eng.chain_threshold
    saved_min = eng.expand_device_min
    eng.chain_threshold = 1 << 60
    eng.expand_device_min = 1 << 62
    eng.run(fanout)  # warm the host path
    host_times = []
    for _ in range(3):
        t0 = time.time()
        eng.run(fanout)
        host_times.append(time.time() - t0)
    host_s = min(host_times)
    eng.chain_threshold = saved_thr
    eng.expand_device_min = saved_min
    emit({
        "metric": "engine21m_chain_fanout_edges_per_sec",
        "value": round(edges / chain_s, 1),
        "unit": "edges/s",
        "edges": edges,
        "fused_levels": fused,
        "chain_reject": chain_reject,
        "planner": planner_decs,
        "ms": round(chain_s * 1e3, 1),
        "host_ms": round(host_s * 1e3, 1),
        "device_vs_host": round(host_s / chain_s, 2),
        "platform": jax.devices()[0].platform,
    })

    baselines = {"3hop_coactor": 2.5, "4level_detail": 32.5}  # warm ms, i7
    for label, q in (("3hop_coactor", co_actor), ("4level_detail", detail)):
        t0 = time.time()
        out = eng.run(q)
        cold_ms = (time.time() - t0) * 1e3
        assert out, f"{label} empty"
        times = []
        for _ in range(10):
            t0 = time.time()
            eng.run(q)
            times.append((time.time() - t0) * 1e3)
        times.sort()
        p50 = times[len(times) // 2]
        emit({
            "metric": f"engine21m_{label}_warm_p50",
            "value": round(p50, 2),
            "unit": "ms",
            **vs(baselines[label] / p50),
            "cold_ms": round(cold_ms, 1),
        })
    print(f"# final rss {rss_gb():.1f}GB", flush=True)
    if os.environ.get("B21_OUT"):
        print(f"# wrote {os.environ['B21_OUT']}", flush=True)


def build_chunk(start_director: int, n_directors: int) -> str:
    """Film-graph chunk with uids disjoint from other chunks.  Re-uses
    bench_engine.build's shape but offsets every uid and entity label by
    the chunk base so chunks interconnect only through shared actor names
    (like separate loader batches, which share nothing but xids)."""
    import random

    rng = random.Random(1000 + start_director)
    lines = []
    # uid space: reserve a fixed 140-uid window per director (>= 1 dir +
    # 8 films + 48 performances) plus a global actor/genre block at the top
    ACTORS = 400_000
    GENRES = 32
    PER_DIR = 140
    base_fixed = 1 + GENRES + ACTORS

    def u(x):
        return f"<0x{x:x}>"

    def zipfish(mean: float, hi: int) -> int:
        """Bounded Pareto(α=2) integer with the given mean: realistic
        heavy-tailed degrees (a few prolific directors/ensemble films)
        instead of the uniform tiling VERDICT r2 flagged as flattering
        caps and cache behavior."""
        return max(1, min(hi, int(rng.paretovariate(2.0) * mean / 2)))

    if start_director == 0:
        for gi in range(GENRES):
            lines.append(f'{u(1 + gi)} <name> "Genre {gi}" .')
        # actor names are written lazily by the first chunk only
        for ai in range(ACTORS):
            lines.append(f'{u(1 + GENRES + ai)} <name> "Actor {ai}" .')
    for di in range(start_director, start_director + n_directors):
        cursor = base_fixed + di * PER_DIR
        d = cursor
        cursor += 1
        lines.append(f'{u(d)} <name> "Director {di}" .')
        for fi in range(zipfish(8, 15)):
            f = cursor
            cursor += 1
            lines.append(f'{u(f)} <name> "Film {di}-{fi}" .')
            y = 1960 + rng.randrange(60)
            lines.append(
                f'{u(f)} <initial_release_date> "{y}-0{1 + rng.randrange(9)}-1{rng.randrange(9)}" .'
            )
            lines.append(f"{u(d)} <director.film> {u(f)} .")
            # popular genres dominate (zipf over the genre table)
            lines.append(f"{u(f)} <genre> {u(1 + zipfish(4, GENRES) - 1)} .")
            for _ in range(zipfish(6, 8)):
                p = cursor
                cursor += 1
                # celebrity skew: a small head of actors takes most roles
                a = 1 + GENRES + int(ACTORS * (rng.random() ** 4.0))
                lines.append(f"{u(p)} <performance.actor> {u(a)} .")
                lines.append(f"{u(f)} <starring> {u(p)} .")
    return "\n".join(lines)


if __name__ == "__main__":
    main()
