"""Engine-latency benchmark: the wiki performance-page queries.

Mirrors the reference's published query latencies (BASELINE.md:
3-hop Tom-Hanks-style co-actor query 2-3ms warm / 8-9ms cold;
4-level Spielberg detail query 30-35ms warm / 87ms cold, on an i7
laptop over the Freebase 21M film graph).  Builds a synthetic film
graph at configurable scale, bulk-loads it through the real mutation
path (native scanner when available), and measures the same two query
shapes through parse → execute → JSON.

Usage: python bench_engine.py            (env: BE_DIRECTORS, BE_RUNS)
Prints one JSON line per query shape.
"""

import json
import os
import random
import time

from dgraph_tpu.models import PostingStore
from dgraph_tpu.query import QueryEngine

SCHEMA = """
    name: string @index(term, exact) .
    initial_release_date: datetime @index(year) .
    director.film: uid @reverse @count .
    genre: uid @reverse .
    starring: uid .
    performance.actor: uid @reverse .
"""


def build(n_directors: int, films_per: int = 8, actors_per_film: int = 6,
          n_actors: int | None = None, seed: int = 7) -> str:
    rng = random.Random(seed)
    n_actors = n_actors or n_directors * 3
    lines = []
    uid = 1

    def u(x):
        return f"<0x{x:x}>"

    genres = []
    for gi in range(24):
        genres.append(uid)
        lines.append(f'{u(uid)} <name> "Genre {gi}" .')
        uid += 1
    actors = []
    for ai in range(n_actors):
        actors.append(uid)
        lines.append(f'{u(uid)} <name> "Actor {ai}" .')
        uid += 1
    for di in range(n_directors):
        d = uid
        uid += 1
        lines.append(f'{u(d)} <name> "Director {di}" .')
        for fi in range(films_per):
            f = uid
            uid += 1
            lines.append(f'{u(f)} <name> "Film {di}-{fi}" .')
            y = 1960 + rng.randrange(60)
            lines.append(f'{u(f)} <initial_release_date> "{y}-0{1 + rng.randrange(9)}-1{rng.randrange(9)}" .')
            lines.append(f'{u(d)} <director.film> {u(f)} .')
            lines.append(f'{u(f)} <genre> {u(rng.choice(genres))} .')
            for _ in range(actors_per_film):
                p = uid
                uid += 1
                a = rng.choice(actors)
                lines.append(f'{u(p)} <performance.actor> {u(a)} .')
                lines.append(f'{u(f)} <starring> {u(p)} .')
    return "\n".join(lines)


def main():
    # honor JAX_PLATFORMS=cpu / probe a possibly-wedged TPU exactly like
    # bench.py (sitecustomize consumes the env var before user code)
    from bench import ensure_backend

    print("# backend: %s" % ensure_backend(), flush=True)
    n_directors = int(os.environ.get("BE_DIRECTORS", 2000))
    runs = int(os.environ.get("BE_RUNS", 20))

    st = PostingStore()
    eng = QueryEngine(st)
    t0 = time.time()
    rdf = build(n_directors)
    gen_s = time.time() - t0
    t0 = time.time()
    eng.run("mutation { schema { %s } set { %s } }" % (SCHEMA, rdf))
    load_s = time.time() - t0
    n_quads = rdf.count("\n") + 1

    # the two wiki shapes, seeded on a mid-graph entity
    co_actor = """
    { me(func: eq(name, "Actor 7")) {
        ~performance.actor { ~starring {
          name
          starring { performance.actor { name } }
        } }
    } }"""
    detail = """
    { dir(func: eq(name, "Director 11")) {
        name
        director.film (orderasc: initial_release_date) {
          name
          initial_release_date
          genre { name }
          starring { performance.actor { name } }
        }
    } }"""

    results = {}
    for label, q in (("3hop_coactor", co_actor), ("4level_detail", detail)):
        cold0 = time.time()
        out = eng.run(q)
        cold_ms = (time.time() - cold0) * 1e3
        assert out, f"{label} returned empty"
        times = []
        for _ in range(runs):
            t0 = time.time()
            eng.run(q)
            times.append((time.time() - t0) * 1e3)
        times.sort()
        results[label] = {
            "cold_ms": round(cold_ms, 2),
            "warm_p50_ms": round(times[len(times) // 2], 2),
            "warm_min_ms": round(times[0], 2),
        }

    # -- order-by at scale: device segmented rank-sort vs host sorted -------
    # (worker/sort.go analog; VERDICT r1 #5).  One fan-out node with 1M+
    # children ordered by an int value.
    n_big = int(os.environ.get("BE_ORDER_N", 1_000_000))
    import numpy as np

    from dgraph_tpu.models.store import Edge
    from dgraph_tpu.models.types import TypeID, TypedValue
    from dgraph_tpu.query.engine import QueryEngine as _QE

    st2 = PostingStore()
    st2.apply_schema("rank: int .\nbig: uid .")
    rng = np.random.default_rng(5)
    kids = np.arange(2, n_big + 2)
    st2.bulk_set_uid_edges("big", np.full(n_big, 1), kids)
    pd = st2.pred("rank")
    vals = rng.integers(0, 1 << 30, size=n_big)
    for u, v in zip(kids.tolist(), vals.tolist()):
        pd.values[(u, "")] = TypedValue(TypeID.INT, int(v))
    st2.dirty.add("rank")
    eng2 = QueryEngine(st2)
    qo = "{ q(func: uid(0x1)) { big (orderasc: rank, first: 10) { _uid_ } } }"
    eng2.run(qo)  # warm (arena + compile)
    t0 = time.time()
    dev_out = eng2.run(qo)
    dev_ms = (time.time() - t0) * 1e3
    orig = _QE._device_order_perm
    _QE._device_order_perm = lambda *a, **k: None
    try:
        t0 = time.time()
        host_out = eng2.run(qo)
        host_ms = (time.time() - t0) * 1e3
    finally:
        _QE._device_order_perm = orig
    assert dev_out == host_out, "device order != host order at 1M"
    results["orderby_1m"] = {
        "n": n_big,
        "device_ms": round(dev_ms, 1),
        "host_ms": round(host_ms, 1),
        "speedup": round(host_ms / dev_ms, 2),
    }

    # -- incremental arena refresh: mutate+query p50 on a 10M-edge pred ----
    # (VERDICT r3 item 6: delta overlay vs full rebuild, target >= 10x)
    n_inc = int(os.environ.get("BE_INC_N", 10_000_000))
    import numpy as np

    st3 = PostingStore()
    st3.apply_schema("name: string @index(exact) .\nbig: uid .")
    rng3 = np.random.default_rng(11)
    st3.bulk_set_uid_edges(
        "big", rng3.integers(1, 1_000_001, size=n_inc), rng3.integers(1, 1_000_001, size=n_inc)
    )
    from dgraph_tpu.models.store import Edge as _Edge

    eng3 = QueryEngine(st3)
    eng3.run("{ q(func: uid(0x1)) { big { _uid_ } } }")  # build the arena

    def mutate_and_query(dst_base, n_rounds=9):
        # dst_base must differ per phase: re-adding an existing edge is a
        # no-op touch that skips arena work entirely (a round-4 audit
        # caught the phases sharing dsts, so "full rebuild" measured
        # no-ops at 0.4ms)
        times = []
        for i in range(n_rounds):
            t0 = time.time()
            st3.apply(_Edge(pred="big", src=1, dst=dst_base + i))
            eng3.run("{ q(func: uid(0x1)) { big (first: 3) { _uid_ } } }")
            times.append((time.time() - t0) * 1e3)
        times.sort()
        return times[len(times) // 2]

    inc_p50 = mutate_and_query(2_000_000)
    # force the full-rebuild path for the same workload
    orig_delta_max = PostingStore.DELTA_MAX
    PostingStore.DELTA_MAX = 0
    try:
        full_p50 = mutate_and_query(2_100_000)
    finally:
        PostingStore.DELTA_MAX = orig_delta_max
    results["incremental_refresh_10m"] = {
        "edges": n_inc,
        "incremental_p50_ms": round(inc_p50, 1),
        "full_rebuild_p50_ms": round(full_p50, 1),
        "speedup": round(full_p50 / inc_p50, 2),
    }

    # -- fused-chain A/B: engine edges/s on a big fan-out chain ------------
    # (VERDICT r2 #2: an ENGINE-level device number, not just raw kernels.)
    # Same query, same engine; the knob is whether eligible uid chains
    # fuse into one device program (query/chain.py) or run per-level.
    qc = "{ q(func: has(director.film)) { director.film { starring { performance.actor { name } } } } }"
    eng.chain_threshold = 0
    eng.run(qc)  # warm: arenas, LUTs, compile
    t0 = time.time()
    fused_out = eng.run(qc)
    fused_ms = (time.time() - t0) * 1e3
    edges = eng.stats["edges"]
    fused_levels = eng.stats["chain_fused_levels"]
    eng.chain_threshold = 10**18
    eng.run(qc)  # warm the per-level path too
    t0 = time.time()
    plain_out = eng.run(qc)
    plain_ms = (time.time() - t0) * 1e3
    assert eng.stats["edges"] == edges, "paths traversed different edge counts"
    assert json.dumps(fused_out, sort_keys=True, default=str) == json.dumps(
        plain_out, sort_keys=True, default=str
    ), "fused chain != per-level results"
    import jax

    results["chain_fanout"] = {
        "edges": edges,
        "fused_levels": fused_levels,
        "fused_ms": round(fused_ms, 1),
        "per_level_ms": round(plain_ms, 1),
        "fused_edges_per_sec": round(edges / (fused_ms / 1e3), 1),
        "speedup": round(plain_ms / fused_ms, 2),
        "platform": jax.devices()[0].platform,
    }

    for label, r in results.items():
        print(json.dumps({"metric": f"engine_{label}", **r}))
    print(
        f"# graph: {n_directors} directors, {n_quads} quads "
        f"(gen {gen_s:.1f}s, load {load_s:.1f}s = {n_quads/load_s:,.0f} quads/s); "
        f"{runs} warm runs. Reference (i7, 21M graph): 3hop 2-3ms warm / "
        f"8-9ms cold; 4level 30-35ms warm / 87ms cold (BASELINE.md)."
    )


if __name__ == "__main__":
    main()
