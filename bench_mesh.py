"""Sharded-vs-local expansion throughput on the virtual 8-device mesh
(VERDICT r3 item 4: a recorded ratio at a 21M-scale predicate).

Runs on the CPU backend with xla_force_host_platform_device_count=8 —
the same harness the driver's dryrun uses — so the ratio measures the
SPMD program structure (shard_map + all_gather + device reassembly), not
chip count: 8 virtual devices share one host's cores, so the expected
win is bounded by core utilization, and the interesting numbers are
(a) sharded ≈ local (no pathological collective overhead) and (b) the
per-level host reassembly of round 2 is gone (one packed transfer).

Usage: python bench_mesh.py   (env: BM_EDGES, default 21_000_000)
"""

import os

# BM_PLATFORM=tpu runs on real hardware (a pod slice exposes its chips as
# the mesh; the ICI crossover curve in PARITY.md comes from that mode);
# default is the 8-device virtual CPU mesh for structure validation
_REAL = os.environ.get("BM_PLATFORM", "cpu") != "cpu"
if not _REAL:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()

import json
import time

import jax

if not _REAL:
    jax.config.update("jax_platforms", "cpu")

import numpy as np

from dgraph_tpu import ops
from dgraph_tpu.models.arena import csr_dense_from_edges
from dgraph_tpu.parallel.mesh import (
    make_mesh,
    shard_arena_rows,
    sharded_expand_segments,
)


def main():
    n_edges = int(os.environ.get("BM_EDGES", 21_000_000))
    n_nodes = max(1024, n_edges // 10)
    rng = np.random.default_rng(5)
    src = rng.integers(1, n_nodes + 1, size=n_edges)
    dst = rng.integers(1, n_nodes + 1, size=n_edges)
    t0 = time.time()
    a = csr_dense_from_edges(src, dst, n_nodes)
    build_s = time.time() - t0

    mesh = make_mesh(8, data=1)
    t0 = time.time()
    sa = shard_arena_rows(a.h_src, a.h_offsets, a.host_dst(), 8)
    shard_s = time.time() - t0

    frontiers = [
        np.unique(rng.integers(1, n_nodes + 1, size=4096)) for _ in range(10)
    ]
    cap = ops.bucket(
        max(
            int(a.degree_of_rows(a.rows_for_uids_host(f)).sum())
            for f in frontiers
        )
    )

    # warm both paths (compile)
    sharded_expand_segments(mesh, sa, frontiers[0], cap)
    rows0 = ops.pad_rows(a.rows_for_uids_host(frontiers[0]), ops.bucket(len(frontiers[0])))
    out, seg, _ = ops.expand_csr(a.offsets, a.dst, rows0, cap)
    np.asarray(out)

    t0 = time.time()
    edges = 0
    for f in frontiers:
        o, ptr = sharded_expand_segments(mesh, sa, f, cap)
        edges += len(o)
    sharded_s = time.time() - t0

    t0 = time.time()
    edges_l = 0
    for f in frontiers:
        rows = ops.pad_rows(a.rows_for_uids_host(f), ops.bucket(len(f)))
        out, seg, _t = ops.expand_csr(a.offsets, a.dst, rows, cap)
        seg_h = np.asarray(seg)
        edges_l += int((seg_h >= 0).sum())
    local_s = time.time() - t0

    assert edges == edges_l, (edges, edges_l)

    # crossover sweep: where does sharded beat local as expansion size
    # grows?  One point per frontier size (VERDICT r3 weak #5 asked for a
    # curve, not an anecdote).  On the virtual CPU mesh this exercises
    # structure; the ICI curve comes from running the same sweep on a pod.
    curve = []
    for n_seed in (256, 1024, 4096, 16384, 65536):
        fs = [np.unique(rng.integers(1, n_nodes + 1, size=n_seed)) for _ in range(3)]
        capn = ops.bucket(max(
            int(a.degree_of_rows(a.rows_for_uids_host(f)).sum()) for f in fs
        ))
        sharded_expand_segments(mesh, sa, fs[0], capn)  # warm
        t0 = time.time()
        for f in fs:
            sharded_expand_segments(mesh, sa, f, capn)
        sh_ms = (time.time() - t0) / len(fs) * 1e3
        rows = ops.pad_rows(a.rows_for_uids_host(fs[0]), ops.bucket(len(fs[0])))
        np.asarray(ops.expand_csr(a.offsets, a.dst, rows, capn)[0])  # warm
        t0 = time.time()
        for f in fs:
            rows = ops.pad_rows(a.rows_for_uids_host(f), ops.bucket(len(f)))
            out, seg, _t = ops.expand_csr(a.offsets, a.dst, rows, capn)
            np.asarray(seg)
        lo_ms = (time.time() - t0) / len(fs) * 1e3
        curve.append({
            "seeds": n_seed, "cap": capn,
            "sharded_ms": round(sh_ms, 1), "local_ms": round(lo_ms, 1),
            "ratio_local_over_sharded": round(lo_ms / sh_ms, 2),
        })

    # serving-plane arm (PR 17): the same expansions dispatched THROUGH
    # the MeshExecutor entry points the server actually calls
    # (dgraph_tpu/mesh/executor.py) — devguard bracket + placement +
    # attribution included — plus the fused multi-hop program whose
    # cross-chip frontier exchange runs between scan levels on the ICI,
    # A/B'd against the same hops as separate per-level dispatches.
    from dgraph_tpu.mesh.executor import MeshExecutor
    from dgraph_tpu.mesh.programs import exchange_bytes_per_hop

    class _Arenas:
        """The executor's ArenaManager surface, minimally: one already
        sharded predicate (the bench controls placement explicitly)."""

        def __init__(self, mesh, sa):
            self.mesh = mesh
            self._sa = sa

        def sharded_csr(self, attr, reverse=False):
            return self._sa

    ex = MeshExecutor(_Arenas(mesh, sa))
    stats = {}
    ex.expand("link", False, frontiers[0], cap, stats)  # warm
    t0 = time.time()
    for f in frontiers:
        ex.expand("link", False, f, cap, stats)
    exec_s = time.time() - t0

    n_hops = int(os.environ.get("BM_HOPS", 3))
    hop_cap = ops.bucket(int(os.environ.get("BM_HOP_CAP", 65536)))
    seed_f = frontiers[0][: min(len(frontiers[0]), hop_cap)]
    ex.multi_hop("link", False, seed_f, n_hops, hop_cap, stats)  # warm
    t0 = time.time()
    fs, _totals = ex.multi_hop("link", False, seed_f, n_hops, hop_cap, stats)
    fused_s = time.time() - t0
    # the ladder: the same traversal as n_hops separate sharded
    # dispatches, each frontier crossing the host between levels —
    # exactly the per-hop round trip the fused program deletes
    from dgraph_tpu.ops.sets import SENT

    t0 = time.time()
    f = seed_f
    ladder = []
    for _ in range(n_hops):
        o, _ptr = ex.expand("link", False, f, hop_cap, stats)
        f = np.unique(o)[: hop_cap]
        ladder.append(f)
    ladder_s = time.time() - t0
    # parity: the fused program's per-level frontiers match the ladder's
    for lvl in range(n_hops):
        got = np.asarray(fs[lvl])
        got = got[got != SENT]
        assert np.array_equal(got, ladder[lvl][: len(got)]), f"hop {lvl}"

    executor = {
        "expand_ms": round(exec_s / len(frontiers) * 1e3, 1),
        "n_hops": n_hops,
        "hop_cap": hop_cap,
        "fused_multi_hop_ms": round(fused_s * 1e3, 1),
        "ladder_multi_hop_ms": round(ladder_s * 1e3, 1),
        "ratio_ladder_over_fused": round(ladder_s / fused_s, 2),
        "exchange_bytes_per_hop": exchange_bytes_per_hop(mesh, hop_cap),
    }

    print(json.dumps({
        "metric": "mesh_sharded_vs_local_expand",
        "edges_per_query": edges // len(frontiers),
        "sharded_ms": round(sharded_s / len(frontiers) * 1e3, 1),
        "local_ms": round(local_s / len(frontiers) * 1e3, 1),
        "ratio_local_over_sharded": round(local_s / sharded_s, 2),
        "n_devices": 8,
        "platform": jax.devices()[0].platform + ("-mesh" if _REAL else "-virtual-mesh"),
        "build_s": round(build_s, 1),
        "shard_s": round(shard_s, 1),
        "crossover_curve": curve,
        "executor": executor,
    }))


if __name__ == "__main__":
    main()
