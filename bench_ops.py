"""Per-kernel microbenchmarks: the measured numbers behind
docs/ROOFLINE.md, reproducible in one command.

Measures, at headline-bench-like shapes (200-query batches):
  - expand_inline_grouped      (XLA slot-map)
  - expand_inline_grouped_pallas (Pallas slot-map; interpret off-TPU)
  - sort_unique dedup at the hop-2 width
  - member_mask set membership
One JSON line per kernel: {"kernel", "value", "unit", "platform"}.

Usage: python bench_ops.py    (env: BO_NODES/BO_EDGES/BO_Q scale it;
same wedged-TPU probe contract as bench.py)
"""

import json
import os
import time

import numpy as np


def main():
    from bench import ensure_backend

    platform = ensure_backend()
    import jax
    import jax.numpy as jnp

    from dgraph_tpu import ops
    from dgraph_tpu.ops.sets import SENT
    from bench import build_graph

    n_nodes = int(os.environ.get("BO_NODES", 500_000))
    n_edges = int(os.environ.get("BO_EDGES", 4_000_000))
    Q = int(os.environ.get("BO_Q", 200))
    n_seeds = 2048

    a = build_graph(n_nodes, n_edges)
    metap, ov = a.inline_layout_grouped()
    deg = (a.h_offsets[1:] - a.h_offsets[:-1]).astype(np.int64)
    rng = np.random.default_rng(7)
    fronts = []
    for _ in range(Q):
        f = np.unique(rng.integers(1, n_nodes + 1, size=n_seeds))
        key = np.asarray(ops.skey_encode(f, deg[f] > ops.INLINE))
        fronts.append(f[np.argsort(key, kind="stable")])
    fcap = ops.bucket(max(len(f) for f in fronts))
    capc = ops.bucket_fine(
        max(int(a.ov_chunk_degree_of_rows(f).sum()) for f in fronts)
    )
    pcap = ops.bucket_fine(
        max(int((deg[f] > ops.INLINE).sum()) for f in fronts)
    )
    fmat = jnp.asarray(np.stack([ops.pad_to(f, fcap) for f in fronts]))
    rows = jnp.where(fmat == SENT, -1, fmat)
    edges_total = sum(int(deg[f].sum()) for f in fronts)

    def best(fn, n=4):
        fn()  # compile
        b = float("inf")
        for _ in range(n):
            t0 = time.time()
            jax.block_until_ready(fn())
            b = min(b, time.time() - t0)
        return b

    def emit(kernel, value, unit):
        print(json.dumps({
            "kernel": kernel, "value": round(value, 1), "unit": unit,
            "platform": platform,
        }), flush=True)

    for name, expander in (
        ("expand_inline_grouped", ops.expand_inline_grouped),
        ("expand_inline_grouped_pallas", ops.expand_inline_grouped_pallas),
    ):
        run = jax.jit(jax.vmap(lambda r: expander(metap, ov, r, capc, pcap)))
        s = best(lambda: run(rows))
        emit(name, edges_total / s, "edges/s")

    wide = ops.bucket(fcap * ops.INLINE + capc * ops.CHUNK // 4)
    mat = jnp.asarray(
        rng.integers(1, n_nodes, size=(Q, wide)).astype(np.int32)
    )
    s = best(lambda: jax.jit(jax.vmap(ops.sort_unique))(mat))
    emit("sort_unique", Q * wide / s, "elems/s")

    b = jnp.asarray(
        np.sort(rng.integers(1, n_nodes, size=(Q, 4096)).astype(np.int32), axis=1)
    )
    mm = jax.jit(jax.vmap(ops.member_mask))
    s = best(lambda: mm(mat[:, :4096], b))
    emit("member_mask", Q * 4096 / s, "probes/s")


if __name__ == "__main__":
    main()
