"""Per-kernel microbenchmarks: the measured numbers behind
docs/ROOFLINE.md, reproducible in one command.

Measures, at headline-bench-like shapes (200-query batches):
  - expand_inline_grouped      (XLA slot-map)
  - expand_inline_grouped_pallas (Pallas slot-map; interpret off-TPU)
  - sort_unique dedup at the hop-2 width
  - member_mask set membership
plus the BATCHED-vs-PER-OP comparison for the fused hop executor
(ops/batch.py): for B ∈ {1, 64, 1024} and L ∈ {256, 4096}, one fused
``expand_filter_compact`` program per hop versus the per-op dispatch
sequence (expand, merge, one intersect per predicate, compact), with
DISPATCH AND COMPILE COUNTS recorded per path — the dispatch ratio is
the fusion win the headline bench banks.

One JSON line per measurement: {"kernel", "value", "unit", "platform",
...extras}.

Usage: python bench_ops.py    (env: BO_NODES/BO_EDGES/BO_Q scale it;
same wedged-TPU probe contract as bench.py)
"""

import json
import os
import time

import numpy as np


class DispatchCounter:
    """Counts device dispatches (one per jitted-callable invocation from
    the host, via ``call``) and XLA compiles (via the jax.monitoring
    backend_compile event) while active.

    jax.monitoring offers register but no unregister, so ONE module
    listener dispatches to whichever counter is currently active —
    entering N counters over a run must not accumulate N live closures.
    """

    _active = None
    _listener_installed = False

    def __init__(self):
        self.dispatches = 0
        self.compiles = 0

    @classmethod
    def _install_listener(cls):
        if cls._listener_installed:
            return
        import jax

        def on_event(event, duration, **kw):
            c = cls._active
            if c is not None and event.endswith("backend_compile_duration"):
                c.compiles += 1

        jax.monitoring.register_event_duration_secs_listener(on_event)
        cls._listener_installed = True

    def __enter__(self):
        type(self)._install_listener()
        type(self)._active = self
        return self

    def __exit__(self, *exc):
        type(self)._active = None
        return False

    def call(self, fn, *args, **kw):
        """Invoke a jitted callable, counting it as ONE device dispatch."""
        self.dispatches += 1
        return fn(*args, **kw)


def bench_batched_vs_per_op(platform, emit):
    """The fused-hop dispatch-count comparison: a hop with K filter
    predicates as ONE fused program vs the per-op dispatch sequence the
    pre-fusion engine issued."""
    import jax
    import jax.numpy as jnp

    from dgraph_tpu import ops
    from bench import build_graph

    n_nodes = int(os.environ.get("BO_NODES2", 100_000))
    n_edges = int(os.environ.get("BO_EDGES2", 800_000))
    a = build_graph(n_nodes, n_edges)
    rng = np.random.default_rng(11)
    K = 4  # filter predicates per hop

    merge_op = ops.sort_unique_batch
    intersect_op = ops.intersect_batch
    compact_op = jax.jit(jax.vmap(ops.compact))

    keep_np = [
        np.unique(rng.integers(1, n_nodes + 1, size=n_nodes // 8))
        for _ in range(K)
    ]
    keeps = tuple(
        jnp.asarray(ops.pad_to(k, ops.bucket(len(k)))) for k in keep_np
    )

    for B in (1, 64, 1024):
        for L in (256, 4096):
            seeds = [
                np.unique(rng.integers(1, n_nodes + 1, size=max(4, L // 8)))
                for _ in range(B)
            ]
            cap = ops.bucket(
                max(int(a.degree_of_rows(s).sum()) for s in seeds)
            )
            rows = jnp.asarray(np.stack([ops.pad_rows(s, L) for s in seeds]))
            # per-op building block: its own jitted dispatch per call
            expand_op = jax.jit(jax.vmap(
                lambda r: ops.expand_ascending(a.offsets, a.dst, r, cap)[0]
            ))
            keeps_b = tuple(
                jnp.broadcast_to(k, (B,) + k.shape) for k in keeps
            )

            # fused: ONE program for the whole hop
            with DispatchCounter() as cf:
                r = cf.call(
                    ops.expand_filter_compact_batch,
                    a.offsets, a.dst, rows, cap, keeps,
                )
                jax.block_until_ready(r)
                compiles = cf.compiles
                t0 = time.time()
                r = cf.call(
                    ops.expand_filter_compact_batch,
                    a.offsets, a.dst, rows, cap, keeps,
                )
                jax.block_until_ready(r)
                fused_s = time.time() - t0

            # per-op: expand, merge, K intersects, compact — one
            # dispatch each (the engine's pre-fusion shape)
            def per_op(counter):
                out = counter.call(expand_op, rows)
                u = counter.call(merge_op, out)
                for k in keeps_b:
                    u = counter.call(intersect_op, u, k)
                return counter.call(compact_op, u)

            with DispatchCounter() as cp:
                jax.block_until_ready(per_op(cp))
                n0 = cp.dispatches
                t0 = time.time()
                jax.block_until_ready(per_op(cp))
                per_op_s = time.time() - t0
                per_dispatches = cp.dispatches - n0

            emit("fused_hop_vs_per_op", per_op_s / fused_s, "x speedup", {
                "B": B, "L": L, "predicates": K,
                "fused_dispatches_per_hop": 1,
                "per_op_dispatches_per_hop": per_dispatches,
                "dispatch_ratio": float(per_dispatches),
                "fused_compiles": compiles,
                "fused_s": round(fused_s, 4),
                "per_op_s": round(per_op_s, 4),
            })


def main():
    from bench import ensure_backend

    platform = ensure_backend()
    import jax
    import jax.numpy as jnp

    from dgraph_tpu import ops
    from dgraph_tpu.ops.sets import SENT
    from bench import build_graph

    def emit(kernel, value, unit, extra=None):
        rec = {
            "kernel": kernel, "value": round(value, 1), "unit": unit,
            "platform": platform,
        }
        if extra:
            rec.update(extra)
        print(json.dumps(rec), flush=True)

    bench_batched_vs_per_op(platform, emit)

    n_nodes = int(os.environ.get("BO_NODES", 500_000))
    n_edges = int(os.environ.get("BO_EDGES", 4_000_000))
    Q = int(os.environ.get("BO_Q", 200))
    n_seeds = 2048

    a = build_graph(n_nodes, n_edges)
    metap, ov = a.inline_layout_grouped()
    deg = (a.h_offsets[1:] - a.h_offsets[:-1]).astype(np.int64)
    rng = np.random.default_rng(7)
    fronts = []
    for _ in range(Q):
        f = np.unique(rng.integers(1, n_nodes + 1, size=n_seeds))
        key = np.asarray(ops.skey_encode(f, deg[f] > ops.INLINE))
        fronts.append(f[np.argsort(key, kind="stable")])
    fcap = ops.bucket(max(len(f) for f in fronts))
    capc = ops.bucket_fine(
        max(int(a.ov_chunk_degree_of_rows(f).sum()) for f in fronts)
    )
    pcap = ops.bucket_fine(
        max(int((deg[f] > ops.INLINE).sum()) for f in fronts)
    )
    fmat = jnp.asarray(np.stack([ops.pad_to(f, fcap) for f in fronts]))
    rows = jnp.where(fmat == SENT, -1, fmat)
    edges_total = sum(int(deg[f].sum()) for f in fronts)

    def best(fn, n=4):
        fn()  # compile
        b = float("inf")
        for _ in range(n):
            t0 = time.time()
            jax.block_until_ready(fn())
            b = min(b, time.time() - t0)
        return b

    for name, expander in (
        ("expand_inline_grouped", ops.expand_inline_grouped),
        ("expand_inline_grouped_pallas", ops.expand_inline_grouped_pallas),
    ):
        run = jax.jit(jax.vmap(lambda r: expander(metap, ov, r, capc, pcap)))
        s = best(lambda: run(rows))
        emit(name, edges_total / s, "edges/s")

    wide = ops.bucket(fcap * ops.INLINE + capc * ops.CHUNK // 4)
    mat = jnp.asarray(
        rng.integers(1, n_nodes, size=(Q, wide)).astype(np.int32)
    )
    s = best(lambda: jax.jit(jax.vmap(ops.sort_unique))(mat))
    emit("sort_unique", Q * wide / s, "elems/s")

    b = jnp.asarray(
        np.sort(rng.integers(1, n_nodes, size=(Q, 4096)).astype(np.int32), axis=1)
    )
    mm = jax.jit(jax.vmap(ops.member_mask))
    s = best(lambda: mm(mat[:, :4096], b))
    emit("member_mask", Q * 4096 / s, "probes/s")


if __name__ == "__main__":
    main()
