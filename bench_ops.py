"""Per-kernel microbenchmarks: the measured numbers behind
docs/ROOFLINE.md, reproducible in one command.

Measures, at headline-bench-like shapes (200-query batches):
  - expand_inline_grouped      (XLA slot-map)
  - expand_inline_grouped_pallas (Pallas slot-map; interpret off-TPU)
  - sort_unique dedup at the hop-2 width
  - member_mask set membership
plus the BATCHED-vs-PER-OP comparison for the fused hop executor
(ops/batch.py): for B ∈ {1, 64, 1024} and L ∈ {256, 4096}, one fused
``expand_filter_compact`` program per hop versus the per-op dispatch
sequence (expand, merge, one intersect per predicate, compact), with
DISPATCH AND COMPILE COUNTS recorded per path — the dispatch ratio is
the fusion win the headline bench banks.

PR 16 adds the resident-tier A/B: the Pallas segment-gather over an
HBM-pinned ResidentArena vs expand_csr staged and vs expand_csr paying
the post-mutation re-staging tax, plus intersect_pallas vs
intersect_many at k ∈ {2,4,8} (env: BO_RES_NODES/BO_RES_EDGES/
BO_RES_FRONTIER/BO_RES_SETLEN).  Off-TPU the Pallas arms run in
interpret mode and emit mode=interpret / perf_claim=false — those rows
prove the harness and the dispatch discipline, not a speedup.

One JSON line per measurement: {"kernel", "value", "unit", "platform",
...extras}.

Usage: python bench_ops.py    (env: BO_NODES/BO_EDGES/BO_Q scale it;
same wedged-TPU probe contract as bench.py)
"""

import json
import os
import time

import numpy as np


class DispatchCounter:
    """Counts device dispatches (one per jitted-callable invocation from
    the host, via ``call``) and XLA compiles (via the jax.monitoring
    backend_compile event) while active.

    jax.monitoring offers register but no unregister, so ONE module
    listener dispatches to whichever counter is currently active —
    entering N counters over a run must not accumulate N live closures.
    """

    _active = None
    _listener_installed = False

    def __init__(self):
        self.dispatches = 0
        self.compiles = 0

    @classmethod
    def _install_listener(cls):
        if cls._listener_installed:
            return
        import jax

        def on_event(event, duration, **kw):
            c = cls._active
            if c is not None and event.endswith("backend_compile_duration"):
                c.compiles += 1

        jax.monitoring.register_event_duration_secs_listener(on_event)
        cls._listener_installed = True

    def __enter__(self):
        type(self)._install_listener()
        type(self)._active = self
        return self

    def __exit__(self, *exc):
        type(self)._active = None
        return False

    def call(self, fn, *args, **kw):
        """Invoke a jitted callable, counting it as ONE device dispatch."""
        self.dispatches += 1
        return fn(*args, **kw)


def bench_batched_vs_per_op(platform, emit):
    """The fused-hop dispatch-count comparison: a hop with K filter
    predicates as ONE fused program vs the per-op dispatch sequence the
    pre-fusion engine issued."""
    import jax
    import jax.numpy as jnp

    from dgraph_tpu import ops
    from bench import build_graph

    n_nodes = int(os.environ.get("BO_NODES2", 100_000))
    n_edges = int(os.environ.get("BO_EDGES2", 800_000))
    a = build_graph(n_nodes, n_edges)
    rng = np.random.default_rng(11)
    K = 4  # filter predicates per hop

    merge_op = ops.sort_unique_batch
    intersect_op = ops.intersect_batch
    compact_op = jax.jit(jax.vmap(ops.compact))

    keep_np = [
        np.unique(rng.integers(1, n_nodes + 1, size=n_nodes // 8))
        for _ in range(K)
    ]
    keeps = tuple(
        jnp.asarray(ops.pad_to(k, ops.bucket(len(k)))) for k in keep_np
    )

    for B in (1, 64, 1024):
        for L in (256, 4096):
            seeds = [
                np.unique(rng.integers(1, n_nodes + 1, size=max(4, L // 8)))
                for _ in range(B)
            ]
            cap = ops.bucket(
                max(int(a.degree_of_rows(s).sum()) for s in seeds)
            )
            rows = jnp.asarray(np.stack([ops.pad_rows(s, L) for s in seeds]))
            # per-op building block: its own jitted dispatch per call
            expand_op = jax.jit(jax.vmap(
                lambda r: ops.expand_ascending(a.offsets, a.dst, r, cap)[0]
            ))
            keeps_b = tuple(
                jnp.broadcast_to(k, (B,) + k.shape) for k in keeps
            )

            # fused: ONE program for the whole hop
            with DispatchCounter() as cf:
                r = cf.call(
                    ops.expand_filter_compact_batch,
                    a.offsets, a.dst, rows, cap, keeps,
                )
                jax.block_until_ready(r)
                compiles = cf.compiles
                t0 = time.time()
                r = cf.call(
                    ops.expand_filter_compact_batch,
                    a.offsets, a.dst, rows, cap, keeps,
                )
                jax.block_until_ready(r)
                fused_s = time.time() - t0

            # per-op: expand, merge, K intersects, compact — one
            # dispatch each (the engine's pre-fusion shape)
            def per_op(counter):
                out = counter.call(expand_op, rows)
                u = counter.call(merge_op, out)
                for k in keeps_b:
                    u = counter.call(intersect_op, u, k)
                return counter.call(compact_op, u)

            with DispatchCounter() as cp:
                jax.block_until_ready(per_op(cp))
                n0 = cp.dispatches
                t0 = time.time()
                jax.block_until_ready(per_op(cp))
                per_op_s = time.time() - t0
                per_dispatches = cp.dispatches - n0

            emit("fused_hop_vs_per_op", per_op_s / fused_s, "x speedup", {
                "B": B, "L": L, "predicates": K,
                "fused_dispatches_per_hop": 1,
                "per_op_dispatches_per_hop": per_dispatches,
                "dispatch_ratio": float(per_dispatches),
                "fused_compiles": compiles,
                "fused_s": round(fused_s, 4),
                "per_op_s": round(per_op_s, 4),
            })


def bench_kway_intersection(platform, emit):
    """MXU join tier, k-way grid: for B ∈ {1, 64, 1024} and
    k ∈ {2, 4, 8}, ONE intersect_stack_batch program versus the per-op
    pairwise fold (k-1 intersect_batch dispatches).  Checksum parity
    against the set-op reference is ASSERTED in the bench; the dispatch
    count per k-way intersection drops to O(1)."""
    import jax
    import jax.numpy as jnp

    from dgraph_tpu import ops

    # dense-ish sets (filter predicates over a shared hot neighborhood):
    # the k≥4 rows are where the single-program tier wins; k=2 is the
    # honesty row — "pairwise" IS one op there, so the fused kernel has
    # nothing to fuse and the ratio hovers around 1.
    rng = np.random.default_rng(13)
    n = int(os.environ.get("BO_KWAY_UNIVERSE", 1200))
    L = int(os.environ.get("BO_KWAY_L", 1024))

    # satellite guard: the k-way folds no longer serialize.  The
    # scan-free property is a registered program contract now —
    # the bench just invokes the single source of truth instead of
    # hand-grepping jaxprs (analysis/programs.py, trace-only checks).
    from dgraph_tpu.analysis import programs

    programs.assert_contract("sets.intersect_many")
    programs.assert_contract("sets.union_many")

    for B in (1, 64, 1024):
        for k in (2, 4, 8):
            sets = [
                [
                    np.unique(rng.integers(1, n, size=L - L // 4))
                    for _ in range(k)
                ]
                for _ in range(B)
            ]
            mat = np.stack(
                [
                    np.stack([ops.pad_to(s, L) for s in row])
                    for row in sets
                ]
            )
            dmat = jnp.asarray(mat)
            rows2d = [jnp.asarray(mat[:, i]) for i in range(k)]

            with DispatchCounter() as cf:
                r = cf.call(ops.intersect_stack_batch, dmat)
                jax.block_until_ready(r)
                compiles = cf.compiles
                fused_s = float("inf")
                for _ in range(3):
                    t0 = time.time()
                    r = cf.call(ops.intersect_stack_batch, dmat)
                    jax.block_until_ready(r)
                    fused_s = min(fused_s, time.time() - t0)
            got = np.asarray(r)

            def per_op(counter):
                u = rows2d[0]
                for i in range(1, k):
                    u = counter.call(ops.intersect_batch, u, rows2d[i])
                return u

            with DispatchCounter() as cp:
                ref_out = per_op(cp)
                jax.block_until_ready(ref_out)
                n0 = cp.dispatches
                per_op_s = float("inf")
                for _ in range(3):
                    t0 = time.time()
                    ref_out = per_op(cp)
                    jax.block_until_ready(ref_out)
                    per_op_s = min(per_op_s, time.time() - t0)
                per_dispatches = (cp.dispatches - n0) // 3
            ref_np = np.asarray(ref_out)

            # checksum parity vs the set-op reference, asserted here
            SENT = ops.SENT
            chk_f = np.where(got == SENT, 0, got).sum(dtype=np.int64)
            chk_p = np.where(ref_np == SENT, 0, ref_np).sum(dtype=np.int64)
            assert chk_f == chk_p, (chk_f, chk_p)
            for b in range(B):
                np.testing.assert_array_equal(
                    got[b][got[b] != SENT], ref_np[b][ref_np[b] != SENT]
                )
            assert per_dispatches == k - 1

            emit("kway_intersect_spgemm_vs_per_op", per_op_s / fused_s,
                 "x speedup", {
                     "B": B, "k": k,
                     "spgemm_dispatches": 1,
                     "per_op_dispatches": per_dispatches,
                     "spgemm_compiles": compiles,
                     "checksum": int(chk_f),
                     "parity": "ok",
                     "spgemm_s": round(fused_s, 4),
                     "per_op_s": round(per_op_s, 4),
                 })


def bench_triangle(platform, emit):
    """MXU join tier, fused triangle kernel: two legs + closing-predicate
    tiles in ONE program vs the per-op gather pipeline (expand, dedup,
    expand, dedup, reverse expand, dedup, intersect = 7 dispatches),
    over B ∈ {1, 64, 1024} root sets.  Set parity asserted per row."""
    import jax
    import jax.numpy as jnp

    from dgraph_tpu import ops
    from dgraph_tpu.ops import spgemm
    from dgraph_tpu.query.chain import _topm_deg_sum
    from bench import build_graph

    # DENSE community-shaped subgraph — the worst-case-optimal join's
    # design point (EmptyHeaded's triangle wins are on dense cyclic
    # neighborhoods): every materialized tile lane is useful, while the
    # gather pipeline pays sort width proportional to the fan-out
    # explosion.  Sparse shapes route pairwise via the joinplan cost
    # model — that asymmetry is WHY the route choice exists.
    n_nodes = int(os.environ.get("BO_TRI_NODES", 512))
    n_edges = int(os.environ.get("BO_TRI_EDGES", 32768))
    R = int(os.environ.get("BO_TRI_ROOTS", 48))
    a = build_graph(n_nodes, n_edges, seed=5)
    rev = build_graph(n_nodes, n_edges, seed=6)  # closing pred (reverse)
    t = spgemm.tile_size()
    pt = spgemm.build_tiles(a.h_src, a.h_offsets, a.host_dst(), t=t)
    pr = spgemm.build_tiles(rev.h_src, rev.h_offsets, rev.host_dst(), t=t)
    assert pt is not None and pr is not None
    uni = max(pt.universe, pr.universe)
    m = spgemm.mask_lanes(uni, t)
    rng = np.random.default_rng(17)
    SENT = ops.SENT

    for B in (1, 64, 1024):
        roots = [
            np.unique(rng.integers(1, n_nodes, size=R)) for _ in range(B)
        ]
        Lr = ops.bucket(max(len(r) for r in roots))
        rmat = np.stack([ops.pad_to(r, Lr) for r in roots])
        drmat = jnp.asarray(rmat)
        # masks for the fused path (built once per query in the engine)
        xm = np.zeros((B, m), dtype=np.float32)
        for i, r in enumerate(roots):
            xm[i, r] = 1.0
        dxm = jnp.asarray(xm)

        cap1 = ops.bucket(
            max(int(a.degree_of_rows(r).sum()) for r in roots)
        )
        capw = ops.bucket(
            max(int(rev.degree_of_rows(r).sum()) for r in roots)
        )
        cap2 = ops.bucket(_topm_deg_sum(a, min(cap1, a.n_distinct_dst())))

        # dense arenas: uid == row, but SENT pads must become the -1
        # skip marker (frontier_rows) before entering the slot map
        ex1 = jax.jit(jax.vmap(
            lambda r: ops.expand_ascending(
                a.offsets, a.dst, ops.frontier_rows(r), cap1
            )[0]
        ))
        ex2 = jax.jit(jax.vmap(
            lambda r: ops.expand_ascending(
                a.offsets, a.dst, ops.frontier_rows(r), cap2
            )[0]
        ))
        exw = jax.jit(jax.vmap(
            lambda r: ops.expand_ascending(
                rev.offsets, rev.dst, ops.frontier_rows(r), capw
            )[0]
        ))
        dedup = ops.sort_unique_batch

        def per_op(counter):
            l1 = counter.call(dedup, counter.call(ex1, drmat))
            l2 = counter.call(dedup, counter.call(ex2, l1))
            w = counter.call(dedup, counter.call(exw, drmat))
            return counter.call(ops.intersect_batch, l2, w)

        with DispatchCounter() as cp:
            ref_out = per_op(cp)
            jax.block_until_ready(ref_out)
            n0 = cp.dispatches
            per_op_s = float("inf")
            for _ in range(3):
                t0 = time.time()
                ref_out = per_op(cp)
                jax.block_until_ready(ref_out)
                per_op_s = min(per_op_s, time.time() - t0)
            per_dispatches = (cp.dispatches - n0) // 3
        ref_np = np.asarray(ref_out)

        with DispatchCounter() as cf:
            z = cf.call(
                spgemm.triangle_mask_batch,
                pt.bi, pt.bj, pt.tiles, pt.bi, pt.bj, pt.tiles,
                pr.bi, pr.bj, pr.tiles, dxm,
            )
            jax.block_until_ready(z)
            compiles = cf.compiles
            fused_s = float("inf")
            for _ in range(3):
                t0 = time.time()
                z = cf.call(
                    spgemm.triangle_mask_batch,
                    pt.bi, pt.bj, pt.tiles, pt.bi, pt.bj, pt.tiles,
                    pr.bi, pr.bj, pr.tiles, dxm,
                )
                jax.block_until_ready(z)
                fused_s = min(fused_s, time.time() - t0)
        zm = np.asarray(z)

        # parity: fused closing masks == the set-op reference pipeline
        chk = 0
        for b in range(B):
            want = ref_np[b][ref_np[b] != SENT].astype(np.int64)
            got = np.flatnonzero(zm[b] > 0).astype(np.int64)
            np.testing.assert_array_equal(got, np.unique(want))
            chk += int(got.sum())

        emit("triangle_spgemm_vs_per_op", per_op_s / fused_s, "x speedup", {
            "B": B, "roots": R,
            "spgemm_dispatches": 1,
            "per_op_dispatches": per_dispatches,
            "spgemm_compiles": compiles,
            "tiles": int(pt.n_tiles + pr.n_tiles),
            "checksum": chk,
            "parity": "ok",
            "spgemm_s": round(fused_s, 4),
            "per_op_s": round(per_op_s, 4),
        })


def bench_resident_tier(platform, emit):
    """Resident Pallas tier vs the staged XLA route (PR 16): the
    segment-gather over a ResidentArena pinned in HBM against (a)
    expand_csr on already-staged tensors and (b) expand_csr paying the
    re-staging tax the resident tier deletes (device_put of the CSR
    before the hop — what the staged engine does after every mutation);
    plus the k-way intersect kernel vs intersect_many.  Dispatch and
    compile counts per arm, warm-path timed.

    Honest per-backend note: off-TPU the Pallas kernels run in
    INTERPRET mode — correctness speed, not a perf claim (the emitted
    rows carry mode=interpret so nobody graphs them as one).  The
    numbers that matter come from this same harness on the TPU arm
    (Mosaic lowering is the next chip session's measure-first task);
    the dispatch/compile discipline pins hold on any backend."""
    import jax
    import jax.numpy as jnp

    from dgraph_tpu import ops
    from dgraph_tpu.models.arena import ResidentArena
    from bench import build_graph

    n_nodes = int(os.environ.get("BO_RES_NODES", 200_000))
    n_edges = int(os.environ.get("BO_RES_EDGES", 1_500_000))
    nf = int(os.environ.get("BO_RES_FRONTIER", 2048))
    interp = platform != "tpu"
    note = {"mode": "interpret" if interp else "mosaic",
            "perf_claim": not interp}

    a = build_graph(n_nodes, n_edges)
    ra = ResidentArena.seed(a.h_offsets, a.host_dst(), a.n_rows, a.n_edges)
    rng = np.random.default_rng(13)
    f = np.unique(rng.integers(0, a.n_rows, size=nf)).astype(np.int64)
    rows = jax.device_put(
        np.asarray(ops.pad_rows(f, ops.bucket(len(f))), np.int32)
    )
    deg = (a.h_offsets[1:] - a.h_offsets[:-1]).astype(np.int64)
    total = int(deg[f].sum())
    cap = ops.bucket(total)
    off32 = np.ascontiguousarray(a.h_offsets, dtype=np.int32)
    dst32 = np.ascontiguousarray(a.host_dst(), dtype=np.int32)
    off_dev = jax.device_put(off32)
    dst_dev = jax.device_put(dst32)

    def timed(counter, fn):
        r = fn(counter)  # warm: compile + stage constants
        jax.block_until_ready(r)
        compiles, n0 = counter.compiles, counter.dispatches
        t0 = time.time()
        jax.block_until_ready(fn(counter))
        return time.time() - t0, compiles, counter.dispatches - n0

    with DispatchCounter() as c:
        s, compiles, disp = timed(c, lambda c: c.call(
            ops.gather_pallas_packed, ra.off, ra.dst, rows, cap,
            interpret=interp,
        ))
    emit("gather_resident_pallas", total / s, "edges/s", {
        **note, "frontier": len(f), "cap": cap,
        "dispatches_per_hop": disp, "compiles": compiles,
        "h2d_bytes_per_hop": int(rows.nbytes),
    })

    with DispatchCounter() as c:
        s, compiles, disp = timed(c, lambda c: c.call(
            ops.expand_csr, off_dev, dst_dev, rows, cap
        ))
    emit("gather_staged_xla", total / s, "edges/s", {
        "frontier": len(f), "cap": cap,
        "dispatches_per_hop": disp, "compiles": compiles,
        "h2d_bytes_per_hop": int(rows.nbytes),
    })

    def restaged(counter):
        # the post-mutation hop of the staged route: the CSR crosses
        # host->device again before the gather can run
        o = jax.device_put(off32)
        d = jax.device_put(dst32)
        return counter.call(ops.expand_csr, o, d, rows, cap)

    with DispatchCounter() as c:
        s, compiles, disp = timed(c, restaged)
    emit("gather_staged_xla_restaged", total / s, "edges/s", {
        "frontier": len(f), "cap": cap,
        "dispatches_per_hop": disp, "compiles": compiles,
        "h2d_bytes_per_hop": int(rows.nbytes + off32.nbytes + dst32.nbytes),
    })

    # k-way intersect: the kernel vs the XLA merge tree
    L = int(os.environ.get("BO_RES_SETLEN", 8192))
    for k in (2, 4, 8):
        setsk = [
            np.unique(rng.integers(0, L * 4, size=L * 3 // 4)).astype(
                np.int32
            )
            for _ in range(k)
        ]
        mat = jnp.asarray(np.stack([
            np.asarray(ops.pad_to(s_, L)) for s_ in setsk
        ]))
        with DispatchCounter() as c:
            s, compiles, disp = timed(c, lambda c, m=mat: c.call(
                ops.intersect_pallas, m, interpret=interp
            ))
        emit("intersect_pallas", k * L / s, "elems/s", {
            **note, "k": k, "L": L,
            "dispatches": disp, "compiles": compiles,
        })
        with DispatchCounter() as c:
            s, compiles, disp = timed(c, lambda c, m=mat: c.call(
                ops.intersect_many, m
            ))
        emit("intersect_many_xla", k * L / s, "elems/s", {
            "k": k, "L": L, "dispatches": disp, "compiles": compiles,
        })


def main():
    from bench import ensure_backend

    platform = ensure_backend()
    import jax
    import jax.numpy as jnp

    from dgraph_tpu import ops
    from dgraph_tpu.ops.sets import SENT
    from bench import build_graph

    def emit(kernel, value, unit, extra=None):
        rec = {
            "kernel": kernel, "value": round(value, 1), "unit": unit,
            "platform": platform,
        }
        if extra:
            rec.update(extra)
        print(json.dumps(rec), flush=True)

    bench_batched_vs_per_op(platform, emit)
    bench_kway_intersection(platform, emit)
    bench_triangle(platform, emit)
    bench_resident_tier(platform, emit)

    n_nodes = int(os.environ.get("BO_NODES", 500_000))
    n_edges = int(os.environ.get("BO_EDGES", 4_000_000))
    Q = int(os.environ.get("BO_Q", 200))
    n_seeds = 2048

    a = build_graph(n_nodes, n_edges)
    metap, ov = a.inline_layout_grouped()
    deg = (a.h_offsets[1:] - a.h_offsets[:-1]).astype(np.int64)
    rng = np.random.default_rng(7)
    fronts = []
    for _ in range(Q):
        f = np.unique(rng.integers(1, n_nodes + 1, size=n_seeds))
        key = np.asarray(ops.skey_encode(f, deg[f] > ops.INLINE))
        fronts.append(f[np.argsort(key, kind="stable")])
    fcap = ops.bucket(max(len(f) for f in fronts))
    capc = ops.bucket_fine(
        max(int(a.ov_chunk_degree_of_rows(f).sum()) for f in fronts)
    )
    pcap = ops.bucket_fine(
        max(int((deg[f] > ops.INLINE).sum()) for f in fronts)
    )
    fmat = jnp.asarray(np.stack([ops.pad_to(f, fcap) for f in fronts]))
    rows = jnp.where(fmat == SENT, -1, fmat)
    edges_total = sum(int(deg[f].sum()) for f in fronts)

    def best(fn, n=4):
        fn()  # compile
        b = float("inf")
        for _ in range(n):
            t0 = time.time()
            jax.block_until_ready(fn())
            b = min(b, time.time() - t0)
        return b

    for name, expander in (
        ("expand_inline_grouped", ops.expand_inline_grouped),
        ("expand_inline_grouped_pallas", ops.expand_inline_grouped_pallas),
    ):
        run = jax.jit(jax.vmap(lambda r: expander(metap, ov, r, capc, pcap)))
        s = best(lambda: run(rows))
        emit(name, edges_total / s, "edges/s")

    wide = ops.bucket(fcap * ops.INLINE + capc * ops.CHUNK // 4)
    mat = jnp.asarray(
        rng.integers(1, n_nodes, size=(Q, wide)).astype(np.int32)
    )
    s = best(lambda: jax.jit(jax.vmap(ops.sort_unique))(mat))
    emit("sort_unique", Q * wide / s, "elems/s")

    b = jnp.asarray(
        np.sort(rng.integers(1, n_nodes, size=(Q, 4096)).astype(np.int32), axis=1)
    )
    mm = jax.jit(jax.vmap(ops.member_mask))
    s = best(lambda: mm(mat[:, :4096], b))
    emit("member_mask", Q * 4096 / s, "probes/s")


if __name__ == "__main__":
    main()
