#!/usr/bin/env python
"""Open-loop SLO harness: latency-vs-offered-load until saturation.

Every serving number before this harness was CLOSED-loop: N client
threads firing as fast as responses return, which self-throttles
exactly when the server slows down — the measured "QPS" is then a
property of the feedback loop, not of the service, and the tail
latency hides coordinated omission.  This harness is OPEN-loop
(Banyan's serving-quality argument, PAPERS.md; the wrk2 discipline):

- arrivals are a **Poisson process at a swept offered rate** — the
  whole schedule is drawn up front from a seeded RNG, so a run is
  reproducible and the server's slowness cannot postpone the next
  arrival;
- every request's latency is measured **from its scheduled arrival
  time**, so a sender that fell behind charges the wait to the server
  (no coordinated omission);
- each offered-rate step reports **per-class p50/p99/p999 and the shed
  rate** (HTTP 429/504 are outcomes, not errors), and the sweep stops
  once the server is saturated;
- the output is one SLO-curve JSON **keyed by backend**, with a
  detected **saturation knee** — the number every future perf PR (mesh
  serving, Pallas tier) is judged against.

Workload: a mixed production shape — point reads, 2-hop traversals and
a mutation interleave — not a single query family.  Two ROADMAP
follow-ups fold in as arms of the same harness:

- **qos**: the PR-11 antagonist/victim A/B re-measured open-loop —
  victim p999 vs the antagonist's offered load, QoS on vs off —
  replacing the closed-loop ratio;
- **ivm**: the PR-12 write-rate sweep re-measured open-loop — achieved
  QPS and p99 at a FIXED offered read load while the write rate sweeps.

Knobs (env, all sized for the 2-core CI host by default):
  SLO_RATES          offered-load sweep, qps CSV (default "25,50,100,200,400")
  SLO_STEP_SECONDS   seconds per step (4)
  SLO_NODES/SLO_DEG  store size (20000 / 16)
  SLO_WORKERS        sender threads = max in-flight (32)
  SLO_MIX            class weights "point=0.45,khop=0.45,mutation=0.1"
  SLO_CACHE          result/hop cache during the main sweep (1)
  SLO_SAT_STOP       stop the sweep past this shed rate (0.5)
  SLO_QOS / SLO_IVM  run the arms (1 / 1)
  SLO_QOS_RATES      antagonist offered-load sweep ("50,200")
  SLO_VICTIM_RATE    victim offered load, qps (10)
  SLO_IVM_RATE       fixed read load for the ivm arm (50)
  SLO_IVM_WRITE_RATES  write-rate sweep, writes/s CSV ("0,10,25")
  SLO_SEG            run the segmented-execution arm (1)
  SLO_SEG_VICTIM_RATE / SLO_SEG_ANTAG_RATE  seg-arm offered loads (10 / 8)
  SLO_SEG_DELAY_MS   injected per-dispatch device time for the seg arm (80)
  SLO_SEED           RNG seed (7)
  SLO_OUT            also write the JSON to this path
  --backend mesh     (or SLO_BACKEND=mesh) force the mesh serving plane
                     in every server arm (DGRAPH_TPU_MESH=force, all
                     predicates shard-eligible); the JSON's backend key
                     becomes "<backend>-mesh"
  SLO_SMOKE          arm the CI smoke assertions (monotone shed rate,
                     well-formed JSON) — see .github/workflows/ci.yml
"""

from __future__ import annotations

import http.client
import json
import os
import sys
import threading
import time

import numpy as np

from bench import _serving_store, ensure_backend


# ---------------------------------------------------------------- backend

def _backend_arg() -> str:
    """``--backend mesh`` (or SLO_BACKEND=mesh): run every server arm
    with the mesh serving plane forced on (DGRAPH_TPU_MESH=force, every
    predicate shard-eligible), so the SLO curve measures serving over
    the whole mesh — the output JSON is keyed by backend, so mesh and
    unsharded curves from the same host are directly comparable."""
    if "--backend" in sys.argv:
        which = sys.argv[sys.argv.index("--backend") + 1]
    else:
        which = os.environ.get("SLO_BACKEND", "default")
    if which not in ("default", "mesh"):
        raise SystemExit(f"unknown --backend {which!r} (default | mesh)")
    return which


def _backend_env() -> dict:
    """Extra env pinned into every _ServerArm regime for the selected
    backend (empty = the unsharded default)."""
    if _backend_arg() == "mesh":
        return {
            "DGRAPH_TPU_MESH": "force",
            "DGRAPH_TPU_MESH_SHARD_ROWS": "1",
        }
    return {}


# ---------------------------------------------------------------- helpers

def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_rates(name: str, default: str):
    return [
        float(x) for x in os.environ.get(name, default).split(",")
        if x.strip()
    ]


def pctile(lats, q: float) -> float:
    """Latency percentile in ms over a list of seconds (empty → 0)."""
    if not lats:
        return 0.0
    a = np.sort(np.asarray(lats))
    return float(a[min(len(a) - 1, int(q * (len(a) - 1) + 0.5))]) * 1e3


def latency_summary(lats) -> dict:
    return {
        "n": len(lats),
        "p50_ms": round(pctile(lats, 0.50), 2),
        "p99_ms": round(pctile(lats, 0.99), 2),
        "p999_ms": round(pctile(lats, 0.999), 2),
    }


def poisson_schedule(rate_qps: float, secs: float, rng) -> np.ndarray:
    """Arrival offsets (seconds from step start) of a Poisson process at
    ``rate_qps``, truncated to the step window.  Drawn UP FRONT: the
    server can be arbitrarily slow and the offered load does not move."""
    n = int(rate_qps * secs * 2) + 16
    arrivals = np.cumsum(rng.exponential(1.0 / rate_qps, size=n))
    return arrivals[arrivals < secs]


# -------------------------------------------------------------- workload

def build_mix(n_nodes: int, rng) -> list:
    """The mixed workload: each class is (name, weight, query pool,
    tenant).  Pools are pre-drawn so a step's body generation is a list
    index, never RNG work on the send path."""
    weights = {}
    for part in os.environ.get(
        "SLO_MIX", "point=0.45,khop=0.45,mutation=0.1"
    ).split(","):
        k, _, v = part.partition("=")
        weights[k.strip()] = float(v)
    point = [
        "{ q(func: uid(0x%x)) { c: count(e) } }" % u
        for u in np.unique(rng.integers(1, n_nodes + 1, size=64))
    ]
    khop = []
    for _ in range(64):
        seeds = np.unique(rng.integers(1, n_nodes + 1, size=8))
        ul = ", ".join("0x%x" % u for u in seeds)
        khop.append("{ q(func: uid(%s)) { e { c: count(e) } } }" % ul)
    # mutation interleave: edge toggles on a scratch uid range far above
    # the graph (adds followed by deletes on later draws keep the store
    # from growing without bound across a long sweep)
    mutation = []
    for i in range(64):
        u = 0x500000 + (i % 97)
        verb = "set" if i % 2 == 0 else "delete"
        mutation.append(
            "mutation { %s { <0x%x> <e> <0x%x> . } }" % (verb, u, u + 1)
        )
    pools = {"point": point, "khop": khop, "mutation": mutation}
    return [
        {"name": name, "weight": w, "pool": pools[name], "tenant": ""}
        for name, w in weights.items()
        if w > 0 and name in pools
    ]


# -------------------------------------------------------- open-loop step

def open_loop_step(
    port: int, classes: list, secs: float, seed: int,
    workers: int,
) -> dict:
    """Run one offered-load step against a live server.

    ``classes`` carry their OWN rates: [{name, rate, pool, tenant}] —
    the mixed-workload sweep gives each class a share of one swept
    rate, the qos arm pins the victim's rate while the antagonist's
    sweeps.  Senders are a bounded worker pool pulling a pre-drawn
    merged schedule; when all workers are busy a request starts late
    and the delay is charged to its latency (measured from scheduled
    arrival — the whole point of open loop)."""
    rng = np.random.default_rng(seed)
    events = []  # (offset_s, class index, body, tenant)
    for ci, c in enumerate(classes):
        if c["rate"] <= 0:
            continue
        offs = poisson_schedule(c["rate"], secs, rng)
        pool = c["pool"]
        picks = rng.integers(0, len(pool), size=len(offs))
        for off, pi in zip(offs, picks):
            events.append((float(off), ci, pool[int(pi)], c["tenant"]))
    events.sort(key=lambda e: e[0])
    offered = len(events) / secs if secs else 0.0

    lock = threading.Lock()
    pos = [0]
    per_class = [
        {"lats": [], "ok": 0, "shed": 0, "errors": 0} for _ in classes
    ]
    max_lag = [0.0]
    anchor = time.monotonic() + 0.05

    def sender():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        try:
            while True:
                with lock:
                    i = pos[0]
                    pos[0] += 1
                if i >= len(events):
                    return
                off, ci, body, tenant = events[i]
                due = anchor + off
                delay = due - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                else:
                    with lock:
                        max_lag[0] = max(max_lag[0], -delay)
                headers = {"X-Dgraph-Tenant": tenant} if tenant else {}
                status = -1
                for attempt in (0, 1):
                    try:
                        conn.request(
                            "POST", "/query", body=body.encode(),
                            headers=headers,
                        )
                        r = conn.getresponse()
                        r.read()
                        status = r.status
                        break
                    except OSError:
                        # a keep-alive connection the server closed
                        # between requests raises here — one retry on a
                        # fresh connection absorbs the benign race; a
                        # second failure is a real error (the retry's
                        # extra wait charges this request's latency,
                        # which is the honest accounting)
                        conn.close()
                        conn = http.client.HTTPConnection(
                            "127.0.0.1", port, timeout=60
                        )
                lat = time.monotonic() - due
                rec = per_class[ci]
                with lock:
                    if status == 200:
                        rec["ok"] += 1
                        rec["lats"].append(lat)
                    elif status in (429, 503, 504):
                        # shed IS the mechanism under measurement: the
                        # latency of a shed request is meaningless, the
                        # RATE of shedding is the signal
                        rec["shed"] += 1
                    else:
                        rec["errors"] += 1
        finally:
            conn.close()

    threads = [
        threading.Thread(target=sender, daemon=True, name=f"slo-{i}")
        for i in range(workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=secs * 4 + 120)
    # achieved rate over the SCHEDULED window, not sender wall time: a
    # schedule whose last arrival lands early must not inflate the rate
    wall = max(secs, time.monotonic() - anchor - 0.05)

    total_ok = sum(c["ok"] for c in per_class)
    total_shed = sum(c["shed"] for c in per_class)
    total_err = sum(c["errors"] for c in per_class)
    sent = total_ok + total_shed + total_err
    out_classes = {}
    for c, rec in zip(classes, per_class):
        out_classes[c["name"]] = {
            **latency_summary(rec["lats"]),
            "ok": rec["ok"],
            "shed": rec["shed"],
            "errors": rec["errors"],
            "offered_qps": round(c["rate"], 2),
        }
    return {
        "offered_qps": round(offered, 2),
        "achieved_qps": round(total_ok / wall, 2) if wall else 0.0,
        "sent": sent,
        "shed_rate": round(total_shed / max(sent, 1), 4),
        "error_rate": round(total_err / max(sent, 1), 4),
        "max_start_lag_ms": round(max_lag[0] * 1e3, 1),
        "classes": out_classes,
    }


def detect_knee(steps: list) -> dict | None:
    """The saturation knee: the first step where the server visibly
    stopped keeping up — sheds past 1%, or completions under 90% of the
    offered rate.  None = the sweep never saturated (offer more)."""
    for s in steps:
        if s["shed_rate"] > 0.01:
            return {
                "offered_qps": s["offered_qps"],
                "reason": "shed_rate",
                "shed_rate": s["shed_rate"],
            }
        if s["achieved_qps"] < 0.9 * s["offered_qps"]:
            return {
                "offered_qps": s["offered_qps"],
                "reason": "achieved_below_offered",
                "achieved_qps": s["achieved_qps"],
            }
    return None


# ------------------------------------------------------------- server arm

class _ServerArm:
    """Boot a DgraphServer under a pinned env regime, restore on exit —
    the bench.py save/restore contract, as a context manager."""

    def __init__(self, store, env: dict):
        self._store = store
        self._env = env
        self._saved = {}
        self.srv = None

    def __enter__(self):
        for k, v in self._env.items():
            self._saved[k] = os.environ.get(k)
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        try:
            from dgraph_tpu.serve.server import DgraphServer

            self.srv = DgraphServer(self._store)
            self.srv.start()
        except BaseException:
            # a failed boot skips __exit__ (context-manager protocol):
            # restore HERE or this arm's regime leaks into later arms,
            # which run_slo_bench's arm isolation would then measure
            self._restore()
            raise
        return self.srv

    def __exit__(self, et, ev, tb):
        try:
            self.srv.stop()
        finally:
            self._restore()

    def _restore(self):
        for k, v in self._saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _warmup(port: int, classes: list, n: int = 8) -> None:
    """Untimed compile/cache warmup: one pass over every pool so the
    first measured step never pays XLA compilation.  ``n`` widens the
    pass for arms whose assertions cannot tolerate a single mid-step
    compile (the devfault watchdog)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    try:
        for c in classes:
            for body in c["pool"][:n]:
                conn.request("POST", "/query", body=body.encode())
                conn.getresponse().read()
    finally:
        conn.close()


# ------------------------------------------------------------------ arms

def run_sweep(store, mix_weights: list, rates, secs, workers, seed) -> dict:
    """The main arm: the mixed workload swept over offered rates on the
    production configuration (scheduler + caches + QoS armed)."""
    sat_stop = _env_f("SLO_SAT_STOP", 0.5)
    steps = []
    with _ServerArm(store, {
        "DGRAPH_TPU_SCHED": "1",
        "DGRAPH_TPU_CACHE": os.environ.get("SLO_CACHE", "1"),
        **_backend_env(),
    }) as srv:
        classes = [
            {**c, "rate": 0.0} for c in mix_weights
        ]
        _warmup(srv.port, classes)
        wsum = sum(c["weight"] for c in classes)
        for step_i, rate in enumerate(rates):
            for c in classes:
                c["rate"] = rate * c["weight"] / wsum
            step = open_loop_step(
                srv.port, classes, secs, seed + step_i, workers
            )
            steps.append(step)
            print(
                f"# slo step: offered={step['offered_qps']} "
                f"achieved={step['achieved_qps']} "
                f"shed={step['shed_rate']}",
                file=sys.stderr,
            )
            if step["shed_rate"] > sat_stop:
                # saturated: further steps only melt the host without
                # adding curve — record that we stopped, not silence
                print(
                    f"# slo sweep stopped at {rate} qps "
                    f"(shed {step['shed_rate']} > {sat_stop})",
                    file=sys.stderr,
                )
                break
    return {"steps": steps, "saturation_knee": detect_knee(steps)}


def run_qos_arm(store, rates, secs, workers, seed) -> dict:
    """Victim p999 vs antagonist offered load, QoS on vs off — the
    PR-11 A/B with the closed-loop ratio replaced by a curve."""
    victim_rate = _env_f("SLO_VICTIM_RATE", 10.0)
    rng = np.random.default_rng(seed + 1000)
    n_nodes = int(_env_f("SLO_NODES", 20_000))
    victim_pool = [
        "{ q(func: uid(0x%x)) { c: count(e) } }" % u
        for u in np.unique(rng.integers(1, n_nodes + 1, size=64))
    ]
    antag_pool = []
    for _ in range(64):
        seeds = np.unique(rng.integers(1, n_nodes + 1, size=64))
        ul = ", ".join("0x%x" % u for u in seeds)
        antag_pool.append(
            "{ q(func: uid(%s)) { e { e { c: count(e) } } } }" % ul
        )
    tenants = json.dumps({
        "victim": {"weight": 8, "priority": "high"},
        "antagonist": {
            "weight": 1, "max_queued": 8, "max_inflight": 1,
            "priority": "low",
        },
    })
    out = {"victim_offered_qps": victim_rate, "tenants": json.loads(tenants)}
    for mode, qos in (("qos_on", "1"), ("qos_off", "0")):
        steps = []
        with _ServerArm(store, {
            "DGRAPH_TPU_SCHED": "1",
            "DGRAPH_TPU_CACHE": "0",  # a cached antagonist stresses nothing
            "DGRAPH_TPU_QOS": qos,
            "DGRAPH_TPU_QOS_TENANTS": tenants,
            **_backend_env(),
        }) as srv:
            classes = [
                {"name": "victim", "rate": victim_rate,
                 "pool": victim_pool, "tenant": "victim"},
                {"name": "antagonist", "rate": 0.0,
                 "pool": antag_pool, "tenant": "antagonist"},
            ]
            _warmup(srv.port, classes)
            for step_i, rate in enumerate(rates):
                classes[1]["rate"] = rate
                step = open_loop_step(
                    srv.port, classes, secs, seed + 2000 + step_i, workers
                )
                v = step["classes"]["victim"]
                a = step["classes"]["antagonist"]
                steps.append({
                    "antagonist_offered_qps": rate,
                    "victim_p50_ms": v["p50_ms"],
                    "victim_p99_ms": v["p99_ms"],
                    "victim_p999_ms": v["p999_ms"],
                    "victim_ok": v["ok"],
                    "antagonist_ok": a["ok"],
                    "antagonist_shed": a["shed"],
                })
                print(
                    f"# slo qos[{mode}] antag={rate} "
                    f"victim_p999={v['p999_ms']}ms "
                    f"antag_shed={a['shed']}",
                    file=sys.stderr,
                )
        out[mode] = steps
    return out


def run_ivm_arm(store, secs, workers, seed) -> dict:
    """Achieved QPS + p99 at a FIXED offered read load while the write
    rate sweeps — the PR-12 write-rate sweep, open-loop."""
    read_rate = _env_f("SLO_IVM_RATE", 50.0)
    write_rates = _env_rates("SLO_IVM_WRITE_RATES", "0,10,25")
    rng = np.random.default_rng(seed + 3000)
    n_nodes = int(_env_f("SLO_NODES", 20_000))
    read_pool = []
    for _ in range(64):
        seeds = np.unique(rng.integers(1, n_nodes + 1, size=8))
        ul = ", ".join("0x%x" % u for u in seeds)
        read_pool.append("{ q(func: uid(%s)) { e { c: count(e) } } }" % ul)
    steps = []
    with _ServerArm(store, {
        "DGRAPH_TPU_SCHED": "1",
        "DGRAPH_TPU_CACHE": "1",
        "DGRAPH_TPU_IVM": "1",
        **_backend_env(),
    }) as srv:
        classes = [{
            "name": "read", "rate": read_rate, "pool": read_pool,
            "tenant": "",
        }]
        _warmup(srv.port, classes)
        for step_i, wr in enumerate(write_rates):
            stop = threading.Event()

            def writer(rate=wr):
                if rate <= 0:
                    return
                conn = http.client.HTTPConnection(
                    "127.0.0.1", srv.port, timeout=60
                )
                i = 0
                try:
                    while not stop.is_set():
                        u = 0x70000 + (i % 97)
                        i += 1
                        for verb in ("set", "delete"):
                            conn.request(
                                "POST", "/query",
                                body=(
                                    "mutation { %s { <0x%x> <e> <0x%x> . } }"
                                    % (verb, u, u + 1)
                                ).encode(),
                            )
                            conn.getresponse().read()
                        if stop.wait(1.0 / rate):
                            return
                except OSError:
                    pass
                finally:
                    conn.close()

            wt = threading.Thread(target=writer, daemon=True)
            wt.start()
            try:
                step = open_loop_step(
                    srv.port, classes, secs, seed + 4000 + step_i, workers
                )
            finally:
                stop.set()
                wt.join(timeout=30)
            r = step["classes"]["read"]
            steps.append({
                "write_rate": wr,
                "achieved_qps": step["achieved_qps"],
                "p50_ms": r["p50_ms"],
                "p99_ms": r["p99_ms"],
                "p999_ms": r["p999_ms"],
                "shed_rate": step["shed_rate"],
            })
            print(
                f"# slo ivm write_rate={wr} "
                f"qps={step['achieved_qps']} p99={r['p99_ms']}ms",
                file=sys.stderr,
            )
    return {"read_offered_qps": read_rate, "steps": steps}


def run_devfault_arm(store, rates, secs, workers, seed) -> dict:
    """p999 vs offered load with a MID-SWEEP wedged-dispatch injection,
    devguard on vs off — the PR-15 device-fault A/B.  The bench shares
    the server's process, so the failpoint arms in-process: halfway
    through the middle step, ``device.hop`` starts hanging for
    ``SLO_DEVFAULT_WEDGE_MS`` (default 1500) up to ``SLO_DEVFAULT_HANGS``
    times.  With the guard on the watchdog (``SLO_DEVFAULT_HANG_MS``,
    default 100) bounds each wedge and hot-fails the hop to host —
    byte-identical answers, p999 stays near the deadline; with the
    guard off every wedge rides the serving path in full."""
    from dgraph_tpu.utils import devguard
    from dgraph_tpu.utils.failpoints import fail
    from dgraph_tpu.utils.metrics import DEVICE_FAILOVER

    wedge_ms = _env_f("SLO_DEVFAULT_WEDGE_MS", 1500.0)
    hangs = int(_env_f("SLO_DEVFAULT_HANGS", 2))
    rng = np.random.default_rng(seed + 5000)
    n_nodes = int(_env_f("SLO_NODES", 20_000))
    pool = []
    for _ in range(64):
        seeds = np.unique(rng.integers(1, n_nodes + 1, size=16))
        ul = ", ".join("0x%x" % u for u in seeds)
        pool.append("{ q(func: uid(%s)) { e { e { c: count(e) } } } }" % ul)
    inject_step = len(rates) // 2
    # under --backend mesh every eligible hop dispatches through the
    # mesh plane, so the wedge must land on ITS seam (the PR 17
    # chip-loss site) — device.hop would never fire, and the arm's
    # guarded failover is then mesh → unsharded instead of device → host
    mesh_arm = _backend_arg() == "mesh"
    site = "device.mesh" if mesh_arm else "device.hop"
    domain = "mesh" if mesh_arm else "device"
    out = {"wedge_ms": wedge_ms, "hangs": hangs, "site": site}
    fp_seed = int(os.environ.get("DGRAPH_TPU_FAILPOINT_SEED", "0"))
    for mode, guard in (("devguard_on", "1"), ("devguard_off", "0")):
        fail.reset(fp_seed)
        steps = []
        with _ServerArm(store, {
            "DGRAPH_TPU_SCHED": "1",
            # cached hops dodge the dispatch seam entirely — the arm
            # must measure the seam, not the cache
            "DGRAPH_TPU_CACHE": "0",
            "DGRAPH_TPU_DEVGUARD": guard,
            "DGRAPH_TPU_DEVICE_COOLDOWN_S": "0.2",
            # pin every hop onto the device dispatch seam (env override
            # = static gate; the planner yields the decision)
            "DGRAPH_TPU_EXPAND_DEVICE_MIN": "1",
            **_backend_env(),
        }) as srv:
            # guards read their env at construction: fresh ones per arm
            devguard.reset_for_tests()
            classes = [
                {"name": "khop", "rate": 0.0, "pool": pool, "tenant": ""}
            ]
            # warm under the DEFAULT (compile-tolerant) deadline, then
            # tighten the live watchdog: a cold XLA compile is slow,
            # not wedged — tightening first would latch the guard sick
            # on warmup compiles and pollute the non-injected steps
            _warmup(srv.port, classes, n=len(pool))
            if mesh_arm and guard == "1":
                # warm the UNSHARDED fallback programs too: the injected
                # step's re-planned hops must not pay first-time XLA
                # compiles (a cold compile is slow, not wedged — it
                # would smear p999 past the wedge bound the smoke
                # asserts).  Arm the chip-loss site for the whole pass
                # so every hop takes the degrade path once, then reset
                fail.arm(site, "error(n=1000000)")
                _warmup(srv.port, classes, n=len(pool))
                fail.reset(fp_seed)
                devguard.reset_for_tests()
            devguard.get(domain).hang_ms = _env_f(
                "SLO_DEVFAULT_HANG_MS", 100.0
            )
            for step_i, rate in enumerate(rates):
                classes[0]["rate"] = rate
                injected = step_i == inject_step
                timer = None
                if injected:
                    timer = threading.Timer(
                        secs / 2.0,
                        lambda: fail.arm(
                            site,
                            f"hang(ms={wedge_ms:g},n={hangs})",
                        ),
                    )
                    timer.start()
                fo0 = sum(DEVICE_FAILOVER.snapshot().values())
                try:
                    step = open_loop_step(
                        srv.port, classes, secs, seed + 6000 + step_i,
                        workers,
                    )
                finally:
                    if timer is not None:
                        timer.cancel()
                k = step["classes"]["khop"]
                steps.append({
                    "offered_qps": step["offered_qps"],
                    "achieved_qps": step["achieved_qps"],
                    "p50_ms": k["p50_ms"],
                    "p99_ms": k["p99_ms"],
                    "p999_ms": k["p999_ms"],
                    "shed_rate": step["shed_rate"],
                    "error_rate": step["error_rate"],
                    "injected": injected,
                    "failovers": (
                        sum(DEVICE_FAILOVER.snapshot().values()) - fo0
                    ),
                    "device_state": devguard.get(domain).state,
                })
                print(
                    f"# slo devfault[{mode}] offered={rate} "
                    f"p999={k['p999_ms']}ms"
                    + (" (wedge injected)" if injected else ""),
                    file=sys.stderr,
                )
            # the n-cap is spent by sweep end: the half-open probe must
            # re-admit the device (guard-off has no state to heal)
            healed = guard == "0"
            deadline = time.monotonic() + 15.0
            while not healed and time.monotonic() < deadline:
                healed = devguard.get(domain).state == "healthy"
                if not healed:
                    time.sleep(0.1)
        fail.reset(fp_seed)
        out[mode] = {"steps": steps, "readmitted": healed}
    devguard.reset_for_tests()
    return out


def run_meshchaos_arm(store, rates, secs, workers, seed) -> dict:
    """Open-loop p50/p99/p999 + shed rate across ONE injected chip-loss
    → staged-rejoin cycle on the elastic mesh fault domain (PR 20).

    Mesh backend only: halfway through the middle offered-load step the
    ``device.mesh`` failpoint kills chip ``SLO_MESHCHAOS_CHIP`` (seeded
    by DGRAPH_TPU_FAILPOINT_SEED, so the cycle is reproducible); the
    domain re-shards onto the surviving sub-mesh in-band, the short
    ``SLO_MESHCHAOS_COOLDOWN_S`` probe re-admits the chip, and the
    warm-then-cutover rejoin restores the full-mesh epoch — all while
    the open-loop schedule keeps firing.  The steps record the latency
    and shed cost of the whole cycle; the cycle record proves it
    actually closed (loss + rejoin reshards, full width restored, zero
    surfaced errors)."""
    from dgraph_tpu.utils import devguard
    from dgraph_tpu.utils.failpoints import fail
    from dgraph_tpu.utils.metrics import MESH_RESHARD, QUERY_RESUMED

    if _backend_arg() != "mesh":
        return {"skipped": "meshchaos arm runs under --backend mesh only"}
    import jax

    if len(jax.devices()) < 2:
        return {"skipped": "meshchaos arm needs a multi-chip mesh"}
    chip = int(_env_f("SLO_MESHCHAOS_CHIP", 1))
    cooldown = _env_f("SLO_MESHCHAOS_COOLDOWN_S", 1.0)
    rng = np.random.default_rng(seed + 9000)
    n_nodes = int(_env_f("SLO_NODES", 20_000))
    pool = []
    # pool size is tunable: each distinct query is a compile candidate
    # and the arm warms the pool at BOTH mesh widths — CPU-mesh smoke
    # runs want a handful, a TPU bench round wants the full spread
    for _ in range(int(_env_f("SLO_MESHCHAOS_POOL", 64))):
        seeds = np.unique(rng.integers(1, n_nodes + 1, size=16))
        ul = ", ".join("0x%x" % u for u in seeds)
        pool.append("{ q(func: uid(%s)) { e { e { c: count(e) } } } }" % ul)
    inject_step = len(rates) // 2
    fp_seed = int(os.environ.get("DGRAPH_TPU_FAILPOINT_SEED", "0"))
    fail.reset(fp_seed)
    out = {"chip": chip, "cooldown_s": cooldown}
    with _ServerArm(store, {
        "DGRAPH_TPU_SCHED": "1",
        "DGRAPH_TPU_CACHE": "0",
        "DGRAPH_TPU_DEVGUARD": "1",
        "DGRAPH_TPU_DEVICE_COOLDOWN_S": f"{cooldown:g}",
        "DGRAPH_TPU_EXPAND_DEVICE_MIN": "1",
        **_backend_env(),
    }) as srv:
        devguard.reset_for_tests()
        dom = getattr(srv.engine.arenas, "mesh_fault", None)
        if dom is None:
            return {
                "skipped": "mesh fault domain off "
                "(DGRAPH_TPU_MESH_ELASTIC=0 or single-chip mesh)"
            }
        total = len(dom.devices)
        classes = [
            {"name": "khop", "rate": 0.0, "pool": pool, "tenant": ""}
        ]
        # warm BOTH widths and the rejoin path before measuring: full
        # mesh first, then a throwaway loss→rejoin cycle so the
        # injected step never pays first-time sub-mesh XLA compiles
        # (a cold compile is slow, not lost capacity)
        _warmup(srv.port, classes, n=len(pool))
        fail.arm("device.mesh", f"error(n=1,chip={chip})")
        _warmup(srv.port, classes, n=len(pool))
        deadline = time.monotonic() + 30.0
        while dom.width < total and time.monotonic() < deadline:
            time.sleep(0.1)
        if dom.width < total:
            return {
                "skipped": "warmup loss→rejoin cycle never converged: "
                + json.dumps(dom.status())
            }
        fail.reset(fp_seed)
        rs0 = dict(MESH_RESHARD.snapshot())
        qr0 = dict(QUERY_RESUMED.snapshot())
        epoch0 = dom.epoch
        steps = []
        for step_i, rate in enumerate(rates):
            classes[0]["rate"] = rate
            injected = step_i == inject_step
            timer = None
            if injected:
                timer = threading.Timer(
                    secs / 2.0,
                    lambda: fail.arm(
                        "device.mesh", f"error(n=1,chip={chip})"
                    ),
                )
                timer.start()
            try:
                step = open_loop_step(
                    srv.port, classes, secs, seed + 9100 + step_i,
                    workers,
                )
            finally:
                if timer is not None:
                    timer.cancel()
            k = step["classes"]["khop"]
            steps.append({
                "offered_qps": step["offered_qps"],
                "achieved_qps": step["achieved_qps"],
                "p50_ms": k["p50_ms"],
                "p99_ms": k["p99_ms"],
                "p999_ms": k["p999_ms"],
                "shed_rate": step["shed_rate"],
                "error_rate": step["error_rate"],
                "injected": injected,
                "epoch": dom.epoch,
                "chips_healthy": dom.width,
            })
            print(
                f"# slo meshchaos offered={rate} p999={k['p999_ms']}ms "
                f"width={dom.width}/{total}"
                + (" (chip loss injected)" if injected else ""),
                file=sys.stderr,
            )
        # the cycle must CLOSE: bounded poll for the staged rejoin
        deadline = time.monotonic() + 30.0
        while dom.width < total and time.monotonic() < deadline:
            time.sleep(0.1)
        rs = {
            k: v - rs0.get(k, 0)
            for k, v in MESH_RESHARD.snapshot().items()
        }
        qr = {
            k: v - qr0.get(k, 0)
            for k, v in QUERY_RESUMED.snapshot().items()
        }
        out.update({
            "steps": steps,
            "cycle": {
                "restored": dom.width == total,
                "chips_total": total,
                "epoch_before": epoch0,
                "epoch_after": dom.epoch,
                "reshards": rs,
                "resumed": qr,
            },
        })
    fail.reset(fp_seed)
    devguard.reset_for_tests()
    return out


# every device dispatch seam the mega-query may route through: the
# planner picks chain vs mask-chain vs multi-hop per store shape, and
# the arm must price the dispatch wherever it lands
_SEG_SITES = ("device.chain", "device.spgemm", "device.multi_hop")


def _seg_cancel_probe(port: int, body: str, tid_int: int) -> dict:
    """Fire one mega-query with a sampled traceparent, /admin/cancel it
    the moment the registry has the token (the query is live), and
    report the wall time from cancel-ack to response completion — the
    observed cancellation latency.  Segmented, the token check at the
    next seam bounds it to ~one segment (499); monolithic, the program
    runs to completion first (200)."""
    from dgraph_tpu.utils.failpoints import fail

    tp = "00-%032x-%016x-01" % (tid_int, tid_int)
    res: dict = {}
    base_hits = sum(fail.hits(s) for s in _SEG_SITES)

    def runner():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        try:
            conn.request(
                "POST", "/query", body=body.encode(),
                headers={"Traceparent": tp, "X-Dgraph-Tenant": "antagonist"},
            )
            r = conn.getresponse()
            r.read()
            res["status"] = r.status
        except OSError:
            res["status"] = -1
        finally:
            res["done_at"] = time.monotonic()
            conn.close()

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    # cancelling a QUEUED query measures the pre-run fast path, not the
    # mid-chain latency under test: hold the cancel until the query's
    # first device dispatch fires (the probe runs alone, so the hit
    # delta is attributable)
    deadline = time.monotonic() + 30.0
    while (time.monotonic() < deadline and t.is_alive()
           and sum(fail.hits(s) for s in _SEG_SITES) == base_hits):
        time.sleep(0.002)
    cancel_at = None
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        while time.monotonic() < deadline and t.is_alive():
            conn.request("GET", "/admin/cancel?trace_id=%032x" % tid_int)
            r = conn.getresponse()
            r.read()
            if r.status == 200:
                cancel_at = time.monotonic()
                break
            time.sleep(0.02)  # 404: not admitted yet
    finally:
        conn.close()
    t.join(timeout=120)
    if cancel_at is None or "done_at" not in res:
        return {"error": "cancel never landed on a live query"}
    return {
        "status": res.get("status"),
        "cancel_to_done_ms": round((res["done_at"] - cancel_at) * 1e3, 1),
    }


def run_seg_arm(store, secs, workers, seed) -> dict:
    """Victim p999 under a MEGA-QUERY antagonist, segmentation on vs
    off — the PR-18 A/B.  The antagonist sends deep light (var-block)
    chains — 6 uid levels, pinned onto the fused mask-chain driver via
    DGRAPH_TPU_MXU_JOIN=force + the static chain-gate override, so the
    route never wobbles mid-arm — whose per-dispatch device time is
    injected at the ``device.spgemm`` failpoint with EQUAL total work
    per query in both modes: segmented (k=1) pays delay_ms at each of
    the 6 segment dispatches, monolithic pays 6×delay_ms at its single
    dispatch.  (A materialized 6-deep chain would be a response-encode
    bomb — deg^6 nested output nodes; the var-block shape is the real
    mega-query: all device work, tiny response.)
    The victim is a critical-priority point-read tenant: with
    segmentation on, a queued victim cohort preempts the running
    antagonist at the next seam (dgraph_segment_preempt_us records the
    wait), so its p999 is bounded by ~one segment; off, it waits out
    whole programs.  A mid-flight /admin/cancel probe per mode measures
    the cancellation latency the same way."""
    from dgraph_tpu import obs
    from dgraph_tpu.utils.failpoints import fail
    from dgraph_tpu.utils.metrics import SEGMENT_PREEMPT_US

    # a hair of head sampling so the cancel probe's SAMPLED traceparent
    # joins (the process recorder was built with ratio 0, under which
    # nothing joins and /admin/cancel can target nothing); restored to
    # the env default in the finally
    obs.configure(ratio=1e-9)

    victim_rate = _env_f("SLO_SEG_VICTIM_RATE", 10.0)
    antag_rate = _env_f("SLO_SEG_ANTAG_RATE", 8.0)
    delay_ms = _env_f("SLO_SEG_DELAY_MS", 80.0)
    levels = 6
    total_ms = delay_ms * levels
    rng = np.random.default_rng(seed + 7000)
    n_nodes = int(_env_f("SLO_NODES", 20_000))
    victim_pool = [
        "{ q(func: uid(0x%x)) { uid } }" % u
        for u in np.unique(rng.integers(1, n_nodes + 1, size=64))
    ]
    body = "v as e"
    for _ in range(levels - 1):
        body = "e { %s }" % body
    antag_pool = []
    for _ in range(32):
        seeds = np.unique(rng.integers(1, n_nodes + 1, size=8))
        ul = ", ".join("0x%x" % u for u in seeds)
        antag_pool.append(
            "{ var(func: uid(%s)) { %s } "
            "q(func: uid(v), first: 1) { uid } }" % (ul, body)
        )
    tenants = json.dumps({
        "victim": {"weight": 8, "priority": "critical"},
        "antagonist": {"weight": 1, "max_queued": 16,
                       "priority": "standard"},
    })
    out = {
        "victim_offered_qps": victim_rate,
        "antagonist_offered_qps": antag_rate,
        "delay_ms": delay_ms,
        "levels": levels,
        "total_injected_ms": total_ms,
        "tenants": json.loads(tenants),
    }
    fp_seed = int(os.environ.get("DGRAPH_TPU_FAILPOINT_SEED", "0"))
    try:
        _run_seg_modes(
            store, secs, workers, seed, out, fp_seed,
            victim_pool, antag_pool, tenants, delay_ms, total_ms,
            victim_rate, antag_rate,
        )
    finally:
        obs.configure()  # back to the env-default recorder
    return out


def _run_seg_modes(
    store, secs, workers, seed, out, fp_seed,
    victim_pool, antag_pool, tenants, delay_ms, total_ms,
    victim_rate, antag_rate,
) -> None:
    from dgraph_tpu.utils.failpoints import fail
    from dgraph_tpu.utils.metrics import SEGMENT_PREEMPT_US

    for mode, seg_env, per_dispatch_ms in (
        ("seg_on",
         {"DGRAPH_TPU_SEGMENT": "force", "DGRAPH_TPU_SEGMENT_K": "1"},
         delay_ms),
        ("seg_off", {"DGRAPH_TPU_SEGMENT": "0"}, total_ms),
    ):
        fail.reset(fp_seed)
        with _ServerArm(store, {
            "DGRAPH_TPU_SCHED": "1",
            # a cached mega-query stresses nothing; and cached chains
            # dodge the dispatch seam the arm must measure
            "DGRAPH_TPU_CACHE": "0",
            "DGRAPH_TPU_QOS": "1",
            "DGRAPH_TPU_QOS_TENANTS": tenants,
            # pin the deep chain onto the fused mask-chain driver (env
            # override = static gate; the planner yields the decision)
            "DGRAPH_TPU_CHAIN_THRESHOLD": "1",
            "DGRAPH_TPU_MXU_JOIN": "force",
            # one flush worker: the victim must actually queue behind
            # the running mega-query — with a second worker free the
            # A/B measures nothing
            "DGRAPH_TPU_SCHED_CONCURRENCY": "1",
            **seg_env,
            **_backend_env(),
        }) as srv:
            classes = [
                {"name": "victim", "rate": victim_rate,
                 "pool": victim_pool, "tenant": "victim"},
                {"name": "antagonist", "rate": antag_rate,
                 "pool": antag_pool, "tenant": "antagonist"},
            ]
            _warmup(srv.port, classes)
            p0 = SEGMENT_PREEMPT_US.count()
            # arm AFTER warmup: compiles are slow, not under test.  The
            # delay prices each device dispatch, whichever driver the
            # planner routes the chain to (chain / mask-chain /
            # multi-hop); victims are point lookups on the host route
            # and never pay it.
            for site in _SEG_SITES:
                fail.arm(site, f"delay(ms={per_dispatch_ms:g})")
            try:
                step = open_loop_step(
                    srv.port, classes, secs, seed + 7000, workers
                )
                cancel = _seg_cancel_probe(
                    srv.port, antag_pool[0],
                    0x5E60 + (1 if mode == "seg_on" else 2),
                )
            finally:
                fail.reset(fp_seed)
            v = step["classes"]["victim"]
            a = step["classes"]["antagonist"]
            out[mode] = {
                "victim_p50_ms": v["p50_ms"],
                "victim_p99_ms": v["p99_ms"],
                "victim_p999_ms": v["p999_ms"],
                "victim_ok": v["ok"],
                "antagonist_ok": a["ok"],
                "antagonist_shed": a["shed"],
                "preempts": SEGMENT_PREEMPT_US.count() - p0,
                "cancel": cancel,
            }
            print(
                f"# slo seg[{mode}] victim_p999={v['p999_ms']}ms "
                f"preempts={out[mode]['preempts']} "
                f"cancel={cancel}",
                file=sys.stderr,
            )


# ------------------------------------------------------------------ main

def run_slo_bench() -> dict:
    import jax

    from dgraph_tpu.obs import device as _device

    _device.install_compile_listener()
    _device.stamp_build_info()
    seed = int(_env_f("SLO_SEED", 7))
    n_nodes = int(_env_f("SLO_NODES", 20_000))
    deg = int(_env_f("SLO_DEG", 16))
    secs = _env_f("SLO_STEP_SECONDS", 4.0)
    workers = int(_env_f("SLO_WORKERS", 32))
    rates = _env_rates("SLO_RATES", "25,50,100,200,400")
    rng = np.random.default_rng(seed)
    store = _serving_store(n_nodes, deg)
    mix = build_mix(n_nodes, rng)

    sweep = run_sweep(store, mix, rates, secs, workers, seed)
    qos = None
    if os.environ.get("SLO_QOS", "1") != "0":
        try:
            qos = run_qos_arm(
                store, _env_rates("SLO_QOS_RATES", "50,200"), secs,
                workers, seed,
            )
        except Exception as e:  # arm isolation: the curve survives
            qos = {"error": f"{type(e).__name__}: {e}"}
    ivm = None
    if os.environ.get("SLO_IVM", "1") != "0":
        try:
            ivm = run_ivm_arm(store, secs, workers, seed)
        except Exception as e:
            ivm = {"error": f"{type(e).__name__}: {e}"}
    devfault = None
    if os.environ.get("SLO_DEVFAULT", "1") != "0":
        try:
            devfault = run_devfault_arm(
                store, _env_rates("SLO_DEVFAULT_RATES", "20,40"), secs,
                workers, seed,
            )
        except Exception as e:
            devfault = {"error": f"{type(e).__name__}: {e}"}
    seg = None
    if os.environ.get("SLO_SEG", "1") != "0":
        try:
            seg = run_seg_arm(store, secs, workers, seed)
        except Exception as e:
            seg = {"error": f"{type(e).__name__}: {e}"}
    meshchaos = None
    if os.environ.get("SLO_MESHCHAOS", "1") != "0":
        try:
            meshchaos = run_meshchaos_arm(
                store, _env_rates("SLO_MESHCHAOS_RATES", "20,40"), secs,
                workers, seed,
            )
        except Exception as e:
            meshchaos = {"error": f"{type(e).__name__}: {e}"}

    from dgraph_tpu.obs import ledger as _ledgermod

    out = {
        "metric": "slo_curve",
        # keyed by backend: the mesh arm's curve must never be compared
        # to an unsharded curve under the same key
        "backend": jax.default_backend()
        + ("-mesh" if _backend_arg() == "mesh" else ""),
        "nodes": n_nodes,
        "deg": deg,
        "step_seconds": secs,
        "workers": workers,
        "mix": {c["name"]: c["weight"] for c in mix},
        "offered_sweep": sweep["steps"],
        "saturation_knee": sweep["saturation_knee"],
        "qos": qos,
        "ivm": ivm,
        "devfault": devfault,
        "seg": seg,
        "meshchaos": meshchaos,
        # the serving-path cost account for the whole run (obs/ledger.py):
        # edges/sec across the sweep is achieved_qps × edges-per-query,
        # and this is the series it reconciles against
        "ledger": _ledgermod.aggregate_summary(),
    }
    return out


def smoke_check(out: dict) -> None:
    """The CI gate (SLO_SMOKE=1): the harness is well-formed and the
    physics points the right way — shed rate must be monotone
    non-decreasing in offered load (small tolerance for scheduler
    noise at tiny step sizes)."""
    for key in (
        "metric", "backend", "offered_sweep", "saturation_knee", "mix",
    ):
        assert key in out, f"slo smoke: missing key {key!r}"
    steps = out["offered_sweep"]
    assert len(steps) >= 2, "slo smoke: need at least two offered-load steps"
    for s in steps:
        assert s["sent"] > 0, "slo smoke: a step sent nothing"
        assert s["error_rate"] == 0.0, (
            f"slo smoke: non-shed errors at offered={s['offered_qps']}"
        )
        for cls in s["classes"].values():
            assert cls["p999_ms"] >= cls["p99_ms"] >= cls["p50_ms"] >= 0
    sheds = [s["shed_rate"] for s in steps]
    for a, b in zip(sheds, sheds[1:]):
        assert b >= a - 0.02, (
            f"slo smoke: shed rate not monotone across offered load "
            f"({sheds})"
        )
    dv = out.get("devfault")
    if dv and "error" not in dv:
        on, off = dv["devguard_on"], dv["devguard_off"]
        assert on["readmitted"], (
            "devfault smoke: device not re-admitted after the wedge healed"
        )
        inj_on = next(s for s in on["steps"] if s["injected"])
        inj_off = next(s for s in off["steps"] if s["injected"])
        assert inj_on["failovers"] > 0, (
            "devfault smoke: the wedge never drove a host failover"
        )
        for s in on["steps"]:
            assert s["error_rate"] == 0.0, (
                "devfault smoke: guard-on arm surfaced errors"
            )
        # structural separation: the watchdog bounds the wedge (guard
        # on), the legacy path eats it in full (guard off)
        assert inj_on["p999_ms"] < dv["wedge_ms"], (
            f"devfault smoke: guard did not bound the wedge "
            f"(p999 {inj_on['p999_ms']}ms vs wedge {dv['wedge_ms']}ms)"
        )
        assert inj_off["p999_ms"] >= dv["wedge_ms"] * 0.6, (
            "devfault smoke: guard-off arm never observed the wedge"
        )
    mc = out.get("meshchaos")
    if mc and "error" not in mc and "skipped" not in mc:
        cyc = mc["cycle"]
        assert cyc["restored"], (
            "meshchaos smoke: staged rejoin never restored the full mesh"
        )
        assert cyc["reshards"].get("loss", 0) >= 1, (
            "meshchaos smoke: the injected loss never drove a reshard"
        )
        assert cyc["reshards"].get("rejoin", 0) >= 1, (
            "meshchaos smoke: no rejoin cutover was recorded"
        )
        assert cyc["epoch_after"] > cyc["epoch_before"], (
            "meshchaos smoke: the mesh epoch never advanced"
        )
        for s in mc["steps"]:
            # chip loss is CAPACITY, not errors: the whole cycle —
            # loss, degraded sub-mesh serving, rejoin cutover — must
            # surface zero non-shed errors
            assert s["error_rate"] == 0.0, (
                f"meshchaos smoke: surfaced errors at "
                f"offered={s['offered_qps']}"
            )
    sg = out.get("seg")
    if sg and "error" not in sg:
        on, off = sg["seg_on"], sg["seg_off"]
        total = sg["total_injected_ms"]
        # structural separation: with segmentation on the critical
        # victim preempts at seams (p999 bounded under one program);
        # off, it waits out whole monolithic programs
        assert on["preempts"] > 0, (
            "seg smoke: segmentation never drove a preemption"
        )
        assert on["victim_p999_ms"] < total, (
            f"seg smoke: victim p999 not bounded with segmentation on "
            f"({on['victim_p999_ms']}ms vs program {total}ms)"
        )
        assert off["victim_p999_ms"] >= total * 0.6, (
            "seg smoke: monolithic arm never made the victim wait"
        )
        assert on["victim_p999_ms"] < off["victim_p999_ms"], (
            f"seg smoke: victim p999 did not improve "
            f"({on['victim_p999_ms']}ms on vs {off['victim_p999_ms']}ms off)"
        )
        con, coff = on["cancel"], off["cancel"]
        if "error" not in con and "error" not in coff:
            # mid-chain cancel completes within ~one segment (3x slack
            # for CI scheduling noise) vs the monolithic remainder
            assert con["cancel_to_done_ms"] < sg["delay_ms"] * 3, (
                f"seg smoke: cancel latency not segment-bounded "
                f"({con['cancel_to_done_ms']}ms)"
            )
            assert con["cancel_to_done_ms"] < coff["cancel_to_done_ms"], (
                "seg smoke: segmentation did not shorten cancel latency"
            )


def main() -> None:
    platform = ensure_backend()
    print(f"# backend: {platform}", file=sys.stderr)
    out = run_slo_bench()
    if os.environ.get("SLO_SMOKE") == "1":
        smoke_check(out)
        print("# slo smoke: OK", file=sys.stderr)
    body = json.dumps(out)
    print(body)
    path = os.environ.get("SLO_OUT", "")
    if path:
        with open(path, "w") as f:
            f.write(body + "\n")


if __name__ == "__main__":
    main()
