"""dgraph_tpu — a TPU-native distributed graph query engine.

A ground-up JAX/XLA/Pallas re-design of the capabilities of Dgraph v0.7
(the reference graph database surveyed in SURVEY.md): GraphQL±-style
queries over an RDF-ingested, predicate-sharded posting-list store.

Architecture (TPU-first, not a port):

- ``ops``      batched set-algebra kernels over padded sorted int32 uid sets
               (the TPU-native equivalent of the reference's algo/uidlist.go).
- ``models``   data model: host posting store with mutation semantics, the
               device-resident CSR "arenas" (the equivalent of posting/ +
               badger), schema state, value types.
- ``gql``      GraphQL± lexer/parser (equivalent of gql/ + lex/).
- ``rdf``      N-Quad mutation parser (equivalent of rdf/).
- ``tok``      tokenizers feeding secondary indexes (equivalent of tok/).
- ``query``    the SubGraph execution engine: level-batched device traversal,
               filters, sort, vars, aggregation, output encoding
               (equivalent of query/ + worker/task.go).
- ``parallel`` mesh sharding of arenas + collective frontier expansion
               (equivalent of group/ + worker routing, built on shard_map).
- ``serve``    HTTP serving surface, bulk loader, export
               (equivalent of cmd/dgraph + dgraph/ + client/).
- ``utils``    metrics, errors, config (equivalent of x/).
"""

__version__ = "0.1.0"
