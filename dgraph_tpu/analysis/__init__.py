"""graftcheck — repo-native static analysis + runtime invariants.

The engine's performance story rests on invariants no unit test states:
bounded compile counts on the hop hot path, no host↔device syncs inside
traced bodies, no lock-order inversions between scheduler / cache /
arena / cluster threads, monotonic clocks for every duration.  Go-side
Dgraph leans on ``go vet`` and the race detector for this class of bug;
this package is the Python/JAX equivalent, grown for THIS repo's idioms
rather than generic style:

- :mod:`.framework` — AST rule runner, pragma + baseline suppression;
- :mod:`.rules` — the lint rules (host-sync-in-jit, recompile-hazard,
  wallclock-duration, swallowed-exception);
- :mod:`.lockorder` — static ``with <lock>`` nesting graph over the
  package, cycle detection;
- :mod:`.witness` — runtime lock-order witness recorder (lockdep-style),
  armed during tests by ``tests/conftest.py``;
- :mod:`.pytest_budget` — pytest hooks enforcing per-test JAX compile
  budgets (``analysis/budgets.json``) and ``jax.transfer_guard`` markers;
- :mod:`.programs` — tier 2: the device-program contract checker.  Every
  compiled-kernel factory registers a contract (scan-freedom, dtype
  discipline, donation aliasing, transfer-freedom, cost budget,
  bucket-key soundness) checked on the traced jaxpr/StableHLO against
  golden fingerprints in ``analysis/programs.json``.

CLI: ``python -m dgraph_tpu.analysis`` (see ``--help``; exits nonzero on
any non-baselined finding or lock-order cycle) and ``--programs`` /
``--update-programs`` for tier 2.  Docs: docs/analysis.md.
"""

from dgraph_tpu.analysis.framework import (  # noqa: F401
    Finding,
    Rule,
    load_baseline,
    run_rules,
)
from dgraph_tpu.analysis.rules import ALL_RULES  # noqa: F401
