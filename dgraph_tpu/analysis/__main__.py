"""CLI: ``python -m dgraph_tpu.analysis [paths...]``.

Runs graftlint (AST rules) and the static lock-order pass over the
package (default: the installed ``dgraph_tpu`` tree) and exits nonzero
on any non-baselined finding, lock-order cycle, or self-nesting on a
non-reentrant lock.  CI runs this with the shipped (empty) baseline;
``--write-baseline`` exists for adopting the suite on a tree with
standing debt, not for silencing new findings.

``--programs`` runs graftcheck tier 2 instead: the device-program
contract checker (analysis/programs.py) traces every registered
compiled-kernel factory and enforces its declared invariants plus the
golden jaxpr fingerprints in ``analysis/programs.json``; an intentional
structural change is re-blessed with ``--update-programs`` (which still
refuses to bless a program violating its non-golden contract checks).
CI runs both passes as separate steps.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from dgraph_tpu.analysis.framework import (
    apply_baseline,
    load_baseline,
    run_rules,
    write_baseline,
)
from dgraph_tpu.analysis.lockorder import check_lock_order
from dgraph_tpu.analysis.rules import ALL_RULES

_DEFAULT_EXCLUDE = ("dgraph_tpu/analysis/",)  # the checker's own fixtures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dgraph_tpu.analysis",
        description="graftcheck: repo-native static analysis "
                    "(rule catalog: docs/analysis.md)",
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files/dirs to check (default: the dgraph_tpu package)",
    )
    ap.add_argument(
        "--baseline", default=None,
        help="JSON baseline of accepted finding fingerprints",
    )
    ap.add_argument(
        "--write-baseline", metavar="PATH", default=None,
        help="write current findings as the new baseline and exit 0",
    )
    ap.add_argument(
        "--lock-graph", action="store_true",
        help="print the full static lock-order graph",
    )
    ap.add_argument(
        "--no-lint", action="store_true", help="skip the AST rules"
    )
    ap.add_argument(
        "--no-locks", action="store_true", help="skip the lock-order pass"
    )
    ap.add_argument(
        "--programs", action="store_true",
        help="run the device-program contract checker (tier 2) instead "
             "of the lint/lock passes",
    )
    ap.add_argument(
        "--update-programs", action="store_true",
        help="re-bless the golden program fingerprints "
             "(analysis/programs.json) after the contract checks pass",
    )
    ap.add_argument(
        "--programs-goldens", metavar="PATH", default=None,
        help="alternate goldens file for --programs (default: "
             "analysis/programs.json)",
    )
    ap.add_argument(
        "--races", action="store_true",
        help="run the static thread-escape pass (tier 3) instead of the "
             "lint/lock passes; exits nonzero on any finding not in the "
             "sanctioned-shared manifest",
    )
    ap.add_argument(
        "--shared-manifest", metavar="PATH", default=None,
        help="sanctioned-shared manifest for --races (default: "
             "analysis/shared.json)",
    )
    ap.add_argument(
        "--write-shared", metavar="PATH", default=None,
        help="write current escape findings as the sanctioned-shared "
             "manifest and exit 0 (adoption aid, not a silencer)",
    )
    ns = ap.parse_args(argv)

    if ns.programs or ns.update_programs:
        # tier 2 runs alone: it traces/lowers real kernels (imports jax
        # and the ops modules), a different beast from the AST passes.
        # The mesh.multi_hop contract builds an 8-wide Mesh, so give
        # the host platform 8 devices before the backend initializes
        # (a no-op on real multi-chip backends; tests/conftest.py
        # forces the same count for in-process runs)
        import os

        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

        from dgraph_tpu.analysis.programs import run_check

        return run_check(
            goldens_path=ns.programs_goldens, update=ns.update_programs
        )

    pkg_root = Path(__file__).resolve().parents[1]   # dgraph_tpu/
    repo_root = pkg_root.parent
    roots = ns.paths or [str(pkg_root)]

    if ns.races or ns.write_shared:
        # tier 3 (static half) runs alone, like --programs: same AST
        # substrate as lint/locks but a different verdict and manifest
        from dgraph_tpu.analysis.escape import check_escapes

        findings = check_escapes(
            roots, repo_root=str(repo_root), exclude=_DEFAULT_EXCLUDE
        )
        if ns.write_shared:
            write_baseline(ns.write_shared, findings)
            print(
                f"wrote {len(findings)} fingerprint(s) to {ns.write_shared}"
            )
            return 0
        manifest = ns.shared_manifest or str(
            Path(__file__).resolve().parent / "shared.json"
        )
        fresh = apply_baseline(findings, load_baseline(manifest))
        for f in fresh:
            print(f.render())
        n_base = len(findings) - len(fresh)
        if fresh:
            print(
                f"\nthread-escape: {len(fresh)} finding(s)"
                + (f" ({n_base} sanctioned)" if n_base else "")
            )
            return 1
        print(
            "thread-escape: clean"
            + (f" ({n_base} sanctioned)" if n_base else "")
        )
        return 0

    rc = 0
    if not ns.no_lint:
        findings = run_rules(
            roots, ALL_RULES, repo_root=str(repo_root),
            exclude=_DEFAULT_EXCLUDE,
        )
        if ns.write_baseline:
            write_baseline(ns.write_baseline, findings)
            print(
                f"wrote {len(findings)} fingerprint(s) to {ns.write_baseline}"
            )
            return 0
        fresh = apply_baseline(findings, load_baseline(ns.baseline))
        for f in fresh:
            print(f.render())
        n_base = len(findings) - len(fresh)
        if fresh:
            print(
                f"\ngraftlint: {len(fresh)} finding(s)"
                + (f" ({n_base} baselined)" if n_base else "")
            )
            rc = 1
        else:
            print(
                "graftlint: clean"
                + (f" ({n_base} baselined)" if n_base else "")
            )

    if not ns.no_locks:
        graph, problems = check_lock_order(
            roots, repo_root=str(repo_root), exclude=_DEFAULT_EXCLUDE
        )
        if ns.lock_graph:
            print(graph.render())
        for p in problems:
            print(p)
        if problems:
            rc = 1
        else:
            print(
                f"lock-order: cycle-free "
                f"({len(graph.classes)} lock classes, "
                f"{len(graph.edges)} edges)"
            )
    return rc


if __name__ == "__main__":
    sys.exit(main())
