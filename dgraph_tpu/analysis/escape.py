"""Static thread-escape analysis — graftcheck tier 3's static half.

The lock-order pass proves the locks we take can't deadlock; this pass
asks the prior question: which state needed a lock in the first place?
Following the Eraser discipline (Savage et al.), a field is a race
candidate when it can be *written* from two or more thread contexts and
any write site is outside a ``with <lock>`` scope.

Model (deliberately per-class and conservative, like lockorder):

- **Thread contexts** come from the shared entry model in
  :func:`lockorder.discover_thread_entries` — ``Thread(target=...)``
  (bound-method and bare spellings), tracked ``Executor.submit``,
  ``threading.Timer``, servicer/handler methods, and
  ``# graftlint: thread-entry`` pragmas.  An entry spawned in a
  loop/comprehension or submitted to a pool is *multi*: it counts as
  two contexts on its own.  Everything reachable from an entry via
  intra-class ``self.meth()`` calls (fixpoint) runs in that context;
  public methods additionally run in the "external callers" context.
- **Writes** are ``self.attr = / += ...`` and ``self.attr[k] = ...``
  targets (depth one — ``self.a.b = ...`` mutates another object and
  is out of per-class scope), plus module globals (``global`` rebinds
  and item-stores on module-level names).  ``__init__`` writes are
  exempt: they happen-before any thread this object starts.
- **Locked** means lexically inside ``with <lock-like>`` (reusing
  lockorder's lock-class spellings: declared lock attrs anywhere in the
  package, ``*lock*/*mu*/*cond*``-named receivers, RWLock
  ``.read()/.write()``, per-key ``setdefault(k, Lock())`` aliases), or
  inside a private method whose every intra-class call site is locked
  (the "caller holds the lock" discipline, computed as a fixpoint).
  Closures defined under a lock run later, possibly without it — their
  writes do NOT inherit the lock scope.
- **Exempt**: fields holding locks/executors themselves,
  ``threading.local()`` and ``ContextVar`` fields (per-thread by
  construction), per-connection ``*RequestHandler`` instances (one
  instance per thread; their *global* writes still count).

What this pass cannot see — cross-object writes (``st.failures += 1``
on a struct owned by another class), reader-side races, dynamic
hand-offs — is exactly what the runtime lockset witness
(:mod:`.witness`) covers under tier-1.  The two are a pair.

Sanctioning a deliberate case:

- ``# graftlint: shared[attr] <why>`` on any write site (or the line
  above) accepts the field class-wide; the WHY text is mandatory.
- the manifest ``analysis/shared.json`` (multiset fingerprint baseline,
  shipped empty) accepts findings wholesale — for adopting the pass on
  a tree with standing debt, not for silencing new ones.
"""

from __future__ import annotations

import ast
import re
from collections import defaultdict
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from dgraph_tpu.analysis.framework import FileContext, Finding, iter_py_files
from dgraph_tpu.analysis.lockorder import (
    _dotted,
    _is_executor_ctor,
    _lock_ctor_kind,
    _module_name,
    _strip_rw,
    discover_thread_entries,
)

RULE_ESCAPE = "thread-escape"
RULE_GLOBAL = "global-escape"
RULE_WHY = "shared-needs-why"

_SHARED_RE = re.compile(r"#\s*graftlint:\s*shared\[([A-Za-z0-9_, ]+)\]\s*(.*)")
# receiver names that read as locks even without a visible declaration
# (cross-module attrs like `srv._engine_lock`, local `lock_cm` aliases)
_LOCKY_NAME_RE = re.compile(
    r"(^|_)(lock|rlock|mu|mutex|cond|condition|sem|semaphore|cv)s?(_|$)"
)
_PER_THREAD_CTORS = {
    "threading.local", "local", "contextvars.ContextVar", "ContextVar",
}

_EXT = "ext"     # context token: unknown external caller (counts once)
_INIT = "init"   # context token: __init__ — happens-before thread start


@dataclass
class _Write:
    name: str      # field or global name
    lineno: int
    locked: bool   # lexically under a lock-like `with`
    func: str      # enclosing method/function name


class _FileInfo:
    def __init__(self, path: str, tree: ast.AST, module: str, source: str):
        self.path = path
        self.tree = tree
        self.module = module
        self.source = source
        self.lines = source.splitlines()


# -- package-wide prep ------------------------------------------------------

def _parse_files(
    roots: Iterable[str],
    repo_root: Optional[str],
    exclude: Sequence[str],
) -> List[_FileInfo]:
    base = Path(repo_root) if repo_root else Path(".")
    out: List[_FileInfo] = []
    for f in iter_py_files(roots, exclude=exclude):
        src = f.read_text(encoding="utf-8")
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        rel = f.as_posix()
        try:
            rel = f.resolve().relative_to(base.resolve()).as_posix()
        except ValueError:
            pass
        out.append(_FileInfo(rel, tree, _module_name(f, base), src))
    return out


def _collect_lock_names(files: Sequence[_FileInfo]) -> Set[str]:
    """Every attr/global name assigned a lock ctor anywhere in the
    package — `with self.<name>:` / `with obj.<name>:` then counts as a
    lock scope even when the name itself isn't lock-ish."""
    names: Set[str] = set()
    for fi in files:
        for node in ast.walk(fi.tree):
            if isinstance(node, ast.Assign) and _lock_ctor_kind(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Attribute):
                        names.add(t.attr)
                    elif isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def _name_locky(name: str, lock_names: Set[str]) -> bool:
    return name in lock_names or bool(_LOCKY_NAME_RE.search(name))


def _produces_lock(v: ast.AST, lock_names: Set[str]) -> bool:
    """Does this rvalue evaluate to a lock (for local alias tracking)?"""
    v = _strip_rw(v)
    if _lock_ctor_kind(v) is not None:
        return True
    if isinstance(v, ast.IfExp):
        return _produces_lock(v.body, lock_names) or _produces_lock(
            v.orelse, lock_names
        )
    if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute):
        if v.func.attr in ("setdefault", "get") and len(v.args) >= 2:
            if _lock_ctor_kind(v.args[1]) is not None:
                return True
    if isinstance(v, ast.Attribute):
        return _name_locky(v.attr, lock_names)
    if isinstance(v, ast.Name):
        return _name_locky(v.id, lock_names)
    return False


def _is_lock_like(
    expr: ast.AST, aliases: Set[str], lock_names: Set[str]
) -> bool:
    expr = _strip_rw(expr)
    if isinstance(expr, ast.Name):
        return expr.id in aliases or _name_locky(expr.id, lock_names)
    if isinstance(expr, ast.Attribute):
        return _name_locky(expr.attr, lock_names)
    if isinstance(expr, ast.IfExp):
        # `nullcontext() if local else lock.read()`: optimistic — treat
        # the scope as locked rather than spray findings on every
        # conditional-lock site; the runtime witness sees the truth
        return _is_lock_like(expr.body, aliases, lock_names) or _is_lock_like(
            expr.orelse, aliases, lock_names
        )
    if isinstance(expr, ast.Call):
        if _lock_ctor_kind(expr) is not None:
            return True
        if isinstance(expr.func, ast.Attribute):
            if expr.func.attr in ("setdefault", "get") and len(expr.args) >= 2:
                return _lock_ctor_kind(expr.args[1]) is not None
    return False


# -- per-function scan ------------------------------------------------------

class _FnScan:
    def __init__(self):
        self.writes: List[_Write] = []     # instance-attr writes
        self.gwrites: List[_Write] = []    # module-global writes
        self.sites: List[Tuple[str, bool]] = []  # (self-callee, locked)


def _scan_function(
    fn: ast.AST,
    name: str,
    methods: Set[str],
    lock_names: Set[str],
    module_globals: Set[str],
) -> _FnScan:
    out = _FnScan()
    declared_global: Set[str] = set()
    local_names: Set[str] = set()
    aliases: Set[str] = set()

    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            declared_global.update(node.names)
        elif isinstance(node, ast.Assign):
            if _produces_lock(node.value, lock_names):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        aliases.add(t.id)
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        for a in (
            fn.args.args + fn.args.posonlyargs + fn.args.kwonlyargs
        ):
            local_names.add(a.arg)

    def scan_stmt_exprs(st: ast.AST, held: int) -> None:
        """Writes and self-call sites in ONE statement's expressions —
        nested statement bodies are visited separately, and closures are
        skipped (they run later, maybe without the lock)."""
        stack: List[ast.AST] = []
        for fname, val in ast.iter_fields(st):
            if fname in ("body", "orelse", "finalbody", "handlers"):
                continue
            if isinstance(val, ast.AST):
                stack.append(val)
            elif isinstance(val, list):
                stack.extend(x for x in val if isinstance(x, ast.AST))
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Store
            ):
                if isinstance(node.value, ast.Name) and node.value.id == "self":
                    out.writes.append(
                        _Write(node.attr, node.lineno, held > 0, name)
                    )
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Store
            ):
                base = node.value
                if (
                    isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"
                ):
                    out.writes.append(
                        _Write(base.attr, node.lineno, held > 0, name)
                    )
                elif (
                    isinstance(base, ast.Name)
                    and base.id in module_globals
                    and base.id not in local_names
                ):
                    out.gwrites.append(
                        _Write(base.id, node.lineno, held > 0, name)
                    )
            elif isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Store
            ):
                if node.id in declared_global:
                    out.gwrites.append(
                        _Write(node.id, node.lineno, held > 0, name)
                    )
                else:
                    local_names.add(node.id)
            elif isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                    and f.attr in methods
                ):
                    out.sites.append((f.attr, held > 0))
            stack.extend(ast.iter_child_nodes(node))

    def visit(stmts: Sequence[ast.stmt], held: int) -> None:
        for st in stmts:
            if isinstance(st, (ast.With, ast.AsyncWith)):
                locked_here = any(
                    _is_lock_like(i.context_expr, aliases, lock_names)
                    for i in st.items
                )
                scan_stmt_exprs(st, held)
                visit(st.body, held + (1 if locked_here else 0))
                continue
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(st.body, 0)  # closure: lock scope does not carry
                continue
            scan_stmt_exprs(st, held)
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(st, attr, None)
                if sub:
                    visit(sub, held)
            for h in getattr(st, "handlers", []) or []:
                visit(h.body, held)

    visit(fn.body, 0)
    return out


# -- context propagation ----------------------------------------------------

def _method_contexts(
    methods: Dict[str, ast.AST],
    roots: Dict[str, bool],          # meth -> multi
    scans: Dict[str, _FnScan],
) -> Dict[str, Set]:
    """Token sets per method: ("r", meth, multi) | "ext" | "init",
    propagated along intra-class call edges to a fixpoint."""
    ctxs: Dict[str, Set] = {m: set() for m in methods}
    for m in methods:
        if m in roots:
            ctxs[m].add(("r", m, roots[m]))
        if m == "__init__":
            ctxs[m].add(_INIT)
        elif not m.startswith("_") or (m.startswith("__") and m.endswith("__")):
            ctxs[m].add(_EXT)
    changed = True
    while changed:
        changed = False
        for caller, scan in scans.items():
            for callee, _locked in scan.sites:
                extra = ctxs[caller] - ctxs.get(callee, set())
                if callee in ctxs and extra:
                    ctxs[callee] |= extra
                    changed = True
    for m in methods:  # private, never called: caller unknown — assume shared
        if not ctxs[m]:
            ctxs[m].add(_EXT)
    return ctxs


def _always_locked(
    methods: Dict[str, ast.AST],
    roots: Dict[str, bool],
    scans: Dict[str, _FnScan],
) -> Set[str]:
    """Private methods whose EVERY intra-class call site is under a lock
    (directly, or inside another always-locked method) — the "caller
    holds self._lock" discipline."""
    sites_by_callee: Dict[str, List[Tuple[str, bool]]] = defaultdict(list)
    for caller, scan in scans.items():
        for callee, locked in scan.sites:
            sites_by_callee[callee].append((caller, locked))
    al = {
        m for m in methods
        if m.startswith("_") and not m.endswith("__")
        and m not in roots and sites_by_callee[m]
    }
    changed = True
    while changed:
        changed = False
        for m in list(al):
            for caller, locked in sites_by_callee[m]:
                if not locked and caller not in al:
                    al.discard(m)
                    changed = True
                    break
    return al


def _weight(tokens: Set) -> int:
    w = 0
    for t in tokens:
        if t == _EXT:
            w += 1
        elif isinstance(t, tuple) and t[0] == "r":
            w += 2 if t[2] else 1
    return w


def _describe(tokens: Set) -> str:
    parts = []
    for t in sorted(tokens, key=str):
        if t == _EXT:
            parts.append("external callers")
        elif isinstance(t, tuple) and t[0] == "r":
            parts.append(f"thread:{t[1]}" + (" (multi)" if t[2] else ""))
    return ", ".join(parts)


# -- pragma handling --------------------------------------------------------

def _shared_pragmas(
    ctx: FileContext, linenos: Iterable[int]
) -> Tuple[Set[str], List[int]]:
    """(sanctioned names, pragma lines missing a WHY) across the given
    write sites (each checked on its line and the line above)."""
    sanctioned: Set[str] = set()
    missing_why: List[int] = []
    seen: Set[int] = set()
    for wl in linenos:
        for ln in (wl, wl - 1):
            if ln in seen:
                continue
            seen.add(ln)
            m = _SHARED_RE.search(ctx.line(ln))
            if not m:
                continue
            names = {s.strip() for s in m.group(1).split(",")}
            # a WHY-less pragma still sanctions: the one actionable
            # finding is "write the why", not a duplicate escape report
            if not m.group(2).strip():
                missing_why.append(ln)
            sanctioned |= names
    return sanctioned, missing_why


# -- per-file analysis ------------------------------------------------------

def _check_file(fi: _FileInfo, lock_names: Set[str]) -> List[Finding]:
    ctx = FileContext(
        path=fi.path, source=fi.source, tree=fi.tree, lines=fi.lines
    )
    entries = discover_thread_entries(fi.tree, fi.module, fi.path, fi.lines)
    # qual -> (multi, set of kinds); multi if ANY spawn site is multi
    entry_map: Dict[str, Tuple[bool, Set[str]]] = {}
    for e in entries:
        multi, kinds = entry_map.get(e.qual, (False, set()))
        entry_map[e.qual] = (multi or e.multi, kinds | {e.kind})

    body = fi.tree.body if isinstance(fi.tree, ast.Module) else []

    # module-level state: assignable names and exempt (lock/per-thread)
    module_globals: Set[str] = set()
    g_exempt: Set[str] = set()
    for node in body:
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name):
                module_globals.add(t.id)
                val = getattr(node, "value", None)
                if val is not None and (
                    _lock_ctor_kind(val) is not None
                    or _dotted(getattr(val, "func", val)) in _PER_THREAD_CTORS
                ):
                    g_exempt.add(t.id)

    findings: List[Finding] = []
    # global writes accumulate across every function/method in the file:
    # (write, context tokens of its enclosing function)
    g_accum: List[Tuple[_Write, Set]] = []

    for node in body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = f"{fi.module}.{node.name}"
            scan = _scan_function(
                node, node.name, set(), lock_names, module_globals
            )
            toks: Set = {_EXT}  # any module function is externally callable
            ent = entry_map.get(qual)
            if ent:
                toks.add(("r", node.name, ent[0]))
            for w in scan.gwrites:
                g_accum.append((w, toks))
        elif isinstance(node, ast.ClassDef):
            findings.extend(
                _check_class(fi, ctx, node, entry_map, lock_names,
                             module_globals, g_accum)
            )

    # module-global verdicts
    by_global: Dict[str, List[Tuple[_Write, Set]]] = defaultdict(list)
    for w, toks in g_accum:
        if w.name not in g_exempt and not _name_locky(w.name, lock_names):
            by_global[w.name].append((w, toks))
    for gname, ws in sorted(by_global.items()):
        sanctioned, missing = _shared_pragmas(ctx, (w.lineno for w, _ in ws))
        for ln in missing:
            findings.append(_pragma_why_finding(ctx, ln))
        if gname in sanctioned or "all" in sanctioned:
            continue
        tokens: Set = set()
        for w, toks in ws:
            for t in toks:
                tokens.add(_qualify(t, None))
        unlocked = [w for w, _ in ws if not w.locked]
        if _weight(tokens) >= 2 and unlocked:
            first = min(unlocked, key=lambda w: w.lineno)
            f = Finding(
                rule=RULE_GLOBAL, path=fi.path, line=first.lineno,
                message=(
                    f"module global `{gname}` is written from "
                    f"{_weight(tokens)} thread context(s) "
                    f"[{_describe(tokens)}] and this write is outside any "
                    f"lock scope; guard it, or sanction with "
                    f"`# graftlint: shared[{gname}] <why>`"
                ),
                snippet=ctx.line(first.lineno),
            )
            if not ctx.suppressed(f):
                findings.append(f)
    return findings


def _qualify(token, cls: Optional[str]):
    """Make root tokens unique module-wide for global-write weighting."""
    if isinstance(token, tuple) and token[0] == "r" and cls:
        return ("r", f"{cls}.{token[1]}", token[2])
    return token


def _pragma_why_finding(ctx: FileContext, lineno: int) -> Finding:
    return Finding(
        rule=RULE_WHY, path=ctx.path, line=lineno,
        message=(
            "`# graftlint: shared[...]` pragma has no WHY — state the "
            "reason the unlocked sharing is safe after the closing bracket"
        ),
        snippet=ctx.line(lineno),
    )


def _check_class(
    fi: _FileInfo,
    ctx: FileContext,
    cd: ast.ClassDef,
    entry_map: Dict[str, Tuple[bool, Set[str]]],
    lock_names: Set[str],
    module_globals: Set[str],
    g_accum: List[Tuple[_Write, Set]],
) -> List[Finding]:
    methods: Dict[str, ast.AST] = {}
    for sub in cd.body:
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            methods.setdefault(sub.name, sub)

    # fields that ARE synchronization or per-thread storage
    exempt: Set[str] = set()
    for node in ast.walk(cd):
        if isinstance(node, ast.Assign):
            val = node.value
            is_sync = (
                _lock_ctor_kind(val) is not None
                or _is_executor_ctor(val)
                or _dotted(getattr(val, "func", val)) in _PER_THREAD_CTORS
            )
            if is_sync:
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        exempt.add(t.attr)

    roots: Dict[str, bool] = {}       # instance-context roots
    conn_handler = False
    for m in methods:
        ent = entry_map.get(f"{fi.module}.{cd.name}.{m}")
        if not ent:
            continue
        multi, kinds = ent
        if kinds == {"conn-handler"}:
            conn_handler = True
            # per-connection instance: still a root for GLOBAL writes
            roots[m] = multi
        else:
            roots[m] = multi

    scans = {
        m: _scan_function(fn, m, set(methods), lock_names, module_globals)
        for m, fn in methods.items()
    }
    ctxs = _method_contexts(methods, roots, scans)
    al = _always_locked(methods, roots, scans)

    # contribute global writes with class-qualified tokens
    for m, scan in scans.items():
        toks = {_qualify(t, cd.name) for t in ctxs[m]}
        for w in scan.gwrites:
            g_accum.append((w, toks))

    if conn_handler:
        return []  # instance state is per-connection → per-thread

    by_field: Dict[str, List[_Write]] = defaultdict(list)
    for m, scan in scans.items():
        for w in scan.writes:
            by_field[w.name].append(w)

    findings: List[Finding] = []
    for field, ws in sorted(by_field.items()):
        if field in exempt or _name_locky(field, lock_names):
            continue
        sanctioned, missing = _shared_pragmas(ctx, (w.lineno for w in ws))
        for ln in missing:
            findings.append(_pragma_why_finding(ctx, ln))
        if field in sanctioned or "all" in sanctioned:
            continue
        tokens: Set = set()
        eff: List[_Write] = []
        for w in ws:
            t = ctxs[w.func] - {_INIT}
            if not t:
                continue  # init-only write: happens-before thread start
            tokens |= t
            eff.append(w)
        unlocked = [w for w in eff if not (w.locked or w.func in al)]
        if _weight(tokens) >= 2 and unlocked:
            first = min(unlocked, key=lambda w: w.lineno)
            f = Finding(
                rule=RULE_ESCAPE, path=fi.path, line=first.lineno,
                message=(
                    f"`self.{field}` of {cd.name} is written from "
                    f"{_weight(tokens)} thread context(s) "
                    f"[{_describe(tokens)}] and the write in "
                    f"{first.func}() is outside any lock scope; guard it, "
                    f"or sanction with `# graftlint: shared[{field}] <why>`"
                ),
                snippet=ctx.line(first.lineno),
            )
            if not ctx.suppressed(f):
                findings.append(f)
    return findings


# -- entry ------------------------------------------------------------------

def check_escapes(
    roots: Iterable[str],
    repo_root: Optional[str] = None,
    exclude: Sequence[str] = (),
) -> List[Finding]:
    """All escape findings over the given roots (pragma suppression
    applied; manifest subtraction is the caller's policy)."""
    files = _parse_files(roots, repo_root, exclude)
    lock_names = _collect_lock_names(files)
    out: List[Finding] = []
    for fi in files:
        out.extend(_check_file(fi, lock_names))
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def check_escape_source(
    source: str, path: str = "<snippet>", module: str = "snippet"
) -> List[Finding]:
    """Run the escape pass over an in-memory snippet (test fixtures)."""
    fi = _FileInfo(path, ast.parse(source), module, source)
    lock_names = _collect_lock_names([fi])
    return _check_file(fi, lock_names)
