"""Rule-runner core for graftlint (the AST half of graftcheck).

A rule is a class with an ``id``, a ``doc`` line, and a ``check(ctx)``
method yielding :class:`Finding`.  The runner parses each file once,
hands every rule the same :class:`FileContext` (tree + source lines),
and merges three suppression layers:

- **pragma**: a ``# graftlint: ignore[rule-id]`` comment on the flagged
  line (or the line above it) silences that one finding — for the rare
  site where the pattern is deliberate (e.g. ``since()`` is wall-clock
  *by definition*: it compares against user-visible stored timestamps);
- **baseline file**: a JSON list of finding fingerprints accepted as
  pre-existing debt (``--write-baseline`` emits it).  Fingerprints hash
  the rule id, the relative path, and the *normalized source line* —
  NOT the line number — so unrelated edits above a baselined finding
  don't resurrect it;
- the shipped tree carries an **empty** baseline: new findings fail CI.
"""

from __future__ import annotations

import ast
import hashlib
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator, List, Optional, Sequence

_PRAGMA_RE = re.compile(r"#\s*graftlint:\s*ignore\[([a-z0-9_,\- ]+)\]")


@dataclass
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int          # 1-based
    message: str
    snippet: str = ""  # the offending source line, stripped

    @property
    def fingerprint(self) -> str:
        # line CONTENT, not line number: stable across edits elsewhere
        norm = re.sub(r"\s+", " ", self.snippet.strip())
        h = hashlib.sha1(
            f"{self.rule}::{self.path}::{norm}".encode()
        ).hexdigest()
        return h[:16]

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: [{self.rule}] {self.message}\n"
            f"    {self.snippet.strip()}"
        )


@dataclass
class FileContext:
    """Everything a rule needs about one file, parsed once."""

    path: str                 # repo-relative
    source: str
    tree: ast.AST
    lines: Sequence[str] = field(default_factory=list)

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def finding(self, rule_id: str, node: ast.AST, message: str) -> Finding:
        lineno = getattr(node, "lineno", 1)
        return Finding(
            rule=rule_id,
            path=self.path,
            line=lineno,
            message=message,
            snippet=self.line(lineno),
        )

    def suppressed(self, f: Finding) -> bool:
        for lineno in (f.line, f.line - 1):
            m = _PRAGMA_RE.search(self.line(lineno))
            if m:
                ids = {s.strip() for s in m.group(1).split(",")}
                if f.rule in ids or "all" in ids:
                    return True
        return False


class Rule:
    """Base class; subclasses set ``id``/``doc`` and implement check()."""

    id: str = ""
    doc: str = ""

    def check(self, ctx: FileContext) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError


def iter_py_files(
    roots: Iterable[str], exclude: Sequence[str] = ()
) -> Iterator[Path]:
    for root in roots:
        p = Path(root)
        if p.is_file() and p.suffix == ".py":
            yield p
            continue
        for f in sorted(p.rglob("*.py")):
            rel = f.as_posix()
            if any(pat in rel for pat in exclude):
                continue
            yield f


def run_rules(
    roots: Iterable[str],
    rules: Sequence[Rule],
    repo_root: Optional[str] = None,
    exclude: Sequence[str] = (),
) -> List[Finding]:
    """Parse every file once, run every rule, apply pragma suppression.
    Baseline suppression is the caller's job (it is a policy, not a
    property of the file)."""
    base = Path(repo_root) if repo_root else None
    out: List[Finding] = []
    for f in iter_py_files(roots, exclude=exclude):
        src = f.read_text(encoding="utf-8")
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            rel = _rel(f, base)
            out.append(Finding(
                rule="syntax-error", path=rel, line=e.lineno or 1,
                message=str(e.msg), snippet="",
            ))
            continue
        ctx = FileContext(
            path=_rel(f, base), source=src, tree=tree,
            lines=src.splitlines(),
        )
        for rule in rules:
            for finding in rule.check(ctx):
                if not ctx.suppressed(finding):
                    out.append(finding)
    out.sort(key=lambda x: (x.path, x.line, x.rule))
    return out


def check_source(
    source: str, rules: Sequence[Rule], path: str = "<snippet>"
) -> List[Finding]:
    """Run rules over an in-memory snippet (tests' golden fixtures)."""
    tree = ast.parse(source)
    ctx = FileContext(
        path=path, source=source, tree=tree, lines=source.splitlines()
    )
    out: List[Finding] = []
    for rule in rules:
        for f in rule.check(ctx):
            if not ctx.suppressed(f):
                out.append(f)
    return out


def _rel(f: Path, base: Optional[Path]) -> str:
    if base is not None:
        try:
            return f.resolve().relative_to(base.resolve()).as_posix()
        except ValueError:
            pass
    return f.as_posix()


# -- baseline ---------------------------------------------------------------

def load_baseline(path: Optional[str]) -> List[str]:
    """Fingerprint MULTISET (duplicates meaningful — one entry per
    accepted occurrence)."""
    if not path:
        return []
    p = Path(path)
    if not p.exists():
        return []
    data = json.loads(p.read_text())
    return list(data.get("fingerprints", []))


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    payload = {
        "comment": (
            "accepted pre-existing graftlint findings; regenerate with "
            "`python -m dgraph_tpu.analysis --write-baseline`"
        ),
        # duplicates KEPT: two identical offending lines in one file
        # share a fingerprint, and the baseline must record how many
        # were accepted — see apply_baseline
        "fingerprints": sorted(f.fingerprint for f in findings),
    }
    Path(path).write_text(json.dumps(payload, indent=2) + "\n")


def apply_baseline(
    findings: Sequence[Finding], baseline
) -> List[Finding]:
    """Multiset subtraction, not set membership: a baseline with ONE
    accepted `except Exception: pass` in a file suppresses exactly one
    such finding — adding a second identical line still fails."""
    from collections import Counter

    budget = Counter(baseline)
    out: List[Finding] = []
    for f in findings:
        if budget.get(f.fingerprint, 0) > 0:
            budget[f.fingerprint] -= 1
        else:
            out.append(f)
    return out
