"""Static lock-order graph over the package's ``with <lock>`` nesting.

Nineteen modules take locks; a deadlock needs only two of them to
disagree about order once, under load, on a path no test drives.  This
pass extracts a conservative *lock-class* graph the way the kernel's
lockdep does — every lock CLASS is its declaration site (an attribute
assigned ``threading.Lock()``/``RLock()``/``Condition()``/``RWLock()``,
or a module-global), and an edge A→B means "somewhere, B is acquired
while A is held".  A cycle in that graph is a potential ABBA deadlock.

Scope (kept deliberately conservative so a cycle report is credible):

- ``with`` nesting inside one function body, including multi-item
  ``with a, b:`` forms and locks reached through local aliases
  (``bl = self._build_locks.setdefault(...)`` / ``with bl:``);
- ``self.method()`` calls made while a lock is held propagate the
  callee's acquisitions (fixpoint over same-class methods, plus
  module-level functions for bare calls);
- ``obj.attr`` locks resolve when the attribute name maps to exactly
  one declared lock class in the package (e.g. ``srv._engine_lock``);
  ambiguous names are dropped, not guessed.

Cross-object call chains (scheduler → engine → arena) are exactly what
the static pass CANNOT see — the runtime witness recorder
(:mod:`.witness`), armed for the whole tier-1 run, covers those with
observed acquisition orders.  The two are a pair, not alternatives.

RLock self-nesting is legal and skipped; a self-edge on a plain Lock or
Condition is reported as a finding (it would self-deadlock).
"""

from __future__ import annotations

import ast
import re
from collections import defaultdict
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from dgraph_tpu.analysis.framework import iter_py_files

_LOCK_CTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "RWLock": "RWLock",
    "Lock": "Lock",
    "RLock": "RLock",
    "Condition": "Condition",
}


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _lock_ctor_kind(node: ast.AST) -> Optional[str]:
    """'Lock'/'RLock'/... when ``node`` constructs a lock, else None."""
    if isinstance(node, ast.Call):
        return _LOCK_CTORS.get(_dotted(node.func))
    return None


@dataclass
class LockClass:
    name: str      # canonical: module.Class.attr / module.attr
    kind: str      # Lock | RLock | Condition | RWLock
    site: str      # path:line of the declaration


@dataclass
class Edge:
    src: str
    dst: str
    site: str      # path:line of the inner acquisition
    via: str = ""  # call chain note, "" for direct nesting


@dataclass
class LockGraph:
    classes: Dict[str, LockClass] = field(default_factory=dict)
    edges: Dict[Tuple[str, str], Edge] = field(default_factory=dict)
    self_nesting: List[Edge] = field(default_factory=list)

    def add_edge(self, src: str, dst: str, site: str, via: str = "") -> None:
        if src == dst:
            kind = self.classes.get(src, LockClass(src, "Lock", site)).kind
            if kind != "RLock":
                self.self_nesting.append(Edge(src, dst, site, via))
            return
        self.edges.setdefault((src, dst), Edge(src, dst, site, via))

    def cycles(self) -> List[List[Edge]]:
        """Elementary cycles via DFS over the edge set (the graph is
        tiny — tens of nodes)."""
        adj: Dict[str, List[Edge]] = defaultdict(list)
        for e in self.edges.values():
            adj[e.src].append(e)
        out: List[List[Edge]] = []
        seen_cycles: Set[Tuple[str, ...]] = set()

        def dfs(node: str, path: List[Edge], on_path: Dict[str, int]):
            for e in adj[node]:
                if e.dst in on_path:
                    cyc = path[on_path[e.dst]:] + [e]
                    key = tuple(sorted(x.src for x in cyc))
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        out.append(cyc)
                    continue
                on_path[e.dst] = len(path) + 1
                dfs(e.dst, path + [e], on_path)
                del on_path[e.dst]

        for start in list(adj):
            dfs(start, [], {start: 0})
        return out

    def render(self) -> str:
        lines = [f"lock classes: {len(self.classes)}, edges: {len(self.edges)}"]
        for e in sorted(self.edges.values(), key=lambda e: (e.src, e.dst)):
            via = f"  (via {e.via})" if e.via else ""
            lines.append(f"  {e.src} -> {e.dst}  [{e.site}]{via}")
        return "\n".join(lines)


# -- extraction -------------------------------------------------------------

class _FileInfo:
    def __init__(self, path: str, tree: ast.AST, module: str):
        self.path = path
        self.tree = tree
        self.module = module


def _module_name(f: Path, base: Path) -> str:
    try:
        rel = f.resolve().relative_to(base.resolve())
    except ValueError:
        rel = Path(f.name)
    return ".".join(rel.with_suffix("").parts)


def build_lock_graph(
    roots: Iterable[str],
    repo_root: Optional[str] = None,
    exclude: Sequence[str] = (),
) -> LockGraph:
    base = Path(repo_root) if repo_root else Path(".")
    files: List[_FileInfo] = []
    for f in iter_py_files(roots, exclude=exclude):
        try:
            tree = ast.parse(f.read_text(encoding="utf-8"))
        except SyntaxError:
            continue
        rel = f.as_posix()
        try:
            rel = f.resolve().relative_to(base.resolve()).as_posix()
        except ValueError:
            pass
        files.append(_FileInfo(rel, tree, _module_name(f, base)))

    g = LockGraph()
    # attr name -> set of canonical names (for obj.attr resolution)
    by_attr: Dict[str, Set[str]] = defaultdict(set)
    # (module, class|None, attr) -> canonical
    exact: Dict[Tuple[str, Optional[str], str], str] = {}

    for fi in files:
        _collect_classes(fi, g, by_attr, exact)
    for fi in files:
        _collect_edges(fi, g, by_attr, exact)
    return g


def _collect_classes(fi, g, by_attr, exact) -> None:
    def declare(cls: Optional[str], attr: str, kind: str, lineno: int):
        name = f"{fi.module}.{cls}.{attr}" if cls else f"{fi.module}.{attr}"
        if name not in g.classes:
            g.classes[name] = LockClass(name, kind, f"{fi.path}:{lineno}")
        by_attr[attr].add(name)
        exact[(fi.module, cls, attr)] = name

    for node in ast.walk(fi.tree):
        if isinstance(node, ast.ClassDef):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    kind = _lock_ctor_kind(sub.value)
                    if kind is None:
                        continue
                    for t in sub.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            declare(node.name, t.attr, kind, sub.lineno)
                        elif isinstance(t, ast.Name):
                            declare(node.name, t.id, kind, sub.lineno)
    for node in fi.tree.body if isinstance(fi.tree, ast.Module) else []:
        if isinstance(node, ast.Assign):
            kind = _lock_ctor_kind(node.value)
            if kind is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    declare(None, t.id, kind, node.lineno)


def _strip_rw(expr: ast.AST) -> ast.AST:
    """``x.read()`` / ``x.write()`` (RWLock context managers) → ``x``."""
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr in ("read", "write")
        and not expr.args
    ):
        return expr.func.value
    return expr


class _FuncAcq:
    """Per-function facts: directly-acquired locks, locks acquired while
    holding each lock, and calls made while holding each lock."""

    def __init__(self, qual: str):
        self.qual = qual                       # module.Class.meth / module.fn
        self.acquires: Set[str] = set()        # any acquisition in body
        self.nested: List[Tuple[str, str, str]] = []   # (held, inner, site)
        self.calls_under: List[Tuple[str, str, str]] = []  # (held, callee, site)


def _collect_edges(fi, g, by_attr, exact) -> None:
    funcs: Dict[str, _FuncAcq] = {}

    def resolve(expr: ast.AST, cls: Optional[str], aliases) -> Optional[str]:
        expr = _strip_rw(expr)
        if isinstance(expr, ast.Attribute):
            base = expr.value
            attr = expr.attr
            if isinstance(base, ast.Name) and base.id == "self" and cls:
                hit = exact.get((fi.module, cls, attr))
                if hit:
                    return hit
            cands = by_attr.get(attr, set())
            if len(cands) == 1:
                return next(iter(cands))
            return None
        if isinstance(expr, ast.Name):
            if expr.id in aliases:
                return aliases[expr.id]
            hit = exact.get((fi.module, None, expr.id))
            if hit:
                return hit
            cands = by_attr.get(expr.id, set())
            if len(cands) == 1:
                return next(iter(cands))
        return None

    def lock_alias_value(v: ast.AST, cls: Optional[str]) -> Optional[str]:
        """An expression that *produces* a lock: dict-held per-key locks
        (``d.setdefault(k, threading.Lock())``) become the synthetic
        class ``module.Class.dictattr[]``."""
        if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute):
            if v.func.attr in ("setdefault", "get") and len(v.args) >= 2:
                kind = _lock_ctor_kind(v.args[1])
                if kind is not None:
                    d = v.func.value
                    if (
                        isinstance(d, ast.Attribute)
                        and isinstance(d.value, ast.Name)
                        and d.value.id == "self"
                    ):
                        name = f"{fi.module}.{cls}.{d.attr}[]"
                        if name not in g.classes:
                            g.classes[name] = LockClass(
                                name, kind, f"{fi.path}:{v.lineno}"
                            )
                        by_attr.setdefault(d.attr, set()).add(name)
                        return name
        if _lock_ctor_kind(v) is not None:
            name = f"{fi.module}.<local>:{v.lineno}"
            g.classes.setdefault(
                name, LockClass(name, _lock_ctor_kind(v), f"{fi.path}:{v.lineno}")
            )
            return name
        return None

    def walk_fn(fn: ast.AST, qual: str, cls: Optional[str]) -> None:
        fa = funcs.setdefault(qual, _FuncAcq(qual))
        aliases: Dict[str, str] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.targets[0], ast.Name
            ):
                got = lock_alias_value(node.value, cls)
                if got:
                    aliases[node.targets[0].id] = got

        def visit(stmts, held: List[str]) -> None:
            for st in stmts:
                if isinstance(st, ast.With):
                    acquired: List[str] = []
                    for item in st.items:
                        lk = resolve(item.context_expr, cls, aliases)
                        if lk is not None:
                            fa.acquires.add(lk)
                            site = f"{fi.path}:{st.lineno}"
                            for h in held + acquired:
                                fa.nested.append((h, lk, site))
                            acquired.append(lk)
                    visit(st.body, held + acquired)
                    continue
                if held:
                    # walk WITHOUT descending into nested defs/lambdas:
                    # a closure defined under the lock runs later,
                    # possibly without it — attributing its calls here
                    # would fabricate phantom edges (same scope
                    # discipline as WallClockDuration._walk_scope)
                    stack = [st]
                    while stack:
                        sub = stack.pop()
                        if isinstance(
                            sub,
                            (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda),
                        ):
                            continue
                        if isinstance(sub, ast.Call):
                            callee = _callee_qual(sub, fi.module, cls)
                            if callee:
                                site = f"{fi.path}:{sub.lineno}"
                                for h in held:
                                    fa.calls_under.append((h, callee, site))
                        stack.extend(ast.iter_child_nodes(sub))
                # containers that carry nested statements
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(st, attr, None)
                    if sub and not isinstance(st, ast.With):
                        visit(sub, held)
                for h in getattr(st, "handlers", []) or []:
                    visit(h.body, held)

        visit(fn.body, [])

    def _callee_qual(call: ast.Call, module: str, cls: Optional[str]):
        f = call.func
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "self"
            and cls
        ):
            return f"{module}.{cls}.{f.attr}"
        if isinstance(f, ast.Name):
            return f"{module}.{f.id}"
        return None

    for node in fi.tree.body if isinstance(fi.tree, ast.Module) else []:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walk_fn(node, f"{fi.module}.{node.name}", None)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    walk_fn(
                        sub, f"{fi.module}.{node.name}.{sub.name}", node.name
                    )

    # direct nesting edges
    for fa in funcs.values():
        for held, inner, site in fa.nested:
            g.add_edge(held, inner, site)

    # call propagation: transitive acquires per function (fixpoint),
    # then held-lock -> callee's acquires
    callees: Dict[str, Set[str]] = defaultdict(set)
    for fa in funcs.values():
        for _h, callee, _s in fa.calls_under:
            callees[fa.qual].add(callee)
        # also propagate through calls made while NOT holding: they
        # matter only for computing transitive acquire sets
    trans: Dict[str, Set[str]] = {q: set(fa.acquires) for q, fa in funcs.items()}
    changed = True
    while changed:
        changed = False
        for q, cs in callees.items():
            for c in cs:
                extra = trans.get(c, set()) - trans[q]
                if extra:
                    trans[q] |= extra
                    changed = True
    for fa in funcs.values():
        for held, callee, site in fa.calls_under:
            for lk in trans.get(callee, ()):  # callee's (transitive) locks
                g.add_edge(held, lk, site, via=callee)


# -- thread-entry discovery -------------------------------------------------
#
# One entry model shared by the static passes: the lock-order graph and
# the thread-escape pass (analysis/escape.py) must agree about *which*
# functions run on their own thread, or the two reports contradict each
# other.  An entry is any function handed to the threading runtime:
#
#   threading.Thread(target=self.x) / Thread(target=fn)   kind="thread"
#   <tracked executor>.submit(fn, ...)                    kind="executor"
#   threading.Timer(t, fn)                                kind="timer"
#   do_GET/do_POST on a *RequestHandler class             kind="conn-handler"
#   public methods on a *Servicer class                   kind="handler"
#   # graftlint: thread-entry   (on/above the def line)   kind="pragma"
#
# ``multi`` means the entry can be live on MORE than one thread at once:
# spawned inside a loop/comprehension, submitted to a pool, or invoked
# per-connection by a server.  Escape analysis counts a multi entry as
# two contexts on its own.
#
# Executor receivers are *tracked*: only ``.submit`` on a local or
# self-attribute that was assigned a ``*PoolExecutor(...)`` counts —
# ``self.hop_merger.submit(...)`` (a plain object with a submit method)
# is not a thread entry and must not be classified as one.

_ENTRY_PRAGMA_RE = re.compile(r"#\s*graftlint:\s*thread-entry\b")
_EXECUTOR_CTOR_RE = re.compile(r"(^|\.)(Thread|Process)PoolExecutor$")


@dataclass(frozen=True)
class ThreadEntry:
    qual: str    # module.Class.meth or module.fn
    site: str    # path:line of the spawn/registration/def site
    kind: str    # thread | executor | timer | handler | pragma
    multi: bool  # can run on >1 thread concurrently


def _is_executor_ctor(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and bool(
        _EXECUTOR_CTOR_RE.search(_dotted(node.func))
    )


def _callable_quals(
    expr: ast.AST, module: str, cls: Optional[str]
) -> List[str]:
    """Resolve a callable expression to qualified name(s).

    ``self.x`` → ``module.Class.x``; a bare name → ``module.name``;
    a lambda resolves to every ``self.meth(...)`` call in its body
    (``target=lambda: self._loop(arg)``).  Unresolvable receivers
    (``srv.serve_forever``) yield nothing — dropped, not guessed.
    """
    if isinstance(expr, ast.Lambda):
        out: List[str] = []
        for sub in ast.walk(expr.body):
            if isinstance(sub, ast.Call):
                q = _call_target_qual(sub.func, module, cls)
                if q:
                    out.append(q)
        return out
    q = _call_target_qual(expr, module, cls)
    return [q] if q else []


def _call_target_qual(
    f: ast.AST, module: str, cls: Optional[str]
) -> Optional[str]:
    if (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Name)
        and f.value.id == "self"
        and cls
    ):
        return f"{module}.{cls}.{f.attr}"
    if isinstance(f, ast.Name):
        return f"{module}.{f.id}"
    return None


def discover_thread_entries(
    tree: ast.AST,
    module: str,
    path: str,
    source_lines: Optional[Sequence[str]] = None,
) -> List[ThreadEntry]:
    """All thread entry points declared in one parsed module."""
    entries: List[ThreadEntry] = []
    lines = source_lines or []

    def has_entry_pragma(lineno: int) -> bool:
        for ln in (lineno, lineno - 1):
            if 1 <= ln <= len(lines) and _ENTRY_PRAGMA_RE.search(lines[ln - 1]):
                return True
        return False

    def scan_callable(fn: ast.AST, cls: Optional[str], exec_attrs: Set[str]):
        """Find spawn sites anywhere in ``fn`` (closures included — a
        Thread started from a nested def still starts)."""
        # locals assigned an executor ctor, incl. `with ...Executor() as ex:`
        exec_locals: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and _is_executor_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        exec_locals.add(t.id)
            elif isinstance(node, ast.With):
                for item in node.items:
                    if _is_executor_ctor(item.context_expr) and isinstance(
                        item.optional_vars, ast.Name
                    ):
                        exec_locals.add(item.optional_vars.id)

        loopy: Set[int] = set()  # id() of Call nodes under a lexical loop

        def mark_loops(node: ast.AST, in_loop: bool) -> None:
            if isinstance(node, ast.Call) and in_loop:
                loopy.add(id(node))
            nxt = in_loop or isinstance(
                node,
                (ast.For, ast.AsyncFor, ast.While,
                 ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp),
            )
            for ch in ast.iter_child_nodes(node):
                mark_loops(ch, nxt)

        mark_loops(fn, False)

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            dq = _dotted(node.func)
            site = f"{path}:{node.lineno}"
            if dq in ("threading.Thread", "Thread"):
                for kw in node.keywords:
                    if kw.arg == "target":
                        for q in _callable_quals(kw.value, module, cls):
                            entries.append(ThreadEntry(
                                q, site, "thread", id(node) in loopy
                            ))
            elif dq in ("threading.Timer", "Timer") and len(node.args) >= 2:
                for q in _callable_quals(node.args[1], module, cls):
                    entries.append(ThreadEntry(q, site, "timer", False))
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "submit"
                and node.args
            ):
                recv = node.func.value
                tracked = (
                    isinstance(recv, ast.Name) and recv.id in exec_locals
                ) or (
                    isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id == "self"
                    and recv.attr in exec_attrs
                )
                if tracked:
                    for q in _callable_quals(node.args[0], module, cls):
                        entries.append(ThreadEntry(q, site, "executor", True))

    def class_executor_attrs(cd: ast.ClassDef) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(cd):
            if isinstance(node, ast.Assign) and _is_executor_ctor(node.value):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        out.add(t.attr)
        return out

    body = tree.body if isinstance(tree, ast.Module) else []
    for node in body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_callable(node, None, set())
            if has_entry_pragma(node.lineno):
                entries.append(ThreadEntry(
                    f"{module}.{node.name}", f"{path}:{node.lineno}",
                    "pragma", True,
                ))
        elif isinstance(node, ast.ClassDef):
            exec_attrs = class_executor_attrs(node)
            base_names = [_dotted(b) for b in node.bases]
            is_http_handler = any("RequestHandler" in b for b in base_names)
            is_servicer = node.name.endswith("Servicer") or any(
                b.endswith("Servicer") for b in base_names
            )
            for sub in node.body:
                if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                qual = f"{module}.{node.name}.{sub.name}"
                scan_callable(sub, node.name, exec_attrs)
                if has_entry_pragma(sub.lineno):
                    entries.append(ThreadEntry(
                        qual, f"{path}:{sub.lineno}", "pragma", True
                    ))
                elif is_http_handler and re.fullmatch(r"do_[A-Z]+", sub.name):
                    # one handler INSTANCE per connection: the methods
                    # run on many threads, but each instance is
                    # single-threaded — escape analysis must not treat
                    # instance attrs of a conn-handler as shared
                    entries.append(ThreadEntry(
                        qual, f"{path}:{sub.lineno}", "conn-handler", True
                    ))
                elif is_servicer and not sub.name.startswith("_"):
                    entries.append(ThreadEntry(
                        qual, f"{path}:{sub.lineno}", "handler", True
                    ))
    return entries


# -- entry ------------------------------------------------------------------

def check_lock_order(
    roots: Iterable[str],
    repo_root: Optional[str] = None,
    exclude: Sequence[str] = (),
) -> Tuple[LockGraph, List[str]]:
    """Returns (graph, problem strings) — problems are cycles and
    self-nesting on non-reentrant locks."""
    g = build_lock_graph(roots, repo_root=repo_root, exclude=exclude)
    problems: List[str] = []
    for cyc in g.cycles():
        chain = " -> ".join(e.src for e in cyc) + f" -> {cyc[-1].dst}"
        sites = ", ".join(e.site for e in cyc)
        problems.append(f"lock-order cycle: {chain}  [{sites}]")
    for e in g.self_nesting:
        problems.append(
            f"self-nesting on non-reentrant lock {e.src} at {e.site} "
            "(would self-deadlock)"
        )
    return g, problems
