"""graftcheck tier 2 — device-program contract checker.

Tier 1 (rules.py / lockorder.py / witness.py / pytest_budget.py) guards
the *Python* that builds programs: no host syncs in traced bodies, no
jits in loops, bounded compile counts.  What it cannot see is the
compiled program itself — and after PR 1 (fused hops), PR 9 (MXU tiles)
and PR 10 (calibrated routes) the engine's correctness-and-speed story
*is* program structure: ``intersect_many`` is fast because its jaxpr
contains no serial ``scan``; ``multi_hop`` is cheap because its carry
buffers are donated and aliased; the program cache is bounded because
two frontiers in one capacity bucket trace byte-identical programs.
Those invariants lived as scattered one-off asserts (``"scan[" not in
…`` greps in bench_ops.py/test_spgemm.py) and one *suppressed* donation
warning (ops/batch.py) — folklore, not contract.

This module makes them enforced:

- **`ProgramContract`**: one registered entry per compiled-kernel
  family.  Each contract builds representative *instances* (the kernel
  traced at small bucketed shapes) and declares its invariants:
  scan/while-freedom, no host callbacks, a dtype discipline (the
  uid-int32 / tile-f32 rule), donated-carry aliasing, implicit-transfer
  freedom under ``jax.transfer_guard``, a cost budget, and bucket-key
  soundness (two raw sizes in one cache bucket must trace the SAME
  program — the recompile-storm bug class, caught statically).
- **Golden fingerprints**: every (contract, instance) pair's normalized
  jaxpr hashes into ``analysis/programs.json``.  Structural drift — a
  rewrite reintroducing a scan, losing donation, widening a dtype —
  fails ``python -m dgraph_tpu.analysis --programs`` (and CI) until the
  change is explicitly re-blessed with ``--update-programs``.
- **Site coverage**: every ``jax.jit`` / ``pl.pallas_call`` construction
  in the package maps to a contract (``covers``) or an explicit
  exemption (``EXEMPT_SITES``, with the WHY); the graftlint rule
  ``unregistered-program-factory`` (rules.py) fails on any factory that
  is neither — a future Pallas kernel lands with a contract, not a hope.

Module import stays lightweight by design (rules.py reads the coverage
table during linting): jax, numpy and the ops modules import lazily
inside the contract builders.

Docs: docs/analysis.md ("Program contracts").  CLI: ``python -m
dgraph_tpu.analysis --programs [--update-programs]``.
"""

from __future__ import annotations

import hashlib
import json
import re
import warnings
from dataclasses import dataclass, field
from functools import partial
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

GOLDENS_PATH = Path(__file__).with_name("programs.json")

# checks run by run_check / check_contract; assert_contract defaults to
# the trace-only subset so benches can call it without paying a compile
STRUCTURE_CHECKS = ("scan", "callback", "dtype")
ALL_CHECKS = STRUCTURE_CHECKS + (
    "golden", "stability", "donation", "transfer", "cost", "bucket",
)

@dataclass
class ProgramInstance:
    """One traced shape of a kernel: the call a real caller would make
    (args already through the caller-side bucketing helpers, so the
    fingerprint covers the shape the program cache actually keys on)."""

    key: str                      # bucket key, e.g. "K4xL64"
    fn: Callable                  # the (usually jit-wrapped) kernel
    args: tuple                   # device-ready positional args
    kwargs: dict = field(default_factory=dict)   # static kwargs
    # per-instance invariant overrides (None = inherit the contract's
    # declaration) — e.g. expand_filter_compact is scan-free until a
    # keep-set brings in member_mask's searchsorted binary search:
    donate: Optional[Tuple[int, ...]] = None
    donate_unused_ok: Tuple[int, ...] = ()
    scan_free: Optional[bool] = None
    dtypes: Optional[frozenset] = None


@dataclass
class BucketProbe:
    """Bucket-key soundness probe: ``make(n)`` builds the instance a
    caller at raw size ``n`` would trace; every pair in ``pairs`` maps
    to one cache bucket and must produce identical arg shapes AND
    identical program fingerprints."""

    pairs: Tuple[Tuple[int, int], ...]
    make: Callable[[int], ProgramInstance]


@dataclass
class ProgramContract:
    name: str
    covers: Tuple[str, ...]        # "<relpath>::<qualname>" factory sites
    build: Callable[[], List[ProgramInstance]]
    scan_free: bool = True         # no lax.scan / lax.while in the jaxpr
    dtypes: frozenset = frozenset({"int32", "bool"})
    donate: Tuple[int, ...] = ()   # flat argnums that must be donated
    donate_unused_ok: Tuple[int, ...] = ()  # donated-but-unaliased OK
    transfer_free: bool = True     # runs under transfer_guard("disallow")
    max_bytes: Optional[int] = None  # cost budget; None = tile budget
    max_flops: Optional[int] = None
    bucket_probe: Optional[BucketProbe] = None
    experimental: bool = False     # registered, not yet load-bearing
    notes: str = ""


@dataclass
class Violation:
    contract: str
    instance: str
    check: str
    message: str

    def render(self) -> str:
        return (
            f"[{self.check}] {self.contract} / {self.instance}: "
            f"{self.message}"
        )


# -- jaxpr introspection ------------------------------------------------------


def _core():
    import jax

    try:
        from jax.extend import core  # newer spellings first
        if hasattr(core, "Jaxpr"):
            return core
    except Exception:  # noqa: BLE001 — version-dependent import surface
        pass
    return jax.core


def _sub_jaxprs(param):
    core = _core()
    out = []

    def rec(x):
        if isinstance(x, core.ClosedJaxpr):
            out.append(x.jaxpr)
        elif isinstance(x, core.Jaxpr):
            out.append(x)
        elif isinstance(x, (tuple, list)):
            for e in x:
                rec(e)

    rec(param)
    return out


def _walk_jaxpr(closed):
    """Yield every (sub-)jaxpr of a ClosedJaxpr, outermost first."""
    stack = [closed.jaxpr]
    while stack:
        j = stack.pop()
        yield j
        for eqn in j.eqns:
            for p in eqn.params.values():
                stack.extend(_sub_jaxprs(p))


def primitive_names(closed) -> Set[str]:
    out: Set[str] = set()
    for j in _walk_jaxpr(closed):
        for eqn in j.eqns:
            out.add(eqn.primitive.name)
    return out


def aval_dtypes(closed) -> Set[str]:
    out: Set[str] = set()
    for j in _walk_jaxpr(closed):
        vs = list(j.constvars) + list(j.invars) + list(j.outvars)
        for eqn in j.eqns:
            vs += list(eqn.invars) + list(eqn.outvars)
        for v in vs:
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "dtype"):
                out.add(str(aval.dtype))
    return out


def _trace(inst: ProgramInstance):
    import jax

    fn = partial(inst.fn, **inst.kwargs) if inst.kwargs else inst.fn
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return jax.make_jaxpr(fn)(*inst.args)


_SRC_LOC = re.compile(r"\S+\.py:\d+(:\d+)?")


def fingerprint_of(closed) -> str:
    """Normalized-jaxpr hash.  str(jaxpr) names variables afresh on
    every pretty-print (a, b, c, …), so the text — and hence the hash —
    is deterministic across processes for an unchanged program.  Source
    locations (pallas_call params carry `file.py:line` provenance) are
    scrubbed: the fingerprint pins program STRUCTURE, and must survive
    a comment edit above the kernel or a different checkout path."""
    norm = _SRC_LOC.sub("<src>", str(closed))
    norm = re.sub(r"\s+", " ", norm.strip())
    return hashlib.sha256(norm.encode()).hexdigest()[:16]


def _arg_shapes(inst: ProgramInstance) -> Tuple[Tuple[str, str], ...]:
    import jax

    leaves = jax.tree_util.tree_leaves(inst.args)
    return tuple(
        (str(getattr(x, "shape", ())), str(getattr(x, "dtype", "?")))
        for x in leaves
    )


# -- lowering-level checks (donation, cost) -----------------------------------


def _lower(inst: ProgramInstance):
    """Lower the instance, silencing JAX's lower-time diagnostics (the
    unusable-donation warning is expected for donate_unused_ok carries;
    donation checks read Lowered.args_info + StableHLO attrs instead —
    the warning only fires on the first lowering of a shape per
    process, so it is NOT a usable signal)."""
    import jax

    fn = inst.fn
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        if hasattr(fn, "lower"):
            return fn.lower(*inst.args, **inst.kwargs)
        return jax.jit(
            partial(fn, **inst.kwargs) if inst.kwargs else fn
        ).lower(*inst.args)


_MAIN_SIG = re.compile(r"func\.func public @main\((.*?)\)\s*->", re.S)


def donation_attrs(lowered_text: str) -> Dict[int, Tuple[bool, bool]]:
    """Per flat-arg index: (aliased via tf.aliasing_output, declared via
    jax.buffer_donor) parsed from the StableHLO main signature."""
    m = _MAIN_SIG.search(lowered_text)
    if not m:
        return {}
    out: Dict[int, Tuple[bool, bool]] = {}
    for p in re.split(r",\s*(?=%arg\d+)", m.group(1)):
        am = re.match(r"%arg(\d+)", p)
        if am:
            out[int(am.group(1))] = (
                "tf.aliasing_output" in p, "jax.buffer_donor" in p,
            )
    return out


def _donated_flags(lowered) -> List[bool]:
    """Per flat-arg donation DECLARATION from Lowered.args_info — the
    authoritative, cache-independent signal (the lower-time warning
    only fires on the first lowering of a shape per process)."""
    import jax

    leaves = jax.tree_util.tree_leaves(
        lowered.args_info, is_leaf=lambda a: hasattr(a, "donated")
    )
    return [bool(getattr(a, "donated", False)) for a in leaves]


def _cost_analysis(lowered) -> Optional[dict]:
    try:
        compiled = lowered.compile()
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — cost_analysis is best-effort per backend
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return ca if isinstance(ca, dict) else None


def _default_max_bytes() -> int:
    # the per-arena densified-tile budget doubles as the "no single
    # checked program may touch more than this at representative
    # shapes" ceiling (DGRAPH_TPU_TILE_BUDGET, docs/deploy.md)
    from dgraph_tpu.utils import planconfig

    return planconfig.tile_budget()


# -- per-contract check driver ------------------------------------------------


def check_contract(
    contract: ProgramContract,
    goldens: Optional[dict] = None,
    checks: Sequence[str] = ALL_CHECKS,
) -> Tuple[List[Violation], Dict[str, str], dict]:
    """Run the selected checks; returns (violations, fingerprints,
    stats).  ``goldens`` is the per-contract {instance_key: hash} dict
    (None = skip the golden compare even if 'golden' is selected)."""
    import jax

    violations: List[Violation] = []
    fingerprints: Dict[str, str] = {}
    stats = {"programs": 0, "bytes": 0.0, "flops": 0.0}

    def bad(inst_key: str, check: str, msg: str) -> None:
        violations.append(Violation(contract.name, inst_key, check, msg))

    for inst in contract.build():
        stats["programs"] += 1
        closed = _trace(inst)
        fp = fingerprint_of(closed)
        fingerprints[inst.key] = fp

        if "stability" in checks and fingerprint_of(_trace(inst)) != fp:
            bad(inst.key, "stability",
                "re-tracing the same instance produced a different "
                "fingerprint — the factory is nondeterministic (clock/"
                "RNG/dict-order leaking into the trace)")

        prims = primitive_names(closed)
        scan_free = (
            inst.scan_free if inst.scan_free is not None
            else contract.scan_free
        )
        if "scan" in checks and scan_free:
            for p in ("scan", "while"):
                if p in prims:
                    bad(inst.key, "scan",
                        f"declared scan/while-free but the jaxpr contains "
                        f"`{p}` — a serial loop re-entered the kernel "
                        "(see ops/sets.py intersect_many for the "
                        "tree-reduction discipline; searchsorted keeps a "
                        "scan even 'unrolled', so a kernel that adds a "
                        "binary search must re-declare)")
        if "callback" in checks:
            for p in ("pure_callback", "io_callback", "debug_callback"):
                if p in prims:
                    bad(inst.key, "callback",
                        f"host callback `{p}` inside a compiled kernel: "
                        "every dispatch would round-trip to Python — "
                        "remove the callback (jax.debug.print included) "
                        "from the production program")
        if "dtype" in checks:
            allowed = inst.dtypes if inst.dtypes is not None else contract.dtypes
            stray = aval_dtypes(closed) - allowed
            if stray:
                bad(inst.key, "dtype",
                    f"dtype(s) {sorted(stray)} off the declared "
                    f"discipline {sorted(allowed)} — an implicit "
                    "promotion (f64 upcast, int→float mean, int64 "
                    "emulation) doubles bytes and falls off the fast "
                    "unit; cast explicitly at the host boundary instead")

        if "golden" in checks and goldens is not None:
            want = goldens.get(inst.key)
            if want is None:
                bad(inst.key, "golden",
                    f"no golden fingerprint recorded for this program "
                    f"(got {fp}); bless it with "
                    "`python -m dgraph_tpu.analysis --update-programs`")
            elif want != fp:
                bad(inst.key, "golden",
                    f"program fingerprint drifted: golden {want}, "
                    f"traced {fp} — the compiled structure changed; "
                    "re-run the contract checks and re-bless with "
                    "--update-programs if intentional")

        donate = inst.donate if inst.donate is not None else contract.donate
        unused_ok = tuple(inst.donate_unused_ok) + tuple(
            contract.donate_unused_ok
        )
        need_lower = (
            ("donation" in checks and donate)
            or "cost" in checks
        )
        if need_lower:
            lowered = _lower(inst)
            if "donation" in checks and donate:
                attrs = donation_attrs(lowered.as_text())
                flags = _donated_flags(lowered)
                for argnum in donate:
                    aliased, declared = attrs.get(argnum, (False, False))
                    donated = bool(
                        flags[argnum]
                    ) if argnum < len(flags) else False
                    if not donated:
                        # args_info.donated is the declaration itself
                        # (cache-independent, unlike the lower-time
                        # warning) — losing it means every call now
                        # allocates a fresh carry
                        bad(inst.key, "donation",
                            f"flat arg {argnum} is no longer donated "
                            "(lowered args_info.donated is False) — "
                            "the donate_argnums declaration was lost")
                    elif argnum in unused_ok:
                        pass  # declared, legitimately unaliased carry
                    elif not aliased and not declared:
                        # single-device programs pin the alias pair
                        # statically (tf.aliasing_output) and DROP the
                        # attribute entirely when the donation is
                        # unusable; sharded (shard_map/pjit) programs
                        # instead mark jax.buffer_donor and leave the
                        # pairing to XLA buffer assignment — either
                        # attr means the buffer is reusable, a bare
                        # %arg means the donation was lost
                        bad(inst.key, "donation",
                            f"flat arg {argnum} is donated but NOT "
                            "aliased to any output (no "
                            "tf.aliasing_output / jax.buffer_donor "
                            "attr) — XLA cannot reuse the buffer "
                            "(shape/dtype mismatch with every "
                            "output); fix the carry layout or declare "
                            "it donate_unused_ok with the why")
            if "cost" in checks:
                ca = _cost_analysis(lowered)
                if ca is not None:
                    b = float(ca.get("bytes accessed", 0.0))
                    fl = float(ca.get("flops", 0.0))
                    stats["bytes"] += b
                    stats["flops"] += fl
                    cap_b = (
                        contract.max_bytes
                        if contract.max_bytes is not None
                        else _default_max_bytes()
                    )
                    if b > cap_b:
                        bad(inst.key, "cost",
                            f"program touches {b:.0f} bytes, over the "
                            f"contract budget of {cap_b} — a "
                            "representative-shape program outgrew its "
                            "tile/HBM envelope (densified operand? "
                            "accidental broadcast?)")
                    if (
                        contract.max_flops is not None
                        and fl > contract.max_flops
                    ):
                        bad(inst.key, "cost",
                            f"program costs {fl:.0f} flops, over the "
                            f"contract budget of {contract.max_flops}")

        if "transfer" in checks and contract.transfer_free:
            try:
                import jax.numpy as jnp

                # fresh device copies OUTSIDE the guard: donation-bearing
                # programs consume their carry buffers, and instances of
                # one contract may share fixture arrays
                dargs = jax.tree_util.tree_map(
                    lambda a: jnp.array(a) if hasattr(a, "dtype") else a,
                    inst.args,
                )
                fn = inst.fn
                if not hasattr(fn, "lower"):
                    # bare Python fns would run eagerly, where even a
                    # `x + 1` constant is an implicit transfer — the
                    # contract is about the COMPILED program
                    fn = jax.jit(partial(fn, **inst.kwargs))
                    kwargs = {}
                else:
                    kwargs = inst.kwargs
                with jax.transfer_guard("disallow"):
                    out = fn(*dargs, **kwargs)
                jax.block_until_ready(out)
            except Exception as e:  # noqa: BLE001 — guard raises backend-specific types
                bad(inst.key, "transfer",
                    "implicit host<->device transfer (or failure) while "
                    "running the program on device_put-staged args under "
                    f"jax.transfer_guard('disallow'): {e}")

    if "bucket" in checks and contract.bucket_probe is not None:
        probe = contract.bucket_probe
        for n1, n2 in probe.pairs:
            i1, i2 = probe.make(n1), probe.make(n2)
            if _arg_shapes(i1) != _arg_shapes(i2):
                bad(f"bucket({n1},{n2})", "bucket",
                    f"raw sizes {n1} and {n2} share a cache bucket but "
                    "trace DIFFERENT arg shapes — the factory keys on "
                    "the raw size, so every frontier wiggle compiles a "
                    "fresh program (recompile storm); bucket before "
                    "padding (ops/sets.py bucket/bucket_fine)")
            elif fingerprint_of(_trace(i1)) != fingerprint_of(_trace(i2)):
                bad(f"bucket({n1},{n2})", "bucket",
                    f"raw sizes {n1} and {n2} share a cache bucket and "
                    "arg shapes but trace different programs — a "
                    "non-shape value (the raw size itself?) leaked into "
                    "the trace as a static argument")

    return violations, fingerprints, stats


def assert_contract(
    name: str, checks: Sequence[str] = STRUCTURE_CHECKS
) -> None:
    """Single-source-of-truth entry for benches/tests that used to
    hand-grep jaxprs: run the registered contract's (default:
    trace-only) checks and raise AssertionError on any violation."""
    violations, _, _ = check_contract(REGISTRY[name], checks=checks)
    if violations:
        raise AssertionError(
            f"program contract {name!r} violated:\n"
            + "\n".join("  " + v.render() for v in violations)
        )


# -- goldens ------------------------------------------------------------------


def load_goldens(path: Optional[Path] = None) -> dict:
    p = Path(path) if path else GOLDENS_PATH
    if not p.exists():
        return {}
    return json.loads(p.read_text()).get("programs", {})


def write_goldens(fingerprints: dict, path: Optional[Path] = None) -> None:
    import jax

    p = Path(path) if path else GOLDENS_PATH
    payload = {
        "comment": [
            "Golden program fingerprints per (kernel contract, bucketed",
            "shape): sha256[:16] of the normalized jaxpr.  Structural",
            "drift (a reintroduced scan, lost donation, widened dtype,",
            "changed fusion) fails `python -m dgraph_tpu.analysis",
            "--programs`; re-bless an INTENTIONAL change with",
            "`--update-programs` after the contract checks pass.",
        ],
        "jax": jax.__version__,
        "programs": {
            k: dict(sorted(v.items()))
            for k, v in sorted(fingerprints.items())
        },
    }
    p.write_text(json.dumps(payload, indent=2) + "\n")


def collect_fingerprints(
    registry: Optional[Dict[str, ProgramContract]] = None,
) -> Dict[str, Dict[str, str]]:
    """Trace every registered instance (no lowering/compiling) and
    return {contract: {instance_key: fingerprint}}."""
    reg = REGISTRY if registry is None else registry
    out: Dict[str, Dict[str, str]] = {}
    for name in sorted(reg):
        _, fps, _ = check_contract(reg[name], checks=())
        out[name] = fps
    return out


# -- CLI driver ---------------------------------------------------------------


def run_check(
    registry: Optional[Dict[str, ProgramContract]] = None,
    goldens_path: Optional[Path] = None,
    update: bool = False,
    checks: Sequence[str] = ALL_CHECKS,
    echo: Callable[[str], None] = print,
) -> int:
    """The ``--programs`` entry point: check every registered contract
    against its declared invariants and the golden fingerprints.
    ``update`` re-blesses the goldens (after the non-golden checks still
    pass — a broken program cannot be blessed into the contract)."""
    reg = REGISTRY if registry is None else registry
    goldens = load_goldens(goldens_path)
    active = tuple(c for c in checks if not (update and c == "golden"))
    all_violations: List[Violation] = []
    all_fps: Dict[str, Dict[str, str]] = {}
    n_programs = 0
    for name in sorted(reg):
        contract = reg[name]
        # an absent goldens file / contract entry means every
        # fingerprint is "missing" — a failure to bless, never a skip
        violations, fps, stats = check_contract(
            contract, goldens=goldens.get(name, {}), checks=active
        )
        all_violations.extend(violations)
        all_fps[name] = fps
        n_programs += stats["programs"]
        tag = " [experimental]" if contract.experimental else ""
        status = "ok" if not violations else f"{len(violations)} violation(s)"
        echo(
            f"  {name:32s} {stats['programs']:2d} program(s)  "
            f"{status}{tag}"
        )
    if "golden" in active:
        # the compare must be bidirectional: a golden with no traced
        # program behind it (instance renamed/removed, contract
        # deleted) is dead weight masquerading as a blessed review
        for name in sorted(goldens):
            traced = all_fps.get(name)
            if traced is None:
                all_violations.append(Violation(
                    name, "*", "golden",
                    "goldens carry a contract that is no longer "
                    "registered — remove it via --update-programs",
                ))
                continue
            for key in sorted(set(goldens[name]) - set(traced)):
                all_violations.append(Violation(
                    name, key, "golden",
                    "orphaned golden fingerprint: no registered "
                    "instance traces this key anymore — re-bless with "
                    "--update-programs to drop it",
                ))
    n_contracts = sum(1 for c in reg.values() if not c.experimental)
    n_exp = len(reg) - n_contracts
    for v in all_violations:
        echo(v.render())
    if all_violations:
        echo(
            f"programs: {len(all_violations)} contract violation(s) "
            f"across {n_programs} traced programs"
        )
        return 1
    if update:
        write_goldens(all_fps, goldens_path)
        echo(
            f"programs: blessed {n_programs} fingerprints from "
            f"{n_contracts} contracts (+{n_exp} experimental) into "
            f"{goldens_path or GOLDENS_PATH}"
        )
        return 0
    echo(
        f"programs: clean — {n_contracts} contracts "
        f"(+{n_exp} experimental), {n_programs} programs traced, "
        "fingerprints match goldens"
    )
    return 0


# ============================================================================
# The registry: one contract per compiled-kernel family.
# Builders import jax/numpy/ops lazily so importing this module (the
# lint rule does, per file) costs nothing.
# ============================================================================


def _jnp():
    import jax.numpy as jnp
    import numpy as np

    return jnp, np


def _small_csr():
    """Shared fixture: an 8-row CSR over a 16-uid universe, mixed
    degrees (0..4), host + device forms."""
    jnp, np = _jnp()
    deg = np.array([2, 3, 0, 4, 1, 0, 3, 3], np.int64)
    h_offsets = np.zeros(9, np.int64)
    np.cumsum(deg, out=h_offsets[1:])
    h_dst = (np.arange(h_offsets[-1], dtype=np.int32) * 5) % 16
    # ascending within each row (the arena invariant)
    for i in range(8):
        lo, hi = int(h_offsets[i]), int(h_offsets[i + 1])
        h_dst[lo:hi] = np.sort(h_dst[lo:hi])
    h_src = np.arange(8, dtype=np.int64)
    return (
        h_src, h_offsets, h_dst,
        jnp.asarray(h_offsets.astype(np.int32)), jnp.asarray(h_dst),
    )


def _sets_mat(k: int, length: int):
    jnp, np = _jnp()
    from dgraph_tpu.ops import sets

    return jnp.asarray(
        np.stack([
            sets.pad_to(np.arange(i, i + 5), length) for i in range(k)
        ])
    )


def _b_intersect_many() -> List[ProgramInstance]:
    from dgraph_tpu.ops import sets

    return [
        ProgramInstance(
            f"K{k}xL{l}", sets.intersect_many, (_sets_mat(k, l),)
        )
        for k, l in ((2, 64), (5, 64), (8, 128))
    ]


def _b_union_many() -> List[ProgramInstance]:
    from dgraph_tpu.ops import sets

    return [
        ProgramInstance(f"K{k}xL{l}", sets.union_many, (_sets_mat(k, l),))
        for k, l in ((2, 64), (6, 64))
    ]


def _b_set_algebra() -> List[ProgramInstance]:
    jnp, np = _jnp()
    from dgraph_tpu.ops import sets

    a = jnp.asarray(sets.pad_to(np.arange(0, 20, 2), 64))
    b = jnp.asarray(sets.pad_to(np.arange(0, 30, 3), 64))
    src = jnp.asarray(np.arange(0, 32, 2, dtype=np.int32))
    return [
        ProgramInstance("intersect_L64", sets.intersect, (a, b)),
        ProgramInstance("union_L64", sets.union, (a, b)),
        ProgramInstance("difference_L64", sets.difference, (a, b)),
        ProgramInstance("member_mask_L64", sets.member_mask, (a, b)),
        ProgramInstance("sort_unique_L64", sets.sort_unique, (a,)),
        ProgramInstance("rows_of_L64", sets.rows_of, (src, a)),
        ProgramInstance(
            "range_rows_C64", sets.range_rows,
            (jnp.int32(3), jnp.int32(9)), {"cap": 64},
        ),
        ProgramInstance(
            "unique_dense_U256", sets.unique_dense, (a,),
            {"n_universe": 256, "cap": 64},
        ),
        ProgramInstance("unique_rows_L64", sets.unique_rows_sorted, (a,)),
    ]


def _csr_expand_inst(n_rows: int, raw_cap: int) -> ProgramInstance:
    jnp, np = _jnp()
    from dgraph_tpu.ops import sets

    _, _, _, offsets, dst = _small_csr()
    rows = jnp.asarray(
        sets.pad_rows(
            np.arange(min(n_rows, 8), dtype=np.int64), sets.bucket(n_rows)
        )
    )
    cap = sets.bucket(raw_cap)
    return ProgramInstance(
        f"R{sets.bucket(n_rows)}xC{cap}", sets.expand_csr,
        (offsets, dst, rows), {"cap": cap},
    )


def _b_expand_csr() -> List[ProgramInstance]:
    return [_csr_expand_inst(4, 16), _csr_expand_inst(8, 32)]


def _inline_layout():
    """Small but real inline-head layout (ops/sets.py expand_inline
    docstring): 8 rows, three of them with overflow chunks."""
    jnp, np = _jnp()
    from dgraph_tpu.ops.sets import INLINE, SENT

    degs = [3, 10, 0, 20, 2, 0, 9, 1]
    metap = np.zeros((8, 8), np.int32)
    chunks: list = []
    for i, d in enumerate(degs):
        targets = np.arange(i, i + d, dtype=np.int32)
        head = np.full(INLINE, SENT, np.int32)
        head[: min(d, INLINE)] = targets[: min(d, INLINE)]
        ov = targets[INLINE:]
        metap[i, 0] = len(chunks)
        metap[i, 1] = d
        metap[i, 2:] = head
        for c in range(-(-max(0, d - INLINE) // 8)):
            ch = np.full(8, SENT, np.int32)
            seg = ov[c * 8: (c + 1) * 8]
            ch[: len(seg)] = seg
            chunks.append(ch)
    ovc = np.stack(chunks) if chunks else np.full((1, 8), SENT, np.int32)
    return jnp.asarray(metap), jnp.asarray(ovc)


def _b_expand_inline() -> List[ProgramInstance]:
    jnp, np = _jnp()
    from dgraph_tpu.ops import sets

    metap, ovc = _inline_layout()
    # grouped: overflow rows [1, 3, 6] form the ascending prefix
    grouped = jnp.asarray(
        np.array([1, 3, 6, -1, 0, 4, 7, -1], np.int32)
    )
    anyorder = jnp.asarray(np.array([0, 1, 3, 4, 6, 7, -1, -1], np.int32))
    # chunked layout twin: meta8 lanes (chunk_start, chunk_count, degree)
    meta8 = np.zeros((8, 8), np.int32)
    degs = np.asarray(metap)[:, 1]
    cstart = 0
    for i, d in enumerate(degs):
        cc = -(-int(d) // sets.CHUNK)
        meta8[i, :3] = (cstart, cc, int(d))
        cstart += cc
    chunk_dst = jnp.asarray(
        np.full((max(cstart, 1), sets.CHUNK), sets.SENT, np.int32)
    )
    return [
        ProgramInstance(
            "grouped_B8xP4xC8", sets.expand_inline_grouped,
            (metap, ovc, grouped), {"capc": 8, "pcap": 4},
        ),
        ProgramInstance(
            "seg_B8xC8", sets.expand_inline_seg,
            (metap, ovc, anyorder), {"capc": 8},
        ),
        ProgramInstance(
            "chunked_B8xC8", sets.expand_chunked,
            (jnp.asarray(meta8), chunk_dst, anyorder),
            {"capc": 8, "with_seg": True},
        ),
    ]


def _b_batched_set_ops() -> List[ProgramInstance]:
    jnp, np = _jnp()
    from dgraph_tpu.ops import batch, sets

    a = jnp.asarray(
        np.stack([sets.pad_to(np.arange(i, i + 6), 64) for i in range(4)])
    )
    b = jnp.asarray(
        np.stack([sets.pad_to(np.arange(0, 12, 2), 64)] * 4)
    )
    m3 = jnp.asarray(
        np.stack([np.stack([sets.pad_to(np.arange(3), 32)] * 3)] * 4)
    )
    return [
        ProgramInstance("intersect_B4xL64", batch.intersect_batch, (a, b)),
        ProgramInstance("difference_B4xL64", batch.difference_batch, (a, b)),
        ProgramInstance("union_many_B4xK3xL32", batch.union_many_batch, (m3,)),
        ProgramInstance("member_mask_B4xL64", batch.member_mask_batch, (a, b)),
        ProgramInstance("sort_unique_B4xL64", batch.sort_unique_batch, (a,)),
    ]


def _ascending_inst(n_rows: int, raw_cap: int) -> ProgramInstance:
    jnp, np = _jnp()
    from dgraph_tpu.ops import batch, sets

    _, _, _, offsets, dst = _small_csr()
    rows = jnp.asarray(
        sets.pad_rows(
            np.arange(min(n_rows, 8), dtype=np.int64), sets.bucket(n_rows)
        )
    )
    cap = sets.bucket(raw_cap)
    return ProgramInstance(
        f"R{sets.bucket(n_rows)}xC{cap}", batch.expand_ascending,
        (offsets, dst, rows), {"cap": cap},
    )


def _b_expand_ascending() -> List[ProgramInstance]:
    return [_ascending_inst(4, 16), _ascending_inst(8, 32)]


def _b_expand_filter_compact() -> List[ProgramInstance]:
    jnp, np = _jnp()
    from dgraph_tpu.ops import batch, sets

    _, _, _, offsets, dst = _small_csr()
    keep = jnp.asarray(sets.pad_to(np.arange(0, 16, 2), 32))
    rows1 = jnp.asarray(sets.pad_rows(np.arange(4, dtype=np.int64), 8))
    rowsb = jnp.asarray(
        np.stack([sets.pad_rows(np.arange(4, dtype=np.int64), 8)] * 4)
    )
    return [
        # keep-bearing instances re-declare: the fused member_mask is a
        # searchsorted (log-depth scan + uint32 carry, see _SS_NOTE)
        ProgramInstance(
            "fused_R8xC32xF1", batch.expand_filter_compact,
            (offsets, dst, rows1), {"cap": 32, "keeps": (keep,)},
            scan_free=False, dtypes=_INT_SS,
        ),
        ProgramInstance(
            "fused_R8xC32xF0xO16", batch.expand_filter_compact,
            (offsets, dst, rows1), {"cap": 32, "keeps": (), "cap_out": 16},
        ),
        ProgramInstance(
            "batch_B4xR8xC32", batch._effc_batch,
            (offsets, dst, rowsb), {"cap": 32, "keeps": (keep,),
                                    "cap_out": None},
            scan_free=False, dtypes=_INT_SS,
        ),
    ]


def _b_multi_hop() -> List[ProgramInstance]:
    jnp, np = _jnp()
    from dgraph_tpu.ops import batch, sets

    _, _, _, offsets, dst = _small_csr()
    f = jnp.asarray(sets.pad_to(np.array([0, 1, 3]), 32))
    vis = jnp.asarray(np.full(32, sets.SENT, np.int32))
    lut = jnp.asarray(
        sets.pad_rows(np.arange(8, dtype=np.int64), 16)
    )
    return [
        # track_visited=False leaves the donated visited carry (flat arg
        # 3) untouched — donated but legitimately unaliased.  This is
        # the contract behind ops/batch.py's scoped warning handling.
        ProgramInstance(
            "H2xC32_novisited", batch._multi_hop_jit,
            (offsets, dst, f, vis),
            {"n_hops": 2, "cap": 32, "track_visited": False, "lut": None},
            donate_unused_ok=(3,),
        ),
        ProgramInstance(
            "H3xC32_visited", batch._multi_hop_jit,
            (offsets, dst, f, vis),
            {"n_hops": 3, "cap": 32, "track_visited": True, "lut": lut},
        ),
        # PR 18 segmented variants: the per-segment program the
        # segment loop dispatches at k=1 — the same _multi_hop_jit
        # bucketed on n_hops, so the bucket key stays sound over k and
        # the donated carry contract holds segment-to-segment.
        ProgramInstance(
            "H1xC32_seg", batch._multi_hop_jit,
            (offsets, dst, f, vis),
            {"n_hops": 1, "cap": 32, "track_visited": False, "lut": None},
            donate_unused_ok=(3,),
        ),
        ProgramInstance(
            "H1xC32_seg_visited", batch._multi_hop_jit,
            (offsets, dst, f, vis),
            {"n_hops": 1, "cap": 32, "track_visited": True, "lut": lut},
        ),
    ]


def _b_mesh_multi_hop() -> List[ProgramInstance]:
    jnp, np = _jnp()
    import jax

    from dgraph_tpu.mesh.programs import mesh_multi_hop_step
    from dgraph_tpu.ops import sets
    from dgraph_tpu.parallel.mesh import make_mesh, shard_arena_rows

    if len(jax.devices()) < 8:
        raise RuntimeError(
            "the mesh.multi_hop contract builds an 8-wide Mesh; run "
            "under XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "(the analysis CLI injects this itself when the backend is "
            "uninitialized, and tests/conftest.py forces it for the "
            "whole suite)"
        )
    mesh = make_mesh(8, data=1)
    h_src, h_offsets, h_dst, _, _ = _small_csr()
    sa = shard_arena_rows(h_src, h_offsets, h_dst, 8)
    f32 = jnp.asarray(sets.pad_to(np.array([0, 1, 3], np.int64), 32))
    f64 = jnp.asarray(sets.pad_to(np.array([0, 1, 3], np.int64), 64))
    return [
        ProgramInstance(
            "H2xC32", mesh_multi_hop_step(mesh, 32, 2),
            (sa.src, sa.offsets, sa.dst, f32), {},
        ),
        ProgramInstance(
            "H3xC64", mesh_multi_hop_step(mesh, 64, 3),
            (sa.src, sa.offsets, sa.dst, f64), {},
        ),
        # PR 18 segmented variant: the one-hop step the mesh segment
        # loop dispatches at k=1 (mesh_multi_hop_step's lru_cache
        # bounds the per-k executables).
        ProgramInstance(
            "H1xC32_seg", mesh_multi_hop_step(mesh, 32, 1),
            (sa.src, sa.offsets, sa.dst, f32), {},
        ),
    ]


def _classed() -> tuple:
    jnp, np = _jnp()
    from dgraph_tpu.ops import batch

    h_src, h_offsets, h_dst, offsets, dst = _small_csr()
    ce = batch.ClassedExpander(offsets, dst, h_offsets)
    rows = np.arange(8, dtype=np.int64)
    counts, n_heavy, heavy_edges = ce.class_counts(rows)
    caps = ce.plan_caps(counts, n_heavy, heavy_edges, fine=False)
    mats, _pos = ce.partition(rows, caps)
    return ce, caps, tuple(jnp.asarray(m) for m in mats)


def _b_classed_expander() -> List[ProgramInstance]:
    ce, caps, mats = _classed()
    return [
        ProgramInstance(
            f"materialize_{'x'.join(str(c) for c in caps)}",
            ce.program(caps, mode="materialize"), (mats, ()),
        ),
        ProgramInstance(
            f"frontier_{'x'.join(str(c) for c in caps)}",
            ce.program(caps, mode="frontier"), (mats, ()),
        ),
    ]


def _tiles():
    jnp, np = _jnp()
    from dgraph_tpu.ops import spgemm

    h_src, h_offsets, h_dst, _, _ = _small_csr()
    pt = spgemm.build_tiles(h_src, h_offsets, h_dst, t=spgemm.tile_size())
    m = spgemm.mask_lanes(pt.universe, pt.t)
    return pt, m


def _mask_inst(universe: int) -> ProgramInstance:
    jnp, np = _jnp()
    from dgraph_tpu.ops import spgemm

    pt, _ = _tiles()
    m = spgemm.mask_lanes(universe, pt.t)
    x = jnp.zeros((m,), jnp.float32).at[0].set(1.0)
    return ProgramInstance(
        f"M{m}", spgemm.expand_mask, (pt.bi, pt.bj, pt.tiles, x)
    )


def _b_mask_algebra() -> List[ProgramInstance]:
    jnp, np = _jnp()
    from dgraph_tpu.ops import sets, spgemm

    pt, m = _tiles()
    x = jnp.zeros((m,), jnp.float32).at[3].set(1.0)
    xb = jnp.zeros((4, m), jnp.float32).at[:, 2].set(1.0)
    stack = jnp.ones((3, m), jnp.float32)
    uids = jnp.asarray(sets.pad_to(np.arange(0, 14, 2), 64))
    return [
        ProgramInstance(
            f"expand_M{m}", spgemm.expand_mask, (pt.bi, pt.bj, pt.tiles, x)
        ),
        ProgramInstance(
            f"counts_M{m}", spgemm.expand_counts,
            (pt.bi, pt.bj, pt.tiles, x),
        ),
        ProgramInstance(
            f"expand_B4xM{m}", spgemm.expand_mask_batch,
            (pt.bi, pt.bj, pt.tiles, xb),
        ),
        ProgramInstance(
            f"intersect_masks_K3xM{m}", spgemm.intersect_masks, (stack,)
        ),
        ProgramInstance(
            f"uids_to_mask_M{m}", spgemm.uids_to_mask, (uids,), {"m": m}
        ),
    ]


def _b_intersect_stack() -> List[ProgramInstance]:
    jnp, np = _jnp()
    from dgraph_tpu.ops import spgemm

    mat = _sets_mat(4, 64)
    matb = _sets_mat(3, 64)[None].repeat(2, axis=0)
    return [
        ProgramInstance("K4xL64", spgemm.intersect_stack, (mat,)),
        ProgramInstance(
            "B2xK3xL64", spgemm.intersect_stack_batch, (matb,)
        ),
    ]


def _b_mask_chain() -> List[ProgramInstance]:
    jnp, np = _jnp()
    from dgraph_tpu.ops import spgemm

    pt, m = _tiles()
    x0 = jnp.zeros((m,), jnp.float32).at[0].set(1.0)
    keep = jnp.ones((m,), jnp.float32)
    ops2 = ((pt.bi, pt.bj, pt.tiles), (pt.bi, pt.bj, pt.tiles))
    return [
        ProgramInstance(
            f"L2xM{m}", spgemm.run_mask_chain,
            (ops2, (None, keep), (pt.degs, pt.degs), x0),
        ),
        # PR 18 segmented variant: the single-level chain segment the
        # joinplan segment loop dispatches at k=1, masks threaded
        # device-resident between segments.
        ProgramInstance(
            f"L1xM{m}_seg", spgemm.run_mask_chain,
            (ops2[:1], (keep,), (pt.degs,), x0),
        ),
    ]


def _b_triangle() -> List[ProgramInstance]:
    jnp, np = _jnp()
    from dgraph_tpu.ops import spgemm

    pt, m = _tiles()
    x = jnp.zeros((m,), jnp.float32).at[0].set(1.0)
    xb = jnp.zeros((2, m), jnp.float32).at[:, 0].set(1.0)
    tri = (pt.bi, pt.bj, pt.tiles) * 3
    return [
        ProgramInstance(f"M{m}", spgemm.triangle_mask, (*tri, x)),
        ProgramInstance(
            f"B2xM{m}", spgemm.triangle_mask_batch, (*tri, xb)
        ),
    ]


def _b_order() -> List[ProgramInstance]:
    jnp, np = _jnp()
    from dgraph_tpu.ops import order, sets

    src = jnp.asarray(np.arange(0, 32, 2, dtype=np.int32))
    ranks = jnp.asarray(np.arange(16, dtype=np.int32))
    uids = jnp.asarray(sets.pad_to(np.arange(0, 20, 2), 32))
    seg = jnp.asarray(
        sets.pad_to(np.repeat(np.arange(4), 4), 32, fill=-1)
    )
    r = jnp.asarray(sets.pad_to(np.arange(16), 32, fill=-1))
    return [
        # the rank gather is one vectorized binary search (_SS_NOTE)
        ProgramInstance("gather_ranks_B32", order.gather_ranks,
                        (src, ranks, uids),
                        scan_free=False, dtypes=_INT_SS),
        ProgramInstance("sort_perm_C32_asc", order.segmented_sort_perm,
                        (seg, r), {"desc": False}),
        ProgramInstance("sort_perm_C32_desc", order.segmented_sort_perm,
                        (seg, r), {"desc": True}),
    ]


def _b_packed_expand() -> List[ProgramInstance]:
    jnp, np = _jnp()
    from dgraph_tpu.ops import sets
    from dgraph_tpu.query import engine as qe

    _, _, _, offsets, dst = _small_csr()
    rows = jnp.asarray(sets.pad_rows(np.arange(4, dtype=np.int64), 8))
    metap, ovc = _inline_layout()
    return [
        ProgramInstance(
            "csr_R8xC32", qe._packed_expand_csr,
            (offsets, dst, rows), {"cap": 32},
        ),
        ProgramInstance(
            "inline_B8xC8", qe._packed_expand_inline,
            (metap, ovc, rows), {"capc": 8},
        ),
    ]


def _b_pallas_slotmap() -> List[ProgramInstance]:
    jnp, np = _jnp()
    from dgraph_tpu.ops.pallas_slotmap import slotmap_pallas

    cs = jnp.asarray(np.zeros((1, 128), np.int32))
    cd = jnp.asarray(np.zeros((1, 128), np.int32))
    return [
        ProgramInstance(
            "Q1xP128xC128", slotmap_pallas, (cs, cd),
            {"capc": 128, "interpret": True},
        ),
    ]


def _slotmap_inst(raw_capc: int) -> ProgramInstance:
    """The call _ov_slot_map_pallas (ops/sets.py) makes at a raw chunk
    capacity: capc rounds to the kernel's 128-slot granule."""
    jnp, np = _jnp()
    from dgraph_tpu.ops.pallas_slotmap import slotmap_pallas

    cc = ((raw_capc + 127) >> 7) << 7
    cs = jnp.asarray(np.zeros((1, 128), np.int32))
    cd = jnp.asarray(np.zeros((1, 128), np.int32))
    return ProgramInstance(
        f"Q1xP128xC{cc}", slotmap_pallas, (cs, cd),
        {"capc": cc, "interpret": True},
    )


def _resident_fixture():
    """Tiny CSR in the ResidentArena storage layout (models/arena.py):
    bucketed offsets, dst SENT-padded to _resident_cap's 128-granule +
    slack-tile contract — what ops/pallas_gather.py walks in HBM."""
    jnp, np = _jnp()
    from dgraph_tpu.models.arena import _resident_cap
    from dgraph_tpu.ops import sets

    degs = np.array([3, 0, 5, 2, 1, 0, 4, 2], np.int64)
    off = np.zeros(9, np.int32)
    off[1:] = np.cumsum(degs).astype(np.int32)
    E = int(off[-1])
    dst = np.full(_resident_cap(E), sets.SENT, np.int32)
    dst[:E] = np.arange(100, 100 + E, dtype=np.int32)
    rows = sets.pad_rows(
        np.array([0, 2, 3, 6], np.int64), 8
    ).astype(np.int32)
    return jnp.asarray(off), jnp.asarray(dst), jnp.asarray(rows)


def _gather_inst(raw_cap: int) -> ProgramInstance:
    jnp, np = _jnp()
    from dgraph_tpu.ops import sets
    from dgraph_tpu.ops.pallas_gather import gather_pallas

    off, dst, rows = _resident_fixture()
    cap = sets.bucket(raw_cap)
    return ProgramInstance(
        f"R8xC{cap}", gather_pallas, (off, dst, rows),
        {"cap": cap, "interpret": True},
    )


def _b_pallas_gather() -> List[ProgramInstance]:
    from dgraph_tpu.ops.pallas_gather import gather_pallas_packed

    off, dst, rows = _resident_fixture()
    return [
        _gather_inst(32),
        ProgramInstance(
            "packed_R8xC32", gather_pallas_packed, (off, dst, rows),
            {"cap": 32, "interpret": True},
        ),
    ]


def _b_pallas_intersect() -> List[ProgramInstance]:
    jnp, np = _jnp()
    from dgraph_tpu.ops import sets
    from dgraph_tpu.ops.pallas_intersect import intersect_pallas

    m2 = jnp.asarray(np.stack([
        sets.pad_to(np.arange(0, 20, 2), 64),
        sets.pad_to(np.arange(0, 30, 3), 64),
    ]))
    m4 = jnp.asarray(np.stack([
        sets.pad_to(np.arange(0, 24, k), 64) for k in (2, 3, 4, 6)
    ]))
    return [
        ProgramInstance("K2xL64", intersect_pallas, (m2,),
                        {"interpret": True}),
        ProgramInstance("K4xL64", intersect_pallas, (m4,),
                        {"interpret": True}),
    ]


def _b_resident_merge() -> List[ProgramInstance]:
    jnp, np = _jnp()
    from dgraph_tpu.models import arena as marena
    from dgraph_tpu.ops import sets

    off, dst, _rows = _resident_fixture()
    # padded delta pairs exactly as CSRArena._apply_delta_locked packs
    # them: SENT-filled pads, adds absent from / dels present in the
    # live buffers (the store-journal contract the merge leans on)
    ar = jnp.asarray(sets.pad_to(np.array([0, 2], np.int32), 8))
    ad = jnp.asarray(sets.pad_to(np.array([990, 991], np.int32), 8))
    dr = jnp.asarray(sets.pad_to(np.array([2], np.int32), 8))
    dd = jnp.asarray(sets.pad_to(np.array([103], np.int32), 8))
    return [
        ProgramInstance(
            "E17xD8", marena._resident_merge, (off, dst, ar, ad, dr, dd)
        ),
    ]


_INT = frozenset({"int32", "bool"})
# searchsorted-bearing kernels: jnp.searchsorted lowers to a log-depth
# lax.scan whose index carry is uint32 (documented at ops/sets.py
# _intersect_pair_sorted — the reason intersect_many needed the sort-
# based tree).  Kernels that embed the binary search declare this set
# and scan_free=False; everything else stays on the strict discipline.
_INT_SS = _INT | {"uint32"}
_MASK = frozenset({"float32", "int32", "bool"})
_OPS = "dgraph_tpu/ops"

_SS_NOTE = (
    "  (searchsorted binary searches lower to a bounded log-depth "
    "lax.scan with a uint32 index carry — the declared scan_free=False "
    "/ uint32 allowance covers exactly that, nothing else.)"
)


def _csr_probe() -> BucketProbe:
    # bucket(10) == bucket(12) == 16; bucket(5) == bucket(7) == 8
    return BucketProbe(
        pairs=((10, 12), (5, 7)),
        make=lambda n: _csr_expand_inst(4, n),
    )


def _ascending_probe() -> BucketProbe:
    return BucketProbe(
        pairs=((10, 12),),
        make=lambda n: _ascending_inst(4, n),
    )


def _mask_probe() -> BucketProbe:
    # mask_lanes buckets the block count: two universes under one
    # bucketed block count must share one program
    return BucketProbe(pairs=((10, 16),), make=_mask_inst)


def _gather_probe() -> BucketProbe:
    # bucket(10) == bucket(12) == 16: two frontier totals in one pow2
    # capacity bucket must trace ONE resident-gather program
    return BucketProbe(pairs=((10, 12), (5, 7)), make=_gather_inst)


def _slotmap_probe() -> BucketProbe:
    # 128-slot chunk granule: raw capacities 129 and 250 both pad to 256
    return BucketProbe(pairs=((129, 250),), make=_slotmap_inst)


REGISTRY: Dict[str, ProgramContract] = {
    c.name: c
    for c in (
        ProgramContract(
            name="sets.intersect_many",
            covers=(f"{_OPS}/sets.py::intersect_many",),
            build=_b_intersect_many,
            dtypes=_INT,
            notes="k-way intersection as a log-depth tree reduction; "
                  "the scan-free declaration IS the perf contract "
                  "(bench_ops.py kway grid).",
        ),
        ProgramContract(
            name="sets.union_many",
            covers=(f"{_OPS}/sets.py::union_many",),
            build=_b_union_many,
            dtypes=_INT,
            notes="k-way union as one flat bitonic sort.",
        ),
        ProgramContract(
            name="sets.set_algebra",
            covers=(
                f"{_OPS}/sets.py::count_valid",
                f"{_OPS}/sets.py::compact",
                f"{_OPS}/sets.py::sort_unique",
                f"{_OPS}/sets.py::member_mask",
                f"{_OPS}/sets.py::intersect",
                f"{_OPS}/sets.py::difference",
                f"{_OPS}/sets.py::union",
                f"{_OPS}/sets.py::mask_to_set",
                f"{_OPS}/sets.py::unique_dense",
                f"{_OPS}/sets.py::unique_rows_sorted",
                f"{_OPS}/sets.py::skey_uid",
                f"{_OPS}/sets.py::frontier_rows",
                f"{_OPS}/sets.py::rows_of",
                f"{_OPS}/sets.py::range_rows",
            ),
            build=_b_set_algebra,
            scan_free=False,
            dtypes=_INT_SS,
            notes="the scalar sorted-unique-padded algebra "
                  "(docs/sets-contract.md)." + _SS_NOTE,
        ),
        ProgramContract(
            name="sets.expand_csr",
            covers=(f"{_OPS}/sets.py::expand_csr",),
            build=_b_expand_csr,
            dtypes=_INT,
            bucket_probe=_csr_probe(),
            notes="the engine's hot posting-list gather; bucket pairs "
                  "pin the pow2 capacity discipline.",
        ),
        ProgramContract(
            name="sets.expand_inline",
            covers=(
                f"{_OPS}/sets.py::expand_chunked",
                f"{_OPS}/sets.py::expand_inline_grouped",
                f"{_OPS}/sets.py::expand_inline_seg",
            ),
            build=_b_expand_inline,
            dtypes=_INT,
            notes="chunked/inline-head posting gathers (round-4 fast "
                  "path).",
        ),
        ProgramContract(
            name="batch.set_ops",
            covers=(
                f"{_OPS}/batch.py::intersect_batch",
                f"{_OPS}/batch.py::difference_batch",
                f"{_OPS}/batch.py::union_many_batch",
                f"{_OPS}/batch.py::member_mask_batch",
                f"{_OPS}/batch.py::sort_unique_batch",
            ),
            build=_b_batched_set_ops,
            scan_free=False,
            dtypes=_INT_SS,
            notes="[B, L] vmapped set algebra — one dispatch per "
                  "batch." + _SS_NOTE,
        ),
        ProgramContract(
            name="batch.expand_ascending",
            covers=(f"{_OPS}/batch.py::expand_ascending",),
            build=_b_expand_ascending,
            dtypes=_INT,
            bucket_probe=_ascending_probe(),
            notes="telescoped ascending-row CSR expansion.",
        ),
        ProgramContract(
            name="batch.expand_filter_compact",
            covers=(
                f"{_OPS}/batch.py::expand_filter_compact",
                f"{_OPS}/batch.py::_effc_batch",
            ),
            build=_b_expand_filter_compact,
            dtypes=_INT,
            notes="whole hop (gather -> filter -> compact) in one "
                  "program; the per-op path is >= (2+k) dispatches.  "
                  "Filterless instances are strictly scan-free; "
                  "keep-set instances re-declare per instance (the "
                  "fused member_mask is a searchsorted).",
        ),
        ProgramContract(
            name="batch.multi_hop",
            covers=(f"{_OPS}/batch.py::_multi_hop_jit",),
            build=_b_multi_hop,
            scan_free=False,   # the scan IS the design: one program, N hops
            dtypes=_INT_SS,
            donate=(2, 3),
            donate_unused_ok=(3,),
            notes="lax.scan multi-hop driver with donated (frontier, "
                  "visited) carries.  The program exposes exactly one "
                  "[cap]-shaped output, so at most one carry can alias "
                  "— the visited buffer (flat arg 3) is declared "
                  "donate_unused_ok, which is the checked contract "
                  "behind ops/batch.py's scoped handling of JAX's "
                  "unusable-donation warning (the frontier carry, arg "
                  "2, MUST alias)." + _SS_NOTE,
        ),
        ProgramContract(
            name="batch.classed_expander",
            covers=(f"{_OPS}/batch.py::ClassedExpander._build",),
            build=_b_classed_expander,
            dtypes=_INT,
            notes="degree-classed scatter/sort-free hop programs; "
                  "capacity tuples ride bucket/bucket_fine so the "
                  "family stays bounded "
                  "(tests/test_batch_ops.py::test_program_cache_bound).",
        ),
        ProgramContract(
            name="spgemm.mask_algebra",
            covers=(
                f"{_OPS}/spgemm.py::expand_counts",
                f"{_OPS}/spgemm.py::expand_mask",
                f"{_OPS}/spgemm.py::expand_mask_batch",
                f"{_OPS}/spgemm.py::uids_to_mask",
                f"{_OPS}/spgemm.py::intersect_masks",
            ),
            build=_b_mask_algebra,
            dtypes=_MASK,
            bucket_probe=_mask_probe(),
            notes="MXU tile tier: frontier-bitmap x adjacency products; "
                  "f32 is the tile discipline (MXU-native), int32/bool "
                  "only at the boundaries.",
        ),
        ProgramContract(
            name="spgemm.intersect_stack",
            covers=(
                f"{_OPS}/spgemm.py::intersect_stack",
                f"{_OPS}/spgemm.py::intersect_stack_batch",
            ),
            build=_b_intersect_stack,
            scan_free=False,
            dtypes=_INT_SS,
            notes="k-way uid-set intersection in ONE program (k-1 "
                  "parallel probes + one compacting sort)." + _SS_NOTE,
        ),
        ProgramContract(
            name="spgemm.run_mask_chain",
            covers=(f"{_OPS}/spgemm.py::run_mask_chain",),
            build=_b_mask_chain,
            dtypes=_MASK,
            notes="the generic-join driver: a whole multi-level chain "
                  "as one program, masks device-resident between "
                  "levels.",
        ),
        ProgramContract(
            name="spgemm.triangle_mask",
            covers=(
                f"{_OPS}/spgemm.py::triangle_mask",
                f"{_OPS}/spgemm.py::triangle_mask_batch",
            ),
            build=_b_triangle,
            dtypes=_MASK,
            notes="fused two-legs + cycle-closing kernel.",
        ),
        ProgramContract(
            name="order.segmented_sort",
            covers=(
                f"{_OPS}/order.py::gather_ranks",
                f"{_OPS}/order.py::segmented_sort_perm",
            ),
            build=_b_order,
            dtypes=_INT,
            notes="device-side segmented order-by: rank gather + stable "
                  "(segment, +-rank) lexsort; the gather_ranks instance "
                  "re-declares for its searchsorted probe, the sort "
                  "permutation itself is strictly scan-free.",
        ),
        ProgramContract(
            name="engine.packed_expand",
            covers=(
                "dgraph_tpu/query/engine.py::_make_packed_expand.run",
                "dgraph_tpu/query/engine.py::_make_packed_inline.run",
            ),
            build=_b_packed_expand,
            dtypes=_INT,
            notes="engine-boundary wrappers concatenating (out, seg) "
                  "into one fetch; structurally they must stay thin "
                  "shells over the registered expansion kernels.",
        ),
        ProgramContract(
            name="pallas.slotmap",
            covers=(
                f"{_OPS}/pallas_slotmap.py::slotmap_pallas",
                f"{_OPS}/sets.py::expand_inline_grouped_pallas",
            ),
            build=_b_pallas_slotmap,
            scan_free=False,   # fori_loop over blocks inside the kernel
            dtypes=_INT,
            bucket_probe=_slotmap_probe(),
            notes="PROMOTED (PR 16): wired into the grouped-expansion "
                  "path behind DGRAPH_TPU_SLOTMAP (ops/sets.py "
                  "expand_inline_grouped_auto), full checks — transfer, "
                  "cost, bucket probe — in interpret mode; Mosaic "
                  "lowering itself is still the next chip session's "
                  "measure-first task (which is why auto mode stays "
                  "TPU-backend-gated).",
        ),
        ProgramContract(
            name="pallas.gather",
            covers=(
                f"{_OPS}/pallas_gather.py::gather_pallas",
                f"{_OPS}/pallas_gather.py::gather_pallas_packed",
            ),
            build=_b_pallas_gather,
            scan_free=False,   # the per-row DMA loop is a fori_loop
            # int16: interpret mode models the kernel's DMA semaphores
            # (pltpu.SemaphoreType.DMA scratch) as int16 avals — kernel
            # data stays strictly int32
            dtypes=_INT | {"int16"},
            bucket_probe=_gather_probe(),
            notes="device-resident posting gather (PR 16, the "
                  "route:resident walk primitive): double-buffered "
                  "HBM->VMEM span copies over ResidentArena's pinned "
                  "CSR, byte-identical to expand_csr; checked in "
                  "interpret mode (Mosaic lowering is the next chip "
                  "session's A/B).",
        ),
        ProgramContract(
            name="pallas.intersect",
            covers=(f"{_OPS}/pallas_intersect.py::intersect_pallas",),
            build=_b_pallas_intersect,
            scan_free=False,   # interpret-mode grid loop
            dtypes=_INT,
            notes="k-way (k<=8) sorted-set intersect over the stored "
                  "layout (PR 16, EmptyHeaded-style probe + VPU "
                  "membership tiles), byte-identical to intersect_many; "
                  "checked in interpret mode.",
        ),
        ProgramContract(
            name="resident.merge",
            covers=("dgraph_tpu/models/arena.py::_resident_merge",),
            build=_b_resident_merge,
            scan_free=False,
            dtypes=_INT_SS,
            notes="on-device delta application for resident arenas "
                  "(PR 16): lexsort merge of live edges + netted journal "
                  "pairs into the NEXT epoch's (offsets, dst) — the "
                  "device twin of CSRArena._apply_delta_locked.  Only "
                  "the padded delta pairs ever cross h2d." + _SS_NOTE,
        ),
        ProgramContract(
            name="mesh.multi_hop",
            covers=("dgraph_tpu/mesh/programs.py::mesh_multi_hop_step",),
            build=_b_mesh_multi_hop,
            scan_free=False,   # the hop scan IS the design (+ rows_of's
                               # searchsorted probe)
            dtypes=_INT_SS,
            donate=(3,),
            # the frontier seed aliases the [cap] final-frontier output
            # across the shard_map boundary; transfer_free stays False
            # because the checker's host-built operands reshard onto
            # the 8-wide mesh at call time — on the serving path the
            # ShardedArena operands are placed once and stay resident
            # (models/arena.py sharded_csr cache)
            transfer_free=False,
            notes="PR 17 mesh serving plane: the whole multi-hop chain "
                  "as ONE shard_map program — per-hop cross-chip "
                  "frontier exchange (all_gather of each shard's "
                  "bucketed expand_csr, psum of edge counts) runs "
                  "between lax.scan iterations on the ICI, never "
                  "through the host; byte-parity with the unsharded "
                  "scan driver pinned by tests/test_mesh_serving.py."
                  + _SS_NOTE,
        ),
    )
}


# jit/pallas construction sites that deliberately carry NO traced
# contract — each with the why.  The graftlint rule
# `unregistered-program-factory` accepts a site iff it appears here or
# in some contract's `covers`.
EXEMPT_SITES: Dict[str, str] = {
    "dgraph_tpu/query/chain.py::_run_fused": (
        "composite of registered kernels (expand_inline_seg, "
        "gather_ranks, segmented_sort_perm) whose static spec tuple "
        "comes from engine planning state; covered end-to-end by "
        "tests/test_chain.py parity + the compile-budget hook.  The "
        "PR 18 segmented grouping (static carry flag + level-slice "
        "tuples) is the same composite over a level subrange — "
        "byte-parity with the monolithic call pinned by "
        "tests/test_segments.py"
    ),
    "dgraph_tpu/parallel/mesh.py::sharded_expand_step": (
        "needs a live device Mesh; byte-parity with the registered "
        "expand_csr/sort_unique kernels pinned by tests/test_mesh_*"
    ),
    "dgraph_tpu/parallel/mesh.py::seg_expand_packed_step": (
        "needs a live device Mesh; parity pinned by tests/test_mesh_*"
    ),
    "dgraph_tpu/parallel/mesh.py::batched_hop_step": (
        "needs a live device Mesh; wraps registered "
        "expand_filter_compact"
    ),
    "dgraph_tpu/parallel/mesh.py::tile_expand_step": (
        "needs a live device Mesh; same math as registered "
        "spgemm.expand_mask (psum-combined), parity pinned by "
        "tests/test_spgemm.py mesh case"
    ),
    "dgraph_tpu/utils/calibrate.py::measure": (
        "micro-calibration probe (pre-compiled no-op for dispatch "
        "overhead) — intentionally trivial, never on the serving path"
    ),
    "dgraph_tpu/utils/calibrate.py::measure.gather": (
        "micro-calibration probe (synthetic gather rate)"
    ),
    "dgraph_tpu/utils/calibrate.py::measure.macs": (
        "micro-calibration probe (tile MAC rate)"
    ),
}


def covered_sites() -> Set[str]:
    """Every factory site the registry accounts for (contract covers +
    explicit exemptions) — the lint rule's acceptance set."""
    out: Set[str] = set(EXEMPT_SITES)
    for c in REGISTRY.values():
        out.update(c.covers)
    return out
