"""Pytest hooks enforcing JAX compile-count budgets and transfer guards.

"The second same-shape cohort adds zero programs" used to be one
hand-written assert in tests/test_sched.py; everything else about the
engine's compile story — bounded program families in ops/batch.py, the
hop cache short-circuiting dispatch, module-level jit caching in
query/engine.py — was hope.  These hooks make it a repo-wide gate:

- every backend compile is counted via ``jax.monitoring``'s
  ``/jax/core/compile/backend_compile_duration`` event (one event per
  XLA compilation, cache hits excluded);
- each test's compile delta is checked against a budget resolved as
  ``@pytest.mark.compile_budget(n)`` > ``overrides[nodeid]`` >
  ``overrides[file]`` > ``default`` from ``analysis/budgets.json``
  (``null`` = unlimited).  Budget busts fail the test with the delta in
  the message;
- ``@pytest.mark.transfer_guard`` (optionally ``("log")`` etc.) wraps
  the test body in ``jax.transfer_guard(level)`` — used by the
  hop-dispatch invariant tests to prove the compiled hop programs
  perform zero implicit host↔device transfers when handed
  device-resident arguments;
- ``DGRAPH_TPU_COMPILE_BUDGET_REPORT=1`` prints the top compile
  consumers at session end (how budgets in budgets.json were tuned;
  see docs/analysis.md).

Wired into tier-1 by ``tests/conftest.py`` importing these hook
functions into its module namespace.  Compiles triggered by engine
worker threads land in whichever test is running when the compile
finishes — budgets are therefore per-test *attribution*, not a strict
causal account; the default budget carries headroom for that (and for
jax-internal helper programs like ``jnp.ones``).
"""

from __future__ import annotations

import json
import os
import threading
from contextlib import nullcontext
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import pytest

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_compiles = 0
_installed = False
_budgets: Optional[dict] = None
_per_test: List[Tuple[str, int]] = []


class CompileBudgetExceeded(AssertionError):
    """A test compiled more XLA programs than its budget allows."""


def _on_event_duration(name: str, secs: float, **kw) -> None:
    global _compiles
    if name == _COMPILE_EVENT:
        with _lock:
            _compiles += 1


def install_compile_counter() -> None:
    """Register the jax.monitoring listener (idempotent)."""
    global _installed
    if _installed:
        return
    import jax.monitoring

    jax.monitoring.register_event_duration_secs_listener(_on_event_duration)
    _installed = True


def compile_count() -> int:
    return _compiles


def load_budgets() -> dict:
    global _budgets
    if _budgets is None:
        p = Path(__file__).with_name("budgets.json")
        _budgets = json.loads(p.read_text()) if p.exists() else {}
    return _budgets


def budget_for(item) -> Optional[int]:
    """Marker > nodeid override > file override > default; None/null =
    unlimited."""
    m = item.get_closest_marker("compile_budget")
    if m is not None and m.args:
        return int(m.args[0]) if m.args[0] is not None else None
    b = load_budgets()
    overrides: Dict[str, object] = b.get("overrides", {})
    nodeid = item.nodeid
    if nodeid in overrides:
        v = overrides[nodeid]
        return None if v is None else int(v)
    fname = nodeid.split("::", 1)[0]
    if fname in overrides:
        v = overrides[fname]
        return None if v is None else int(v)
    v = b.get("default")
    return None if v is None else int(v)


# -- pytest hooks (imported by tests/conftest.py) ---------------------------

def budget_plugin_configure(config) -> None:
    config.addinivalue_line(
        "markers",
        "compile_budget(n): cap the number of XLA compilations this test "
        "may trigger (analysis/budgets.json sets the default)",
    )
    config.addinivalue_line(
        "markers",
        "transfer_guard(level='disallow'): run the test body under "
        "jax.transfer_guard(level)",
    )
    install_compile_counter()


@pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    guard = item.get_closest_marker("transfer_guard")
    cm = nullcontext()
    if guard is not None:
        import jax

        level = guard.args[0] if guard.args else "disallow"
        cm = jax.transfer_guard(level)
    before = compile_count()
    try:
        with cm:
            result = yield
    finally:
        # record the delta even when the test body raised: a test that
        # both flakes AND busts its budget must still show up in the
        # DGRAPH_TPU_COMPILE_BUDGET_REPORT accounting
        used = compile_count() - before
        if used:
            _per_test.append((item.nodeid, used))
    # the budget check itself only fires on tests that passed — raising
    # here on an already-failing test would mask its real error
    budget = budget_for(item)
    if budget is not None and used > budget:
        raise CompileBudgetExceeded(
            f"{item.nodeid} triggered {used} XLA compilations, over its "
            f"budget of {budget}.  If the growth is intentional (new "
            "program family, new shape class), raise the budget in "
            "dgraph_tpu/analysis/budgets.json with a comment; if not, "
            "you likely built a jit inside a loop or broke a program "
            "cache key — see docs/analysis.md#compile-budgets"
        )
    return result


def budget_plugin_report(terminalreporter=None) -> List[Tuple[str, int]]:
    """Top compile consumers; printed when
    DGRAPH_TPU_COMPILE_BUDGET_REPORT=1."""
    top = sorted(_per_test, key=lambda x: -x[1])[:25]
    if os.environ.get("DGRAPH_TPU_COMPILE_BUDGET_REPORT") == "1":
        write = (
            terminalreporter.write_line if terminalreporter is not None
            else print
        )
        write(f"compile-budget: {_compiles} total XLA compilations")
        for nodeid, n in top:
            write(f"  {n:5d}  {nodeid}")
    return top
