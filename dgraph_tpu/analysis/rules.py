"""The graftlint rule set — this repo's idioms, not generic style.

Each rule targets a bug class that has actually bitten (or nearly
bitten) this codebase and that the tier-1 suite cannot catch reliably
on a noisy 2-core CPU host:

- ``host-sync-in-jit``: a stray ``.item()`` / ``bool(tracer)`` /
  ``np.asarray`` inside a ``jit``/``scan``/``pallas_call`` body either
  fails at trace time in a rarely-hit branch or — worse — silently
  forces a device→host sync per call and ruins the one-dispatch-per-hop
  story (ops/batch.py).
- ``recompile-hazard``: ``jax.jit`` constructed inside a loop or
  invoked inline (``jax.jit(f)(x)``) defeats jit's weakref cache and
  recompiles per iteration/call; the budgets in
  ``analysis/budgets.json`` would catch the symptom at test time, this
  catches the cause at review time.
- ``wallclock-duration``: interval math on ``time.time()`` breaks under
  NTP slew/step — scheduler deadlines, raft election ticks and cache
  aging must use ``time.monotonic()``.  Wall clock stays legitimate
  where a *user-visible timestamp* is involved (``since()`` compares
  against stored dates; pragma those sites).
- ``swallowed-exception``: a broad ``except Exception: pass`` in
  cluster/raft/loader code turns partial outages into silent data
  gaps; narrow the type or count it via
  ``utils.metrics.note_swallowed`` so operators can see the drop rate.
- ``naked-peer-rpc``: a direct ``urlopen_peer`` (anywhere) or raw
  channel-RPC call (in the cluster peer plane) bypasses PeerClient's
  retry budget, circuit breaker and health ordering — exactly the
  one-shot brittleness PR 5 removed; route it through
  ``cluster/peerclient.py``.
- ``naked-atomic-write``: a direct ``os.replace``/``os.rename`` outside
  ``utils/atomicio.py`` — durable file replacement must go through
  ``atomic_write_file`` (tmp + fsync + replace + directory fsync) or a
  crash can observe a half-state or resurrect the old name.  The rare
  deliberate site (a rename of an already-fully-synced file, a build
  artifact) carries the pragma with a WHY comment.
- ``naked-stage-timing``: direct ``time.perf_counter*`` stage
  bracketing in ``serve/``, ``sched/``, ``query/`` or ``cache/`` —
  stage timing in the serving tree must go through the span API
  (``dgraph_tpu.obs``: hop spans, ``obs.stage``) so the number is
  attributable to a trace instead of vanishing into a local variable;
  ``obs/`` and ``utils/trace.py`` are the sanctioned homes of the raw
  clock reads.
- ``naked-route-threshold``: a raw big-number comparison or a
  ``DGRAPH_TPU_*`` env read in ``query/`` or ``ops/`` — route-gate
  thresholds grew as scattered magic numbers until two independent
  ``262144`` twins (chain.py / joinplan.py) kept the chain scan out of
  3-hop queries it wins (BENCH21M).  Every gate lives in
  ``utils/planconfig.py`` with a documented default, and the decision
  itself belongs to the calibrated planner (``query/planner.py``).
- ``naked-device-sync``: a bare ``.block_until_ready()`` /
  ``jax.block_until_ready`` / ``jax.device_get`` / no-arg ``.item()``
  sync point on the HOST orchestration path in ``query/``, ``ops/``,
  ``parallel/`` or ``sched/`` — a naked sync is exactly where a wedged
  chip blocks a flush worker forever (TPU bench rounds 4-5 ran on one).
  Device syncs in the serving tree go through the device guard's
  watchdog bracket (``utils/devguard.py`` — deadline + SICK latch +
  host failover) or ``obs.block_ready_ms`` (which also attributes the
  wait to the span); a deliberate host-value ``.item()`` carries the
  pragma with the WHY.
- ``unchecked-hop-loop``: a loop in ``query/`` that drives the
  expander/dispatch seam (``expand``/``submit_hop``/``_expand_rows``/
  ``_exec_child``/``multi_hop``) without a ``CancelToken`` checkpoint —
  cooperative cancellation (PR 11, sched/qos.py) only works if EVERY
  hop-dispatching loop checkpoints; one unchecked loop and a
  deadline-expired or disconnected query silently runs to completion
  again.  Call ``engine.checkpoint()`` / ``resolver.checkpoint()`` (or
  ``<token>.check()``) inside the loop, or pragma the site with the
  WHY.

- ``naked-resident-transfer``: a ``jax.device_put`` / ``np.asarray`` /
  ``jnp.asarray`` on a resident arena's device buffers outside
  ``models/arena.py`` — the resident tier's contract (PR 16) is that
  the pinned CSR never re-crosses the host/device boundary after
  seeding; ``ResidentArena.seed``/``apply_delta`` are the only
  sanctioned (and ledger-charged) stagings.

Suppress a deliberate site with ``# graftlint: ignore[rule-id]`` on the
line (or the line above).  docs/analysis.md has the full catalog and
the how-to-add-a-rule walkthrough.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from dgraph_tpu.analysis.framework import FileContext, Finding, Rule


# -- shared AST helpers -----------------------------------------------------

def _dotted(node: ast.AST) -> str:
    """'jax.lax.scan' for nested Attribute/Name chains, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_expr(node: ast.AST, jit_names: Set[str]) -> bool:
    """``jax.jit`` / imported ``jit`` / ``partial(jax.jit, ...)``."""
    d = _dotted(node)
    if d in jit_names:
        return True
    if isinstance(node, ast.Call):
        f = _dotted(node.func)
        if f in ("partial", "functools.partial") and node.args:
            return _is_jit_expr(node.args[0], jit_names)
        return f in jit_names  # jax.jit(fn) / jax.jit(fn, static_...)
    return False


def _jit_call_of(node: ast.AST, jit_names: Set[str]) -> Optional[ast.Call]:
    """The Call node carrying static_arg* keywords, if any."""
    if isinstance(node, ast.Call):
        f = _dotted(node.func)
        if f in jit_names:
            return node
        if f in ("partial", "functools.partial") and node.args:
            if _dotted(node.args[0]) in jit_names:
                return node
    return None


def _jit_aliases(tree: ast.AST) -> Set[str]:
    """Names that mean jax.jit / jax.pmap in this file."""
    names = {"jax.jit", "jax.pmap"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for a in node.names:
                if a.name in ("jit", "pmap"):
                    names.add(a.asname or a.name)
    return names


def _static_params(fn: ast.FunctionDef, call: Optional[ast.Call]) -> Set[str]:
    """Parameter names declared static via static_argnames/static_argnums
    on the jit decorator — those are Python values inside the trace, so
    ``int(cap)``-style coercions on them are fine."""
    if call is None:
        return set()
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    out: Set[str] = set()
    for kw in call.keywords:
        v = kw.value
        if kw.arg == "static_argnames":
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        out.add(e.value)
        elif kw.arg == "static_argnums":
            nums: List[int] = []
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums = [v.value]
            elif isinstance(v, (ast.Tuple, ast.List)):
                nums = [
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, int)
                ]
            for n in nums:
                if 0 <= n < len(params):
                    out.add(params[n])
    return out


# traced-callee POSITIONS per combinator: which positional args are
# functions whose bodies execute under the trace (None = all from that
# index on, for switch's branch list)
_TRACED_ARG_POS = {
    "scan": (0,),
    "while_loop": (0, 1),   # cond_fun AND body_fun both trace
    "fori_loop": (2,),      # (lower, upper, body_fun, init)
    "cond": (1, 2),         # (pred, true_fun, false_fun, *operands)
    "switch": (1,),         # (index, [branch_fns], *operands)
    "vmap": (0,),
    "checkpoint": (0,),
    "remat": (0,),
    "pallas_call": (0,),
}
_COMBINATOR_PREFIXES = ("", "lax.", "jax.", "jax.lax.", "pl.",
                        "jax.experimental.pallas.")


def _traced_functions(
    tree: ast.AST, jit_names: Set[str]
) -> List[Tuple[ast.FunctionDef, Set[str], str]]:
    """Every FunctionDef whose body executes under a trace:
    (node, static param names, why)."""
    out: List[Tuple[ast.FunctionDef, Set[str], str]] = []
    # names handed to scan/cond/fori_loop/pallas_call... as traced callees
    callee_names: Dict[str, str] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = _dotted(node.func)
        base = f.split(".")[-1]
        if base not in _TRACED_ARG_POS or not any(
            f == p + base for p in _COMBINATOR_PREFIXES
        ):
            continue
        for pos in _TRACED_ARG_POS[base]:
            if pos >= len(node.args):
                continue
            arg = node.args[pos]
            if isinstance(arg, ast.Name):
                callee_names[arg.id] = base
            elif isinstance(arg, (ast.List, ast.Tuple)):  # switch branches
                for e in arg.elts:
                    if isinstance(e, ast.Name):
                        callee_names[e.id] = base
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if _is_jit_expr(dec, jit_names):
                out.append(
                    (node, _static_params(node, _jit_call_of(dec, jit_names)),
                     "jit")
                )
                break
        else:
            if node.name in callee_names:
                out.append((node, set(), callee_names[node.name]))
    return out


# -- rule: host-sync-in-jit -------------------------------------------------

_NUMPY_ROOTS = {"np", "numpy", "onp"}
_NUMPY_SYNC_FNS = {"asarray", "array", "ascontiguousarray", "copy"}


class HostSyncInJit(Rule):
    id = "host-sync-in-jit"
    doc = (
        "no .item()/bool()/int()/float() on traced values, np.asarray, "
        "jax.device_get or .block_until_ready() inside jit/scan/"
        "pallas_call bodies"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        jit_names = _jit_aliases(ctx.tree)
        for fn, static, why in _traced_functions(ctx.tree, jit_names):
            params = {
                a.arg for a in fn.args.posonlyargs + fn.args.args
                + fn.args.kwonlyargs
            } - static
            # nested defs inherit tracedness; their params are traced too
            for inner in ast.walk(fn):
                if isinstance(inner, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if inner is not fn:
                        params |= {
                            a.arg for a in inner.args.posonlyargs
                            + inner.args.args + inner.args.kwonlyargs
                        }
            yield from self._check_body(ctx, fn, params, why)

    def _check_body(
        self, ctx: FileContext, fn: ast.FunctionDef,
        traced_params: Set[str], why: str,
    ) -> Iterator[Finding]:
        where = f"inside {why} body `{fn.name}`"
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute):
                if f.attr == "item" and not node.args:
                    yield ctx.finding(
                        self.id, node,
                        f".item() forces a device->host sync {where}; "
                        "keep the value on device or hoist the read out "
                        "of the traced region",
                    )
                    continue
                if f.attr == "block_until_ready":
                    yield ctx.finding(
                        self.id, node,
                        f".block_until_ready() {where} serializes the "
                        "trace against the device stream",
                    )
                    continue
                root = _dotted(f).split(".")[0]
                if root in _NUMPY_ROOTS and f.attr in _NUMPY_SYNC_FNS:
                    if node.args and not _const_like(node.args[0]):
                        yield ctx.finding(
                            self.id, node,
                            f"np.{f.attr}() {where} materializes the "
                            "operand on host every call; use jnp.* or "
                            "move the conversion outside the trace",
                        )
                    continue
            d = _dotted(f)
            if d in ("jax.device_get", "device_get"):
                yield ctx.finding(
                    self.id, node,
                    f"jax.device_get {where} is a host sync; return the "
                    "array and fetch after dispatch",
                )
                continue
            if (
                isinstance(f, ast.Name)
                and f.id in ("bool", "int", "float")
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in traced_params
            ):
                yield ctx.finding(
                    self.id, node,
                    f"{f.id}({node.args[0].id}) {where} concretizes a "
                    "traced value (TracerBoolConversionError at best, a "
                    "silent per-call sync at worst); mark the argument "
                    "static or keep the branch on device (lax.cond/"
                    "jnp.where)",
                )


def _const_like(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_const_like(e) for e in node.elts)
    return False


# -- rule: recompile-hazard -------------------------------------------------

class RecompileHazard(Rule):
    id = "recompile-hazard"
    doc = (
        "jax.jit constructed inside a loop, or invoked inline "
        "(jax.jit(f)(x)) — both defeat the compile cache"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        jit_names = _jit_aliases(ctx.tree)
        loop_spans: List[Tuple[int, int]] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
                end = getattr(node, "end_lineno", node.lineno)
                loop_spans.append((node.lineno, end))
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            # inline invocation: jax.jit(f)(x) — a fresh wrapper per call
            if isinstance(node.func, ast.Call):
                inner = _jit_call_of(node.func, jit_names)
                if inner is not None and inner.args:
                    yield ctx.finding(
                        self.id, node,
                        "jax.jit(f)(...) creates and traces a fresh "
                        "wrapper per call; bind the jitted function once "
                        "(module scope or a cached builder) and call that",
                    )
                    continue
            call = _jit_call_of(node, jit_names)
            if call is None or not call.args:
                continue
            # decorator position is handled by normal function defs
            if any(lo <= node.lineno <= hi for lo, hi in loop_spans):
                yield ctx.finding(
                    self.id, node,
                    "jax.jit constructed inside a loop recompiles every "
                    "iteration; hoist it out or cache it keyed on the "
                    "static arguments (see ops/batch.py ClassedExpander."
                    "_program)",
                )


# -- rule: wallclock-duration -----------------------------------------------

def _is_walltime_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and _dotted(node.func) in ("time.time", "datetime.datetime.now")
        and not node.args
    )


class WallClockDuration(Rule):
    id = "wallclock-duration"
    doc = (
        "interval math on time.time() — deadlines, tick loops and age "
        "computations must use time.monotonic()"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # scopes: module + each function gets its own timeish-name set
        scopes: List[ast.AST] = [ctx.tree]
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node)
        seen: Set[int] = set()
        for scope in scopes:
            timeish = self._timeish_names(scope)
            for node in self._walk_scope(scope):
                if id(node) in seen:
                    continue
                hit = None
                if isinstance(node, ast.BinOp) and isinstance(
                    node.op, (ast.Add, ast.Sub)
                ):
                    if (
                        _is_walltime_call(node.left)
                        or _is_walltime_call(node.right)
                        or self._timeish(node.left, timeish)
                        or self._timeish(node.right, timeish)
                    ):
                        hit = (
                            "duration/deadline arithmetic on time.time() "
                            "drifts under NTP slew and can go backwards "
                            "on clock steps; use time.monotonic() for "
                            "intervals (wall clock is for user-visible "
                            "timestamps only)"
                        )
                elif isinstance(node, ast.Compare):
                    sides = [node.left] + list(node.comparators)
                    if any(_is_walltime_call(s) for s in sides):
                        hit = (
                            "comparing time.time() against a deadline is "
                            "interval logic; use time.monotonic()"
                        )
                if hit is not None:
                    seen.add(id(node))
                    yield ctx.finding(self.id, node, hit)

    @classmethod
    def _timeish_names(cls, scope: ast.AST) -> Set[str]:
        # same scope boundary as the expression pass (_walk_scope):
        # nested defs keep their own timeish sets — a closure's
        # `ts = time.time()` must not taint the enclosing scope's `ts`
        names: Set[str] = set()
        for node in cls._walk_scope(scope):
            if isinstance(node, ast.Assign) and _is_walltime_call(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
                    elif isinstance(t, ast.Tuple):
                        names.update(
                            e.id for e in t.elts if isinstance(e, ast.Name)
                        )
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Tuple
            ):
                # total, t0 = 0, time.time()
                for t in node.targets:
                    if isinstance(t, ast.Tuple) and len(t.elts) == len(
                        node.value.elts
                    ):
                        for tgt, val in zip(t.elts, node.value.elts):
                            if isinstance(tgt, ast.Name) and _is_walltime_call(
                                val
                            ):
                                names.add(tgt.id)
        return names

    @staticmethod
    def _timeish(node: ast.AST, timeish: Set[str]) -> bool:
        return isinstance(node, ast.Name) and node.id in timeish

    @staticmethod
    def _walk_scope(scope: ast.AST):
        """Walk a scope without descending into nested function defs
        (each gets its own pass with its own timeish set)."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(node))


# -- rule: swallowed-exception ----------------------------------------------

_BROAD = {"Exception", "BaseException"}


def _broad_handler(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:
        return True
    if isinstance(t, (ast.Name, ast.Attribute)):
        return _dotted(t).split(".")[-1] in _BROAD
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, (ast.Name, ast.Attribute))
            and _dotted(e).split(".")[-1] in _BROAD
            for e in t.elts
        )
    return False


def _silent_body(body: Sequence[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(
            stmt.value, ast.Constant
        ):
            continue  # docstring / ellipsis
        return False
    return True


class SwallowedException(Rule):
    id = "swallowed-exception"
    doc = (
        "broad `except Exception: pass` hides partial outages; narrow "
        "the type or count it (utils.metrics.note_swallowed)"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _broad_handler(node) and _silent_body(node.body):
                yield ctx.finding(
                    self.id, node,
                    "broad exception swallowed silently — a downed peer, "
                    "a bad record and a typo all vanish here; catch the "
                    "narrow type you mean, or at minimum count the drop "
                    "via utils.metrics.note_swallowed(site, exc)",
                )


# -- rule: naked-peer-rpc ---------------------------------------------------

_CHANNEL_RPC_ATTRS = {
    "unary_unary", "unary_stream", "stream_unary", "stream_stream",
}


class NakedPeerRpc(Rule):
    id = "naked-peer-rpc"
    doc = (
        "direct urlopen_peer / channel-RPC call outside cluster/"
        "peerclient.py — peer RPCs must route through PeerClient "
        "(retry budget, per-peer circuit breaker, health ordering)"
    )

    # ``urlopen_peer`` is flagged EVERYWHERE (it exists only for peer
    # calls); raw gRPC multicallables are flagged only under cluster/ —
    # serve/ChannelPool and client/ are the PUBLIC API surface, where a
    # naked channel RPC is the client's own business.
    def check(self, ctx: FileContext) -> Iterator[Finding]:
        path = ctx.path.replace("\\", "/")
        if path.endswith("cluster/peerclient.py"):
            return  # the one legitimate home of both call forms
        in_cluster = "cluster/" in path
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = _dotted(f).split(".")[-1]
            if name == "urlopen_peer":
                yield ctx.finding(
                    self.id, node,
                    "one-shot urlopen_peer call bypasses PeerClient: no "
                    "retry/backoff budget, no circuit breaker, and a "
                    "down peer costs a full connect timeout here — use "
                    "ClusterService.peerclient.urlopen(...)",
                )
            elif (
                in_cluster
                and isinstance(f, ast.Attribute)
                and f.attr in _CHANNEL_RPC_ATTRS
            ):
                yield ctx.finding(
                    self.id, node,
                    f"raw channel.{f.attr}() in the cluster peer plane "
                    "bypasses PeerClient — use peerclient.grpc_unary(...) "
                    "so retries/breakers cover this RPC too",
                )


# -- rule: naked-atomic-write -----------------------------------------------

_RENAME_FNS = {"replace", "rename", "renames"}


def _os_rename_aliases(tree: ast.AST) -> Set[str]:
    """Bare names that mean os.replace/os.rename in this file
    (``from os import replace [as rp]``)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "os":
            for a in node.names:
                if a.name in _RENAME_FNS:
                    out.add(a.asname or a.name)
    return out


class NakedAtomicWrite(Rule):
    id = "naked-atomic-write"
    doc = (
        "direct os.replace / os.rename outside utils/atomicio.py — "
        "durable file replacement must go through atomic_write_file "
        "(tmp + fsync + replace + dir fsync) or a crash can observe "
        "half-state"
    )

    # every step of the dance matters: a replace without the tmp-fsync
    # can install a file whose BLOCKS are not on disk yet (content
    # garbage after a crash); without the directory fsync the rename
    # itself can roll back and resurrect the old name.  The helper does
    # both; a naked call almost certainly skips at least one.
    def check(self, ctx: FileContext) -> Iterator[Finding]:
        path = ctx.path.replace("\\", "/")
        if path.endswith("utils/atomicio.py"):
            return  # the one legitimate home of the raw call
        aliases = _os_rename_aliases(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            d = _dotted(f)
            named = d in ("os.replace", "os.rename", "os.renames") or (
                isinstance(f, ast.Name) and f.id in aliases
            )
            if not named:
                continue
            fn = d.split(".")[-1] if d else f.id  # type: ignore[union-attr]
            yield ctx.finding(
                self.id, node,
                f"naked os.{fn}() skips the fsync'd tmp+replace+dir-sync "
                "dance — a crash here can install unsynced content or "
                "resurrect the old name; use utils.atomicio."
                "atomic_write_file (or pragma a rename of an "
                "already-fully-synced file, with the WHY)",
            )


# -- rule: naked-stage-timing -----------------------------------------------

def _is_perf_counter_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and _dotted(node.func).split(".")[-1]
        in ("perf_counter", "perf_counter_ns")
        and not node.args
    )


class NakedStageTiming(Rule):
    id = "naked-stage-timing"
    doc = (
        "direct time.perf_counter* stage bracketing in serve/, sched/, "
        "query/ or cache/ — route stage timing through the span API "
        "(dgraph_tpu.obs: hop spans / obs.stage) so the number lands in "
        "traces, not a local variable"
    )

    # only the serving tree: these are the layers whose stage timings
    # the flight recorder exists to attribute.  obs/ and utils/trace.py
    # ARE the span API — the raw clock reads live there by design.
    _DIRS = ("serve/", "sched/", "query/", "cache/")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        path = ctx.path.replace("\\", "/")
        if "obs/" in path or path.endswith("utils/trace.py"):
            return
        if not any(d in path for d in self._DIRS):
            return
        # same scope discipline as wallclock-duration: names assigned
        # from perf_counter in a scope taint only that scope
        scopes: List[ast.AST] = [ctx.tree]
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node)
        seen: Set[int] = set()
        for scope in scopes:
            timers = self._timer_names(scope)
            for node in WallClockDuration._walk_scope(scope):
                if id(node) in seen:
                    continue
                if not (
                    isinstance(node, ast.BinOp)
                    and isinstance(node.op, (ast.Add, ast.Sub))
                ):
                    continue
                sides = (node.left, node.right)
                if any(_is_perf_counter_call(s) for s in sides) or any(
                    isinstance(s, ast.Name) and s.id in timers
                    for s in sides
                ):
                    seen.add(id(node))
                    yield ctx.finding(
                        self.id, node,
                        "perf_counter stage bracketing outside the span "
                        "API: this duration can never be attributed to a "
                        "trace — wrap the stage in obs.stage(stats, key) "
                        "or record it as a span attr (dgraph_tpu/obs/), "
                        "or pragma the site with the WHY",
                    )

    @staticmethod
    def _timer_names(scope: ast.AST) -> Set[str]:
        names: Set[str] = set()
        for node in WallClockDuration._walk_scope(scope):
            if isinstance(node, ast.Assign) and _is_perf_counter_call(
                node.value
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Tuple
            ):
                for t in node.targets:
                    if isinstance(t, ast.Tuple) and len(t.elts) == len(
                        node.value.elts
                    ):
                        for tgt, val in zip(t.elts, node.value.elts):
                            if isinstance(
                                tgt, ast.Name
                            ) and _is_perf_counter_call(val):
                                names.add(tgt.id)
        return names


# -- rule: naked-route-threshold --------------------------------------------

def _const_int(node: ast.AST) -> Optional[int]:
    """Fold an integer-literal expression: plain Constant, unary minus,
    and BinOps of constants (``1 << 21``, ``4 * 1024``) — the spellings
    magic thresholds actually use."""
    if isinstance(node, ast.Constant) and type(node.value) is int:
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _const_int(node.operand)
        return -v if v is not None else None
    if isinstance(node, ast.BinOp):
        l, r = _const_int(node.left), _const_int(node.right)
        if l is None or r is None:
            return None
        try:
            if isinstance(node.op, ast.LShift):
                return l << r
            if isinstance(node.op, ast.Mult):
                return l * r
            if isinstance(node.op, ast.Add):
                return l + r
            if isinstance(node.op, ast.Sub):
                return l - r
            if isinstance(node.op, ast.Pow):
                return l**r if 0 <= r <= 64 else None
        except (OverflowError, ValueError):
            return None
    return None


class NakedRouteThreshold(Rule):
    id = "naked-route-threshold"
    doc = (
        "raw numeric route-gate comparison or DGRAPH_TPU_* env read in "
        "query//ops/ — thresholds live in utils/planconfig.py (documented "
        "defaults, override detection) and decisions in query/planner.py "
        "(calibrated cost model)"
    )

    # query/ and ops/ are the layers where route gates live; the config
    # module itself sits in utils/ — outside the scanned dirs by design,
    # so it needs no exemption.  The literal floor (65536) is far above
    # any capacity/bucket constant but below every historical gate
    # (262144, 1<<21, 1<<22); disabling-style sentinels (1 << 60) are
    # exactly the pattern that belongs behind a planconfig name too.
    _DIRS = ("query/", "ops/")
    _FLOOR = 65536

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        path = ctx.path.replace("\\", "/")
        if not any(d in path for d in self._DIRS):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                knob = None
                if d in ("os.environ.get", "os.getenv") and node.args:
                    a0 = node.args[0]
                    if isinstance(a0, ast.Constant) and isinstance(
                        a0.value, str
                    ):
                        knob = a0.value
                if knob is not None and knob.startswith("DGRAPH_TPU_"):
                    yield ctx.finding(
                        self.id, node,
                        f"env read of {knob} in the routing layers: knob "
                        "reads belong in utils/planconfig.py (one table "
                        "of documented defaults the planner can treat as "
                        "overridable) — two independently-grown 262144 "
                        "twins is how we got here",
                    )
            elif isinstance(node, ast.Compare):
                operands = [node.left] + list(node.comparators)
                for op in operands:
                    v = _const_int(op)
                    if v is not None and abs(v) >= self._FLOOR:
                        yield ctx.finding(
                            self.id, node,
                            f"naked numeric gate ({v}) in a comparison: "
                            "name it in utils/planconfig.py (or derive it "
                            "from the calibrated model in "
                            "query/planner.py) so the threshold is "
                            "documented, overridable and auditable — or "
                            "pragma the site with the WHY",
                        )
                        break


# -- rule: naked-version-key --------------------------------------------------

def _storeish(node: ast.AST) -> bool:
    """Does this expression read like a store reference (``store``,
    ``self.store``, ``self._server.store``, ``self.engine.store``)?"""
    d = _dotted(node)
    return d == "store" or d.endswith(".store")


class NakedVersionKey(Rule):
    id = "naked-version-key"
    doc = (
        "bare store.version read in the cache-keying layers — "
        "predicate-scoped cache versions live in dgraph_tpu/ivm/"
        "versions.py (hop_version/result_version/version_for); a new "
        "view keyed on the GLOBAL version quietly regrows one-write-"
        "invalidates-everything"
    )

    # the layers that construct cache keys / freshness probes; the ivm/
    # package is the sanctioned home and sits outside them by design.
    # Both spellings are flagged: a plain ``<x>.store.version``
    # attribute read and the duck-typed ``getattr(<store>, "version")``.
    _DIRS = ("cache/", "query/", "sched/", "serve/")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        path = ctx.path.replace("\\", "/")
        if not any(d in path for d in self._DIRS):
            return
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr == "version"
                and _storeish(node.value)
            ):
                yield ctx.finding(
                    self.id, node,
                    "bare store.version read: key caches through "
                    "dgraph_tpu/ivm/versions.py (predicate-scoped "
                    "freshness) — or pragma the site with WHY it is "
                    "not a cache key",
                )
            elif isinstance(node, ast.Call):
                if (
                    _dotted(node.func) == "getattr"
                    and len(node.args) >= 2
                    and isinstance(node.args[1], ast.Constant)
                    and node.args[1].value == "version"
                    and _storeish(node.args[0])
                ):
                    yield ctx.finding(
                        self.id, node,
                        "bare getattr(store, \"version\") read: key "
                        "caches through dgraph_tpu/ivm/versions.py "
                        "(predicate-scoped freshness) — or pragma the "
                        "site with WHY it is not a cache key",
                    )


# -- rule: naked-device-sync --------------------------------------------------

class NakedDeviceSync(Rule):
    id = "naked-device-sync"
    doc = (
        "bare .block_until_ready()/jax.block_until_ready/jax.device_get/"
        ".item() sync point in query/, ops/, parallel/ or sched/ — device "
        "syncs in the serving tree go through the device guard "
        "(utils/devguard.py watchdog bracket) or obs.block_ready_ms, so a "
        "wedged chip can never block a flush worker forever"
    )

    # the serving layers whose host orchestration dispatches device
    # programs; utils/devguard.py (the watchdog's home) and obs/ (the
    # block_ready_ms wrapper) sit outside them by design.  In-jit sync
    # points are host-sync-in-jit's jurisdiction — this rule covers the
    # HOST side of the seam, so it skips traced bodies to keep one
    # finding per bug class.
    _DIRS = ("query/", "ops/", "parallel/", "sched/")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        path = ctx.path.replace("\\", "/")
        if not any(d in path for d in self._DIRS):
            return
        jit_names = _jit_aliases(ctx.tree)
        traced_lines: Set[int] = set()
        for fn, _static, _why in _traced_functions(ctx.tree, jit_names):
            end = getattr(fn, "end_lineno", fn.lineno)
            traced_lines.update(range(fn.lineno, end + 1))
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if node.lineno in traced_lines:
                continue  # host-sync-in-jit owns the traced bodies
            f = node.func
            hit = None
            if isinstance(f, ast.Attribute):
                if f.attr == "block_until_ready" or (
                    f.attr == "item" and not node.args
                ):
                    hit = f.attr
            d = _dotted(f)
            if d in ("jax.block_until_ready", "jax.device_get", "device_get"):
                hit = d
            if hit is None:
                continue
            yield ctx.finding(
                self.id, node,
                f"naked `{hit}` sync point on the host orchestration "
                "path: a wedged dispatch blocks this worker with no "
                "deadline and no failover — bracket the dispatch+fetch "
                "with the device guard (utils/devguard.py run()) or use "
                "obs.block_ready_ms so the wait is watchdogged and "
                "span-attributed, or pragma a deliberate host-value "
                ".item() with the WHY",
            )


# -- rule: unchecked-hop-loop -----------------------------------------------

# the expander/dispatch seam: calls that (directly or one wrapper deep)
# cost a hop dispatch per iteration.  ``expand`` as a BARE name covers
# the local-closure shape (query/shortest.py's lazy expander); the rest
# are the engine/resolver method names.
_HOP_SEAM_ATTRS = {
    "expand", "_expand", "_expand_rows", "_exec_child",
    "_exec_child_inner", "submit_hop", "multi_hop",
}
# segmented dataflow (PR 18): a host loop that re-dispatches a carry
# through a bounded program segment — by convention every segment
# driver names its per-segment dispatch helper `_dispatch_segment`
# (ops/batch.py, query/chain.py, query/joinplan.py, mesh/executor.py)
_SEG_SEAM_ATTRS = {"_dispatch_segment"}
_HOP_CHECK_ATTRS = {"checkpoint"}


def _is_seam_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr in _HOP_SEAM_ATTRS
    return isinstance(f, ast.Name) and f.id == "expand"


def _is_segment_dispatch_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr in _SEG_SEAM_ATTRS
    return isinstance(f, ast.Name) and f.id in _SEG_SEAM_ATTRS


def _is_checkpoint_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr in _HOP_CHECK_ATTRS:
            return True
        # the scheduler yield point between program segments
        # (sched/segments.py): segments.seam(...) probes the token AND
        # offers preemption — it IS the checkpoint of a segment loop
        if f.attr == "seam":
            return "segment" in _dotted(f).lower()
        # direct token probe: <something>cancel/token<something>.check()
        if f.attr == "check":
            root = _dotted(f).lower()
            return "cancel" in root or "token" in root
    return isinstance(f, ast.Name) and f.id in _HOP_CHECK_ATTRS


class UncheckedHopLoop(Rule):
    id = "unchecked-hop-loop"
    doc = (
        "loop driving the expander/dispatch seam (query/) or "
        "re-dispatching a segment carry (_dispatch_segment in "
        "query//ops//mesh/) without a CancelToken checkpoint or "
        "segments.seam() yield point — cooperative cancellation and "
        "segment preemption need a probe between EVERY pair of "
        "dispatches"
    )

    # query/ is the layer that drives hop dispatches in loops; ops/
    # loops run INSIDE jitted programs where a checkpoint is impossible
    # by design (the documented cancellation granularity is one
    # dispatched program), and sched/ owns the token itself.  The ONE
    # exception to the ops//mesh/ exemption is the segment driver
    # (PR 18): its `_dispatch_segment` loop is a HOST loop between
    # bounded programs — exactly where a yield point is possible and
    # required — so those calls are checked in all three layers.
    _DIRS = ("query/",)
    _SEG_DIRS = ("query/", "ops/", "mesh/")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        path = ctx.path.replace("\\", "/")
        hop_layer = any(d in path for d in self._DIRS)
        seg_layer = any(d in path for d in self._SEG_DIRS)
        if not hop_layer and not seg_layer:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                continue
            has_seam = False
            has_seg = False
            has_check = False
            for sub in ast.walk(node):
                if hop_layer and _is_seam_call(sub):
                    has_seam = True
                elif seg_layer and _is_segment_dispatch_call(sub):
                    has_seg = True
                elif _is_checkpoint_call(sub):
                    has_check = True
            if (has_seam or has_seg) and not has_check:
                what = (
                    "re-dispatches a program-segment carry"
                    if has_seg
                    else "dispatches hop expansions"
                )
                yield ctx.finding(
                    self.id, node,
                    f"this loop {what} but never probes the request's "
                    "CancelToken or yield point: a deadline-expired, "
                    "disconnected, or preemptable query keeps burning "
                    "engine time here — call engine.checkpoint() / "
                    "segments.seam() / <token>.check() between "
                    "dispatches, or pragma the site with the WHY",
                )


# -- rule: unregistered-metric ------------------------------------------------

# the MetricsRegistry constructor methods (utils/metrics.py) — the only
# sanctioned way a dgraph_* series comes into existence
_METRIC_CTORS = {
    "counter", "gauge", "func_gauge", "labeled", "multilabeled",
    "labeled_gauge", "multilabeled_gauge", "histogram",
    "labeled_histogram",
}


class UnregisteredMetric(Rule):
    id = "unregistered-metric"
    doc = (
        "dgraph_* metric series constructed without a row in the "
        "docs/deploy.md metric catalog — every exported series must be "
        "documented where operators build dashboards and alerts, or it "
        "is dark data with a scrape cost"
    )

    # lazily-parsed catalog: the backticked dgraph_* names in deploy.md's
    # "### Metric catalog" section (scoped to the section so prose
    # elsewhere mentioning a series does not register it).  Tests
    # override ``catalog_override`` to pin the set.
    catalog_override: Optional[Set[str]] = None
    _catalog_cache: Optional[Set[str]] = None

    @classmethod
    def catalog(cls) -> Set[str]:
        if cls.catalog_override is not None:
            return cls.catalog_override
        if cls._catalog_cache is None:
            names: Set[str] = set()
            doc = (
                Path(__file__).resolve().parents[2]
                / "docs" / "deploy.md"
            )
            if doc.exists():
                in_section = False
                for line in doc.read_text(encoding="utf-8").splitlines():
                    if line.startswith("### Metric catalog"):
                        in_section = True
                        continue
                    if in_section and line.startswith("#"):
                        break
                    if in_section:
                        names.update(
                            re.findall(r"`(dgraph_[a-z0-9_]+)`", line)
                        )
            cls._catalog_cache = names
        return cls._catalog_cache

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        catalog = self.catalog()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (
                isinstance(f, ast.Attribute) and f.attr in _METRIC_CTORS
            ):
                continue
            # the series name is the first positional OR the name=
            # keyword — a kwarg spelling must not slip the gate
            a0 = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "name"),
                None,
            )
            if a0 is None:
                continue
            if not (
                isinstance(a0, ast.Constant)
                and isinstance(a0.value, str)
                and a0.value.startswith("dgraph_")
            ):
                continue
            name = a0.value
            # histogram exposition appends _bucket/_sum/_count; the
            # catalog documents the family name, which is what is
            # constructed here — exact match is the contract
            if name not in catalog:
                yield ctx.finding(
                    self.id, node,
                    f"series {name!r} has no row in the docs/deploy.md "
                    "metric catalog (### Metric catalog): add one — "
                    "name, type, labels, one-line meaning — or pragma "
                    "the site with WHY the series is deliberately "
                    "undocumented",
                )


# -- rule: unregistered-program-factory --------------------------------------

# the compiled-program constructors: jax.jit / jax.pmap (via the shared
# alias helper) plus pallas_call in its import spellings
_PALLAS_NAMES = {
    "pallas_call", "pl.pallas_call", "pallas.pallas_call",
    "jax.experimental.pallas.pallas_call",
}


def _factory_names(tree: ast.AST) -> Set[str]:
    return _jit_aliases(tree) | _PALLAS_NAMES


def _is_factory_construction(node: ast.AST, names: Set[str]) -> bool:
    """A Call that actually BUILDS a compiled-program factory:
    ``jax.jit(fn)`` / ``pl.pallas_call(kernel, ...)`` /
    ``partial(jax.jit, static_argnames=...)(fn)`` with operands (a bare
    ``jax.jit`` reference constructs nothing)."""
    if not isinstance(node, ast.Call):
        return False
    if _dotted(node.func) in names and bool(node.args):
        return True
    # the curried spelling: partial(jax.jit, ...)(fn) — the inner
    # partial(...) Call is not itself a construction (so no double
    # count), the application to fn is
    f = node.func
    return (
        isinstance(f, ast.Call)
        and _dotted(f.func) in ("partial", "functools.partial")
        and bool(f.args)
        and _is_jit_expr(f.args[0], names)
        and bool(node.args)
    )


class UnregisteredProgramFactory(Rule):
    id = "unregistered-program-factory"
    doc = (
        "jax.jit / pl.pallas_call construction in dgraph_tpu/ whose "
        "factory site is not registered in the device-program contract "
        "registry (analysis/programs.py) — every compiled kernel "
        "carries a checked contract or an explicit exemption"
    )

    # tests pin the acceptance set; production reads the live registry
    coverage_override: Optional[Set[str]] = None

    @classmethod
    def coverage(cls) -> Set[str]:
        if cls.coverage_override is not None:
            return cls.coverage_override
        # lazy: programs.py imports nothing heavy at module level by
        # design, so the lint pass stays cheap
        from dgraph_tpu.analysis.programs import covered_sites

        return covered_sites()

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        path = ctx.path.replace("\\", "/")
        if not (
            path.startswith("dgraph_tpu/") or "/dgraph_tpu/" in path
        ) or "analysis/" in path:
            return
        names = _factory_names(ctx.tree)
        sites: List[Tuple[ast.AST, str]] = []
        self._visit(ctx.tree, [], names, sites, set())
        cov = self.coverage()
        for node, qual in sites:
            key = f"{path}::{qual}"
            if key not in cov:
                yield ctx.finding(
                    self.id, node,
                    f"compiled-program factory `{key}` is not registered "
                    "in the program-contract registry: add a "
                    "ProgramContract covering it (or an EXEMPT_SITES "
                    "entry with the why) in dgraph_tpu/analysis/"
                    "programs.py — kernels land with a contract, not a "
                    "hope (docs/analysis.md#program-contracts)",
                )

    def _visit(
        self, node: ast.AST, stack: List[str], names: Set[str],
        out: List[Tuple[ast.AST, str]], seen: Set[int],
    ) -> None:
        qual = ".".join(stack) if stack else "<module>"
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_expr(dec, names):
                    # anchor on the decorator line so the pragma sits
                    # where the construction is
                    out.append((dec, ".".join(stack + [node.name])))
                    seen.update(
                        id(s) for s in ast.walk(dec)
                        if isinstance(s, ast.Call)
                    )
            stack = stack + [node.name]
        elif isinstance(node, ast.ClassDef):
            stack = stack + [node.name]
        elif isinstance(node, ast.Assign) and _is_factory_construction(
            node.value, names
        ):
            # `intersect_batch = jax.jit(...)` at module level is named
            # by its target; inside a factory function the function IS
            # the site name
            t = node.targets[0]
            site = (
                t.id if qual == "<module>" and isinstance(t, ast.Name)
                else qual
            )
            out.append((node, site))
            seen.add(id(node.value))
        elif (
            _is_factory_construction(node, names) and id(node) not in seen
        ):
            out.append((node, qual))
            seen.add(id(node))
        for child in ast.iter_child_nodes(node):
            self._visit(child, stack, names, out, seen)


# -- rule: naked-resident-transfer --------------------------------------------

def _residentish(node: ast.AST) -> bool:
    """Does this expression reach into a resident arena's device
    buffers?  Matches any name/attribute mentioning ``resident`` (e.g.
    ``arena.resident()``, ``self._resident``) and the ``off``/``dst``
    lanes of a receiver conventionally named for one (``ra``/``nra``)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute):
            if "resident" in sub.attr:
                return True
            if sub.attr in ("off", "dst"):
                base = sub.value
                if isinstance(base, ast.Name) and base.id in (
                    "ra", "nra", "resident"
                ):
                    return True
                if (
                    isinstance(base, ast.Call)
                    and isinstance(base.func, ast.Attribute)
                    and base.func.attr == "resident"
                ):
                    return True
        elif isinstance(sub, ast.Name) and "resident" in sub.id:
            return True
    return False


class NakedResidentTransfer(Rule):
    id = "naked-resident-transfer"
    doc = (
        "jax.device_put / np.asarray / jnp.asarray on a resident "
        "arena's device buffers outside models/arena.py — the resident "
        "tier's whole contract is that the CSR never re-crosses the "
        "host/device boundary after seeding (ledger h2d/d2h = 0 for a "
        "warm hop); staging or fetching those buffers elsewhere "
        "reintroduces the transfer tax the tier deletes, uncharged"
    )

    # models/arena.py is the sanctioned home of every resident-buffer
    # staging (ResidentArena.seed / apply_delta, both ledger-charged)
    _HOME = "models/arena.py"
    _XFER = (
        "jax.device_put", "device_put",
        "np.asarray", "numpy.asarray", "np.array", "numpy.array",
        "jnp.asarray", "jnp.array",
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.path.replace("\\", "/").endswith(self._HOME):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if _dotted(node.func) not in self._XFER:
                continue
            if any(_residentish(a) for a in node.args):
                yield ctx.finding(
                    self.id, node,
                    "transfer primitive on a resident arena buffer: the "
                    "pinned CSR must never re-cross the boundary outside "
                    "models/arena.py (seed/apply_delta, ledger-charged) "
                    "— expand via ResidentArena.expand_packed and fetch "
                    "only the packed result, or pragma the site with the "
                    "WHY",
                )


# -- rule: naked-collective ---------------------------------------------------

class NakedCollective(Rule):
    id = "naked-collective"
    doc = (
        "shard_map / psum / all_gather / ppermute outside dgraph_tpu/"
        "mesh/ and dgraph_tpu/parallel/ — cross-chip collectives are "
        "the mesh plane's contract surface (placement-invariant "
        "reassembly, exchange-bytes ledger attribution, program "
        "contracts); a collective grown elsewhere ships none of that"
    )

    # the two sanctioned homes: parallel/ (per-hop mesh steps) and
    # mesh/ (the fused serving plane, PR 17)
    _HOMES = ("dgraph_tpu/mesh/", "dgraph_tpu/parallel/")
    _COLLECTIVES = frozenset(
        {"shard_map", "psum", "all_gather", "ppermute"}
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        path = ctx.path.replace("\\", "/")
        if any(h in path for h in self._HOMES):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            name = dotted.rsplit(".", 1)[-1]
            if name not in self._COLLECTIVES:
                continue
            yield ctx.finding(
                self.id, node,
                f"cross-chip collective `{dotted}` outside the mesh "
                "plane: collectives live in dgraph_tpu/mesh/ (fused "
                "serving programs) or dgraph_tpu/parallel/ (per-hop "
                "steps), where reassembly stays placement-invariant, "
                "exchange bytes are ledger-charged, and the program "
                "carries a checked contract — move the program there "
                "or pragma the site with the WHY",
            )


ALL_RULES: Tuple[Rule, ...] = (
    HostSyncInJit(),
    RecompileHazard(),
    WallClockDuration(),
    SwallowedException(),
    NakedPeerRpc(),
    NakedAtomicWrite(),
    NakedStageTiming(),
    NakedRouteThreshold(),
    NakedVersionKey(),
    NakedDeviceSync(),
    UncheckedHopLoop(),
    UnregisteredMetric(),
    UnregisteredProgramFactory(),
    NakedResidentTransfer(),
    NakedCollective(),
)
