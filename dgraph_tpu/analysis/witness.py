"""Runtime lock-order witness — lockdep for the Python side of the engine.

The static pass (:mod:`.lockorder`) sees ``with`` nesting and same-class
calls; it cannot see a scheduler worker that holds the engine read lock
while the arena manager takes its cache lock while the hop cache takes
its own — that order only exists at runtime, across objects and
threads.  This recorder observes it.

Mechanism: :func:`arm` swaps a proxy ``threading`` namespace into every
loaded ``dgraph_tpu.*`` module, so locks **constructed after arming**
are wrapper objects that report acquire/release to a global witness.
Like lockdep, locks are grouped into *classes by construction site*
(``sched/scheduler.py:135`` names every scheduler's condition); the
witness maintains a per-thread held stack and a global first-seen order
table of (held, acquired) pairs.  Seeing both (A, B) and (B, A) —
from any two threads, any two tests, any two instances of the classes
— is an inversion: the interleaving that deadlocks may never fire in
CI, but the *order disagreement* is already provable.  Same-class
pairs get a second, instance-serial table: two instances of ONE class
taken in both orders (the two-caches ABBA that collapses to a
self-edge at class level) is caught by wrapper serial, while true
reentrancy on a single RLock instance stays exempt.  RWLocks are
instrumented at the class level (read and write side both count as
holding the lock class; their internal condition is deliberately NOT
witnessed — it would only add leaf noise).

Exclusions (documented, deliberate):

- ``utils.metrics`` — its locks are hot leaf locks (verified: no
  metric method calls out while holding one); witnessing them costs
  measurable tier-1 time for zero ordering information;
- locks created at import time (``models.arena._BUILD_LOCK``,
  ``native._lock``) predate arming — the static pass covers their
  nesting;
- ``analysis.*`` itself.

Armed for the whole tier-1 run by ``tests/conftest.py``; any inversion
fails the session.  ``Witness()`` instances can also be used directly
(the seeded-inversion test in tests/test_analysis.py does).

**Tier 3 — Eraser lockset witness** (co-gated by ``DGRAPH_TPU_RACES``,
default on whenever the lock witness is armed): classes that declare
``__race_fields__ = frozenset({...})`` get their ``__setattr__``
wrapped *at arm time* — the unarmed serving path keeps the original
slot/dict setattr and allocates nothing.  Every write to a declared
field feeds the classic lockset state machine (Savage et al.):

- first write → *Exclusive*, owned by the writing thread; same-owner
  writes are a lock-free fast path and — authentic Eraser — do NOT
  refine the lockset, so init-before-share patterns stay silent;
- first write by a second thread → *Shared-Modified*; the candidate
  lockset becomes the locks that thread holds (witnessed wrappers on
  the per-thread held stack).  An empty lockset here is the tolerated
  single-writer HAND-OFF (scheduler → flush worker), not yet a race;
- every further write intersects the lockset with the held set; an
  EMPTY lockset on a write by a thread other than the last writer is
  a data race — reported with both write sites and failing the session
  through the same ``sessionfinish`` path as lock inversions.

Explicit hand-off points reset a struct's field states (new epoch, new
owner): ``obs.ledger.activate`` and ``SchedRequest.complete/fail`` are
wrapped at arm time, mirroring the happens-before edges the pooled
ledger actually relies on (``req.wait()``/``complete()``).

Scope note: ``__setattr__`` sees attribute REBINDS — scalar counters,
state enums, published references.  ``self.d[k] = v`` mutates the dict,
not the attribute; container-valued fields are covered by locking the
container writes (the static escape pass checks those sites).
"""

from __future__ import annotations

import itertools
import os
import sys
import threading as _real_threading
from typing import Dict, FrozenSet, List, Optional, Tuple

_INFRA_FILES = ("analysis/witness.py", "utils/rwlock.py", "threading.py")

# per-wrapper monotonic serials (NOT id(): ids recycle after GC and a
# recycled id could alias a dead lock into a false inversion)
_serial = itertools.count(1)

# writer identity for the lockset state machine (NOT get_ident(): the
# OS recycles idents the moment a thread exits, so two short-lived
# sequential writers would alias into one and hide the alternation that
# defines a ping-pong race)
_thread_tokens = itertools.count(1)
_tls = _real_threading.local()


def _thread_token() -> int:
    tok = getattr(_tls, "token", None)
    if tok is None:
        tok = next(_thread_tokens)
        _tls.token = tok
    return tok


def races_enabled() -> bool:
    """Lockset-witness gate: ``DGRAPH_TPU_RACES=0`` opts out (the lock
    witness itself stays governed by ``DGRAPH_TPU_WITNESS``)."""
    return os.environ.get("DGRAPH_TPU_RACES", "1") != "0"


def _short_stack(skip: int = 2, depth: int = 4) -> str:
    """Compact caller stack (innermost first), infra frames elided."""
    try:
        f = sys._getframe(skip)
    except ValueError:  # pragma: no cover
        return "<unknown>"
    parts: List[str] = []
    while f is not None and len(parts) < depth:
        fn = f.f_code.co_filename.replace("\\", "/")
        if not any(fn.endswith(s) for s in _INFRA_FILES):
            short = "/".join(fn.rsplit("/", 3)[-3:])
            parts.append(f"{short}:{f.f_lineno}")
        f = f.f_back
    return " <- ".join(parts) or "<unknown>"


def _creation_site(skip: int = 2) -> str:
    """file:line of the nearest non-infrastructure caller frame."""
    best = None
    f = sys._getframe(skip)
    for _ in range(10):
        if f is None:
            break
        fn = f.f_code.co_filename.replace("\\", "/")
        if not any(fn.endswith(s) for s in _INFRA_FILES):
            short = "/".join(fn.rsplit("/", 3)[-3:])
            return f"{short}:{f.f_lineno}"
        if best is None:
            short = "/".join(fn.rsplit("/", 3)[-3:])
            best = f"{short}:{f.f_lineno}"
        f = f.f_back
    return best or "<unknown>"


class Witness:
    """Order table + per-thread held stacks.  All bookkeeping uses REAL
    threading primitives and never calls out while holding its own lock
    (the witness must not deadlock the system it watches)."""

    def __init__(self) -> None:
        self._mu = _real_threading.Lock()
        self._tls = _real_threading.local()
        # class level: (a, b) -> "a@siteA -> b@siteB" for the FIRST
        # observation of class b acquired while class a held
        self._order: Dict[Tuple[str, str], str] = {}
        # instance level, for SAME-class pairs only: two instances of
        # one lock class taken in both orders is the classic ABBA the
        # class table cannot see (both directions collapse to a
        # self-edge).  Keyed by wrapper serials; bounded below.
        self._inst_order: Dict[Tuple[int, int], str] = {}
        self._inst_saturated = False
        self._inversions: List[str] = []
        # Eraser lockset state: instance serial -> field -> _FieldState
        self._fields: Dict[int, Dict[str, "_FieldState"]] = {}
        self._field_count = 0
        self._field_saturated = False
        self._races: List[str] = []
        self.active = True

    _INST_CAP = 100_000  # instance-pair table bound (serials churn)
    _FIELD_CAP = 200_000  # field-state table bound (instances churn)

    # -- core events --------------------------------------------------------

    def _held(self) -> List[Tuple[str, int]]:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def note_acquire(self, name: str, serial: int = 0) -> None:
        if not self.active:
            return
        held = self._held()
        if held:
            site = None
            for h, hs in held:
                if h == name:
                    if not serial or not hs or hs == serial:
                        continue  # reentrant (RLock) — not an order fact
                    # same class, DIFFERENT instances: track by serial
                    if (hs, serial) not in self._inst_order:
                        if site is None:
                            site = _creation_site(2)
                        with self._mu:
                            if (hs, serial) not in self._inst_order:
                                if len(self._inst_order) < self._INST_CAP:
                                    self._inst_order[(hs, serial)] = site
                                elif not self._inst_saturated:
                                    # no silent caps: past this point
                                    # same-class inversion detection is
                                    # degraded — say so once, loudly
                                    self._inst_saturated = True
                                    print(
                                        "graftcheck witness: instance-"
                                        f"order table hit its {self._INST_CAP}"
                                        "-pair cap; same-class inversion "
                                        "detection is degraded for the "
                                        "rest of this run",
                                        file=sys.stderr,
                                    )
                                rev = self._inst_order.get((serial, hs))
                                if rev is not None:
                                    self._inversions.append(
                                        "lock-order inversion (two "
                                        f"instances of class {name}): "
                                        f"#{hs} -> #{serial} @ {site} BUT "
                                        f"#{serial} -> #{hs} @ {rev}"
                                    )
                    continue
                if (h, name) not in self._order:  # racy pre-check is fine:
                    # worst case two threads compute the site; insert
                    # below is serialized under _mu
                    if site is None:
                        site = _creation_site(2)
                    with self._mu:
                        if (h, name) not in self._order:
                            self._order[(h, name)] = f"{h} then {name} @ {site}"
                            rev = self._order.get((name, h))
                            if rev is not None:
                                self._inversions.append(
                                    f"lock-order inversion: [{name} -> {h}] "
                                    f"seen as {rev}; BUT [{h} -> {name}] "
                                    f"seen as {self._order[(h, name)]}"
                                )
        held.append((name, serial))

    def note_release(self, name: str, serial: int = 0) -> None:
        if not self.active:
            return
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name and (not serial or held[i][1] == serial):
                del held[i]
                return

    # -- Eraser lockset (tier 3) --------------------------------------------

    def note_field_write(self, obj, name: str) -> None:
        """One write to a declared race field — drive the lockset state
        machine.  The same-owner Exclusive path is lock-free and walks
        no frames: that is the overhead bound for single-writer structs
        (ledgers between hand-offs, per-request state)."""
        if not self.active:
            return
        try:
            s = getattr(obj, "_race_serial", None)
        except Exception:  # noqa: BLE001 — exotic __getattr__: not ours
            return
        if s is None:
            try:
                s = next(_serial)
                object.__setattr__(obj, "_race_serial", s)
            except (AttributeError, TypeError):
                return  # __slots__ without a _race_serial slot
        tid = _thread_token()
        per = self._fields.get(s)
        st = per.get(name) if per is not None else None
        if st is None:
            with self._mu:
                per = self._fields.setdefault(s, {})
                st = per.get(name)
                if st is None:
                    if self._field_count >= self._FIELD_CAP:
                        if not self._field_saturated:
                            # no silent caps: say so once, loudly
                            self._field_saturated = True
                            print(
                                "graftcheck witness: field-state table "
                                f"hit its {self._FIELD_CAP}-entry cap; "
                                "race detection is degraded for the "
                                "rest of this run",
                                file=sys.stderr,
                            )
                        return
                    per[name] = _FieldState(tid, _short_stack(3))
                    self._field_count += 1
                    _bump_fields_metric()
                    return
        if not st.shared and st.owner == tid:
            return  # Exclusive, same owner: Eraser does NOT refine here
        heldset = frozenset(self._held())
        with self._mu:
            if not st.shared:
                # Exclusive -> Shared-Modified: the candidate lockset is
                # whatever the second writer holds.  Empty is the
                # tolerated single hand-off, not yet a race.
                st.shared = True
                st.lockset = heldset
                st.last_writer = tid
                st.last_site = _short_stack(3)
                return
            ls = st.lockset & heldset
            alternated = tid != st.last_writer
            prev_writer, prev_site = st.last_writer, st.last_site
            st.lockset = ls
            st.last_writer = tid
            if ls:
                # locked steady state: elide the stack walk (hot path
                # for properly-guarded shared counters)
                return
            site = _short_stack(3)
            st.last_site = site
            if alternated and not st.reported:
                st.reported = True
                self._races.append(
                    f"data race: {type(obj).__name__}.{name} "
                    f"(instance #{s}): write by thread {tid} at [{site}] "
                    "with EMPTY lockset; previous write by thread "
                    f"{prev_writer} at [{prev_site or '<locked write, stack elided>'}]; "
                    f"first write by thread {st.owner} at [{st.first_site}]"
                )

    def reset_fields(self, obj) -> None:
        """Hand-off point: forget this instance's field states so the
        next writer starts a fresh Exclusive epoch (the caller asserts a
        happens-before edge — ledger activate, request completion)."""
        try:
            s = getattr(obj, "_race_serial", None)
        except Exception:  # noqa: BLE001
            return
        if s is None:
            return
        with self._mu:
            per = self._fields.pop(s, None)
            if per:
                self._field_count -= len(per)

    # -- reporting ----------------------------------------------------------

    def inversions(self) -> List[str]:
        with self._mu:
            return list(self._inversions)

    def races(self) -> List[str]:
        with self._mu:
            return list(self._races)

    def edges(self) -> Dict[Tuple[str, str], str]:
        with self._mu:
            return dict(self._order)


class _FieldState:
    """Lockset state for ONE field of ONE instance (keyed by the
    instance's monotonic serial — ids recycle, serials don't)."""

    __slots__ = (
        "owner", "first_site", "last_writer", "last_site",
        "lockset", "shared", "reported",
    )

    def __init__(self, owner: int, first_site: str) -> None:
        self.owner = owner            # first writer's thread id
        self.first_site = first_site
        self.last_writer = owner
        self.last_site: Optional[str] = None
        self.lockset: FrozenSet = frozenset()
        self.shared = False
        self.reported = False


_fields_metric = None


def _bump_fields_metric() -> None:
    global _fields_metric
    if _fields_metric is None:
        from dgraph_tpu.utils.metrics import RACE_WITNESS_FIELDS
        _fields_metric = RACE_WITNESS_FIELDS
    _fields_metric.add(1)


# -- wrapper primitives -----------------------------------------------------

class _WLock:
    """threading.Lock/RLock wrapper reporting to a witness."""

    def __init__(self, witness: Witness, name: str, inner) -> None:
        self._w = witness
        self._name = name
        self._inner = inner
        self._ws = next(_serial)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._w.note_acquire(self._name, self._ws)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._w.note_release(self._name, self._ws)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover
        return f"<witnessed {self._name} {self._inner!r}>"


class _WCondition(_real_threading.Condition):
    """Condition subclass reporting to a witness.  ``wait`` releases the
    underlying lock, so the held-stack entry pops for the wait's
    duration — otherwise every post-wait acquisition would look nested
    under the condition."""

    def __init__(self, witness: Witness, name: str, lock=None) -> None:
        super().__init__(lock)
        self._wname = name
        self._w = witness
        self._ws = next(_serial)
        # threading.Condition.__init__ binds self.acquire/self.release
        # as INSTANCE attributes (the inner lock's bound methods), which
        # would shadow any class-level override — rebind them here so
        # direct cond.acquire()/release() calls are witnessed too.
        # (Condition.wait uses _release_save/_acquire_restore, which go
        # straight to the inner lock — our wait() override covers that.)
        inner_acquire, inner_release = self.acquire, self.release

        def acquire(*a, **k):
            ok = inner_acquire(*a, **k)
            if ok:
                self._w.note_acquire(self._wname, self._ws)
            return ok

        def release():
            self._w.note_release(self._wname, self._ws)
            inner_release()

        self.acquire = acquire
        self.release = release

    def __enter__(self):
        r = super().__enter__()
        self._w.note_acquire(self._wname, self._ws)
        return r

    def __exit__(self, *exc):
        self._w.note_release(self._wname, self._ws)
        return super().__exit__(*exc)

    def wait(self, timeout: Optional[float] = None):
        self._w.note_release(self._wname, self._ws)
        try:
            return super().wait(timeout)
        finally:
            self._w.note_acquire(self._wname, self._ws)
    # wait_for() is inherited and loops over wait() — covered.


class _ThreadingProxy:
    """Module-shaped object delegating to real ``threading`` with the
    lock constructors swapped for witnessing ones.  Injected into a
    module's ``threading`` global, so only dgraph_tpu code sees it."""

    def __init__(self, witness: Witness) -> None:
        self._w = witness

    def Lock(self):
        return _WLock(self._w, _creation_site(), _real_threading.Lock())

    def RLock(self):
        return _WLock(self._w, _creation_site(), _real_threading.RLock())

    def Condition(self, lock=None):
        return _WCondition(self._w, _creation_site(), lock)

    def __getattr__(self, name: str):
        return getattr(_real_threading, name)


# -- arming -----------------------------------------------------------------

_EXCLUDE_MODULES = (
    "dgraph_tpu.analysis",
    "dgraph_tpu.utils.metrics",   # hot leaf locks, verified no fan-out
    "dgraph_tpu.utils.rwlock",    # instrumented at class level below
)

_global: Optional[Witness] = None
_patched: List[Tuple[object, str, object]] = []  # (obj, attr, original)


def arm() -> Witness:
    """Install the witness into every loaded dgraph_tpu module (and any
    imported later gets covered when arm() is called again — conftest
    arms once after test collection, which imports everything).
    Idempotent; returns the global witness."""
    global _global
    if _global is None:
        _global = Witness()
    w = _global
    proxy = _ThreadingProxy(w)
    for name, mod in list(sys.modules.items()):
        if mod is None or not name.startswith("dgraph_tpu"):
            continue
        if any(name.startswith(e) for e in _EXCLUDE_MODULES):
            continue
        cur = getattr(mod, "threading", None)
        if cur is _real_threading:
            _patched.append((mod, "threading", cur))
            mod.threading = proxy
    _instrument_rwlock(w)
    if races_enabled():
        _instrument_race_classes()
        _instrument_handoffs()
    return w


def disarm() -> None:
    """Restore patched namespaces.  Wrapper locks already embedded in
    live objects keep functioning (the witness just goes inactive)."""
    global _global
    for obj, attr, orig in _patched:
        setattr(obj, attr, orig)
    _patched.clear()
    for cls, own_setattr in _race_patched:
        if own_setattr is not None:
            cls.__setattr__ = own_setattr
        else:
            try:
                del cls.__setattr__
            except AttributeError:  # pragma: no cover
                pass
        try:
            del cls._race_instrumented
        except AttributeError:  # pragma: no cover
            pass
    _race_patched.clear()
    if _global is not None:
        _global.active = False
        _global = None


def current() -> Optional[Witness]:
    return _global


def _instrument_rwlock(w: Witness) -> None:
    """Patch RWLock at the class level: read and write side both count
    as holding the lock's class (an RWLock inversion is an inversion no
    matter which side each thread took — the write side excludes both)."""
    from dgraph_tpu.utils import rwlock as _rw

    if getattr(_rw.RWLock, "_witnessed", False):
        return
    _rw.RWLock._witnessed = True
    orig_init = _rw.RWLock.__init__
    orig = {
        m: getattr(_rw.RWLock, m)
        for m in ("acquire_read", "release_read", "acquire_write",
                  "release_write")
    }

    def __init__(self):  # noqa: N807
        orig_init(self)
        self._witness_name = _creation_site()
        self._witness_serial = next(_serial)

    def make(method, note_after_acquire: bool):
        o = orig[method]
        if note_after_acquire:
            def wrapped(self):
                o(self)
                wit = current()
                if wit is not None:
                    wit.note_acquire(
                        getattr(self, "_witness_name", "rwlock"),
                        getattr(self, "_witness_serial", 0),
                    )
        else:
            def wrapped(self):
                wit = current()
                if wit is not None:
                    wit.note_release(
                        getattr(self, "_witness_name", "rwlock"),
                        getattr(self, "_witness_serial", 0),
                    )
                o(self)
        return wrapped

    _rw.RWLock.__init__ = __init__
    _rw.RWLock.acquire_read = make("acquire_read", True)
    _rw.RWLock.acquire_write = make("acquire_write", True)
    _rw.RWLock.release_read = make("release_read", False)
    _rw.RWLock.release_write = make("release_write", False)


# -- Eraser instrumentation (tier 3) ----------------------------------------

# (cls, its own pre-wrap __setattr__ or None if it inherited object's)
_race_patched: List[Tuple[type, Optional[object]]] = []


def _instrument_race_classes() -> None:
    """Wrap ``__setattr__`` on every loaded class declaring
    ``__race_fields__``.  Installed at arm time ONLY: before arming (and
    after disarm) annotated classes keep the original slot/dict setattr
    — the unarmed serving path pays nothing and allocates nothing."""
    for name, mod in list(sys.modules.items()):
        if mod is None or not name.startswith("dgraph_tpu"):
            continue
        if any(name.startswith(e) for e in _EXCLUDE_MODULES):
            continue
        for obj in list(vars(mod).values()):
            if isinstance(obj, type) and "__race_fields__" in vars(obj):
                _instrument_one_class(obj)


def _instrument_one_class(cls: type) -> None:
    if vars(cls).get("_race_instrumented"):
        return
    fields = frozenset(vars(cls)["__race_fields__"])
    own = vars(cls).get("__setattr__")
    orig = cls.__setattr__  # resolved: own override or object/slot setattr

    def __setattr__(self, name, value, _orig=orig, _fields=fields):
        _orig(self, name, value)
        if name in _fields:
            wit = _global
            if wit is not None and wit.active:
                wit.note_field_write(self, name)

    cls.__setattr__ = __setattr__
    cls._race_instrumented = True
    _race_patched.append((cls, own))


def _instrument_handoffs() -> None:
    """Wrap the hand-off points that establish happens-before edges for
    the pooled ledger: ``activate`` (flush worker takes ownership) and
    ``SchedRequest.complete/fail`` (``req.wait()`` releases the blocked
    handler, which owns the struct from then on).  Each wrap resets the
    struct's field states — a new Exclusive epoch for the new owner."""
    led = sys.modules.get("dgraph_tpu.obs.ledger")
    if led is not None and not getattr(led.activate, "_race_wrap", False):
        orig_activate = led.activate

        def activate(l, _orig=orig_activate):  # noqa: E741 — ledger arg
            wit = _global
            if wit is not None and wit.active:
                wit.reset_fields(l)
            return _orig(l)

        activate._race_wrap = True
        led.activate = activate
        _patched.append((led, "activate", orig_activate))
    if led is not None and not getattr(led.finish, "_race_wrap", False):
        # finish() drains + resets + recycles through the pool: the end
        # of the struct's life under this request.  Reset BEFORE the
        # original so finish's own reset() stores open a fresh epoch
        # owned by the draining thread, and the next start()'s tenant
        # write — which lands before activate() can reset — reads as
        # the tolerated pool hand-off, not a ping-pong with the
        # previous request's writers.
        orig_finish = led.finish

        def finish(l, _orig=orig_finish):  # noqa: E741 — ledger arg
            wit = _global
            if wit is not None and wit.active:
                wit.reset_fields(l)
            return _orig(l)

        finish._race_wrap = True
        led.finish = finish
        _patched.append((led, "finish", orig_finish))

    coh = sys.modules.get("dgraph_tpu.sched.cohort")
    if coh is not None:
        for meth in ("complete", "fail"):
            orig = getattr(coh.SchedRequest, meth)
            if getattr(orig, "_race_wrap", False):
                continue

            def _make(o):
                def wrapped(self, *a, **k):
                    wit = _global
                    if wit is not None and wit.active:
                        led_obj = getattr(self, "ledger", None)
                        if led_obj is not None:
                            wit.reset_fields(led_obj)
                    return o(self, *a, **k)

                wrapped._race_wrap = True
                return wrapped

            setattr(coh.SchedRequest, meth, _make(orig))
            _patched.append((coh.SchedRequest, meth, orig))
