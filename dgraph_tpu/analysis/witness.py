"""Runtime lock-order witness — lockdep for the Python side of the engine.

The static pass (:mod:`.lockorder`) sees ``with`` nesting and same-class
calls; it cannot see a scheduler worker that holds the engine read lock
while the arena manager takes its cache lock while the hop cache takes
its own — that order only exists at runtime, across objects and
threads.  This recorder observes it.

Mechanism: :func:`arm` swaps a proxy ``threading`` namespace into every
loaded ``dgraph_tpu.*`` module, so locks **constructed after arming**
are wrapper objects that report acquire/release to a global witness.
Like lockdep, locks are grouped into *classes by construction site*
(``sched/scheduler.py:135`` names every scheduler's condition); the
witness maintains a per-thread held stack and a global first-seen order
table of (held, acquired) pairs.  Seeing both (A, B) and (B, A) —
from any two threads, any two tests, any two instances of the classes
— is an inversion: the interleaving that deadlocks may never fire in
CI, but the *order disagreement* is already provable.  Same-class
pairs get a second, instance-serial table: two instances of ONE class
taken in both orders (the two-caches ABBA that collapses to a
self-edge at class level) is caught by wrapper serial, while true
reentrancy on a single RLock instance stays exempt.  RWLocks are
instrumented at the class level (read and write side both count as
holding the lock class; their internal condition is deliberately NOT
witnessed — it would only add leaf noise).

Exclusions (documented, deliberate):

- ``utils.metrics`` — its locks are hot leaf locks (verified: no
  metric method calls out while holding one); witnessing them costs
  measurable tier-1 time for zero ordering information;
- locks created at import time (``models.arena._BUILD_LOCK``,
  ``native._lock``) predate arming — the static pass covers their
  nesting;
- ``analysis.*`` itself.

Armed for the whole tier-1 run by ``tests/conftest.py``; any inversion
fails the session.  ``Witness()`` instances can also be used directly
(the seeded-inversion test in tests/test_analysis.py does).
"""

from __future__ import annotations

import itertools
import sys
import threading as _real_threading
from typing import Dict, List, Optional, Tuple

_INFRA_FILES = ("analysis/witness.py", "utils/rwlock.py", "threading.py")

# per-wrapper monotonic serials (NOT id(): ids recycle after GC and a
# recycled id could alias a dead lock into a false inversion)
_serial = itertools.count(1)


def _creation_site(skip: int = 2) -> str:
    """file:line of the nearest non-infrastructure caller frame."""
    best = None
    f = sys._getframe(skip)
    for _ in range(10):
        if f is None:
            break
        fn = f.f_code.co_filename.replace("\\", "/")
        if not any(fn.endswith(s) for s in _INFRA_FILES):
            short = "/".join(fn.rsplit("/", 3)[-3:])
            return f"{short}:{f.f_lineno}"
        if best is None:
            short = "/".join(fn.rsplit("/", 3)[-3:])
            best = f"{short}:{f.f_lineno}"
        f = f.f_back
    return best or "<unknown>"


class Witness:
    """Order table + per-thread held stacks.  All bookkeeping uses REAL
    threading primitives and never calls out while holding its own lock
    (the witness must not deadlock the system it watches)."""

    def __init__(self) -> None:
        self._mu = _real_threading.Lock()
        self._tls = _real_threading.local()
        # class level: (a, b) -> "a@siteA -> b@siteB" for the FIRST
        # observation of class b acquired while class a held
        self._order: Dict[Tuple[str, str], str] = {}
        # instance level, for SAME-class pairs only: two instances of
        # one lock class taken in both orders is the classic ABBA the
        # class table cannot see (both directions collapse to a
        # self-edge).  Keyed by wrapper serials; bounded below.
        self._inst_order: Dict[Tuple[int, int], str] = {}
        self._inst_saturated = False
        self._inversions: List[str] = []
        self.active = True

    _INST_CAP = 100_000  # instance-pair table bound (serials churn)

    # -- core events --------------------------------------------------------

    def _held(self) -> List[Tuple[str, int]]:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def note_acquire(self, name: str, serial: int = 0) -> None:
        if not self.active:
            return
        held = self._held()
        if held:
            site = None
            for h, hs in held:
                if h == name:
                    if not serial or not hs or hs == serial:
                        continue  # reentrant (RLock) — not an order fact
                    # same class, DIFFERENT instances: track by serial
                    if (hs, serial) not in self._inst_order:
                        if site is None:
                            site = _creation_site(2)
                        with self._mu:
                            if (hs, serial) not in self._inst_order:
                                if len(self._inst_order) < self._INST_CAP:
                                    self._inst_order[(hs, serial)] = site
                                elif not self._inst_saturated:
                                    # no silent caps: past this point
                                    # same-class inversion detection is
                                    # degraded — say so once, loudly
                                    self._inst_saturated = True
                                    print(
                                        "graftcheck witness: instance-"
                                        f"order table hit its {self._INST_CAP}"
                                        "-pair cap; same-class inversion "
                                        "detection is degraded for the "
                                        "rest of this run",
                                        file=sys.stderr,
                                    )
                                rev = self._inst_order.get((serial, hs))
                                if rev is not None:
                                    self._inversions.append(
                                        "lock-order inversion (two "
                                        f"instances of class {name}): "
                                        f"#{hs} -> #{serial} @ {site} BUT "
                                        f"#{serial} -> #{hs} @ {rev}"
                                    )
                    continue
                if (h, name) not in self._order:  # racy pre-check is fine:
                    # worst case two threads compute the site; insert
                    # below is serialized under _mu
                    if site is None:
                        site = _creation_site(2)
                    with self._mu:
                        if (h, name) not in self._order:
                            self._order[(h, name)] = f"{h} then {name} @ {site}"
                            rev = self._order.get((name, h))
                            if rev is not None:
                                self._inversions.append(
                                    f"lock-order inversion: [{name} -> {h}] "
                                    f"seen as {rev}; BUT [{h} -> {name}] "
                                    f"seen as {self._order[(h, name)]}"
                                )
        held.append((name, serial))

    def note_release(self, name: str, serial: int = 0) -> None:
        if not self.active:
            return
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name and (not serial or held[i][1] == serial):
                del held[i]
                return

    # -- reporting ----------------------------------------------------------

    def inversions(self) -> List[str]:
        with self._mu:
            return list(self._inversions)

    def edges(self) -> Dict[Tuple[str, str], str]:
        with self._mu:
            return dict(self._order)


# -- wrapper primitives -----------------------------------------------------

class _WLock:
    """threading.Lock/RLock wrapper reporting to a witness."""

    def __init__(self, witness: Witness, name: str, inner) -> None:
        self._w = witness
        self._name = name
        self._inner = inner
        self._ws = next(_serial)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._w.note_acquire(self._name, self._ws)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._w.note_release(self._name, self._ws)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover
        return f"<witnessed {self._name} {self._inner!r}>"


class _WCondition(_real_threading.Condition):
    """Condition subclass reporting to a witness.  ``wait`` releases the
    underlying lock, so the held-stack entry pops for the wait's
    duration — otherwise every post-wait acquisition would look nested
    under the condition."""

    def __init__(self, witness: Witness, name: str, lock=None) -> None:
        super().__init__(lock)
        self._wname = name
        self._w = witness
        self._ws = next(_serial)
        # threading.Condition.__init__ binds self.acquire/self.release
        # as INSTANCE attributes (the inner lock's bound methods), which
        # would shadow any class-level override — rebind them here so
        # direct cond.acquire()/release() calls are witnessed too.
        # (Condition.wait uses _release_save/_acquire_restore, which go
        # straight to the inner lock — our wait() override covers that.)
        inner_acquire, inner_release = self.acquire, self.release

        def acquire(*a, **k):
            ok = inner_acquire(*a, **k)
            if ok:
                self._w.note_acquire(self._wname, self._ws)
            return ok

        def release():
            self._w.note_release(self._wname, self._ws)
            inner_release()

        self.acquire = acquire
        self.release = release

    def __enter__(self):
        r = super().__enter__()
        self._w.note_acquire(self._wname, self._ws)
        return r

    def __exit__(self, *exc):
        self._w.note_release(self._wname, self._ws)
        return super().__exit__(*exc)

    def wait(self, timeout: Optional[float] = None):
        self._w.note_release(self._wname, self._ws)
        try:
            return super().wait(timeout)
        finally:
            self._w.note_acquire(self._wname, self._ws)
    # wait_for() is inherited and loops over wait() — covered.


class _ThreadingProxy:
    """Module-shaped object delegating to real ``threading`` with the
    lock constructors swapped for witnessing ones.  Injected into a
    module's ``threading`` global, so only dgraph_tpu code sees it."""

    def __init__(self, witness: Witness) -> None:
        self._w = witness

    def Lock(self):
        return _WLock(self._w, _creation_site(), _real_threading.Lock())

    def RLock(self):
        return _WLock(self._w, _creation_site(), _real_threading.RLock())

    def Condition(self, lock=None):
        return _WCondition(self._w, _creation_site(), lock)

    def __getattr__(self, name: str):
        return getattr(_real_threading, name)


# -- arming -----------------------------------------------------------------

_EXCLUDE_MODULES = (
    "dgraph_tpu.analysis",
    "dgraph_tpu.utils.metrics",   # hot leaf locks, verified no fan-out
    "dgraph_tpu.utils.rwlock",    # instrumented at class level below
)

_global: Optional[Witness] = None
_patched: List[Tuple[object, str, object]] = []  # (obj, attr, original)


def arm() -> Witness:
    """Install the witness into every loaded dgraph_tpu module (and any
    imported later gets covered when arm() is called again — conftest
    arms once after test collection, which imports everything).
    Idempotent; returns the global witness."""
    global _global
    if _global is None:
        _global = Witness()
    w = _global
    proxy = _ThreadingProxy(w)
    for name, mod in list(sys.modules.items()):
        if mod is None or not name.startswith("dgraph_tpu"):
            continue
        if any(name.startswith(e) for e in _EXCLUDE_MODULES):
            continue
        cur = getattr(mod, "threading", None)
        if cur is _real_threading:
            _patched.append((mod, "threading", cur))
            mod.threading = proxy
    _instrument_rwlock(w)
    return w


def disarm() -> None:
    """Restore patched namespaces.  Wrapper locks already embedded in
    live objects keep functioning (the witness just goes inactive)."""
    global _global
    for obj, attr, orig in _patched:
        setattr(obj, attr, orig)
    _patched.clear()
    if _global is not None:
        _global.active = False
        _global = None


def current() -> Optional[Witness]:
    return _global


def _instrument_rwlock(w: Witness) -> None:
    """Patch RWLock at the class level: read and write side both count
    as holding the lock's class (an RWLock inversion is an inversion no
    matter which side each thread took — the write side excludes both)."""
    from dgraph_tpu.utils import rwlock as _rw

    if getattr(_rw.RWLock, "_witnessed", False):
        return
    _rw.RWLock._witnessed = True
    orig_init = _rw.RWLock.__init__
    orig = {
        m: getattr(_rw.RWLock, m)
        for m in ("acquire_read", "release_read", "acquire_write",
                  "release_write")
    }

    def __init__(self):  # noqa: N807
        orig_init(self)
        self._witness_name = _creation_site()
        self._witness_serial = next(_serial)

    def make(method, note_after_acquire: bool):
        o = orig[method]
        if note_after_acquire:
            def wrapped(self):
                o(self)
                wit = current()
                if wit is not None:
                    wit.note_acquire(
                        getattr(self, "_witness_name", "rwlock"),
                        getattr(self, "_witness_serial", 0),
                    )
        else:
            def wrapped(self):
                wit = current()
                if wit is not None:
                    wit.note_release(
                        getattr(self, "_witness_name", "rwlock"),
                        getattr(self, "_witness_serial", 0),
                    )
                o(self)
        return wrapped

    _rw.RWLock.__init__ = __init__
    _rw.RWLock.acquire_read = make("acquire_read", True)
    _rw.RWLock.acquire_write = make("acquire_write", True)
    _rw.RWLock.release_read = make("release_read", False)
    _rw.RWLock.release_write = make("release_write", False)
