"""Snapshot-versioned two-tier query cache (ISSUE 3).

Tier 1 (`HopCache`, cache/hop.py): hop-expansion memoization at the
DeviceExpander seam — repeat per-level expansions over an unchanged
store snapshot skip the device dispatch entirely.

Tier 2 (`ResultCache`, cache/result.py): whole-response memoization in
front of the cohort scheduler — repeat queries skip admission, cohort
wait and execution.

Both tiers share the `VersionedLFUCache` core (cache/core.py):
mutation-epoch invalidation via the store's monotonic ``version``,
incremental generation sweeping, and byte-budgeted LFU-with-aging
admission.  Gate: ``DGRAPH_TPU_CACHE`` (default on; ``0`` restores
the cache-less path byte-identically).
"""

from dgraph_tpu.cache.core import VersionedLFUCache, cache_enabled
from dgraph_tpu.cache.hop import HopCache, frontier_digest
from dgraph_tpu.cache.result import ResultCache, cacheable, request_digest

__all__ = [
    "VersionedLFUCache",
    "HopCache",
    "ResultCache",
    "cache_enabled",
    "cacheable",
    "frontier_digest",
    "request_digest",
]
