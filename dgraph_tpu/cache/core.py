"""Snapshot-versioned, byte-budgeted cache core shared by both tiers.

The reference Dgraph's own published numbers (BASELINE.md) show the
warm path is the product: the same query drops ~3× once posting lists
are hot.  Banyan (PAPERS.md) makes the matching observation for graph
query *services*: under concurrent skewed workloads, cross-query reuse
of intermediate results dominates served QPS.  This module supplies the
one mechanism both cache tiers (cache/hop.py, cache/result.py) share:

- **Snapshot versioning.**  Every entry is keyed under a caller-chosen
  monotonic version — since IVM (dgraph_tpu/ivm/versions.py) the
  footprint-scoped predicate version, the store's global mutation
  ``version`` before it / under ``DGRAPH_TPU_IVM=0``.  A probe carries
  the *current* version; an entry recorded under any older version can
  never match, so invalidation is O(1): no flush stall, no lockstep
  with writers.  ``repair_where`` additionally lets the IVM layer
  transform-and-re-key entries a delta can fix in place.

- **Generation sweeping.**  Dead-version entries still occupy budget
  until reclaimed.  Rather than a stop-the-world flush (a latency
  cliff exactly when a mutation already disturbed the warm path),
  every put sweeps a bounded handful of stale entries — reclamation
  cost is amortized across the operations that need the space.

- **LFU-with-aging admission/eviction** under a byte budget.  Plain
  LRU lets one megaquery walk the whole hot head out of the cache;
  plain LFU never forgets, so yesterday's hot key squats forever.
  Here each entry carries a frequency that ages (halves) every
  ``age_interval`` puts, eviction takes the lowest (frequency, recency)
  victim, and entries larger than ``max_entry_frac`` of the budget are
  refused admission outright — one giant expansion cannot displace
  thousands of hot small ones (the scan-resistance half of TinyLFU's
  argument, without the sketch).

Thread-safe; all operations are O(1) amortized except eviction scans,
which touch only as many entries as they free.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional, Tuple


def cache_enabled() -> bool:
    """The DGRAPH_TPU_CACHE gate (default ON; ``0`` restores today's
    cache-less behavior byte-identically)."""
    return os.environ.get("DGRAPH_TPU_CACHE", "1") != "0"


class _Entry:
    __slots__ = ("value", "version", "nbytes", "freq", "seq", "born")

    def __init__(self, value, version: int, nbytes: int, seq: int):
        self.value = value
        self.version = version
        self.nbytes = nbytes
        self.freq = 1.0
        self.seq = seq          # recency tiebreak (monotonic put/hit seq)
        self.born = time.monotonic()


class VersionedLFUCache:
    """One cache tier: dict of key → entry under a byte budget.

    ``stats_hook(event, entry_or_none)`` fires outside hot math but
    inside the lock-free tail of each operation with event ∈
    {"hit", "miss", "stale", "evicted", "rejected"} so the tiers can
    pump the metrics registry without this module importing it.
    """

    def __init__(
        self,
        budget_bytes: int,
        max_entry_frac: float = 0.125,
        age_interval: int = 256,
        sweep_limit: int = 32,
        stats_hook: Optional[Callable] = None,
    ):
        self.budget_bytes = int(budget_bytes)
        self.max_entry_bytes = max(1, int(self.budget_bytes * max_entry_frac))
        self.age_interval = max(1, int(age_interval))
        self.sweep_limit = max(1, int(sweep_limit))
        self._hook = stats_hook
        self._lock = threading.Lock()
        self._m: Dict[object, _Entry] = {}
        self._bytes = 0
        self._seq = 0
        self._puts_since_age = 0
        # rotating sweep cursor: a list snapshot of keys consumed a few
        # per put, rebuilt when exhausted — bounded work per operation
        self._sweep_keys: list = []

    # -- introspection -----------------------------------------------------

    @property
    def occupancy_bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._m)

    # -- operations --------------------------------------------------------

    def get(self, key, version: int):
        """Return (value, age_seconds) on a live hit, else None.  An
        entry recorded under an older version counts as stale (dead),
        is reclaimed immediately, and reads as a miss."""
        return self.get_ev(key, version)[0]

    def get_ev(self, key, version: int):
        """``(hit_or_None, event, nbytes)`` — the probe plus WHICH event
        it was (hit / miss / stale) and the hit entry's stored byte
        size, for callers that record the outcome on a trace span
        (cache/hop.py, cache/result.py) without re-deriving either from
        the stats hook or a fresh footprint walk."""
        hit = None
        nbytes = 0
        with self._lock:
            e = self._m.get(key)
            if e is None:
                ev = "miss"
            elif e.version != version:
                del self._m[key]
                self._bytes -= e.nbytes
                ev = "stale"
            else:
                e.freq += 1.0
                self._seq += 1
                e.seq = self._seq
                ev = "hit"
                nbytes = e.nbytes
                hit = (e.value, time.monotonic() - e.born)
        hook = self._hook
        if hook is not None:
            hook(ev, e if hit is not None else None)
        return hit, ev, nbytes

    def contains(self, key, version: int) -> bool:
        """Live-entry probe with NO side effects (no heat, no reclaim,
        no stats) — lets callers skip redundant value preparation before
        a re-put of a key a twin already stored."""
        with self._lock:
            e = self._m.get(key)
            return e is not None and e.version == version

    def put(self, key, version: int, value, nbytes: int) -> bool:
        """Admit ``value`` under the budget; returns False when refused
        (over the per-entry cap, or a zero budget).  Also performs one
        bounded generation sweep and, when needed, LFU-aging eviction."""
        nbytes = int(nbytes)
        if self.budget_bytes <= 0 or nbytes > self.max_entry_bytes:
            hook = self._hook
            if hook is not None:
                hook("rejected", None)
            return False
        evicted = 0
        with self._lock:
            self._sweep_locked(version)
            old = self._m.get(key)
            if old is not None:
                self._bytes -= old.nbytes
            self._seq += 1
            e = _Entry(value, version, nbytes, self._seq)
            if old is not None and old.version == version:
                e.freq = old.freq + 1.0  # re-put of a live key keeps heat
                e.born = old.born        # …and its age (hit-age histogram
                # must not reset when coalesced twins re-store the entry)
            self._m[key] = e
            self._bytes += nbytes
            self._puts_since_age += 1
            if self._puts_since_age >= self.age_interval:
                self._puts_since_age = 0
                for ent in self._m.values():
                    ent.freq *= 0.5
            evicted = self._evict_locked(protect=key)
        hook = self._hook
        if hook is not None:
            for _ in range(evicted):
                hook("evicted", None)
        return True

    def repair_where(
        self,
        pred: Callable[[object], bool],
        old_version: int,
        new_version: int,
        fix: Callable,
    ) -> Tuple[int, int]:
        """IVM delta repair (dgraph_tpu/ivm/): for every entry whose KEY
        satisfies ``pred``, entries recorded at exactly ``old_version``
        are transformed by ``fix(value) -> (new_value, nbytes) | None``
        and RE-KEYED to ``new_version`` (heat and age preserved — the
        repaired entry IS the same logical entry); entries at any other
        version, and entries ``fix`` declines, are dropped.  Returns
        (repaired, dropped).

        ``fix`` runs under the tier lock — callers gate repair to small
        deltas (query/planner.py repair_route), so the hold is bounded
        the same way the eviction scan is."""
        repaired = dropped = 0
        with self._lock:
            for k in [k for k in self._m if pred(k)]:
                e = self._m[k]
                out = None
                if e.version == old_version:
                    out = fix(e.value)
                if out is None:
                    del self._m[k]
                    self._bytes -= e.nbytes
                    dropped += 1
                    continue
                value, nbytes = out
                self._bytes += int(nbytes) - e.nbytes
                e.value = value
                e.nbytes = int(nbytes)
                e.version = new_version
                repaired += 1
        return repaired, dropped

    def rekey_where(
        self,
        pred: Callable[[object], bool],
        keyfn: Callable[[object], object],
    ) -> int:
        """Move every entry whose KEY satisfies ``pred`` to
        ``keyfn(key)``, preserving value/version/heat/age (the moved
        entry IS the same logical entry — used by the arena-epoch flip,
        which changes WHERE a hop result is keyed, not whether it is
        still correct).  A collision with an existing destination key
        keeps the moved entry (the mover has strictly fresher context).
        Returns how many entries moved."""
        moved = 0
        with self._lock:
            for k in [k for k in self._m if pred(k)]:
                nk = keyfn(k)
                if nk == k:
                    continue
                e = self._m.pop(k)
                old = self._m.get(nk)
                if old is not None:
                    self._bytes -= old.nbytes
                self._m[nk] = e
                moved += 1
        return moved

    def drop_where(self, pred: Callable[[object], bool]) -> int:
        """Remove every entry whose KEY satisfies ``pred`` (explicit
        invalidation — e.g. tier 1 on arena eviction).  Returns count."""
        with self._lock:
            dead = [k for k in self._m if pred(k)]
            for k in dead:
                self._bytes -= self._m.pop(k).nbytes
            return len(dead)

    def clear(self) -> None:
        with self._lock:
            self._m.clear()
            self._bytes = 0
            self._sweep_keys = []

    # -- internals (lock held) ---------------------------------------------

    def _sweep_locked(self, version: int) -> None:
        """Reclaim up to sweep_limit dead-version entries — the
        incremental generation sweep (no global flush stall)."""
        if not self._sweep_keys:
            self._sweep_keys = list(self._m.keys())
        n = 0
        while self._sweep_keys and n < self.sweep_limit:
            k = self._sweep_keys.pop()
            e = self._m.get(k)
            n += 1
            if e is not None and e.version != version:
                del self._m[k]
                self._bytes -= e.nbytes

    def _evict_locked(self, protect) -> int:
        """Evict lowest-(freq, seq) entries until within budget; never
        the entry just admitted.  ONE O(n) heapify per overflowing put,
        then O(log n) per victim — not a full scan per eviction (an
        at-budget steady state evicts on every miss-put, so the per-put
        cost is what bounds admission-path latency under the tier lock).
        Returns how many were evicted."""
        if self._bytes <= self.budget_bytes:
            return 0
        import heapq

        heap = [
            (e.freq, e.seq, k)
            for k, e in self._m.items()
            if k != protect
        ]
        heapq.heapify(heap)
        n = 0
        while self._bytes > self.budget_bytes and heap:
            _f, _s, victim = heapq.heappop(heap)
            e = self._m.pop(victim, None)
            if e is None:
                continue
            self._bytes -= e.nbytes
            n += 1
        return n


def env_bytes(name: str, default: int) -> int:
    """Parse a byte-count env knob (plain integer bytes)."""
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default
