"""Tier 1: hop-expansion memoization at the DeviceExpander seam.

The engine's per-level expansion — ``(arena, predicate, direction,
frontier) → (out_flat, seg_ptr)`` — is deterministic over an immutable
arena snapshot (the property the cohort HopMerger already relies on to
deal union expansions back byte-identically, sched/cohort.py).  That
makes it memoizable: key the call by ``(arena identity, predicate,
direction, frontier digest, predicate version)`` and a repeat hop under
an unchanged PREDICATE returns the SAME arrays with zero device work —
no dispatch, no transport round trip, no compile-cache probe.  Under
PR 2's zipf serving workload the head queries re-execute the same hops
thousands of times against an unchanged store; this tier converts each
of those re-executions into a dict probe.

IVM (dgraph_tpu/ivm/): the version in the key is the PREDICATE's
last-mutation version (ivm/versions.py::hop_version — the global
``store.version`` under ``DGRAPH_TPU_IVM=0``), so writes to other
predicates never touch this tier's entries; and a small delta to the
entry's own predicate REPAIRS it in place (``repair_pred`` below,
driven by ``ArenaManager._try_apply_delta`` under the planner's
repair-vs-rebuild gate) instead of dropping it — the entry carries its
frontier for exactly this purpose.

A hit must short-circuit BEFORE dispatch so the existing compile-count
guards hold (a cached hop adds zero programs by construction).

On residency: the expander's contract returns the one host fetch the
packed device paths already concatenate into a single transfer
(query/engine.py `_packed_*`), and every downstream consumer is host
code.  Caching THOSE arrays — rather than device handles — means a hit
pays no device interaction at all: the round trip was paid once at
fill time, and a device-array entry would force a fresh device→host
fetch per hit (strictly worse on every backend, catastrophically so
through a remote-transport tunnel).  Entries pin host RAM, not HBM, so
the byte budget rides beside the arena budget instead of competing
with it.  Entries hold exactly the arrays the expansion returned — the
engine treats
(out_flat, seg_ptr) as immutable (every downstream transform allocates
fresh arrays: masks, windows, permutations), so sharing is safe the
same way HopMerger's dealt segments and the scheduler's singleflight
results are.

Eviction: byte-budgeted LFU-with-aging (cache/core.py) so one
megaquery's giant frontier cannot walk the hot head out; explicit drop
when the ArenaManager evicts an arena (models/arena.py) so a rebuilt
arena at a recycled ``id()`` can never alias a dead entry's key.

Knobs: ``DGRAPH_TPU_CACHE`` (shared gate), ``DGRAPH_TPU_CACHE_HOP_BYTES``
(budget, default 64 MiB, 0 disables this tier only).
"""

from __future__ import annotations

import hashlib
from typing import Optional, Tuple

import numpy as np

from dgraph_tpu import obs
from dgraph_tpu.cache.core import VersionedLFUCache, env_bytes
from dgraph_tpu.obs import ledger
from dgraph_tpu.utils.metrics import (
    QCACHE_HIT_AGE,
    QCACHE_HOP_BYTES,
    QCACHE_HOP_EVENTS,
)

_DEFAULT_BUDGET = 64 << 20


def frontier_digest(src: np.ndarray) -> bytes:
    """Order-sensitive digest of a frontier uid array (expansion output
    depends on row order, so permutations must NOT collide)."""
    a = np.ascontiguousarray(src, dtype=np.int64)
    h = hashlib.blake2b(a.tobytes(), digest_size=16)
    return h.digest()


class HopCache:
    """One per ArenaManager (per store): expansions are arena-snapshot
    state, exactly like the arenas themselves."""

    def __init__(self, budget_bytes: Optional[int] = None):
        self._c = VersionedLFUCache(
            budget_bytes=(
                budget_bytes
                if budget_bytes is not None
                else env_bytes("DGRAPH_TPU_CACHE_HOP_BYTES", _DEFAULT_BUDGET)
            ),
            stats_hook=self._on_event,
        )

    def _on_event(self, event: str, entry) -> None:
        QCACHE_HOP_EVENTS.add(event)
        QCACHE_HOP_BYTES.set(self._c.occupancy_bytes)

    # -- introspection (tests / bench) -------------------------------------

    @property
    def occupancy_bytes(self) -> int:
        return self._c.occupancy_bytes

    @property
    def max_entry_bytes(self) -> int:
        """Per-entry admission cap — the expander pre-screens on the
        ESTIMATED result size so a hopeless megaquery never even pays
        for the frontier digest."""
        return self._c.max_entry_bytes

    def __len__(self) -> int:
        return len(self._c)

    # -- the seam -----------------------------------------------------------

    def key_for(self, arena, attr: str, reverse: bool, src: np.ndarray):
        """Precompute the entry key — the digest is the expensive part
        (big frontiers hash megabytes), and a miss needs the SAME key
        for its fill put, so the expander computes it once per call.

        The arena EPOCH (PR 16: bumped once per applied delta,
        models/arena.py) rides at index 3: an entry filled before a
        delta can never match a probe after it through key equality
        alone — ``id()`` recycling protection (``drop_arena``) and
        version staleness both remain, but the epoch closes the window
        where an id-keyed entry could outlive the SNAPSHOT it was
        computed against (the delta-driven twin of the PR 15
        eviction-vs-in-flight race).  Repaired entries are re-keyed to
        the new epoch (``repair_pred``); unrepaired stale-epoch entries
        are dropped eagerly (``drop_stale_epoch``)."""
        return (
            id(arena), attr, bool(reverse),
            getattr(arena, "epoch", 0), frontier_digest(src),
        )

    def get(
        self, arena, attr: str, reverse: bool, src: np.ndarray, version: int,
        key=None,
    ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        if key is None:
            key = self.key_for(arena, attr, reverse, src)
        sp = obs.current_span()
        if sp is None:  # unsampled hot path: probe only
            hit, ev, nb = self._c.get_ev(key, version)
        else:
            # sampled: the probe records its outcome (hit/miss/stale) and
            # the stored payload size, so a trace shows WHICH hops the
            # cache absorbed and how many bytes each hit saved
            with sp.child("cache.hop") as cs:
                hit, ev, nb = self._c.get_ev(key, version)
                cs.set_attr("pred", attr)
                cs.set_attr("outcome", ev)
                if hit is not None:
                    cs.set_attr("bytes", nb)
        led = ledger.current()
        if led is not None:
            led.note_cache("hop", ev, nb or 0)
        if hit is None:
            return None
        value, age = hit
        QCACHE_HIT_AGE.observe(age)
        return value[0], value[1]

    def put(
        self,
        arena,
        attr: str,
        reverse: bool,
        src: np.ndarray,
        version: int,
        out: np.ndarray,
        seg_ptr: np.ndarray,
        key=None,
    ) -> None:
        if key is None:
            key = self.key_for(arena, attr, reverse, src)
        # the FRONTIER rides in the entry beside the expansion: delta
        # repair (repair_pred below) must know which rows an edge delta
        # touches, and the digest in the key is one-way.  Its bytes are
        # charged to the budget like the payload's.
        frontier = np.ascontiguousarray(src, dtype=np.int64)
        nbytes = (
            int(out.nbytes) + int(seg_ptr.nbytes) + int(frontier.nbytes) + 64
        )
        self._c.put(key, version, (out, seg_ptr, frontier), nbytes)
        # admissions and sweeps change occupancy without a get-event
        QCACHE_HOP_BYTES.set(self._c.occupancy_bytes)

    # -- delta repair (dgraph_tpu/ivm/) --------------------------------------

    def repair_pred(
        self,
        arena_id: int,
        attr: str,
        reverse: bool,
        adds: np.ndarray,
        dels: np.ndarray,
        old_version: int,
        new_version: int,
        old_epoch: int = 0,
        new_epoch: int = 0,
    ):
        """Apply a predicate's edge deltas to every cached entry for
        ``(arena_id, attr, reverse)`` recorded at ``old_version``,
        re-keying survivors to ``new_version`` — entries the delta
        cannot repair (or that sit at any other version) drop.  Called
        from ``ArenaManager._try_apply_delta`` after the arena's own
        host mirrors were updated, under the repair cost gate
        (query/planner.py).  Returns (repaired, dropped).

        ``old_epoch → new_epoch``: the delta that drives this repair
        also bumped the arena's epoch (a key element since PR 16), so
        entries at the pre-delta epoch are MOVED to the post-delta key
        first — otherwise the value repair would strand them at a key no
        probe can ever form again.  The defaults (0, 0) are a no-op for
        callers predating the epoch (and for direct test drivers)."""
        from dgraph_tpu.ivm.repair import repair_hop_entry

        def match(k):
            return k[0] == arena_id and k[1] == attr and k[2] == bool(reverse)

        if new_epoch != old_epoch:
            self._c.rekey_where(
                lambda k: match(k) and k[3] == old_epoch,
                lambda k: k[:3] + (new_epoch,) + k[4:],
            )

        def fix(value):
            out, seg_ptr, frontier = value
            fixed = repair_hop_entry(out, seg_ptr, frontier, adds, dels)
            if fixed is None:
                return None
            out2, seg2 = fixed
            nbytes = (
                int(out2.nbytes) + int(seg2.nbytes)
                + int(frontier.nbytes) + 64
            )
            return (out2, seg2, frontier), nbytes

        res = self._c.repair_where(
            match,
            old_version,
            new_version,
            fix,
        )
        QCACHE_HOP_BYTES.set(self._c.occupancy_bytes)
        return res

    # -- invalidation --------------------------------------------------------

    def drop_stale_epoch(self, arena_id: int, epoch: int) -> int:
        """Drop every entry for ``arena_id`` NOT keyed at ``epoch`` —
        the post-delta sweep (``ArenaManager._try_apply_delta``): any
        entry the repair pass did not carry forward describes a snapshot
        that no longer exists, and must not squat in the budget waiting
        for its generation sweep."""
        n = self._c.drop_where(
            lambda k: k[0] == arena_id and k[3] != epoch
        )
        QCACHE_HOP_BYTES.set(self._c.occupancy_bytes)
        return n

    def drop_arena(self, arena_id: int) -> int:
        """Explicit drop when the ArenaManager evicts (or rebuilds) an
        arena: its ``id()`` may be recycled by a LATER allocation, and
        id-keyed entries must never outlive the object they describe."""
        n = self._c.drop_where(lambda k: k[0] == arena_id)
        QCACHE_HOP_BYTES.set(self._c.occupancy_bytes)
        return n

    def clear(self) -> None:
        self._c.clear()
        QCACHE_HOP_BYTES.set(0)
