"""Tier 2: whole-response memoization in front of the cohort scheduler.

The scheduler's singleflight (sched/scheduler.py) already collapses
identical requests that overlap in time; this tier extends the reuse
window from "while a twin is in flight" to "until the next mutation":
``(request key, store version) → (response dict, engine stats)``.  A
hit skips parsing's downstream entirely — no admission, no cohort
wait, no engine shell, no read-lock acquisition — which under zipf
traffic converts the head of the popularity curve into dict probes.

The request key is the serving layer's singleflight key — query text +
canonical (sorted-JSON) variables + debug flag — digested so the cache
holds no unbounded query texts.  Sharing the cached response dict is
safe by the same argument the scheduler's singleflight documents:
handlers only encode results, never mutate them.  Responses that
depend on wall-clock (``math(since(...))``) are detected at parse
shape and never cached.

Invalidation is the shared snapshot-version scheme (cache/core.py),
SCOPED since IVM (dgraph_tpu/ivm/): the scheduler keys each entry on
the max last-mutation version over the request's referenced-predicate
footprint (ivm/versions.py::result_version; the global
``store.version`` when the footprint is unknowable or under
``DGRAPH_TPU_IVM=0``), so a mutation only kills the responses that
actually read its predicates; stale entries die logically at the
version advance and are reclaimed by the incremental sweep.

Knobs: ``DGRAPH_TPU_CACHE`` (shared gate),
``DGRAPH_TPU_CACHE_RESULT_BYTES`` (budget, default 32 MiB, 0 disables
this tier only).
"""

from __future__ import annotations

import hashlib
from typing import Optional, Tuple

from dgraph_tpu import obs
from dgraph_tpu.cache.core import VersionedLFUCache, env_bytes
from dgraph_tpu.obs import ledger
from dgraph_tpu.utils.metrics import (
    QCACHE_HIT_AGE,
    QCACHE_RESULT_BYTES,
    QCACHE_RESULT_EVENTS,
)

_DEFAULT_BUDGET = 32 << 20


def request_digest(key) -> bytes:
    """Normalized request digest: the serving layer's (text, canonical
    vars, debug) singleflight key, hashed so cache keys are fixed-size."""
    h = hashlib.blake2b(digest_size=16)
    for part in key:
        h.update(repr(part).encode("utf-8", "surrogatepass"))
        h.update(b"\x00")
    return h.digest()


def cacheable(parsed) -> bool:
    """A parsed request whose response is a pure function of (query,
    store snapshot): read-only and free of wall-clock math.  Mutations
    never reach the scheduler path, but the guard is cheap and keeps
    this module's contract self-contained."""
    if parsed.mutation is not None:
        return False

    def clock_free(mt) -> bool:
        if mt is None:
            return True
        if getattr(mt, "fn", None) == "since":
            return False
        return all(clock_free(c) for c in getattr(mt, "children", ()))

    def walk(q) -> bool:
        if not clock_free(getattr(q, "math_exp", None)):
            return False
        return all(walk(c) for c in q.children)

    return all(walk(q) for q in parsed.queries)


def _approx_bytes(obj) -> int:
    """Rough recursive footprint of a response dict — budget accounting,
    not accounting-grade (strings dominate real responses)."""
    if isinstance(obj, dict):
        return 64 + sum(
            _approx_bytes(k) + _approx_bytes(v) for k, v in obj.items()
        )
    if isinstance(obj, (list, tuple)):
        return 56 + sum(_approx_bytes(v) for v in obj)
    if isinstance(obj, str):
        return 49 + len(obj)
    if isinstance(obj, (bytes, bytearray)):
        return 33 + len(obj)
    return 28


class ResultCache:
    """One per server: responses are store-snapshot state."""

    def __init__(self, budget_bytes: Optional[int] = None):
        self._c = VersionedLFUCache(
            budget_bytes=(
                budget_bytes
                if budget_bytes is not None
                else env_bytes(
                    "DGRAPH_TPU_CACHE_RESULT_BYTES", _DEFAULT_BUDGET
                )
            ),
            stats_hook=self._on_event,
        )

    def _on_event(self, event: str, entry) -> None:
        QCACHE_RESULT_EVENTS.add(event)
        QCACHE_RESULT_BYTES.set(self._c.occupancy_bytes)

    @property
    def occupancy_bytes(self) -> int:
        return self._c.occupancy_bytes

    def __len__(self) -> int:
        return len(self._c)

    def hits(self) -> int:
        return QCACHE_RESULT_EVENTS.snapshot().get("hit", 0)

    def get(self, key, version: int) -> Optional[Tuple[dict, dict]]:
        """(response, stats) for the request ``key`` at ``version``, or
        None.  The returned response is SHARED — read-only downstream."""
        sp = obs.current_span()
        if sp is None:  # unsampled hot path: probe only
            hit, ev, nb = self._c.get_ev(request_digest(key), version)
        else:
            # sampled: a tier-2 hit is the single most latency-deciding
            # event a request can have — the span says so explicitly
            # (outcome + the STORED size: re-walking the response here
            # would add O(response) work to the fastest path we have)
            with sp.child("cache.result") as cs:
                hit, ev, nb = self._c.get_ev(request_digest(key), version)
                cs.set_attr("outcome", ev)
                if hit is not None:
                    cs.set_attr("bytes", nb)
        led = ledger.current()
        if led is not None:
            # a tier-2 hit is the whole request's account: no engine
            # numbers ever merge in, so the cost story reads "served
            # from cache for free", which is the truth
            led.note_cache("result", ev, nb or 0)
        if hit is None:
            return None
        value, age = hit
        QCACHE_HIT_AGE.observe(age)
        return value

    def put(self, key, version: int, response: dict, stats: dict) -> None:
        k = request_digest(key)
        # singleflight deals one result to K coalesced twins and each
        # calls put on return — one stored it already, so the other K-1
        # skip the footprint walk (benign race: a double put is a no-op
        # re-store of the same value)
        if self._c.contains(k, version):
            return
        self._c.put(
            k,
            version,
            (response, stats),
            _approx_bytes(response) + _approx_bytes(stats),
        )
        # admissions and sweeps change occupancy without a get-event
        QCACHE_RESULT_BYTES.set(self._c.occupancy_bytes)

    def clear(self) -> None:
        self._c.clear()
        QCACHE_RESULT_BYTES.set(0)
