"""Command-line entry points.

Equivalent of the reference's cmd/ tree:
- ``python -m dgraph_tpu.cli.server``  ≈ cmd/dgraph (the server binary)
- ``python -m dgraph_tpu.cli.loader``  ≈ cmd/dgraphloader (bulk RDF loader)
- ``python -m dgraph_tpu.cli.posting_iterator`` ≈ cmd/postingiterator
"""
