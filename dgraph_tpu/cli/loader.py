"""Bulk RDF loader.

Equivalent of cmd/dgraphloader/main.go: gzip-aware line reader
(readLine:68), batches of N quads through the batching client
(processFile:151), optional schema file first (processSchemaFile:85),
round-robin over multiple server addresses (setupConnection:222), and
checkpoint/resume per input file via client sync marks.
"""

from __future__ import annotations

import argparse
import gzip
import sys
import time
from typing import Iterator, Tuple

from dgraph_tpu.client import (
    BatchMutationOptions,
    DgraphClient,
    HttpTransport,
    SyncMarks,
)
from dgraph_tpu.client.client import Transport


def _make_transport(addr: str, use_grpc: bool, cafile: str = "") -> Transport:
    """One server's transport: gRPC (the reference loader's native wire,
    cmd/dgraphloader/main.go:222 grpc conns) or HTTP.  gRPC targets may
    be given bare (host:port) or as http(s)://host:port (mapped to the
    +1000 convention); https-derived targets need ``cafile`` (--ca) and
    dial TLS-verified (GrpcTransport's pinned-CA path — a --tls_cert
    server would otherwise fail every RPC)."""
    if not use_grpc:
        return HttpTransport(addr)
    from dgraph_tpu.client import GrpcTransport

    # the CA applies only to https-derived targets: handing it to a
    # plaintext member of a mixed fleet would dial TLS into a plaintext
    # listener and fail every RPC with an opaque UNAVAILABLE
    return GrpcTransport(
        addr, cafile=cafile if addr.startswith("https://") else ""
    )


class RoundRobinTransport(Transport):
    """Spread requests over several servers (loader main.go:222)."""

    def __init__(self, addrs, use_grpc: bool = False, cafile: str = ""):
        import itertools
        import threading

        self._ts = [_make_transport(a, use_grpc, cafile) for a in addrs]
        self._next = itertools.cycle(self._ts)
        self._lock = threading.Lock()

    def run(self, text, variables=None):
        with self._lock:
            t = next(self._next)
        return t.run(text, variables)


def open_lines(path: str) -> Iterator[Tuple[int, str]]:
    """(1-based line number, stripped line) pairs; transparent gzip."""
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt", encoding="utf-8", errors="replace") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if line and not line.startswith("#"):
                yield i, line


def load_file(
    client: DgraphClient,
    path: str,
    marks: SyncMarks | None = None,
    batch: int = 1000,
    window: int = 4,
    progress_every: float = 2.0,
) -> int:
    """Stream one RDF file through the client; returns quads submitted.

    Checkpointing: quads accumulate into line-delimited chunks; each
    chunk's last line number is begun before submit and marked done only
    after a flush that covers it.  Up to ``window`` chunks are enqueued
    between flushes so the client's ``pending`` workers actually overlap
    submissions (one flush per window, not per chunk)."""
    skip_through = marks.done_until(path) if marks else 0
    pending: list = []
    in_flight: list = []
    chunk_end = 0
    n = 0
    t0 = time.monotonic()  # interval math only: rate + progress beats
    last_report = t0

    def drain():
        nonlocal in_flight
        if not in_flight and not pending:
            return
        client.flush()
        if marks:
            for ce in in_flight:
                marks.done(path, ce)
        in_flight = []

    def submit_chunk():
        nonlocal pending
        if not pending:
            return
        if marks:
            marks.begin(path, chunk_end)
        for q in pending:
            client.batch_set(q)
        in_flight.append(chunk_end)
        pending = []
        if len(in_flight) >= max(1, window):
            drain()

    for line_no, line in open_lines(path):
        if line_no <= skip_through:
            continue
        pending.append(line)
        chunk_end = line_no
        n += 1
        if len(pending) >= batch:
            submit_chunk()
            now = time.monotonic()
            if now - last_report >= progress_every:
                rate = n / max(now - t0, 1e-9)
                print(f"  {path}: {n} quads, {rate:,.0f}/s", file=sys.stderr)
                last_report = now
    submit_chunk()
    drain()
    return n


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="dgraph-tpu-loader", description=__doc__)
    p.add_argument("--rdf", "-r", required=True, nargs="+",
                   help="RDF N-Quad files (.rdf or .rdf.gz)")
    p.add_argument("--schema", "-s", default="", help="schema file to apply first")
    p.add_argument("--dgraph", "-d", default="http://127.0.0.1:8080",
                   help="comma-separated server addresses")
    p.add_argument("--batch", type=int, default=1000)
    p.add_argument("--concurrent", "-c", type=int, default=4,
                   help="concurrent in-flight batch submitters")
    p.add_argument("--cd", dest="client_dir", default="",
                   help="client checkpoint dir (enables resume)")
    p.add_argument("--grpc", action="store_true",
                   help="connect over gRPC (protos.Dgraph/Run) instead of "
                        "HTTP; http(s):// addresses map to port + 1000")
    p.add_argument("--ca", default="",
                   help="pinned CA / server-cert PEM for https gRPC "
                        "targets (a --tls_cert server serves gRPC over "
                        "TLS; required with https:// + --grpc)")
    ns = p.parse_args(argv)

    addrs = [a.strip() for a in ns.dgraph.split(",") if a.strip()]
    transport = (
        RoundRobinTransport(addrs, use_grpc=ns.grpc, cafile=ns.ca)
        if len(addrs) > 1
        else _make_transport(addrs[0], ns.grpc, ns.ca)
    )
    client = DgraphClient(
        transport, BatchMutationOptions(size=ns.batch, pending=ns.concurrent)
    )
    marks = SyncMarks(ns.client_dir) if ns.client_dir else None

    if ns.schema:
        with open(ns.schema) as f:
            client.add_schema(f.read())
        print(f"applied schema from {ns.schema}", file=sys.stderr)

    total, t0 = 0, time.monotonic()
    for path in ns.rdf:
        total += load_file(client, path, marks, batch=ns.batch, window=ns.concurrent)
    client.close()
    dt = time.monotonic() - t0
    print(f"loaded {total} quads in {dt:.1f}s ({total / max(dt, 1e-9):,.0f}/s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
