"""Debug tool: dump the posting store.

Equivalent of cmd/postingiterator/main.go — iterate the persisted store
and print each posting (predicate, uid, dst/value)."""

from __future__ import annotations

import argparse
import sys

from dgraph_tpu.models.wal import DurableStore
from dgraph_tpu.serve.export import iter_rdf_lines


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="posting-iterator", description=__doc__)
    p.add_argument("--p", dest="postings_dir", default="p")
    p.add_argument("--pred", default="", help="only this predicate")
    ns = p.parse_args(argv)
    store = DurableStore(ns.postings_dir)
    try:
        for line in iter_rdf_lines(store):
            if ns.pred and f"<{ns.pred}>" not in line:
                continue
            print(line)
    finally:
        store.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
