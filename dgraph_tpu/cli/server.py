"""The server binary.

Equivalent of cmd/dgraph/main.go: flags (+ optional YAML config merge,
setupConfigOpts:85), storage bring-up, HTTP surface, health gating, and
a clean shutdown path.  The boot order mirrors main:675: open stores →
schema/posting init (implicit in DurableStore) → serving surface →
health OK.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys

from dgraph_tpu.models.wal import DurableStore
from dgraph_tpu.serve.server import DgraphServer
from dgraph_tpu.utils.config import Options


def build_options(argv=None) -> Options:
    p = argparse.ArgumentParser(prog="dgraph-tpu", description=__doc__)
    # YAML is applied BEFORE flags (cmd/dgraph/main.go:164-168): config
    # values become the flag defaults, so explicit flags always win
    pre = argparse.ArgumentParser(add_help=False)
    pre.add_argument("--config", default="")
    pre_ns, _ = pre.parse_known_args(argv)
    d = Options()
    if pre_ns.config:
        d = d.merged_with_yaml(pre_ns.config)
    p.add_argument("--p", dest="postings_dir", default=d.postings_dir,
                   help="directory to store posting state + snapshots")
    p.add_argument("--w", dest="wal_dir", default=d.wal_dir,
                   help="(reserved) separate wal dir; DurableStore keeps wal beside postings")
    p.add_argument("--export", dest="export_path", default=d.export_path)
    p.add_argument("--port", type=int, default=d.port)
    p.add_argument("--grpc_port", type=int, default=d.grpc_port,
                   help="gRPC listener port (protos.Dgraph service); "
                        "0 = http port + 1000, -1 disables")
    p.add_argument("--dumpsg", default=d.dumpsg,
                   help="directory to dump each query's execution-shape "
                        "tree as JSON (offline plan inspection)")
    p.add_argument("--memory_mb", type=int, default=d.memory_mb,
                   help="HBM budget for device arenas in MB (0 = unlimited); "
                        "cold arenas LRU-evict to the host store")
    p.add_argument("--bind", default=d.bind)
    p.add_argument("--sync", dest="sync_writes", action="store_true",
                   default=d.sync_writes)
    p.add_argument("--snapshot_wal_mb", type=float,
                   default=d.snapshot_wal_mb,
                   help="seal+compact the WAL once it passes this many "
                        "MB (0 = env DGRAPH_TPU_SNAPSHOT_WAL_MB or 64)")
    p.add_argument("--snapshot_wal_records", type=int,
                   default=d.snapshot_wal_records,
                   help="seal+compact once this many records are "
                        "journaled (0 = env DGRAPH_TPU_SNAPSHOT_WAL_RECORDS "
                        "or 200000)")
    p.add_argument("--idx", dest="raft_id", type=int, default=d.raft_id)
    p.add_argument("--groups", dest="group_ids", default=d.group_ids)
    p.add_argument("--peer", default=d.peer)
    p.add_argument("--peer_groups", default=d.peer_groups,
                   help='per-peer group placement "1=0,1;2=0,2"; absent '
                        "peers serve every group")
    p.add_argument("--join", default=d.join,
                   help="address of a live cluster member; boot as a "
                        "joining node and acquire membership at runtime")
    p.add_argument("--my", dest="my_addr", default=d.my_addr)
    p.add_argument("--trace", dest="trace_ratio", type=float, default=d.trace_ratio)
    p.add_argument("--expose_trace", action="store_true", default=d.expose_trace)
    p.add_argument("--tls_cert", default=d.tls_cert)
    p.add_argument("--tls_key", default=d.tls_key)
    p.add_argument("--cluster_secret", default=d.cluster_secret,
                   help="shared secret required on intra-cluster endpoints "
                        "(/raft*, /assign-uids); empty disables the gate")
    p.add_argument("--peer_ca", default=d.peer_ca,
                   help="PEM CA bundle to verify peer TLS certs against "
                        "(CA pinning for the raft plane)")
    p.add_argument("--peer_tls_insecure", action="store_true",
                   default=d.peer_tls_insecure,
                   help="explicitly skip peer TLS verification "
                        "(throwaway self-signed clusters only)")
    p.add_argument("--raft_transport", default=d.raft_transport,
                   choices=("http", "grpc"),
                   help="raft frame carrier between servers; grpc uses "
                        "/protos.Worker/RaftMessage at peer http port+1000")
    p.add_argument("--workers", type=int, default=d.workers)
    p.add_argument("--num_pending", type=int, default=d.num_pending)
    p.add_argument("--max_edges", type=int, default=d.max_edges)
    p.add_argument("--config", default="", help="YAML config file (flat key: value)")
    p.add_argument("--cpu", dest="cpu_profile", default=d.cpu_profile,
                   help="write a CPU profile (pstats format) here on "
                        "shutdown (main.go:181 --cpu analog)")
    p.add_argument("--mem", dest="mem_profile", default=d.mem_profile,
                   help="write a memory allocation profile (tracemalloc "
                        "top-50 text) here on shutdown")
    p.add_argument("--compile_cache", default=d.compile_cache,
                   help="persistent XLA compilation cache dir; 'auto' = "
                        "<postings>/.jitcache, '' disables (repeat cold "
                        "starts skip the seconds-long first compile)")
    ns = p.parse_args(argv)
    # start from the YAML-merged defaults so Options fields without a flag
    # survive (previously YAML-only keys like workers were dropped)
    merged = {**d.__dict__, **{k: getattr(ns, k) for k in vars(ns) if k != "config"}}
    return Options(**merged)


def main(argv=None) -> int:
    # honor JAX_PLATFORMS=cpu even though this image's sitecustomize
    # imports jax at interpreter startup (consuming the env var before
    # user code runs): config.update works any time before backend init.
    # Without this a CPU-only deployment (or a wedged TPU) hangs in
    # _auto_mesh's jax.devices() probe.
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    opts = build_options(argv)
    # snapshot thresholds: explicit flags win over the env (the
    # Snapshotter reads the env at construction — models/durability.py)
    if opts.snapshot_wal_mb:
        os.environ["DGRAPH_TPU_SNAPSHOT_WAL_MB"] = str(opts.snapshot_wal_mb)
    if opts.snapshot_wal_records:
        os.environ["DGRAPH_TPU_SNAPSHOT_WAL_RECORDS"] = str(
            opts.snapshot_wal_records
        )
    # the gRPC listener port this process will bind (0 = http port + 1000)
    grpc_port = (
        -1
        if opts.grpc_port < 0
        else (opts.grpc_port or (opts.port + 1000 if opts.port else 0))
    )
    if opts.raft_transport == "grpc":
        # fail fast: a node whose raft plane is gRPC but that serves no
        # gRPC listener (or lacks grpcio) can neither send nor receive
        # frames — it would boot, never elect, and give no hint why
        if grpc_port <= 0 or opts.port <= 0:
            print(
                "--raft_transport grpc requires explicit --port and an "
                "enabled gRPC listener (--grpc_port >= 0); peers derive "
                "each other's raft targets as http port + the same offset",
                file=sys.stderr,
            )
            return 2
        try:
            import grpc  # noqa: F401
        except ImportError:
            print(
                "--raft_transport grpc requires grpcio, which is not "
                "importable in this environment",
                file=sys.stderr,
            )
            return 2
    if opts.compile_cache:
        # persistent XLA compilation cache: a restarted server re-uses
        # every compiled query shape instead of paying the seconds-long
        # Mosaic/XLA compile again (the reference has no compile step at
        # all, so repeat cold-start parity depends on this)
        import jax

        cache_dir = (
            os.path.join(opts.postings_dir, ".jitcache")
            if opts.compile_cache == "auto"
            else opts.compile_cache
        )
        try:
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        except (OSError, AttributeError) as e:
            print(f"warning: compile cache disabled: {e}", file=sys.stderr)
    # profiling surface (setupProfiling, cmd/dgraph/main.go:181).  The
    # CPU profile covers QUERY EXECUTION (enabled per-request under the
    # engine lock — cProfile is per-thread, and a main-thread profiler
    # would only see the idle join loop); tracemalloc covers boot too.
    profiler = None
    if opts.cpu_profile:
        import cProfile

        profiler = cProfile.Profile()
    if opts.mem_profile:
        import tracemalloc

        tracemalloc.start(10)
    cluster = None
    if opts.join and not opts.peer:
        # runtime join: boot passive with only ourselves, then announce
        from dgraph_tpu.cluster.service import ClusterService

        scheme = "https" if opts.tls_cert else "http"
        my_addr = opts.my_addr or f"{scheme}://127.0.0.1:{opts.port}"
        cluster = ClusterService(
            node_id=str(opts.raft_id),
            my_addr=my_addr,
            peers={str(opts.raft_id): my_addr},
            group_ids=[int(g) for g in opts.group_ids.split(",") if g.strip()],
            directory=opts.postings_dir,
            sync_writes=opts.sync_writes,
            secret=opts.cluster_secret,
            peer_ca=opts.peer_ca,
            peer_tls_insecure=opts.peer_tls_insecure,
            raft_transport=opts.raft_transport,
            grpc_port_offset=max(0, grpc_port - opts.port),
            passive=True,
        )
        cluster.start()
        cluster.join_cluster(opts.join)
        store = cluster.store
    elif opts.peer:
        # clustered boot (StartRaftNodes analog): durability lives in the
        # raft logs + snapshots under the postings dir
        from dgraph_tpu.cluster.service import (
            ClusterService,
            parse_peer_groups,
            parse_peers,
        )

        scheme = "https" if opts.tls_cert else "http"
        my_addr = opts.my_addr or f"{scheme}://127.0.0.1:{opts.port}"
        cluster = ClusterService(
            node_id=str(opts.raft_id),
            my_addr=my_addr,
            peers=parse_peers(opts.peer, default_scheme=scheme),
            group_ids=[int(g) for g in opts.group_ids.split(",") if g.strip()],
            directory=opts.postings_dir,
            sync_writes=opts.sync_writes,
            secret=opts.cluster_secret,
            peer_ca=opts.peer_ca,
            peer_tls_insecure=opts.peer_tls_insecure,
            raft_transport=opts.raft_transport,
            grpc_port_offset=max(0, grpc_port - opts.port),
            peer_groups=parse_peer_groups(opts.peer_groups),
        )
        has_https_peer = any(
            a.startswith("https://") for a in cluster.peers.values()
        )
        if has_https_peer and not opts.peer_ca and not opts.peer_tls_insecure:
            print(
                "warning: TLS peers will be verified against the system "
                "trust store; for self-signed cluster certs pass --peer_ca "
                "(pin) or --peer_tls_insecure",
                file=sys.stderr,
            )
        cluster.start()
        store = cluster.store
    else:
        store = DurableStore(opts.postings_dir, sync_writes=opts.sync_writes)
    if opts.trace_ratio > 0 and not os.environ.get("DGRAPH_TPU_TRACE_RATIO"):
        # --trace drives BOTH samplers: the legacy /debug/requests ring
        # (below, via DgraphServer) and the flight recorder's head
        # sampler (obs/spans.py) — one operator knob, the env var wins
        # when set explicitly
        from dgraph_tpu import obs

        obs.configure(ratio=opts.trace_ratio)
    srv = DgraphServer(
        store,
        port=opts.port,
        bind=opts.bind,
        export_path=opts.export_path,
        trace_ratio=opts.trace_ratio,
        expose_trace=opts.expose_trace,
        tls_cert=opts.tls_cert,
        tls_key=opts.tls_key,
        cluster=cluster,
        profiler=profiler,
        arena_budget_mb=opts.memory_mb,
        dumpsg_path=opts.dumpsg,
    )
    srv.start()
    print(f"dgraph-tpu serving at {srv.addr}  (dashboard at /, queries at /query)")
    grpc_srv = None
    if grpc_port >= 0:
        try:
            from dgraph_tpu.serve.grpc_server import GrpcServer

            grpc_srv = GrpcServer(srv, bind=opts.bind, port=grpc_port)
            grpc_srv.start()
            print(f"gRPC (protos.Dgraph) at {opts.bind}:{grpc_srv.port}")
        except ImportError:
            print("grpcio unavailable; gRPC surface disabled", file=sys.stderr)
            grpc_srv = None

    stop = {"requested": False}

    def on_signal(signum, frame):
        # disarm: a second Ctrl+C must not re-enter stop() on the same
        # thread while the first holds the (non-reentrant) stop lock
        signal.signal(signal.SIGINT, signal.SIG_IGN)
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        stop["requested"] = True
        srv.stop()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    def dump_profiles():
        if profiler is not None:
            profiler.dump_stats(opts.cpu_profile)
            print(f"cpu profile written to {opts.cpu_profile}")
        if opts.mem_profile:
            import tracemalloc

            snap = tracemalloc.take_snapshot()
            with open(opts.mem_profile, "w") as f:
                for stat in snap.statistics("lineno")[:50]:
                    f.write(str(stat) + "\n")
            print(f"memory profile written to {opts.mem_profile}")

    try:
        while srv._thread is not None and srv._thread.is_alive():
            srv._thread.join(timeout=0.5)
    except KeyboardInterrupt:
        pass
    # stop() is idempotent and holds its lock through teardown, so this
    # blocks until the store is durably closed even when shutdown was
    # initiated by /admin/shutdown on a daemon thread
    if grpc_srv is not None:
        grpc_srv.stop()
    srv.stop()
    dump_profiles()
    return 0


if __name__ == "__main__":
    sys.exit(main())
