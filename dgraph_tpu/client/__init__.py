"""Client SDK: batching mutations, query, unmarshal, checkpointing.

Equivalent of the reference's client/ package: `NewDgraphClient`-style
batching client (client/mutations.go:206) with pipelined request workers
(makeRequests:364), typed edge builders (client/client.go:266+), reflTag
unmarshal (client/unmarshal.go:253), and per-source-file checkpoint
watermarks for resumable bulk loads (client/checkpoint.go:29-95).
"""

from dgraph_tpu.client.client import (
    BatchMutationOptions,
    DgraphClient,
    Edge as ClientEdge,
    EmbeddedTransport,
    GrpcTransport,
    HttpTransport,
)
from dgraph_tpu.client.checkpoint import SyncMarks
from dgraph_tpu.client.unmarshal import unmarshal

__all__ = [
    "BatchMutationOptions",
    "DgraphClient",
    "ClientEdge",
    "EmbeddedTransport",
    "GrpcTransport",
    "HttpTransport",
    "SyncMarks",
    "unmarshal",
]
