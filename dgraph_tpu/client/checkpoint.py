"""Client-side load checkpointing.

Equivalent of client/checkpoint.go:29-95: per-source-file watermarks
persisted client-side so an interrupted bulk load resumes where it left
off.  The reference stores marks in a client badger; here a JSON file
updated atomically.  Contract: the loader calls `begin(file, line_no)`
before submitting a batch ending at line_no and `done(file, line_no)`
after the server acks it; `done_until(file)` after restart says which
lines to skip.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict

from dgraph_tpu.utils.watermark import WaterMark


class SyncMarks:
    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, "checkpoints.json")
        self._marks: Dict[str, WaterMark] = {}
        self._persisted: Dict[str, int] = {}
        self._lock = threading.Lock()
        if os.path.exists(self.path):
            with open(self.path) as f:
                self._persisted = {k: int(v) for k, v in json.load(f).items()}

    def _wm(self, file: str) -> WaterMark:
        with self._lock:
            wm = self._marks.get(file)
            if wm is None:
                wm = self._marks[file] = WaterMark(file)
                base = self._persisted.get(file, 0)
                if base:
                    wm.begin(base)
                    wm.done(base)
            return wm

    def done_until(self, file: str) -> int:
        """Highest line index fully applied in a previous or current run."""
        return max(self._persisted.get(file, 0), self._wm(file).done_until())

    def begin(self, file: str, line_no: int) -> None:
        self._wm(file).begin(line_no)

    def done(self, file: str, line_no: int) -> None:
        wm = self._wm(file)
        wm.done(line_no)
        self._persist(file, wm.done_until())

    def _persist(self, file: str, mark: int) -> None:
        with self._lock:
            if mark <= self._persisted.get(file, 0):
                return
            self._persisted[file] = mark
            from dgraph_tpu.utils.atomicio import atomic_write_file

            # fsync'd tmp+replace: a crash mid-persist must keep the OLD
            # checkpoint (replaying a few lines is safe; a torn JSON file
            # would abort the next resume entirely)
            atomic_write_file(
                self.path, json.dumps(self._persisted).encode()
            )
