"""Batching Dgraph client.

Mirrors client/mutations.go: callers stream N-Quads via BatchSet /
BatchDelete; `pending` worker threads drain batches of `size` quads and
submit them as mutation blocks; Flush waits for everything in flight.
Two transports: HTTP (the reference's network client) and embedded
(the reference's in-process InMemoryComm client, dgraph/embedded.go:39).
"""

from __future__ import annotations

import json
import queue
import threading
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class Transport:
    def run(self, text: str, variables: Optional[dict] = None) -> dict:
        raise NotImplementedError


class HttpTransport(Transport):
    """HTTP transport; ``binary=True`` requests protobuf wire-format
    responses (Accept: application/protobuf — the reference's gRPC
    Response surface, serve/proto.py) and decodes them to the JSON path's
    result-dict shape, with proto3's inherent divergences: a ONE-element
    scalar list decodes as the bare scalar (repeated-field ambiguity,
    serve/proto.py decode_node docstring) and mutation code/message
    strings are not carried (Response has no fields for them).  Wire
    bytes are ~2-5× smaller than JSON for uid-heavy results."""

    def __init__(self, addr: str, binary: bool = False):
        self.addr = addr.rstrip("/")
        self.binary = binary

    def run(self, text: str, variables: Optional[dict] = None) -> dict:
        req = urllib.request.Request(
            self.addr + "/query", data=text.encode("utf-8"), method="POST"
        )
        if variables:
            req.add_header("X-Dgraph-Vars", json.dumps(variables))
        if self.binary:
            req.add_header("Accept", "application/protobuf")
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                raw = resp.read()
                if self.binary and resp.headers.get("Content-Type", "").startswith(
                    "application/protobuf"
                ):
                    from dgraph_tpu.serve.proto import decode_response

                    out = decode_response(raw)
                else:
                    out = json.loads(raw.decode())
        except urllib.error.HTTPError as e:
            # the server answers errors with a JSON {code, message} body;
            # surface the message, not just the status line
            try:
                body = json.loads(e.read().decode())
                msg = body.get("message", str(e))
            except Exception:  # noqa: BLE001
                msg = str(e)
            raise RuntimeError(msg) from None
        if out.get("code") == "ErrorInvalidRequest":
            raise RuntimeError(out.get("message", "request failed"))
        return out


class EmbeddedTransport(Transport):
    """In-process transport against a DgraphServer (or bare engine)."""

    def __init__(self, server):
        self.server = server

    def run(self, text: str, variables: Optional[dict] = None) -> dict:
        return self.server.run_query(text, variables)


class GrpcTransport(Transport):
    """gRPC transport against serve/grpc_server.py — the reference
    client's native wire (client/client.go over protos.Dgraph/Run).
    Channels come from a shared refcounted pool with a CheckVersion
    liveness probe (the worker/conn.go:108 pool analog); call close()
    to release this transport's reference.

    ``target`` is a bare host:port, or an http(s):// server address
    (mapped to the +1000 gRPC port convention).  A server started with
    --tls_cert serves gRPC over TLS, so https-derived targets require
    ``cafile`` (its cert / a pinned CA, PEM) and dial a verified
    grpc.secure_channel — mirroring GrpcRaftTransport: there is no
    silent plaintext downgrade and no unverified-TLS mode."""

    _pool = None  # class-level shared ChannelPool

    def __init__(self, target: str, cafile: str = ""):
        from dgraph_tpu.serve.grpc_server import ChannelPool

        if GrpcTransport._pool is None:
            GrpcTransport._pool = ChannelPool()
        if "://" in target:
            from dgraph_tpu.cluster.transport import grpc_target_of

            if target.startswith("https://") and not cafile:
                raise ValueError(
                    "https gRPC targets require cafile= (the server's "
                    "TLS cert or a pinned CA): dialing plaintext into a "
                    "--tls_cert server fails every RPC"
                )
            target = grpc_target_of(target, 1000)
        self.target = target
        self.cafile = cafile
        self._chan = GrpcTransport._pool.get(target, cafile or None)
        self._run = self._chan.unary_unary("/protos.Dgraph/Run")
        self._check = self._chan.unary_unary("/protos.Dgraph/CheckVersion")
        self._assign = self._chan.unary_unary("/protos.Dgraph/AssignUids")

    def run(self, text: str, variables: Optional[dict] = None) -> dict:
        import grpc

        from dgraph_tpu.serve.grpc_server import encode_request
        from dgraph_tpu.serve.proto import decode_response

        try:
            raw = self._run(encode_request(text, variables))
        except grpc.RpcError as e:
            raise RuntimeError(e.details() or str(e.code())) from None
        return decode_response(raw)

    def check_version(self) -> str:
        from dgraph_tpu.serve.grpc_server import decode_version

        return decode_version(self._check(b""))

    def assign_uids(self, n: int) -> tuple:
        from dgraph_tpu.serve.grpc_server import (
            decode_assigned_ids,
            encode_num,
        )

        return decode_assigned_ids(self._assign(encode_num(n)))

    def close(self) -> None:
        if self._chan is not None:
            GrpcTransport._pool.release(self.target, self.cafile or None)
            self._chan = None


@dataclass
class BatchMutationOptions:
    """client/mutations.go:56 BatchMutationOptions."""

    size: int = 1000
    pending: int = 4


@dataclass
class Edge:
    """One pending N-Quad, built by the typed setters
    (client/client.go Edge + SetValue*)."""

    subject: str
    predicate: str
    object_id: str = ""
    literal: str = ""
    lang: str = ""

    @staticmethod
    def connect(subj: str, pred: str, obj: str) -> "Edge":
        return Edge(subj, pred, object_id=obj)

    @staticmethod
    def value(subj: str, pred: str, v, lang: str = "") -> "Edge":
        if isinstance(v, bool):
            lit = f'"{str(v).lower()}"^^<xs:boolean>'
        elif isinstance(v, int):
            lit = f'"{v}"^^<xs:int>'
        elif isinstance(v, float):
            lit = f'"{v}"^^<xs:float>'
        else:
            s = str(v).replace("\\", "\\\\").replace('"', '\\"')
            lit = f'"{s}"'
        return Edge(subj, pred, literal=lit, lang=lang)

    def nquad(self) -> str:
        subj = self.subject if self.subject.startswith("_:") else f"<{self.subject}>"
        if self.object_id:
            obj = f"<{self.object_id}>" if not self.object_id.startswith("_:") else self.object_id
        else:
            obj = self.literal + (f"@{self.lang}" if self.lang else "")
        return f"{subj} <{self.predicate}> {obj} ."


class DgraphClient:
    """Pipelined batching client (client/mutations.go NewDgraphClient)."""

    def __init__(self, transport: Transport, opts: BatchMutationOptions = BatchMutationOptions()):
        self.transport = transport
        self.opts = opts
        self._set_q: "queue.Queue[Optional[str]]" = queue.Queue(maxsize=opts.size * opts.pending)
        self._del_q: "queue.Queue[Optional[str]]" = queue.Queue(maxsize=opts.size * opts.pending)
        self._err: Optional[BaseException] = None
        self._last_op: Optional[str] = None
        self._prod_lock = threading.Lock()
        self._mutations = 0
        self._lock = threading.Lock()
        self._workers: List[threading.Thread] = []
        self._stop = threading.Event()
        for i in range(opts.pending):
            t = threading.Thread(target=self._worker, name=f"client-batch-{i}", daemon=True)
            t.start()
            self._workers.append(t)

    # -- public mutation surface ------------------------------------------

    def query(self, text: str, variables: Optional[dict] = None) -> dict:
        return self.transport.run(text, variables)

    def batch_set(self, e) -> None:
        self._check_err()
        with self._prod_lock:
            self._op_barrier("set")
            self._set_q.put(e.nquad() if isinstance(e, Edge) else str(e))

    def batch_delete(self, e) -> None:
        self._check_err()
        with self._prod_lock:
            self._op_barrier("del")
            self._del_q.put(e.nquad() if isinstance(e, Edge) else str(e))

    def _op_barrier(self, op: str) -> None:
        """Sets and deletes travel in separate queues drained concurrently;
        without a barrier a delete enqueued after a set of the same quad
        could reach the server first.  On an op-type flip, drain what's
        queued so cross-op order is preserved.  Caller holds _prod_lock so
        the flip check and the enqueue are atomic across producer threads
        (alternating ops serialize — bulk loads are single-op, so the
        common path never blocks here)."""
        if self._last_op != op:
            if self._last_op is not None:
                self._set_q.join()
                self._del_q.join()
                self._check_err()
            self._last_op = op

    def add_schema(self, schema: str) -> None:
        self.transport.run("mutation { schema {\n" + schema + "\n} }")

    def flush(self) -> None:
        """Drain all queued quads and wait (BatchFlush, mutations.go:452)."""
        self._set_q.join()
        self._del_q.join()
        self._check_err()

    def close(self) -> None:
        self.flush()
        self._stop.set()
        # wake workers blocked on get()
        for _ in self._workers:
            self._set_q.put(None)
        for t in self._workers:
            t.join(timeout=5)

    def mutation_count(self) -> int:
        return self._mutations

    # -- internals ---------------------------------------------------------

    def _check_err(self):
        if self._err is not None:
            raise RuntimeError(f"batch worker failed: {self._err}")

    def _drain(self, q: "queue.Queue", first: Optional[str]) -> List[str]:
        batch = [] if first is None else [first]
        while len(batch) < self.opts.size:
            try:
                item = q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                q.task_done()
                continue
            batch.append(item)
        return batch

    def _submit(self, sets: List[str], dels: List[str]) -> None:
        parts = []
        if sets:
            parts.append("set {\n" + "\n".join(sets) + "\n}")
        if dels:
            parts.append("delete {\n" + "\n".join(dels) + "\n}")
        self.transport.run("mutation {\n" + "\n".join(parts) + "\n}")
        with self._lock:
            self._mutations += 1

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._set_q.get(timeout=0.05)
            except queue.Empty:
                # nothing queued for set; try deletes
                try:
                    dfirst = self._del_q.get_nowait()
                except queue.Empty:
                    continue
                dels = self._drain(self._del_q, dfirst)
                try:
                    self._submit([], dels)
                except BaseException as e:  # noqa: BLE001
                    # several workers can fail at once: publish the
                    # error under the client lock, not as a bare store
                    with self._lock:
                        self._err = e
                finally:
                    for _ in dels:
                        self._del_q.task_done()
                continue
            if first is None:
                self._set_q.task_done()
                continue
            sets = self._drain(self._set_q, first)
            try:
                self._submit(sets, [])
            except BaseException as e:  # noqa: BLE001
                with self._lock:
                    self._err = e
            finally:
                for _ in sets:
                    self._set_q.task_done()
