"""Unmarshal query-response nodes into typed Python objects.

Equivalent of client/unmarshal.go:253 — the Go client reflects over
struct tags to fill user structs from protobuf Node trees.  The Python
analog fills dataclasses (or plain classes with annotations) from the
JSON response tree: field name = predicate (override with
`dgraph_field` metadata), nested dataclass / List[dataclass] fields
recurse.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, List, Type, TypeVar, get_args, get_origin, get_type_hints

T = TypeVar("T")


def _field_key(f: dataclasses.Field) -> str:
    return f.metadata.get("dgraph", f.name) if f.metadata else f.name


def _convert_scalar(v: Any, t: Type) -> Any:
    if t is int:
        return int(v)
    if t is float:
        return float(v)
    if t is bool:
        return v if isinstance(v, bool) else str(v).lower() == "true"
    if t is str:
        return str(v)
    return v


def unmarshal(node: dict, cls: Type[T]) -> T:
    """Fill one dataclass instance from one response node dict."""
    if not dataclasses.is_dataclass(cls):
        raise TypeError(f"unmarshal target must be a dataclass, got {cls!r}")
    hints = get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        key = _field_key(f)
        if key not in node:
            continue
        v = node[key]
        t = hints.get(f.name, f.type)
        origin = get_origin(t)
        if origin in (list, typing.List):
            (inner,) = get_args(t) or (Any,)
            items = v if isinstance(v, list) else [v]
            if dataclasses.is_dataclass(inner):
                kwargs[f.name] = [unmarshal(x, inner) for x in items]
            else:
                kwargs[f.name] = [_convert_scalar(x, inner) for x in items]
        elif dataclasses.is_dataclass(t):
            item = v[0] if isinstance(v, list) else v
            kwargs[f.name] = unmarshal(item, t)
        else:
            item = v[0] if isinstance(v, list) else v
            if isinstance(item, dict):
                # scalar predicates may come back as attribute dicts
                item = item.get(key, item)
            kwargs[f.name] = _convert_scalar(item, t)
    return cls(**kwargs)


def unmarshal_list(nodes: List[dict], cls: Type[T]) -> List[T]:
    return [unmarshal(n, cls) for n in nodes]
