"""Cluster layer: predicate sharding, membership, UID leasing, consensus.

The TPU-native restructuring of the reference's group/ + worker/groups.go
+ worker/lease.go + worker/draft.go: predicates shard to groups (device
mesh slices or hosts); a single metadata group (group 0) owns membership
and the UID lease; replication is a Raft log per group feeding each
replica's DurableStore.
"""

from dgraph_tpu.cluster.groups import GroupConfig, fingerprint64
from dgraph_tpu.cluster.lease import LeaseManager

__all__ = ["GroupConfig", "fingerprint64", "LeaseManager"]
