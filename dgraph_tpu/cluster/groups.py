"""Predicate → group sharding configuration.

Equivalent of the reference's group/conf.go: a config of rules
``gid: pred, prefix*`` with a ``default: fp % N + k`` fallback
(ParseConfig group/conf.go:105, fpGroup:182, BelongsTo:190).  Groups are
the unit of placement: in the reference a group is a Raft cluster; here
a group is (a) a replication group on hosts and (b) a shard slice of the
device mesh for arena placement (parallel/mesh.py consumes the same
mapping).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


def fingerprint64(s: str) -> int:
    """Stable 64-bit FNV-1a over utf-8 (stand-in for farm.Fingerprint64;
    only stability across hosts matters, not the exact hash family)."""
    h = 0xCBF29CE484222325
    for b in s.encode("utf-8"):
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


_DEFAULT_RE = re.compile(r"^fp\s*%\s*(\d+)\s*(?:\+\s*(\d+))?$")


@dataclass
class GroupConfig:
    """Parsed sharding rules; immutable after parse."""

    # gid -> exact predicate names
    exact: Dict[str, int] = field(default_factory=dict)
    # (prefix, gid), longest-prefix-wins
    prefixes: List[Tuple[str, int]] = field(default_factory=list)
    mod: int = 1
    offset: int = 1

    @classmethod
    def parse(cls, text: str) -> "GroupConfig":
        """Format (group/conf.go:105): one rule per line —
        ``<gid>: pred1, pref*`` or ``default: fp % N + k``; '#' comments."""
        cfg = cls()
        seen_default = False
        for lineno, raw in enumerate(text.splitlines(), 1):
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            head, _, body = line.partition(":")
            head, body = head.strip(), body.strip()
            if not body:
                raise ValueError(f"groups config line {lineno}: missing ':'")
            if head == "default":
                m = _DEFAULT_RE.match(body)
                if not m:
                    raise ValueError(
                        f"groups config line {lineno}: default must be 'fp % N [+ k]'"
                    )
                cfg.mod = int(m.group(1))
                cfg.offset = int(m.group(2) or 0)
                seen_default = True
                continue
            if not head.isdigit():
                raise ValueError(f"groups config line {lineno}: bad group id {head!r}")
            gid = int(head)
            for tok in body.split(","):
                tok = tok.strip()
                if not tok:
                    continue
                if tok.endswith("*"):
                    cfg.prefixes.append((tok[:-1], gid))
                else:
                    if tok in cfg.exact:
                        raise ValueError(
                            f"groups config line {lineno}: duplicate rule for {tok!r}"
                        )
                    cfg.exact[tok] = gid
        if not seen_default and (cfg.exact or cfg.prefixes):
            # reference requires an explicit default when rules exist
            raise ValueError("groups config: missing 'default: fp % N + k' rule")
        cfg.prefixes.sort(key=lambda p: -len(p[0]))  # longest prefix wins
        return cfg

    @classmethod
    def single_group(cls) -> "GroupConfig":
        """No config file: everything in group 1 (ParseGroupConfig:165)."""
        return cls()

    def belongs_to(self, pred: str) -> int:
        gid = self.exact.get(pred)
        if gid is not None:
            return gid
        for prefix, g in self.prefixes:
            if pred.startswith(prefix):
                return g
        return fingerprint64(pred) % self.mod + self.offset

    def known_groups(self) -> List[int]:
        out = set(self.exact.values()) | {g for _, g in self.prefixes}
        out.update(range(self.offset, self.offset + self.mod))
        return sorted(out)


# metadata group: membership + uid lease live here (worker/worker.go:59
# places "_lease_"; we pin group 0 explicitly like groups.go's group-0
# membership convention)
METADATA_GROUP = 0
