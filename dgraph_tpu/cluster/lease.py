"""Leased UID allocation.

Equivalent of the reference's worker/assign.go + worker/lease.go: a
central counter owned by the metadata group's leader hands out uid
ranges; lease extension is itself a durable proposal so a restarted
leader never re-issues uids (proposeAndWaitForLease, lease.go:106).
Extensions are batched — the counter is bumped in chunks of at least
``min_lease`` so one durable write covers many allocations
(minLeaseNum batching, lease.go:88-98).

Here the "proposal" is a callable supplied by the owner: standalone it
journals straight into the store's WAL (LEASE records); under
replication the Raft node wires it to ProposeAndWait.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Tuple


class LeaseManager:
    """Monotonic uid-range allocator with durable batched leases."""

    def __init__(
        self,
        propose_lease: Callable[[int], None],
        start: int = 1,
        min_lease: int = 10_000,
    ):
        """``propose_lease(new_max)`` must durably record that uids up to
        ``new_max`` (exclusive) may be handed out before it returns."""
        self._propose = propose_lease
        self._lock = threading.Lock()
        self._next = start      # next uid to hand out
        self._leased = start    # uids below this are durably leased
        self.min_lease = min_lease

    @property
    def max_assigned(self) -> int:
        return self._next - 1

    def init_from_recovery(self, next_uid: int, leased_through: Optional[int] = None):
        """After WAL replay: resume above everything ever leased."""
        with self._lock:
            self._leased = max(self._leased, leased_through or next_uid)
            # never reuse any uid that may have been handed out under the
            # old lease, even if unused — monotonicity is the contract
            self._next = self._leased

    def reserve_through(self, uid: int) -> None:
        """Mark an explicitly-named uid as taken: the allocator must never
        hand it out as a fresh uid.  Extends the durable lease (batched)
        when the uid lies beyond the leased window; always advances the
        allocation cursor past it."""
        with self._lock:
            if uid >= self._leased:
                new_max = max(uid + 1, self._leased + self.min_lease)
                self._propose(new_max)
                self._leased = new_max
            self._next = max(self._next, uid + 1)

    def assign(self, n: int) -> Tuple[int, int]:
        """Allocate n consecutive uids; returns [start, end] inclusive
        (AssignUids semantics, worker/assign.go:37)."""
        if n <= 0:
            raise ValueError("must request at least one uid")
        with self._lock:
            start = self._next
            end = start + n - 1
            if end >= self._leased:
                new_max = max(end + 1, self._leased + self.min_lease)
                self._propose(new_max)  # durable before handing out
                self._leased = new_max
            self._next = end + 1
            return start, end
