"""PeerClient: the single gate every intra-cluster RPC goes through.

The reference survives slow and dead peers because its worker conn pool
retries, follows leader hints, and balances reads across replicas
(worker/conn.go, groups.go:268 AnyServer).  Before this module, our
reproduction issued every cross-server read and forwarded proposal as a
ONE-SHOT ``urlopen_peer`` call: a single down peer added a full
connect-timeout stall to every query touching its group.  PeerClient
owns three defenses, applied to every peer call:

1. **Retry with exponential backoff + full jitter under a deadline
   budget.**  The caller hands an overall ``budget`` (seconds); each
   attempt's timeout is derived from the REMAINING budget split over the
   remaining attempts, so three attempts against a 3s budget never take
   9s, and backoff sleeps are clamped to never overshoot the deadline.

2. **A per-peer circuit breaker** (closed → open after
   ``breaker_threshold`` consecutive failures → half-open single probe
   after ``breaker_cooldown`` seconds).  Open circuits shed calls in
   microseconds (:class:`BreakerOpenError`) instead of re-paying the
   connect timeout per query; a successful half-open probe closes the
   circuit, a failed one re-opens it for another cooldown.  An HTTP
   error response (409 leader hint, 404, …) counts as a breaker SUCCESS:
   the peer answered — the failure is application-level, not transport.
   Breaker state is scoped per ``(peer, op)``, not per peer alone: the
   raft heartbeats that keep flowing to a peer whose snapshot endpoint
   is partitioned must not keep closing the read plane's breaker (and a
   broken raft port must not shed that peer's healthy reads).  A fully
   dead peer opens every op's circuit within one threshold each.

3. **Per-peer health scores**: :meth:`order_by_health` sorts a replica
   candidate list healthiest-first, so group reads try a live replica
   before the one that just timed out instead of always ``members[0]``.

Every attempt passes through the failpoint ``peerclient.<op>``
(utils/failpoints.py), which is how the chaos suite injects
deterministic faults below the retry/breaker machinery.

``DGRAPH_TPU_RESILIENCE=0`` is the escape hatch: calls degrade to the
pre-PR single-shot behavior (one attempt, legacy timeout, no breaker,
no degraded-read bookkeeping) so serving responses are byte-identical
to the old tree.

Env knobs: ``DGRAPH_TPU_RPC_ATTEMPTS`` (default 3),
``DGRAPH_TPU_RPC_BACKOFF`` (base seconds, default 0.05; cap 2.0),
``DGRAPH_TPU_BREAKER_THRESHOLD`` (default 5),
``DGRAPH_TPU_BREAKER_COOLDOWN`` (seconds, default 2.0).

graftlint enforces the funnel: the ``naked-peer-rpc`` rule flags any
direct ``urlopen_peer`` / channel-RPC call outside this module
(analysis/rules.py).
"""

from __future__ import annotations

import os
import random
import threading
import time
import urllib.error
from typing import Callable, Dict, List, Optional, Tuple

from dgraph_tpu import obs
from dgraph_tpu.cluster.transport import PeerAuth, urlopen_peer
from dgraph_tpu.utils.env import env_float as _env_f
from dgraph_tpu.utils.failpoints import fail
from dgraph_tpu.utils.health import HalfOpenGate
from dgraph_tpu.utils.metrics import (
    BREAKER_STATE,
    BREAKER_TRANSITIONS,
    PEER_BACKOFF,
    PEER_RPC,
    PEER_RPC_ATTEMPTS,
)

CLOSED, HALF_OPEN, OPEN = "closed", "half-open", "open"
_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

# per-attempt timeout floor: below this a local-network RPC cannot even
# complete a TCP+HTTP round trip, so slicing the budget thinner than
# this just manufactures failures
_MIN_ATTEMPT_TIMEOUT = 0.05


def resilience_enabled() -> bool:
    """The DGRAPH_TPU_RESILIENCE gate (default ON)."""
    return os.environ.get("DGRAPH_TPU_RESILIENCE", "1") != "0"


class PeerUnavailableError(OSError):
    """Every attempt failed (or the budget ran out) for one peer."""

    def __init__(self, peer: str, op: str, detail: str = ""):
        self.peer = peer
        self.op = op
        super().__init__(
            f"peer {peer} unavailable for {op}" + (f": {detail}" if detail else "")
        )


class BreakerOpenError(PeerUnavailableError):
    """Shed without touching the network: the peer's circuit is open."""

    def __init__(self, peer: str, op: str, retry_after: float):
        self.retry_after = retry_after
        super().__init__(peer, op, f"circuit open (retry in ~{retry_after:.1f}s)")


class StaleUnavailableError(OSError):
    """A cross-server read found the owner group unreachable AND holds no
    cached snapshot to degrade to.  The serving layer maps this to
    HTTP 503 + Retry-After / gRPC UNAVAILABLE — a retriable service
    condition, not a client error."""

    def __init__(self, msg: str, retry_after: float = 2.0):
        self.retry_after = retry_after
        super().__init__(msg)


class _PeerState:
    __slots__ = (
        "failures", "state", "gate",
        "last_success", "last_failure", "total_failures", "_race_serial",
    )

    # graftcheck tier 3: breaker counters and state transitions must
    # all carry PeerClient._lock — the armed lockset witness proves the
    # "mutated under PeerClient._lock" comment below stays true
    __race_fields__ = frozenset({
        "failures", "state", "last_success", "last_failure",
        "total_failures",
    })

    def __init__(self):
        self.failures = 0           # consecutive transport failures
        self.state = CLOSED
        # cooldown + half-open probe-slot discipline: the shared helper
        # (utils/health.py HalfOpenGate — StorageHealth and the device
        # guard ride the same one), mutated under PeerClient._lock
        self.gate = HalfOpenGate()
        self.last_success = 0.0     # monotonic; 0 = never
        self.last_failure = 0.0
        self.total_failures = 0

    @property
    def opened_at(self) -> float:
        return self.gate.opened_at


class PeerClient:
    """One instance per ClusterService, shared with its raft transports."""

    def __init__(
        self,
        auth: Optional[PeerAuth] = None,
        *,
        attempts: Optional[int] = None,
        backoff_base: Optional[float] = None,
        backoff_cap: float = 2.0,
        breaker_threshold: Optional[int] = None,
        breaker_cooldown: Optional[float] = None,
        rng: Optional[random.Random] = None,
    ):
        self.auth = auth
        self.attempts = int(
            attempts
            if attempts is not None
            else _env_f("DGRAPH_TPU_RPC_ATTEMPTS", 3)
        )
        self.backoff_base = (
            backoff_base
            if backoff_base is not None
            else _env_f("DGRAPH_TPU_RPC_BACKOFF", 0.05)
        )
        self.backoff_cap = backoff_cap
        self.breaker_threshold = int(
            breaker_threshold
            if breaker_threshold is not None
            else _env_f("DGRAPH_TPU_BREAKER_THRESHOLD", 5)
        )
        self.breaker_cooldown = (
            breaker_cooldown
            if breaker_cooldown is not None
            else _env_f("DGRAPH_TPU_BREAKER_COOLDOWN", 2.0)
        )
        # backoff jitter rng: seeded for tests, fresh entropy otherwise
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        # breaker/health state per (peer, op) — see the module docstring
        # for why per-peer alone is wrong (heartbeats masking partitions)
        self._peers: Dict[Tuple[str, str], _PeerState] = {}

    # -- breaker ------------------------------------------------------------

    def _state(self, peer: str, op: str) -> _PeerState:
        st = self._peers.get((peer, op))
        if st is None:
            st = self._peers[(peer, op)] = _PeerState()
        return st

    def _set_state(self, peer: str, op: str, st: _PeerState, state: str) -> None:
        if st.state != state:
            st.state = state
            BREAKER_TRANSITIONS.add((peer, op, state))
        BREAKER_STATE.set(f"{peer}:{op}", _STATE_GAUGE[state])

    def _admit(self, peer: str, op: str) -> Tuple[bool, float, Optional[int]]:
        """(admitted, retry_after, probe_token).  Transitions
        open→half-open when the cooldown elapsed, allowing exactly one
        probe at a time.  A non-None ``probe_token`` tells the caller IT
        holds the half-open probe slot — it must hand the token back to
        ``_release_probe`` on every exit path, or the breaker wedges
        shedding forever.  The token (not a bare flag) keeps a slow call
        admitted under an EARLIER state from releasing a probe slot it
        never held."""
        now = time.monotonic()
        with self._lock:
            st = self._state(peer, op)
            if st.state == CLOSED:
                return True, 0.0, None
            granted, retry_after, token = st.gate.admit(
                now, self.breaker_cooldown, half_open=st.state == HALF_OPEN
            )
            if granted and st.state == OPEN:
                self._set_state(peer, op, st, HALF_OPEN)
            return granted, retry_after, token

    def _release_probe(self, peer: str, op: str, token: int) -> None:
        """Free the half-open probe slot WITHOUT judging the peer — runs
        on every probe exit path (including zero-attempt budget
        exhaustion and KeyboardInterrupt).  A stale token (the slot was
        re-granted to a newer probe) is a no-op."""
        with self._lock:
            st = self._peers.get((peer, op))
            if st is not None:
                st.gate.release(token)

    def _record(self, peer: str, op: str, ok: bool) -> None:
        now = time.monotonic()
        with self._lock:
            st = self._state(peer, op)
            if ok:
                st.failures = 0
                st.last_success = now
                self._set_state(peer, op, st, CLOSED)
            else:
                st.failures += 1
                st.total_failures += 1
                st.last_failure = now
                if st.state == HALF_OPEN or st.failures >= self.breaker_threshold:
                    st.gate.open(now)
                    self._set_state(peer, op, st, OPEN)

    def state_of(self, peer: str, op: Optional[str] = None) -> str:
        """Breaker state for one op, or the WORST state across the peer's
        ops (OPEN > HALF_OPEN > CLOSED) when ``op`` is None."""
        with self._lock:
            if op is not None:
                st = self._peers.get((peer, op))
                return st.state if st is not None else CLOSED
            worst = CLOSED
            for (p, _o), st in self._peers.items():
                if p != peer:
                    continue
                if st.state == OPEN:
                    return OPEN
                if st.state == HALF_OPEN:
                    worst = HALF_OPEN
            return worst

    def snapshot(self) -> Dict[str, dict]:
        """Per-peer, per-op breaker/health view for /health."""
        now = time.monotonic()
        with self._lock:
            out: Dict[str, dict] = {}
            for (peer, op), st in self._peers.items():
                out.setdefault(peer, {})[op] = {
                    "breaker": st.state,
                    "consecutive_failures": st.failures,
                    "total_failures": st.total_failures,
                    "last_success_age_s": (
                        round(now - st.last_success, 3) if st.last_success else None
                    ),
                    "last_failure_age_s": (
                        round(now - st.last_failure, 3) if st.last_failure else None
                    ),
                }
            return out

    def order_by_health(
        self,
        members: List[Tuple[str, str]],
        op: Optional[str] = None,
    ) -> List[Tuple[str, str]]:
        """Sort (node_id, addr) candidates healthiest-first: closed
        circuits before open ones, fewer consecutive failures before
        more, most-recent success first.  ``op`` narrows the judgment to
        one op's state (a peer whose raft port is down can still be the
        best snapshot source).  Stable, and open peers are kept (last
        resort — their breaker sheds in microseconds)."""
        if not resilience_enabled():
            return list(members)
        now = time.monotonic()
        with self._lock:
            def key(item: Tuple[str, str]):
                nid = item[0]
                if op is not None:
                    st = self._peers.get((nid, op))
                    sts = [st] if st is not None else []
                else:
                    sts = [s for (p, _o), s in self._peers.items() if p == nid]
                if not sts:
                    return (0, 0, 0.0)
                is_open = (
                    1
                    if any(
                        s.state == OPEN
                        and now - s.opened_at < self.breaker_cooldown
                        for s in sts
                    )
                    else 0
                )
                fails = sum(s.failures for s in sts)
                last = max(s.last_success for s in sts)
                return (is_open, fails, -last)

            return sorted(members, key=key)

    # -- calls --------------------------------------------------------------

    def call(
        self,
        peer: str,
        op: str,
        attempt: Callable[[Optional[float]], object],
        *,
        budget: Optional[float] = None,
        attempts: Optional[int] = None,
        off_timeout: Optional[float] = None,
        transient: Tuple[type, ...] = (OSError,),
        alive: Optional[Callable[[BaseException], bool]] = None,
        slice_budget: bool = True,
    ):
        """Run ``attempt(per_attempt_timeout)`` with retries/backoff under
        the budget and the peer's breaker.

        ``transient`` classifies retriable transport failures (gRPC
        callers extend it with ``grpc.RpcError``).  ``HTTPError`` always
        passes through un-retried — the peer is alive — and counts as a
        breaker success.  ``alive`` refines ``transient`` for exception
        types that cover both cases: a transient-matched exception it
        judges alive gets the HTTPError treatment (un-retried, breaker
        success) — how gRPC's single ``RpcError`` distinguishes a
        responding peer (INVALID_ARGUMENT, UNAUTHENTICATED, …) from a
        dead one (UNAVAILABLE).  ``off_timeout`` is the single-attempt
        timeout used when DGRAPH_TPU_RESILIENCE=0 (defaults to
        ``budget``).

        ``slice_budget=False`` gives EVERY attempt the full remaining
        budget instead of splitting it over the attempts left.  This is
        for calls that legitimately block server-side while succeeding
        (a forwarded proposal committing, a join waiting for its MEMBER
        record to apply, a raft frame to a loaded peer): slicing would
        time out work that was about to succeed and re-send it — the
        duplicate-proposal amplification this module exists to kill.
        Retries then only ever fire on failures FASTER than the budget
        (connect refused, RST, injected faults), which leave most of the
        window intact; a first attempt that times out consumes the whole
        budget and simply raises."""
        if not resilience_enabled():
            fail.point(f"peerclient.{op}")
            return attempt(off_timeout if off_timeout is not None else budget)
        n_attempts = max(1, int(attempts if attempts is not None else self.attempts))
        deadline = None if budget is None else time.monotonic() + budget
        # flight recorder: the calling thread's span (the query's engine
        # span, a forwarder's root, …) — every attempt below records one
        # child with the breaker/backoff outcome, so a trace shows each
        # wire try, not just the final verdict.  None = unsampled: no
        # span objects anywhere on this path.
        tsp = obs.current_span()
        admitted, retry_after, probe_token = self._admit(peer, op)
        if not admitted:
            PEER_RPC.add((peer, op, "open"))
            if tsp is not None:
                with tsp.child(f"rpc.{op}") as a:
                    a.set_attr("peer", peer)
                    a.set_attr("outcome", "breaker_open")
                    a.set_attr("retry_after_s", round(retry_after, 3))
            raise BreakerOpenError(peer, op, retry_after)
        last: Optional[BaseException] = None
        made = 0  # attempts actually issued (≠ n_attempts under sheds)
        try:
            for i in range(n_attempts):
                if deadline is None:
                    per = budget
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    # split remaining over the attempts left, but never
                    # slice below the floor — a sub-floor timeout cannot
                    # complete a round trip and would charge the breaker
                    # a manufactured failure against a healthy peer
                    # (deadline overshoot is bounded by the floor)
                    per = remaining if not slice_budget else (
                        remaining / (n_attempts - i)
                    )
                    per = max(per, _MIN_ATTEMPT_TIMEOUT)
                made = i + 1
                asp = None
                if tsp is not None:
                    asp = tsp.child(f"rpc.{op}")
                    asp.set_attr("peer", peer)
                    asp.set_attr("attempt", i + 1)
                    if per is not None:
                        asp.set_attr("timeout_s", round(per, 3))
                try:
                    try:
                        fail.point(f"peerclient.{op}")
                        res = attempt(per)
                        if asp is not None:
                            # BEFORE the finally publishes the span: a
                            # reader racing the finish must never see a
                            # successful attempt with no outcome
                            asp.set_attr("outcome", "ok")
                    except urllib.error.HTTPError as e:
                        # an HTTP response IS the peer talking: transport is fine
                        self._record(peer, op, True)
                        PEER_RPC.add((peer, op, "http_error"))
                        PEER_RPC_ATTEMPTS.observe(i + 1)
                        if asp is not None:
                            asp.set_attr("outcome", "http_error")
                            asp.set_attr("code", getattr(e, "code", 0))
                        raise
                    except transient as e:
                        if alive is not None and alive(e):
                            # the peer RESPONDED with an application-level
                            # rejection: transport is fine, same rule as the
                            # HTTPError arm above
                            self._record(peer, op, True)
                            PEER_RPC.add((peer, op, "http_error"))
                            PEER_RPC_ATTEMPTS.observe(i + 1)
                            if asp is not None:
                                asp.set_attr("outcome", "http_error")
                            raise
                        last = e
                        self._record(peer, op, False)
                        if asp is not None:
                            asp.set_attr("outcome", "transient")
                            asp.set_attr("error", type(e).__name__)
                            asp.set_attr(
                                "breaker", self.state_of(peer, op)
                            )
                        if self.state_of(peer, op) == OPEN:
                            break  # this attempt tripped the breaker: stop burning budget
                        if i + 1 < n_attempts:
                            b = min(
                                self.backoff_cap, self.backoff_base * (2 ** i)
                            ) * self._rng.random()
                            if deadline is not None:
                                b = min(b, max(0.0, deadline - time.monotonic()))
                            PEER_BACKOFF.observe(b)
                            if asp is not None:
                                # close the attempt span BEFORE sleeping:
                                # a 5ms refused connect must not render
                                # as a 500ms "slow peer" — the deliberate
                                # backoff rides as an attr, not as span
                                # duration (finish is idempotent; the
                                # finally below no-ops)
                                asp.set_attr("backoff_s", round(b, 4))
                                asp.finish()
                            if b > 0:
                                time.sleep(b)
                        continue
                    except Exception as e:
                        # not transient, not an HTTP response: the peer spoke
                        # garbage (BadStatusLine, truncated frame, …).  Count
                        # it as a transport failure — un-recorded, a half-open
                        # probe's flag would leak and wedge the breaker shut.
                        self._record(peer, op, False)
                        PEER_RPC.add((peer, op, "unavailable"))
                        PEER_RPC_ATTEMPTS.observe(i + 1)
                        if asp is not None:
                            asp.set_attr("outcome", "garbage")
                            asp.set_attr("error", type(e).__name__)
                        raise
                finally:
                    if asp is not None:
                        asp.finish()
                self._record(peer, op, True)
                PEER_RPC.add((peer, op, "ok"))
                PEER_RPC_ATTEMPTS.observe(i + 1)
                return res
            PEER_RPC.add((peer, op, "unavailable"))
            PEER_RPC_ATTEMPTS.observe(made)
            raise PeerUnavailableError(
                peer, op,
                f"{type(last).__name__}: {last}" if last else "budget exhausted",
            ) from last
        finally:
            if probe_token is not None:
                self._release_probe(peer, op, probe_token)

    def urlopen(
        self,
        peer: str,
        req,
        *,
        op: str,
        budget: Optional[float] = None,
        attempts: Optional[int] = None,
        off_timeout: Optional[float] = None,
        slice_budget: bool = True,
    ):
        """The HTTP peer call: ``urlopen_peer`` wrapped in retry/breaker.
        Returns the (context-managed) response object."""
        # trace propagation: a sampled caller's context rides the W3C
        # traceparent header, so the remote node records ITS half of the
        # trace under the same trace_id (obs/spans.py)
        sp = obs.current_span()
        if sp is not None and hasattr(req, "add_header"):
            req.add_header("Traceparent", obs.format_traceparent(sp))

        def attempt(t: Optional[float]):
            return urlopen_peer(req, t if t is not None else 10.0, self.auth)

        return self.call(
            peer, op, attempt,
            budget=budget, attempts=attempts, off_timeout=off_timeout,
            slice_budget=slice_budget,
        )

    def grpc_unary(
        self,
        peer: str,
        op: str,
        channel,
        method: str,
        payload: bytes,
        *,
        metadata=None,
        budget: Optional[float] = None,
        attempts: Optional[int] = None,
        slice_budget: bool = True,
    ):
        """The gRPC peer call (raft frames over the Worker plane).  The
        channel-RPC invocation lives HERE so graftlint's naked-peer-rpc
        funnel holds for both transports."""
        import grpc

        # multicallables are cached ON the channel (their lifetime), not
        # rebuilt per frame — this is the raft hot path, one send per
        # heartbeat per peer
        try:
            mcs = channel._dgraph_tpu_multicallables
        except AttributeError:
            mcs = channel._dgraph_tpu_multicallables = {}
        rpc = mcs.get(method)
        if rpc is None:
            rpc = mcs[method] = channel.unary_unary(method)

        # trace propagation, gRPC leg: traceparent rides metadata (same
        # W3C field the HTTP leg puts in a header)
        sp = obs.current_span()
        if sp is not None:
            metadata = list(metadata or []) + [
                ("traceparent", obs.format_traceparent(sp))
            ]

        def attempt(t: Optional[float]):
            return rpc(payload, timeout=t, metadata=metadata)

        # every RpcError carries a status; only these mean the peer
        # itself is unreachable/slow.  Anything else (UNAUTHENTICATED on
        # a secret mismatch, INVALID_ARGUMENT, UNIMPLEMENTED, …) is the
        # peer ANSWERING with a rejection — retrying doubles traffic to
        # an alive peer and opening its breaker misreports a config
        # error as a network outage
        transient_codes = (
            grpc.StatusCode.UNAVAILABLE,
            grpc.StatusCode.DEADLINE_EXCEEDED,
            grpc.StatusCode.CANCELLED,
        )

        def peer_alive(e: BaseException) -> bool:
            code = getattr(e, "code", None)
            try:
                return code is not None and code() not in transient_codes
            except Exception:  # noqa: BLE001 — unknown error shape:
                return False   # keep the old everything-transient rule

        # ValueError: grpcio raises it when the channel closed under the
        # call mid-shutdown — transient for a sender loop, same as before
        return self.call(
            peer, op, attempt,
            budget=budget, attempts=attempts,
            transient=(grpc.RpcError, OSError, ValueError),
            alive=peer_alive, slice_budget=slice_budget,
        )
