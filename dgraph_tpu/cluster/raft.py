"""Raft consensus for replication groups.

Equivalent of the reference's worker/draft.go + vendored etcd/raft +
raftwal/: one Raft node per (server × group) replicates a mutation log;
committed entries are applied to the group's DurableStore; snapshots
compact the log once applied state is durably synced (draft.go:827-877's
"snapshot only up to the synced watermark" contract).

Design: a single event-loop thread per node owns ALL state (the same
model as etcd/raft's Run loop, draft.go:709) — messages, proposals and
ticks arrive on one queue, so there are no data races by construction.
Safety-critical persistence (term/vote on change, log entries before
acking) goes through the same CRC-framed Wal as the store.

Transport is pluggable: InMemoryTransport for tests/embedded mode
(worker.Config.InMemoryComm analog), gRPC in serve/worker_service.py.
"""

from __future__ import annotations

import os
import queue
import random
import struct
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from dgraph_tpu.models import codec
from dgraph_tpu.models.wal import Wal, replay_records
from dgraph_tpu.utils.atomicio import atomic_write_file
from dgraph_tpu.utils.env import env_float, env_int
from dgraph_tpu.utils.failpoints import fail


def propose_patience(timeout: Optional[float] = None) -> float:
    """How long a proposer waits for commit+apply before giving up.

    ``DGRAPH_TPU_PROPOSE_TIMEOUT`` overrides the 10s default (read at
    call time so tests can set it per-module): on a slow or instrumented
    host a single commit+apply round trip can exceed 10s, and a
    timed-out proposal invites the client to re-post a duplicate that
    queues behind the still-running original — patience here is what
    breaks that amplification loop.  An explicit ``timeout`` argument
    always wins."""
    if timeout is not None:
        return timeout
    return env_float("DGRAPH_TPU_PROPOSE_TIMEOUT", 10.0)

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


@dataclass
class Entry:
    term: int
    index: int
    data: bytes


@dataclass
class VoteReq:
    term: int
    candidate: str
    last_log_index: int
    last_log_term: int
    # pre-vote (raft thesis §9.6 / etcd PreVote, the refinement the
    # reference gets from etcd/raft): a probe at term+1 that mutates NO
    # persistent state — a partitioned node rejoining cannot inflate the
    # cluster term and force a needless election
    pre: bool = False


@dataclass
class VoteResp:
    term: int
    granted: bool
    sender: str
    pre: bool = False


@dataclass
class TimeoutNow:
    """Leadership transfer (draft.go:788-805 TransferLeadership): the
    leader tells its most caught-up follower to campaign IMMEDIATELY
    (bypassing pre-vote and its own election timer), so a graceful stop
    hands off leadership with no availability gap."""

    term: int
    leader: str


@dataclass
class AppendReq:
    term: int
    leader: str
    prev_log_index: int
    prev_log_term: int
    entries: List[Entry]
    leader_commit: int


@dataclass
class AppendResp:
    term: int
    success: bool
    match_index: int
    sender: str


@dataclass
class SnapshotReq:
    term: int
    leader: str
    last_index: int
    last_term: int
    data: bytes


@dataclass
class SnapshotResp:
    term: int
    sender: str
    last_index: int


class Transport:
    """Delivers messages between nodes; implementations must be safe to
    call from the node loop thread."""

    def send(self, to: str, group: int, msg) -> None:  # pragma: no cover
        raise NotImplementedError


class InMemoryTransport(Transport):
    """Single-process delivery (embedded/InMemoryComm mode). Supports
    partitions for tests (cut/heal)."""

    def __init__(self):
        self.nodes: Dict[Tuple[str, int], "RaftNode"] = {}
        self._cut: set = set()
        self._lock = threading.Lock()

    def register(self, node: "RaftNode") -> None:
        with self._lock:
            self.nodes[(node.node_id, node.group)] = node

    def cut(self, a: str, b: str) -> None:
        with self._lock:
            self._cut.add((a, b))
            self._cut.add((b, a))

    def heal(self) -> None:
        with self._lock:
            self._cut.clear()

    def send(self, to: str, group: int, msg) -> None:
        with self._lock:
            sender = getattr(msg, "leader", None) or getattr(
                msg, "candidate", None
            ) or getattr(msg, "sender", None)
            if (sender, to) in self._cut:
                return
            node = self.nodes.get((to, group))
        if node is not None:
            node.deliver(msg)


# -- persistent state -------------------------------------------------------

_HS = struct.Struct("<QI")  # term, voted_for length follows


class RaftStorage:
    """Durable term/vote/log/snapshot (raftwal/wal.go analog).

    Layout in dir/: hardstate.bin (term + voted_for, atomic rewrite),
    raft.log (Wal of entries), snapshot.meta + snapshot.bin.
    """

    def __init__(self, directory: str, sync: bool = False):
        os.makedirs(directory, exist_ok=True)
        self.dir = directory
        self._hs_path = os.path.join(directory, "hardstate.bin")
        self._log_path = os.path.join(directory, "raft.log")
        self._snap_meta = os.path.join(directory, "snapshot.meta")
        self._snap_path = os.path.join(directory, "snapshot.bin")
        self.term = 0
        self.voted_for: Optional[str] = None
        self.snap_index = 0
        self.snap_term = 0
        self.entries: List[Entry] = []  # entries after snap_index
        t0 = time.monotonic()
        self._replay_stats: dict = {}
        self._load()
        self._wal = Wal(self._log_path, sync=sync)
        if self._replay_stats.get("records") or self._replay_stats.get(
            "torn_bytes"
        ):
            # the raft twin of DurableStore's recovery line: how much log
            # was replayed and whether a torn tail was cut (crash matrix
            # asserts this observability survives a kill at any site)
            import sys

            print(
                f"# recovery {directory}: snap_index={self.snap_index} "
                f"log_records={self._replay_stats.get('records', 0)} "
                f"torn_bytes={self._replay_stats.get('torn_bytes', 0)} "
                f"duration={time.monotonic() - t0:.4f}s",
                file=sys.stderr,
            )

    def _load(self) -> None:
        if os.path.exists(self._hs_path):
            with open(self._hs_path, "rb") as f:
                raw = f.read()
            self.term, vlen = _HS.unpack_from(raw, 0)
            self.voted_for = (
                raw[_HS.size : _HS.size + vlen].decode() if vlen else None
            )
        if os.path.exists(self._snap_meta):
            with open(self._snap_meta, "rb") as f:
                self.snap_index, self.snap_term = struct.unpack("<QQ", f.read(16))
        for payload in replay_records(self._log_path, stats=self._replay_stats):
            term, pos = codec.uvarint(payload, 0)
            index, pos = codec.uvarint(payload, pos)
            data = bytes(payload[pos:])
            # replay may contain superseded suffixes from old terms; a
            # later append with the same index overwrites (truncate-then-
            # append is recorded as re-append in the log stream)
            e = Entry(term, index, data)
            while self.entries and self.entries[-1].index >= index:
                self.entries.pop()
            if index > self.snap_index:
                self.entries.append(e)

    def save_hardstate(self, term: int, voted_for: Optional[str]) -> None:
        self.term, self.voted_for = term, voted_for
        v = (voted_for or "").encode()
        # durable BEFORE any vote/term is acted on (Raft's safety
        # prerequisite); crash sites raft.hardstate.{tmp,replace}
        atomic_write_file(
            self._hs_path, _HS.pack(term, len(v)) + v, site="raft.hardstate"
        )

    def append(self, entries: List[Entry]) -> None:
        fail.point("raft.log_append")
        for e in entries:
            buf = bytearray()
            codec.put_uvarint(buf, e.term)
            codec.put_uvarint(buf, e.index)
            buf.extend(e.data)
            self._wal.append(bytes(buf))
            while self.entries and self.entries[-1].index >= e.index:
                self.entries.pop()
            self.entries.append(e)
        self._wal.flush()

    def last_index(self) -> int:
        return self.entries[-1].index if self.entries else self.snap_index

    def last_term(self) -> int:
        return self.entries[-1].term if self.entries else self.snap_term

    def term_at(self, index: int) -> Optional[int]:
        if index == self.snap_index:
            return self.snap_term
        if index < self.snap_index:
            return None  # compacted away
        i = index - self.snap_index - 1
        if 0 <= i < len(self.entries):
            return self.entries[i].term
        return None

    def entries_from(self, index: int) -> List[Entry]:
        i = index - self.snap_index - 1
        if i < 0:
            return []
        return self.entries[i:]

    def entry_at(self, index: int) -> Optional[Entry]:
        i = index - self.snap_index - 1
        if 0 <= i < len(self.entries):
            return self.entries[i]
        return None

    def save_snapshot(self, index: int, term: int, data: bytes) -> None:
        """Install/record a snapshot and drop covered entries.  Order
        matters for crash safety: data first, META LAST — snap_index only
        advances once the data it points at is durably in place (a crash
        between the two replays the old snapshot + full log, which is
        merely slower, never wrong)."""
        atomic_write_file(self._snap_path, data, site="raft.snapshot")
        atomic_write_file(
            self._snap_meta, struct.pack("<QQ", index, term)
        )
        self.entries = [e for e in self.entries if e.index > index]
        self.snap_index, self.snap_term = index, term
        # rewrite the log with only the surviving suffix
        self._wal.reset()
        tail, self.entries = self.entries, []
        self.append(tail)

    def load_snapshot(self) -> Optional[bytes]:
        if not os.path.exists(self._snap_path) or self.snap_index == 0:
            return None
        with open(self._snap_path, "rb") as f:
            return f.read()

    def close(self) -> None:
        self._wal.close()


# -- the node ---------------------------------------------------------------

class RaftNode:
    """One replica of one group's log (draft.go node analog)."""

    def __init__(
        self,
        node_id: str,
        group: int,
        peers: List[str],
        storage: RaftStorage,
        transport: Transport,
        apply_fn: Callable[[int, bytes], None],
        snapshot_fn: Optional[Callable[[], bytes]] = None,
        restore_fn: Optional[Callable[[bytes], None]] = None,
        tick_ms: int = 15,
        election_ticks: int = 10,
        snapshot_threshold: Optional[int] = None,
        passive: bool = False,
    ):
        self.node_id = node_id
        self.group = group
        self.peers = [p for p in peers if p != node_id]
        self.storage = storage
        self.transport = transport
        self.apply_fn = apply_fn
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self.tick_s = tick_ms / 1000.0
        self.election_ticks = election_ticks
        # raft-log compaction threshold: the raft leg of the snapshot
        # knob family (the store WAL has DGRAPH_TPU_SNAPSHOT_WAL_MB/
        # _RECORDS; /admin/snapshot force-compacts both planes)
        self.snapshot_threshold = (
            snapshot_threshold
            if snapshot_threshold is not None
            else env_int("DGRAPH_TPU_SNAPSHOT_RAFT_RECORDS", 10_000)
        )

        # passive: a joining node that does not yet know the membership —
        # it never campaigns (it would split-brain-elect itself with an
        # empty log) until activated by the first add_peer (JoinCluster
        # analog, draft.go:1049)
        self.passive = passive
        self.state = FOLLOWER
        self.leader_id: Optional[str] = None
        self.commit_index = storage.snap_index
        self.last_applied = storage.snap_index
        self.next_index: Dict[str, int] = {}
        self.match_index: Dict[str, int] = {}
        self.votes: set = set()
        self._prevotes: set = set()
        self._prevoting = False  # an open pre-vote round of OUR own
        # ticks since we last heard from a live leader — the pre-vote
        # stickiness clock.  Deliberately separate from _elapsed, which
        # our own election activity resets (etcd tracks these apart too).
        self._since_leader = 0
        self._transfer_target: Optional[str] = None
        self._transfer_ticks = 0
        self._transfer_sent = False
        self._elapsed = 0
        self._timeout = self._rand_timeout()
        self._inbox: "queue.Queue" = queue.Queue()
        self._pending: Dict[int, Future] = {}  # log index -> proposal future
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._applying_snapshot = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        snap = self.storage.load_snapshot()
        if snap is not None and self.restore_fn is not None:
            self.restore_fn(snap)
        self._thread = threading.Thread(
            target=self._run, name=f"raft-{self.node_id}-g{self.group}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._stop.is_set():  # idempotent: tests/admin can double-stop
            return
        # graceful-stop leadership transfer (draft.go:788-805): hand the
        # lead to the most caught-up follower and wait briefly for its
        # first heartbeat to demote us, so the group never waits out an
        # election timeout.  Crash-stops skip this naturally (no stop()).
        if self.state == LEADER and self.peers and self._thread is not None:
            # graftlint: shared[_transfer_sent] GIL-atomic bool handshake: stop() arms it False then polls; _run stores True exactly once — no compound update, staleness bounded by the poll sleep
            self._transfer_sent = False
            self._inbox.put(("transfer",))
            deadline = time.monotonic() + 2.0
            # exit on demotion (new leader's message reached us) OR once
            # TimeoutNow has flown plus a short grace — when our inbound
            # plane is already closing we can't observe the demotion, and
            # the handoff itself completes on the survivors' side
            while self.state == LEADER and time.monotonic() < deadline:
                if self._transfer_sent:
                    time.sleep(self.tick_s * 4)
                    break
                time.sleep(self.tick_s)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.storage.close()

    def transfer_leadership(self) -> None:
        """Ask the most caught-up follower to take over (TimeoutNow)."""
        self._inbox.put(("transfer",))

    def request_snapshot(self) -> None:
        """Force a raft-log compaction regardless of threshold
        (/admin/snapshot's cluster leg).  Runs on the loop thread — the
        only thread allowed to touch storage — at the next dequeue."""
        self._inbox.put(("snapshot",))

    # -- public API (thread-safe) -------------------------------------------

    def deliver(self, msg) -> None:
        self._inbox.put(("msg", msg))

    def propose(self, data: bytes) -> Future:
        fut: Future = Future()
        self._inbox.put(("propose", data, fut))
        return fut

    def add_peer(self, nid: str) -> None:
        """Runtime membership addition (single-server change, the
        simplified ConfChange the reference gets from etcd/raft): the
        peer joins the replication set and — on the leader — starts
        receiving appends/snapshots immediately.  Idempotent."""
        self._inbox.put(("conf_add", nid))

    def remove_peer(self, nid: str) -> None:
        """Runtime membership removal: a MEMBER record declaring a peer's
        group placement excludes it from groups it does not serve — a
        voter that never answers would otherwise depress this group's
        quorum forever.  Idempotent; removing an absent peer is a no-op."""
        self._inbox.put(("conf_remove", nid))

    def propose_and_wait(self, data: bytes, timeout: Optional[float] = None):
        """draft.go:341 ProposeAndWait: block until applied or error."""
        return self.propose(data).result(timeout=propose_patience(timeout))

    @property
    def is_leader(self) -> bool:
        return self.state == LEADER

    # -- event loop ---------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._inbox.get(timeout=self.tick_s)
            except queue.Empty:
                try:
                    self._tick()
                except Exception:  # noqa: BLE001
                    import traceback

                    traceback.print_exc()
                continue
            kind = item[0]
            try:
                if kind == "msg":
                    self._handle(item[1])
                elif kind == "propose":
                    self._handle_propose(item[1], item[2])
                elif kind == "conf_add":
                    self._handle_conf_add(item[1])
                elif kind == "conf_remove":
                    self._handle_conf_remove(item[1])
                elif kind == "transfer":
                    self._handle_transfer()
                elif kind == "snapshot":
                    self._maybe_snapshot(force=True)
            except Exception:  # noqa: BLE001 — a bad entry/storage error must
                # not silently kill the event loop and wedge the group
                import traceback

                traceback.print_exc()
                if kind == "propose" and not item[2].done():
                    item[2].set_exception(RuntimeError("raft apply failed"))

    def _rand_timeout(self) -> int:
        return self.election_ticks + random.randrange(self.election_ticks)

    def _tick(self) -> None:
        if self.state == LEADER:
            if self._transfer_target is not None:
                self._transfer_ticks -= 1
                if self._transfer_ticks <= 0:
                    self._finish_transfer()  # best effort at deadline
            self._broadcast_append()
            return
        if self.passive:
            return  # joining node: wait to be contacted, never campaign
        self._elapsed += 1
        self._since_leader += 1
        if self._elapsed >= self._timeout:
            self._prevote()

    def _handle_conf_add(self, nid: str) -> None:
        if nid == self.node_id:
            # learning only our OWN id must not activate a passive joiner:
            # with an empty peer list it would instantly self-elect and
            # force the real leader down when their messages cross
            return
        if nid not in self.peers:
            self.peers.append(nid)
            self.next_index[nid] = self.storage.last_index() + 1
            self.match_index[nid] = 0
            if self.state == LEADER:
                self._send_append(nid)
        # learning a real peer activates a passive joiner
        self.passive = False

    def _handle_transfer(self) -> None:
        if self.state != LEADER or not self.peers:
            return
        # flush our tail, pick the most caught-up peer, and hand off only
        # once it confirms our last index (etcd waits for catch-up before
        # MsgTimeoutNow); a tick-bounded deadline fires best-effort if the
        # confirmation never lands
        self._broadcast_append()
        target = max(self.peers, key=lambda p: self.match_index.get(p, 0))
        self._transfer_target = target
        self._transfer_ticks = self.election_ticks
        if self.match_index.get(target, 0) >= self.storage.last_index():
            self._finish_transfer()

    def _finish_transfer(self) -> None:
        target = self._transfer_target
        self._transfer_target = None
        if target is not None and self.state == LEADER:
            self.transport.send(
                target, self.group, TimeoutNow(self.storage.term, self.node_id)
            )
        self._transfer_sent = True

    def _handle_conf_remove(self, nid: str) -> None:
        if nid == self.node_id or nid not in self.peers:
            return
        self.peers.remove(nid)
        self.next_index.pop(nid, None)
        self.match_index.pop(nid, None)
        if self.state == LEADER:
            # quorum may have shrunk: entries waiting on the removed voter
            # can be committable now
            self._maybe_commit()

    # -- elections ----------------------------------------------------------

    def _prevote(self) -> None:
        """Probe electability at term+1 without touching persistent state;
        only a pre-vote majority starts a real (term-bumping) campaign."""
        if not self.peers:
            self._campaign()
            return
        self._prevotes = {self.node_id}
        self._prevoting = True
        self._elapsed = 0
        self._timeout = self._rand_timeout()
        req = VoteReq(
            term=self.storage.term + 1,
            candidate=self.node_id,
            last_log_index=self.storage.last_index(),
            last_log_term=self.storage.last_term(),
            pre=True,
        )
        for p in self.peers:
            self.transport.send(p, self.group, req)

    def _campaign(self) -> None:
        if not self.peers:  # single-node group: self-elect immediately
            self.storage.save_hardstate(self.storage.term + 1, self.node_id)
            self._become_leader()
            return
        self.state = CANDIDATE
        self.storage.save_hardstate(self.storage.term + 1, self.node_id)
        self.votes = {self.node_id}
        self._elapsed = 0
        self._timeout = self._rand_timeout()
        req = VoteReq(
            term=self.storage.term,
            candidate=self.node_id,
            last_log_index=self.storage.last_index(),
            last_log_term=self.storage.last_term(),
        )
        for p in self.peers:
            self.transport.send(p, self.group, req)

    def _become_leader(self) -> None:
        self.state = LEADER
        self.leader_id = self.node_id
        self._prevoting = False
        self._prevotes = set()
        self._transfer_target = None
        self._transfer_ticks = 0
        nxt = self.storage.last_index() + 1
        self.next_index = {p: nxt for p in self.peers}
        self.match_index = {p: 0 for p in self.peers}
        # commit a no-op to learn the commit point of prior terms (Raft §8)
        self._append_local(b"")
        self._broadcast_append()

    def _step_down(self, term: int, leader: Optional[str] = None) -> None:
        if term > self.storage.term:
            self.storage.save_hardstate(term, None)
        was_leader = self.state == LEADER
        self.state = FOLLOWER
        if leader is not None:
            self.leader_id = leader
            self._since_leader = 0  # heard from a live leader just now
        self._elapsed = 0
        self._timeout = self._rand_timeout()
        self._prevoting = False
        self._prevotes = set()
        # a transfer begun under an old leadership must not fire later
        self._transfer_target = None
        self._transfer_ticks = 0
        if was_leader:
            err = RuntimeError("leadership lost")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()

    # -- proposals ----------------------------------------------------------

    def _handle_propose(self, data: bytes, fut: Future) -> None:
        if self.state != LEADER:
            fut.set_exception(
                NotLeaderError(self.leader_id)
            )
            return
        # register the future BEFORE appending: with no peers the append
        # commits and resolves pending futures synchronously
        idx = self.storage.last_index() + 1
        self._pending[idx] = fut
        self._append_local(data)
        self._broadcast_append()

    def _append_local(self, data: bytes) -> int:
        idx = self.storage.last_index() + 1
        self.storage.append([Entry(self.storage.term, idx, data)])
        if not self.peers:
            self._advance_commit(idx)
        return idx

    # -- replication --------------------------------------------------------

    def _broadcast_append(self) -> None:
        for p in self.peers:
            self._send_append(p)

    def _send_append(self, peer: str) -> None:
        nxt = self.next_index.get(peer, self.storage.last_index() + 1)
        prev = nxt - 1
        prev_term = self.storage.term_at(prev)
        if prev_term is None:
            # follower is behind the snapshot horizon: ship the snapshot
            snap = self.storage.load_snapshot()
            if snap is None and self.snapshot_fn is not None:
                snap = self.snapshot_fn()
            if snap is None:
                snap = b""
            self.transport.send(
                peer,
                self.group,
                SnapshotReq(
                    term=self.storage.term,
                    leader=self.node_id,
                    last_index=self.storage.snap_index,
                    last_term=self.storage.snap_term,
                    data=snap,
                ),
            )
            return
        entries = self.storage.entries_from(nxt)
        self.transport.send(
            peer,
            self.group,
            AppendReq(
                term=self.storage.term,
                leader=self.node_id,
                prev_log_index=prev,
                prev_log_term=prev_term,
                entries=entries,
                leader_commit=self.commit_index,
            ),
        )

    # -- message handling ----------------------------------------------------

    def _handle(self, msg) -> None:
        if isinstance(msg, VoteReq):
            self._on_vote_req(msg)
        elif isinstance(msg, VoteResp):
            self._on_vote_resp(msg)
        elif isinstance(msg, AppendReq):
            self._on_append(msg)
        elif isinstance(msg, AppendResp):
            self._on_append_resp(msg)
        elif isinstance(msg, SnapshotReq):
            self._on_snapshot(msg)
        elif isinstance(msg, SnapshotResp):
            self._on_snapshot_resp(msg)
        elif isinstance(msg, TimeoutNow):
            self._on_timeout_now(msg)

    def _on_timeout_now(self, m: TimeoutNow) -> None:
        """Transfer target: campaign NOW, bypassing pre-vote and the
        election timer (we were chosen as most caught-up; the old leader
        is about to stop)."""
        if m.term < self.storage.term or self.state == LEADER:
            return
        if self.passive or not self.peers:
            # a joiner that has not learned the membership yet would
            # "win" a single-node election and split-brain — ignore
            return
        self._campaign()

    def _on_vote_req(self, m: VoteReq) -> None:
        if m.pre:
            # pre-vote: assess, mutate NOTHING persistent.  Reject while
            # this node believes a live leader exists (heard from it
            # within the minimum election timeout) — leader stickiness,
            # the property that makes rejoining nodes non-disruptive.
            up_to_date = (m.last_log_term, m.last_log_index) >= (
                self.storage.last_term(),
                self.storage.last_index(),
            )
            leader_alive = (
                self.state == LEADER
                or (
                    self.leader_id is not None
                    and self._since_leader < self.election_ticks
                )
            )
            grant = m.term >= self.storage.term and up_to_date and not leader_alive
            self.transport.send(
                m.candidate, self.group,
                VoteResp(self.storage.term, grant, self.node_id, pre=True),
            )
            return
        if m.term < self.storage.term:
            self.transport.send(
                m.candidate, self.group,
                VoteResp(self.storage.term, False, self.node_id),
            )
            return
        if m.term > self.storage.term:
            self._step_down(m.term)
        up_to_date = (m.last_log_term, m.last_log_index) >= (
            self.storage.last_term(),
            self.storage.last_index(),
        )
        grant = up_to_date and self.storage.voted_for in (None, m.candidate)
        if grant:
            self.storage.save_hardstate(self.storage.term, m.candidate)
            self._elapsed = 0
        self.transport.send(
            m.candidate, self.group, VoteResp(self.storage.term, grant, self.node_id)
        )

    def _on_vote_resp(self, m: VoteResp) -> None:
        if m.pre:
            if m.term > self.storage.term:
                # a rejection from a higher-term node: adopt the term so
                # a later REAL campaign is viable (without this, a stale
                # node with the freshest log can deadlock the election)
                self._step_down(m.term)
                return
            if (
                self._prevoting  # stale grants after the round closed
                # (e.g. a live leader re-acknowledged us) must not count
                and m.granted
                and self.state != LEADER
                and m.term <= self.storage.term + 1
            ):
                self._prevotes.add(m.sender)
                if len(self._prevotes) * 2 > len(self.peers) + 1:
                    self._prevotes = set()
                    self._prevoting = False
                    self._campaign()
            return
        if self.state != CANDIDATE or m.term != self.storage.term:
            if m.term > self.storage.term:
                self._step_down(m.term)
            return
        if m.granted:
            self.votes.add(m.sender)
            if len(self.votes) * 2 > len(self.peers) + 1:
                self._become_leader()

    def _on_append(self, m: AppendReq) -> None:
        if m.term < self.storage.term:
            self.transport.send(
                m.leader, self.group,
                AppendResp(self.storage.term, False, 0, self.node_id),
            )
            return
        self._step_down(m.term, leader=m.leader)
        prev_term = self.storage.term_at(m.prev_log_index)
        if prev_term is None or prev_term != m.prev_log_term:
            # prev missing (behind our snapshot / past our log): hint the
            # leader where to resume as next_index = snap_index + 1.  The
            # +1 bias keeps the hint truthy even for an EMPTY log
            # (snap_index 0) — a fresh runtime joiner otherwise degrades
            # to a one-entry-per-roundtrip backoff walk.  0 = no hint
            # (term-mismatch case).
            self.transport.send(
                m.leader, self.group,
                AppendResp(self.storage.term, False,
                           self.storage.snap_index + 1
                           if prev_term is None else 0, self.node_id),
            )
            return
        new = [e for e in m.entries if e.index > self.storage.last_index()
               or self.storage.term_at(e.index) != e.term]
        if new:
            self.storage.append(new)  # durably, before acking
        match = m.prev_log_index + len(m.entries)
        if m.leader_commit > self.commit_index:
            self._set_commit(min(m.leader_commit, self.storage.last_index()))
        self.transport.send(
            m.leader, self.group,
            AppendResp(self.storage.term, True, match, self.node_id),
        )

    def _on_append_resp(self, m: AppendResp) -> None:
        if m.term > self.storage.term:
            self._step_down(m.term)
            return
        if self.state != LEADER:
            return
        if m.success:
            self.match_index[m.sender] = max(
                self.match_index.get(m.sender, 0), m.match_index
            )
            self.next_index[m.sender] = self.match_index[m.sender] + 1
            self._maybe_commit()
            # pending leadership transfer: hand off the moment the chosen
            # target confirms our whole log
            if (
                self._transfer_target == m.sender
                and self.match_index[m.sender] >= self.storage.last_index()
            ):
                self._finish_transfer()
        else:
            # back off; a truthy hint is the follower's snap_index + 1
            # (jump straight there), 0 means plain log mismatch
            hint = m.match_index
            cur = self.next_index.get(m.sender, self.storage.last_index() + 1)
            self.next_index[m.sender] = max(1, hint if hint else cur - 1)
            self._send_append(m.sender)

    def _on_snapshot(self, m: SnapshotReq) -> None:
        if m.term < self.storage.term:
            return
        self._step_down(m.term, leader=m.leader)
        if m.last_index <= self.storage.snap_index:
            return
        self.storage.save_snapshot(m.last_index, m.last_term, m.data)
        if self.restore_fn is not None:
            self.restore_fn(m.data)
        self.commit_index = max(self.commit_index, m.last_index)
        self.last_applied = max(self.last_applied, m.last_index)
        self.transport.send(
            m.leader, self.group,
            SnapshotResp(self.storage.term, self.node_id, m.last_index),
        )

    def _on_snapshot_resp(self, m: SnapshotResp) -> None:
        if self.state != LEADER:
            return
        self.match_index[m.sender] = max(
            self.match_index.get(m.sender, 0), m.last_index
        )
        self.next_index[m.sender] = m.last_index + 1

    # -- commit / apply ------------------------------------------------------

    def _maybe_commit(self) -> None:
        for idx in range(self.storage.last_index(), self.commit_index, -1):
            votes = 1 + sum(1 for p in self.peers if self.match_index.get(p, 0) >= idx)
            if votes * 2 > len(self.peers) + 1 and self.storage.term_at(idx) == self.storage.term:
                self._set_commit(idx)
                break

    def _advance_commit(self, idx: int) -> None:
        if self.storage.term_at(idx) == self.storage.term:
            self._set_commit(idx)

    def _set_commit(self, idx: int) -> None:
        self.commit_index = idx
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            entry = self.storage.entry_at(self.last_applied)
            apply_err: Optional[Exception] = None
            if entry is not None and entry.data:
                try:
                    self.apply_fn(entry.index, entry.data)
                except Exception as e:  # noqa: BLE001 — a bad entry must not
                    # wedge the group: report to the proposer and keep
                    # advancing, as the reference resolves the proposal
                    # with the apply error (draft.go process→props.Done)
                    import traceback

                    traceback.print_exc()
                    apply_err = e
            fut = self._pending.pop(self.last_applied, None)
            if fut is not None and not fut.done():
                if apply_err is not None:
                    fut.set_exception(apply_err)
                else:
                    fut.set_result(self.last_applied)
        self._maybe_snapshot()

    def _maybe_snapshot(self, force: bool = False) -> None:
        if self.snapshot_fn is None:
            return
        behind = self.last_applied - self.storage.snap_index
        if behind <= 0 or (not force and behind < self.snapshot_threshold):
            return
        term = self.storage.term_at(self.last_applied)
        if term is None:
            return
        data = self.snapshot_fn()
        self.storage.save_snapshot(self.last_applied, term, data)


class NotLeaderError(Exception):
    """Proposal sent to a non-leader; carries the leader hint for
    client-side redirect (the reference forwards via AnyServer/Leader
    routing, worker/groups.go:323)."""

    def __init__(self, leader: Optional[str]):
        super().__init__(f"not the leader; try {leader!r}")
        self.leader = leader
