"""A replicated store group: Raft log → PostingStore replicas.

Equivalent of the reference's per-group stack (worker/draft.go
processMutation → runMutations → posting apply): mutations are encoded
as codec record batches, proposed through the group's Raft node, and
applied to every replica's store when committed.  The Raft log IS the
durability layer here (the reference similarly persists raft WAL +
posting store; our snapshot = the store state record-stream, so a
restarted or lagging replica restores from it and replays the log
suffix — retrieveSnapshot, draft.go:679).
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Dict, List, Optional

from dgraph_tpu.models import codec
from dgraph_tpu.models.store import Edge, PostingStore
from dgraph_tpu.models.wal import apply_record, iter_state_records
from dgraph_tpu.cluster.raft import RaftNode, RaftStorage, Transport

_HDR = struct.Struct("<II")


def encode_batch(records: List[bytes]) -> bytes:
    buf = bytearray()
    codec.put_uvarint(buf, len(records))
    for r in records:
        codec.put_uvarint(buf, len(r))
        buf.extend(r)
    return bytes(buf)


def decode_batch(data: bytes) -> List[bytes]:
    n, pos = codec.uvarint(data, 0)
    out = []
    for _ in range(n):
        ln, pos = codec.uvarint(data, pos)
        out.append(data[pos : pos + ln])
        pos += ln
    return out


def state_to_bytes(store: PostingStore) -> bytes:
    """Full store state as CRC-framed record stream (snapshot payload)."""
    buf = bytearray()
    for payload in iter_state_records(store):
        buf.extend(_HDR.pack(len(payload), zlib.crc32(payload)))
        buf.extend(payload)
    return bytes(buf)


def pred_to_bytes(store: PostingStore, pred: str) -> bytes:
    """One predicate's postings as a CRC-framed record stream — the
    payload of the cross-server read path (/pred-snapshot).  The analog of
    the reference's PredicateAndSchemaData shard stream
    (worker/predicate.go:71-201), scoped to one predicate."""
    pd = store.peek(pred)
    buf = bytearray()
    if pd is None:
        return bytes(buf)
    for src in sorted(pd.edges):
        for dst in sorted(pd.edges[src]):
            payload = codec.encode_edge(
                Edge(pred=pred, src=src, dst=dst,
                     facets=pd.edge_facets.get((src, dst)))
            )
            buf.extend(_HDR.pack(len(payload), zlib.crc32(payload)))
            buf.extend(payload)
    for (src, lang) in sorted(pd.values):
        payload = codec.encode_edge(
            Edge(pred=pred, src=src, value=pd.values[(src, lang)],
                 lang=lang, facets=pd.value_facets.get(src))
        )
        buf.extend(_HDR.pack(len(payload), zlib.crc32(payload)))
        buf.extend(payload)
    return bytes(buf)


def bytes_to_pred(data: bytes, pred: str):
    """Decode a pred_to_bytes stream into a standalone PredicateData."""
    tmp = PostingStore()
    pos = 0
    n = len(data)
    while pos + _HDR.size <= n:
        length, crc = _HDR.unpack_from(data, pos)
        start = pos + _HDR.size
        payload = data[start : start + length]
        if zlib.crc32(payload) != crc:
            raise ValueError("corrupt predicate snapshot payload")
        apply_record(tmp, payload)
        pos = start + length
    return tmp.peek(pred)


def bytes_to_state(data: bytes, store: PostingStore) -> None:
    """Replace store contents from a snapshot payload."""
    store._preds.clear()
    store.uids._xid_to_uid.clear()
    store.uids._next = 1
    store.members.clear()
    store.dirty.add("*")
    pos = 0
    n = len(data)
    while pos + _HDR.size <= n:
        length, crc = _HDR.unpack_from(data, pos)
        start = pos + _HDR.size
        payload = data[start : start + length]
        if zlib.crc32(payload) != crc:
            raise ValueError("corrupt snapshot payload")
        apply_record(store, payload)
        pos = start + length
    # full-store replacement: predicates absent from the snapshot kept
    # their old per-pred versions above — only an IVM floor bump makes
    # every footprint-keyed cache entry stale (ivm/versions.py)
    note = getattr(store, "note_global_change", None)
    if note is not None:
        note()


class ReplicatedGroup:
    """One server's replica of one group (draft.go node + its store)."""

    def __init__(
        self,
        node_id: str,
        group: int,
        peers: List[str],
        directory: str,
        transport: Transport,
        sync_writes: bool = False,
        **raft_opts,
    ):
        self.store = PostingStore()
        self.group = group
        # per-predicate change versions = the raft index of the last record
        # touching the predicate: durable-monotone across restarts and
        # identical on every replica (unlike a process-local counter, which
        # could repeat a value over different content after a restart and
        # make remote readers' 304 checks serve stale data forever)
        self.pred_versions: Dict[str, int] = {}
        self._lock = threading.Lock()  # guards store during apply/snapshot
        storage = RaftStorage(
            os.path.join(directory, f"raft-g{group}"), sync=sync_writes
        )
        self.node = RaftNode(
            node_id=node_id,
            group=group,
            peers=peers,
            storage=storage,
            transport=transport,
            apply_fn=self._apply_committed,
            snapshot_fn=self._snapshot_state,
            restore_fn=self._restore_state,
            **raft_opts,
        )

    def start(self) -> None:
        self.node.start()

    def stop(self) -> None:
        self.node.stop()

    def force_snapshot(self) -> None:
        """Compact this group's raft log now (/admin/snapshot's cluster
        leg): group replicas ride the same trigger machinery as the
        single-node store WAL's Snapshotter."""
        self.node.request_snapshot()

    # -- raft callbacks (loop thread) ---------------------------------------

    def _apply_committed(self, index: int, data: bytes) -> None:
        with self._lock:
            for payload in decode_batch(data):
                pred = apply_record(self.store, payload)
                if pred is not None:
                    self.pred_versions[pred] = index

    def _snapshot_state(self) -> bytes:
        with self._lock:
            return state_to_bytes(self.store)

    def _restore_state(self, data: bytes) -> None:
        if not data:
            return
        with self._lock:
            bytes_to_state(data, self.store)
            # every predicate in the snapshot is current as of its index
            snap_idx = self.node.storage.snap_index
            self.pred_versions = {
                p: snap_idx for p in self.store._preds.keys()
            }

    def pred_version(self, pred: str) -> int:
        """Caller holds _lock (or tolerates a racy read)."""
        return self.pred_versions.get(pred, 0)

    # -- public write path ---------------------------------------------------

    def propose_edges(
        self, edges: List[Edge], timeout: Optional[float] = None
    ) -> None:
        """MutateOverNetwork's per-group proposeOrSend (mutation.go:319)."""
        self.node.propose_and_wait(
            encode_batch([codec.encode_edge(e) for e in edges]), timeout
        )

    def propose_schema(self, text: str, timeout: Optional[float] = None) -> None:
        self.node.propose_and_wait(
            encode_batch([codec.encode_schema(text)]), timeout
        )

    def propose_records(
        self, records: List[bytes], timeout: Optional[float] = None
    ) -> None:
        self.node.propose_and_wait(encode_batch(records), timeout)
