"""Cluster service: the server binary's StartRaftNodes analog.

Wires the cluster layer into a serving process (reference
worker/groups.go:109 StartRaftNodes + dgraph/server.go storage bring-up):

- one ReplicatedGroup (Raft node + replica store) per group this server
  serves, talking to peers over HttpRaftTransport (POST /raft/<group>);
- group 0 is the metadata group (worker/groups.go:404): schema text, uid
  leases (LEASE records) and xid assignments (XID records) replicate
  through it;
- data predicates route to groups by GroupConfig (group/conf.go rules);
- `ClusterStore` — the store facade handed to the query engine: writes
  become Raft proposals to the owning group (MutateOverNetwork's
  proposeOrSend, worker/mutation.go:319 — non-leaders forward over HTTP
  to the leader); reads come from per-predicate SNAPSHOT copies of the
  local replica stores, refreshed when the replica applies new records,
  so queries never race the raft apply threads (the reference's
  immutable-layer read semantics).

Reads are local-replica reads: any server answers queries from its own
replicas (AnyServer read balancing, worker/groups.go:268) — writes are
linearizable through Raft, reads are eventually consistent, as in the
reference.
"""

from __future__ import annotations

import http.client
import threading
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from dgraph_tpu.models import codec
from dgraph_tpu.models.store import Edge, PostingStore, PredicateData
from dgraph_tpu.models.schema import SchemaState
from dgraph_tpu.cluster.groups import GroupConfig
from dgraph_tpu.cluster.lease import LeaseManager
from dgraph_tpu.cluster.peerclient import (
    PeerClient,
    StaleUnavailableError,
    resilience_enabled,
)
from dgraph_tpu.cluster.raft import NotLeaderError, propose_patience
from dgraph_tpu.cluster.replica import ReplicatedGroup, encode_batch
from dgraph_tpu.cluster.transport import (
    HttpRaftTransport,
    PeerAuth,
    decode_msg,
)

METADATA_GROUP = 0


def parse_peers(peer_spec: str, default_scheme: str = "http") -> Dict[str, str]:
    """"1@host:8080,2@host:8081" (or full http(s):// urls) → id→addr.
    Bare host:port entries take ``default_scheme`` — a TLS-enabled server
    must default its peers to https or raft frames hit TLS listeners as
    plaintext and are silently dropped."""
    out: Dict[str, str] = {}
    for part in peer_spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "@" not in part:
            raise ValueError(f"peer {part!r} must be id@host:port")
        nid, addr = part.split("@", 1)
        if not addr.startswith(("http://", "https://")):
            addr = f"{default_scheme}://" + addr
        out[nid.strip()] = addr
    return out


def parse_peer_groups(spec: str) -> Dict[str, List[int]]:
    """"1=0,1;2=0,2" → {peer-id: [group,...]}.  Empty spec = {} (every
    peer serves every group)."""
    out: Dict[str, List[int]] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        nid, _, gs = part.partition("=")
        if not gs:
            raise ValueError(f"peer_groups entry {part!r} must be id=g1,g2")
        out[nid.strip()] = [int(g) for g in gs.split(",") if g.strip()]
    return out


class ClusterService:
    """Owns this server's raft groups, transport, lease and store facade."""

    def __init__(
        self,
        node_id: str,
        my_addr: str,
        peers: Dict[str, str],          # id -> addr, INCLUDING self
        group_ids: List[int],
        directory: str,
        group_config: Optional[GroupConfig] = None,
        sync_writes: bool = False,
        secret: str = "",
        peer_ca: str = "",
        peer_tls_insecure: bool = False,
        peer_groups: Optional[Dict[str, List[int]]] = None,
        raft_transport: str = "http",
        grpc_port_offset: int = 1000,
        **raft_opts,
    ):
        if METADATA_GROUP not in group_ids:
            group_ids = [METADATA_GROUP] + list(group_ids)
        self.node_id = node_id
        self.peers = dict(peers)
        self.peers.setdefault(node_id, my_addr)
        data_groups = sorted(g for g in group_ids if g != METADATA_GROUP)
        if group_config is not None:
            self.conf = group_config
        elif data_groups:
            # contiguous data groups 1..N: fingerprint mod N + 1
            self.conf = GroupConfig.parse(f"default: fp % {len(data_groups)} + 1")
        else:
            self.conf = GroupConfig.single_group()
        self.auth = PeerAuth(secret=secret, cafile=peer_ca, insecure=peer_tls_insecure)
        # one PeerClient for every peer RPC this server issues — the
        # retry/backoff/breaker funnel (cluster/peerclient.py); the raft
        # transports share it so a peer that times out on the read plane
        # is ALSO known-bad to the raft sender loops (and vice versa)
        self.peerclient = PeerClient(auth=self.auth)
        others = {nid: a for nid, a in self.peers.items() if nid != node_id}
        if raft_transport == "grpc":
            # raft frames over the gRPC Worker plane (the reference's
            # native raft leg, draft.go:1017).  gRPC listeners sit at the
            # http port + offset (the CLI's --grpc_port convention); the
            # transport derives targets per message, so members learned
            # or re-addressed at runtime route correctly too.
            from dgraph_tpu.cluster.transport import GrpcRaftTransport

            self.transport = GrpcRaftTransport(
                others,
                secret=secret,
                port_offset=grpc_port_offset,
                auth=self.auth,
                peerclient=self.peerclient,
            )
        else:
            self.transport = HttpRaftTransport(
                others, auth=self.auth, peerclient=self.peerclient
            )
        # static placement (group/conf.go's server-side complement): which
        # groups each peer serves.  None/missing peer = serves everything
        # (full replication, the pre-placement behavior).  The metadata
        # group always spans every server.  MEMBER records refine this at
        # runtime (groups.go syncMemberships analog).
        self.peer_groups: Dict[str, Tuple[int, ...]] = {
            nid: tuple(sorted(set(gs) | {METADATA_GROUP}))
            for nid, gs in (peer_groups or {}).items()
        }
        self.peer_groups[node_id] = tuple(sorted(group_ids))
        peer_ids = sorted(self.peers)

        def raft_peers(g: int) -> List[str]:
            # a group's raft cluster spans only the servers that SERVE it;
            # peers with unknown placement are assumed to serve everything
            return [
                nid
                for nid in peer_ids
                if g == METADATA_GROUP
                or nid not in self.peer_groups
                or g in self.peer_groups[nid]
            ]

        self.groups: Dict[int, ReplicatedGroup] = {
            g: ReplicatedGroup(
                node_id=node_id, group=g, peers=raft_peers(g), directory=directory,
                transport=self.transport, sync_writes=sync_writes, **raft_opts,
            )
            for g in group_ids
        }
        self.lease = LeaseManager(self._propose_lease)
        self._stopped = False
        self.store = ClusterStore(self)
        # runtime membership: MEMBER records applied on the metadata
        # replica rewire this server live (groups.go applyMembershipUpdate)
        self.groups[METADATA_GROUP].store.member_hook = self._on_member_applied

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        for g in self.groups.values():
            g.start()
        # resume the lease above everything the metadata replica has seen
        meta = self.groups[METADATA_GROUP].store
        self.lease.init_from_recovery(meta.uids.max_uid + 1)
        # announce our own placement through the metadata group so every
        # server learns group→server routing (syncMemberships,
        # worker/groups.go:404 — periodic there, once-with-retry here
        # since membership is static between joins)
        threading.Thread(
            target=self._announce_self, name="announce", daemon=True
        ).start()

    def _announce_self(self) -> None:
        import sys
        import time

        rec = codec.encode_member(
            self.node_id, self.peers[self.node_id], sorted(self.groups)
        )
        attempt = 0
        delay = 0.2
        while not self._stopped:
            try:
                self.propose_records(METADATA_GROUP, [rec])
                return
            except Exception as e:  # noqa: BLE001 — keep trying: peers
                # route reads/writes by this announcement; giving up
                # silently would leave our groups unreachable forever
                attempt += 1
                if attempt == 25:
                    print(
                        f"# server {self.node_id}: membership announcement "
                        f"still failing after {attempt} attempts "
                        f"({type(e).__name__}: {e}); retrying",
                        file=sys.stderr,
                    )
                time.sleep(delay)
                delay = min(delay * 1.5, 2.0)

    def stop(self) -> None:
        self._stopped = True
        for g in self.groups.values():
            g.stop()
        self.transport.stop()

    def has_leader(self) -> bool:
        return all(g.node.leader_id is not None for g in self.groups.values())

    def health_summary(self) -> dict:
        """Peer/breaker/raft-leader state for the /health endpoint."""
        return {
            "node": self.node_id,
            "peers": self.peerclient.snapshot(),
            "raft": {
                str(gid): {
                    "leader": g.node.leader_id,
                    "is_leader": g.node.is_leader,
                    "snap_index": g.node.storage.snap_index,
                    "last_applied": g.node.last_applied,
                }
                for gid, g in sorted(self.groups.items())
            },
            "degraded": self.store.degraded_info(),
        }

    def snapshot_all(self) -> None:
        """Force raft-log compaction on every group this server serves
        (/admin/snapshot — the cluster twin of DurableStore.snapshot)."""
        for g in self.groups.values():
            g.force_snapshot()

    # -- runtime membership (JoinCluster, draft.go:1049 / groups.go:600) ----

    def _wait_local_apply(self, cond: Callable[[], bool], timeout: float = 5.0) -> bool:
        """Poll until a forwarded proposal becomes visible on the LOCAL
        replica (shared by xid assignment, schema apply and join)."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return True
            time.sleep(0.005)
        return False

    def _on_member_applied(self, nid: str, addr: str, groups=()) -> None:
        """Called (from a raft apply thread) when a MEMBER record lands on
        the metadata replica: rewire transport + the member's groups'
        peer sets.  Idempotent; safe on replay and snapshot restore.
        Dict updates are atomic reference swaps — HTTP handler threads
        iterate self.peers/addr_of concurrently."""
        if nid != self.node_id:
            self.peers = {**self.peers, nid: addr}
            # transport-agnostic rewiring: the gRPC transport derives its
            # target from the http address itself (update_peer validates).
            # Validation failures must NOT raise: this runs on the raft
            # apply thread, and aborting would leave the committed batch
            # partially applied on this replica — skip the rewiring (the
            # peer stays unreachable, which is true) and log instead.
            try:
                self.transport.update_peer(nid, addr)
            except ValueError as e:
                import sys as _sys

                print(
                    f"warning: cannot route raft frames to member {nid} "
                    f"at {addr!r}: {e}",
                    file=_sys.stderr,
                )
        member_groups = set(groups) if groups else None
        if member_groups is not None:
            self.peer_groups = {
                **self.peer_groups,
                nid: tuple(sorted(member_groups | {METADATA_GROUP})),
            }
        for gid, g in self.groups.items():
            # empty group list = legacy record = member serves every group;
            # the metadata group always includes every member
            if member_groups is None or gid in member_groups or gid == METADATA_GROUP:
                g.node.add_peer(nid)
            else:
                # the record authoritatively says this member does NOT
                # serve gid: drop it from the voter set so it can never
                # depress the group's quorum
                g.node.remove_peer(nid)

    def servers_of_group(self, gid: int) -> List[Tuple[str, str]]:
        """(node_id, addr) of every server EXPLICITLY placing group
        ``gid``, self excluded — the remote-read / remote-propose
        candidate list.  Peers with unknown placement are NOT counted:
        in legacy full-replication clusters every server already holds
        every group locally, so routing to an undeclared peer could only
        hit a server that errors 'group not served here'."""
        out = []
        for nid, addr in sorted(self.peers.items()):
            if nid == self.node_id:
                continue
            gs = self.peer_groups.get(nid)
            if gs is not None and gid in gs:
                out.append((nid, addr))
        return out

    def handle_join(self, nid: str, addr: str, groups=()) -> Dict[str, str]:
        """Server side of a join request: replicate the new member
        through the metadata group and hand back the full peer map so the
        joiner can configure itself.  propose_records returning means the
        membership IS committed (leader applied it); a lagging LOCAL
        apply only delays this server's own view, so it must not fail
        the join — the joiner would be a committed member with no
        removal path."""
        # propose the FULL membership (idempotent): the metadata log then
        # carries every member, so a joiner's restart — whose static
        # config lists only itself — replays the complete peer map
        records = [
            codec.encode_member(n, a, sorted(self.groups))
            for n, a in sorted(self.peers.items())
        ]
        records.append(codec.encode_member(nid, addr, sorted(groups)))
        self.propose_records(METADATA_GROUP, records)
        meta = self.groups[METADATA_GROUP].store
        self._wait_local_apply(lambda: nid in meta.members)
        peers = dict(self.peers)
        peers[nid] = addr
        return peers

    def join_cluster(self, seed_addr: str, timeout: float = 15.0) -> None:
        """Joiner side: announce ourselves to a live cluster via any
        server, then adopt the returned peer map.  The metadata leader's
        raft nodes start replicating to us the moment our MEMBER record
        applies on them; our passive nodes catch up via snapshot+log."""
        import json as _json

        req = urllib.request.Request(
            seed_addr.rstrip("/") + "/join",
            data=_json.dumps(
                {
                    "id": self.node_id,
                    "addr": self.peers[self.node_id],
                    "groups": sorted(self.groups),
                }
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        # key the breaker/metrics by the seed's node id when we know it
        # (static peer lists) so /health and dgraph_peer_rpc_total keep
        # one namespace per peer; a runtime joiner booted with only
        # itself has nothing better than the address yet
        seed_key = next(
            (
                nid
                for nid, a in self.peers.items()
                if a.rstrip("/") == seed_addr.rstrip("/")
            ),
            seed_addr,
        )
        # slice_budget=False, like forward: the seed server legitimately
        # blocks while the MEMBER record commits + applies
        # (_wait_local_apply), so a budget slice times out a join that
        # was about to succeed on a loaded host — the first attempt owns
        # the window, the retry covers only fast transport failures
        with self.peerclient.urlopen(
            seed_key, req, op="join", budget=timeout, attempts=2,
            slice_budget=False,
        ) as resp:
            got = _json.loads(resp.read())
        for nid, addr in got["peers"].items():
            self._on_member_applied(nid, addr)

    # -- raft plane (server endpoints call these) ---------------------------

    def deliver(self, group: int, body: bytes) -> None:
        g = self.groups.get(group)
        if g is not None:
            g.node.deliver(decode_msg(body))

    def propose_local(
        self, group: int, batch: bytes, timeout: Optional[float] = None
    ) -> None:
        """Propose on THIS server; raises NotLeaderError for the forwarder."""
        self.groups[group].node.propose_and_wait(batch, propose_patience(timeout))

    def propose_records(
        self, group: int, records: List[bytes], timeout: Optional[float] = None
    ) -> None:
        """Propose, forwarding to the leader over HTTP when we're not it
        (proposeOrSend: local → ProposeAndWait, remote → RPC).  A group
        this server does not place routes straight to that group's
        servers (MutateOverNetwork's remote grpc Mutate leg)."""
        timeout = propose_patience(timeout)
        batch = encode_batch(records)
        if group not in self.groups:
            return self._propose_remote_group(group, batch, timeout)
        self._route_to_leader(
            lambda: self.propose_local(group, batch, timeout),
            lambda peer: self._forward(peer, group, batch, timeout),
        )

    def _propose_remote_group(self, group: int, batch: bytes, timeout: float):
        members = self.servers_of_group(group)
        if not members:
            raise NotLeaderError(None)
        tried: set = set()
        target = members[0][0]
        for _ in range(2 * len(members) + 2):
            if target is None or target in tried:
                target = next(
                    (nid for nid, _a in members if nid not in tried), None
                )
                if target is None:
                    break
            _res, hint, ok = self._forward(target, group, batch, timeout)
            if ok:
                return
            tried.add(target)
            target = hint
        raise NotLeaderError(None)

    def _route_to_leader(
        self,
        local_fn: Callable[[], object],
        forward_fn: Callable[[str], Tuple[object, Optional[str], bool]],
    ):
        """The shared leader-chasing loop: try locally, follow leader
        hints, fall back to untried peers; bounded attempts.

        ``forward_fn(peer) -> (result, leader_hint, ok)``."""
        tried: set = set()
        target: Optional[str] = None  # None = local
        for _ in range(4):
            if target is None or target == self.node_id:
                try:
                    return local_fn()
                except NotLeaderError as e:
                    tried.add(self.node_id)
                    target = e.leader or self._next_untried(tried)
            else:
                result, hint, ok = forward_fn(target)
                if ok:
                    return result
                tried.add(target)
                target = hint or self._next_untried(tried)
            if target is None:
                break
        raise NotLeaderError(None)

    def _next_untried(self, tried: set) -> Optional[str]:
        for nid in sorted(self.peers):
            if nid not in tried:
                return nid
        return None

    def _forward(self, peer: str, group: int, batch: bytes, timeout: float):
        url = f"{self.peers[peer]}/raft-propose/{group}"
        req = urllib.request.Request(
            url, data=batch, headers={"Content-Type": "application/octet-stream"}
        )
        try:
            # budget = the proposal timeout (the old blanket `timeout+2`
            # survives only as the RESILIENCE=0 single-shot timeout);
            # transport failures retry with backoff inside the budget,
            # 409 leader hints come back instantly as HTTPError.
            # slice_budget=False: a forwarded proposal legitimately
            # BLOCKS while the leader commits+applies, so the FIRST
            # attempt must own the whole window — a half-window slice
            # times out work about to succeed and re-POSTs a duplicate
            # batch at the slow leader (the amplification loop the
            # propose_patience docstring describes); the retry only
            # fires on fast transport failures that leave the budget
            # intact
            with self.peerclient.urlopen(
                peer, req, op="forward",
                budget=timeout, attempts=2, off_timeout=timeout + 2,
                slice_budget=False,
            ) as resp:
                resp.read()
                return None, None, True
        except urllib.error.HTTPError as e:
            if e.code == 409:  # not the leader; body is the hint (or empty)
                hint = e.read().decode("utf-8").strip()
                return None, (hint or None), False
            return None, None, False
        except OSError:
            return None, None, False

    def _propose_lease(self, new_max: int) -> None:
        self.propose_records(METADATA_GROUP, [codec.encode_lease(new_max)])

    # -- cross-server reads (ServeTask analog, worker/task.go:54-68) --------

    def _iter_replicas(self, gid: int, op: str, timeout: float):
        """Shared cross-server-read replica walk: yields
        ``(nid, addr, per_replica_budget)`` for ``gid``'s servers,
        healthiest replica first (AnyServer read balancing, breaker-
        aware: the replica that just timed out sorts last, and its open
        breaker sheds in microseconds rather than re-stalling).  The
        overall ``timeout`` budget is split over the replicas still
        untried — a cold-breaker blackholed first replica must not
        starve a healthy second one of its chance (the last replica
        keeps everything that is left) — and iteration stops once the
        budget is spent (legacy one-shot semantics keep going when
        resilience is off)."""
        import time as _time

        deadline = _time.monotonic() + timeout
        members = self.peerclient.order_by_health(
            self.servers_of_group(gid), op=op
        )
        for i, (nid, addr) in enumerate(members):
            remaining = deadline - _time.monotonic()
            if remaining <= 0 and resilience_enabled():
                break  # the fetch's OVERALL budget is spent
            yield nid, addr, remaining / (len(members) - i)

    def fetch_pred_snapshot(
        self, pred: str, gid: int, since: int, timeout: float = 10.0
    ):
        """Pull a predicate snapshot from a server of its owning group.

        Returns (version, payload-bytes) — payload None when the remote
        copy is unchanged since ``since``.  Data ships to the query (the
        inversion of the reference's per-task fan-out): the reader caches
        the predicate and builds device arenas from it locally, so one
        transfer serves every subsequent query until the owner mutates.
        Raises OSError when no owning server is reachable."""
        from urllib.parse import quote

        last_err: Optional[Exception] = None
        for nid, addr, per_replica in self._iter_replicas(
            gid, "snapshot", timeout
        ):
            url = (
                f"{addr}/pred-snapshot?name="
                + quote(pred, safe="")
                + f"&since={since}"
            )
            req = urllib.request.Request(url)
            try:
                with self.peerclient.urlopen(
                    nid, req, op="snapshot",
                    budget=per_replica, off_timeout=timeout,
                ) as resp:
                    ver = int(resp.headers.get("X-Pred-Version", "0"))
                    if resp.status == 204:
                        return ver, None
                    return ver, resp.read()
            except urllib.error.HTTPError as e:
                if e.code == 304:
                    return since, None
                last_err = e
            except OSError as e:
                last_err = e
            except http.client.HTTPException as e:
                # an owner killed MID-RESPONSE truncates the body:
                # resp.read() raises IncompleteRead — an HTTPException,
                # not an OSError — after the peerclient attempt already
                # counted success.  Same remedy as a transport error:
                # try the next replica (legacy one-shot semantics keep
                # the pre-PR immediate propagation)
                if not resilience_enabled():
                    raise
                last_err = e
        raise last_err or OSError(f"no server for group {gid}")

    def fetch_predlist(self, gid: int, timeout: float = 5.0) -> Optional[List[str]]:
        """Predicate names a remote group currently stores; None when no
        owning server is reachable (distinct from a legitimately empty
        group, so stale caches converge after deletes)."""
        import json as _json

        for nid, addr, per_replica in self._iter_replicas(
            gid, "predlist", timeout
        ):
            req = urllib.request.Request(f"{addr}/predlist?group={gid}")
            try:
                with self.peerclient.urlopen(
                    nid, req, op="predlist",
                    budget=per_replica, off_timeout=timeout,
                ) as resp:
                    return list(_json.loads(resp.read()))
            except (urllib.error.HTTPError, OSError):
                continue
        return None

    # -- uid assignment (leader-only, worker/assign.go:59) ------------------

    def assign_local(self, n: int):
        """Assign n uids on THIS server; only the metadata leader may
        (assignUids asserts leadership, worker/assign.go:37)."""
        node = self.groups[METADATA_GROUP].node
        if not node.is_leader:
            raise NotLeaderError(node.leader_id)
        # a freshly-elected leader resumes above every lease any previous
        # leader durably recorded (resetLease on leader change, lease.go:57)
        meta_next = self.groups[METADATA_GROUP].store.uids.max_uid + 1
        if self.lease._leased < meta_next:
            self.lease.init_from_recovery(meta_next)
        return self.lease.assign(n)

    def assign_uids(self, n: int):
        """Route assignment to the metadata leader (AssignUidsOverNetwork)."""
        return self._route_to_leader(
            lambda: self.assign_local(n),
            lambda peer: self._forward_assign(peer, n),
        )

    def reserve_local(self, uid: int) -> Tuple[int, int]:
        """Leader-side explicit-uid reservation: the LEADER's allocation
        cursor must skip uids named explicitly in mutations, even inside
        the already-leased window — a follower-local note would let the
        leader hand the same uid to a blank node later."""
        node = self.groups[METADATA_GROUP].node
        if not node.is_leader:
            raise NotLeaderError(node.leader_id)
        meta_next = self.groups[METADATA_GROUP].store.uids.max_uid + 1
        if self.lease._leased < meta_next:
            self.lease.init_from_recovery(meta_next)
        self.lease.reserve_through(uid)
        return (uid, uid)

    def reserve_uid(self, uid: int) -> None:
        self._route_to_leader(
            lambda: self.reserve_local(uid),
            lambda peer: self._forward_assign(peer, -uid),  # negative = reserve
        )

    def _forward_assign(self, peer: str, n: int):
        url = f"{self.peers[peer]}/assign-uids"
        req = urllib.request.Request(url, data=str(n).encode())
        try:
            with self.peerclient.urlopen(peer, req, op="assign", budget=10) as resp:
                import json

                got = json.loads(resp.read())
                return (int(got["start"]), int(got["end"])), None, True
        except urllib.error.HTTPError as e:
            if e.code == 409:
                hint = e.read().decode("utf-8").strip()
                return None, (hint or None), False
            return None, None, False
        except OSError:
            return None, None, False


class _ClusterUids:
    """uid allocation facade: fresh uids via the replicated lease, xids via
    XID records on the metadata group (worker/assign.go semantics)."""

    def __init__(self, svc: ClusterService):
        self._svc = svc

    @property
    def _meta(self):
        return self._svc.groups[METADATA_GROUP].store.uids

    @property
    def max_uid(self) -> int:
        return max(self._svc.lease.max_assigned, self._meta.max_uid)

    def __len__(self) -> int:
        return len(self._meta)

    def fresh(self, n: int = 1) -> List[int]:
        start, end = self._svc.assign_uids(n)
        return list(range(start, end + 1))

    def assign(self, xid: str) -> int:
        existing = self._meta.lookup(xid)
        if existing is not None:
            return existing
        uid = self.fresh(1)[0]
        self._svc.propose_records(METADATA_GROUP, [codec.encode_xid(xid, uid)])
        # the applied map is authoritative (first XID record in log order
        # wins on every replica); on a follower the local apply can lag the
        # leader's commit, so wait for our record to land
        self._svc._wait_local_apply(lambda: self._meta.lookup(xid) is not None)
        got = self._meta.lookup(xid)
        return got if got is not None else uid

    def lookup(self, xid: str) -> Optional[int]:
        return self._meta.lookup(xid)

    def assign_many(self, xids) -> List[int]:
        return [self.assign(x) for x in xids]

    def reserve_through(self, uid: int) -> None:
        """Explicit uids route to the metadata LEADER's allocator (like
        fresh assignment): only its cursor decides future uids, so a
        follower-local note would not prevent aliasing.  Lease extensions
        batch by min_lease (minLeaseNum, lease.go:88-98)."""
        self._svc.reserve_uid(uid)

    def snapshot(self) -> Dict[str, int]:
        return self._meta.snapshot()


class _PredVersionClock:
    """Per-predicate cache versions for ClusterStore (duck-typed to the
    ``pred_versions`` mapping surface ivm/versions.py and the arena
    manager probe: ``.get(pred, default)`` + ``len()``).

    The obvious implementation — hand back the owning replica's raft
    index — is WRONG across groups: version_for takes a max over the
    footprint, and raft indices from different groups share no scale.
    A footprint {p@groupA, q@groupB} with B's log at index 900 would
    keep version_for pinned at 900 while p bumps 5→6 on A — the bump
    is masked and the stale cache entry keeps serving.  So this clock
    issues CLUSTER-LOCAL monotone ticks: each predicate's tick advances
    exactly when its source version — the owning local replica's
    ``pred_version`` (raft index, scoped to one group) or the remote
    snapshot cache's X-Pred-Version — is observed to change.  Ticks
    from different groups then compose under max() like PostingStore's
    single-scale versions do.

    Process-local by design: the caches these versions key (hop/result
    tiers, arena identity) are process-local too, so a restart starting
    the ticks over matches the caches starting over."""

    def __init__(self, store: "ClusterStore"):
        self._store = store
        self._tick = 0
        self._seen: Dict[str, Tuple[tuple, int]] = {}  # pred -> (src, tick)
        self._floors: Dict[int, int] = {}  # gid -> last-seen group floor
        self._floor_tick = 0
        self._lock = threading.Lock()

    def _source(self, pred: str) -> Optional[tuple]:
        """The pred's current content-version coordinate, or None when
        it has no stable source yet (owner unannounced, or a remote
        pred never fetched).  Never called under self._lock — the
        remote-cache read takes _remote_lock."""
        svc = self._store._svc
        try:
            gid = self._store._owner_gid(pred)
        except OSError:
            return None
        g = svc.groups.get(gid)
        if g is not None:
            # racy read is fine per pred_version's contract: a torn
            # observation at worst issues one extra tick (a cache miss)
            return ("raft", gid, g.pred_version(pred))
        with self._store._remote_lock:
            ent = self._store._remote.get(pred)
        if ent is None:
            return None
        return ("remote", gid, ent[0])

    def get(self, pred: str, default: int = 0) -> int:
        src = self._source(pred)
        with self._lock:
            if src is None:
                # unknown freshness must never look fresh: a new tick
                # per probe keys the entry but can never match it again
                self._tick += 1
                return self._tick
            ent = self._seen.get(pred)
            if ent is not None and ent[0] == src:
                return ent[1]
            self._tick += 1
            self._seen[pred] = (src, self._tick)
            return self._tick

    def floor(self) -> int:
        """The non-scopeable-change floor: advances when any local
        group replica's store floor moves (schema apply, raft snapshot
        restore — bytes_to_state's note_global_change)."""
        svc = self._store._svc
        with self._lock:
            for gid, g in svc.groups.items():
                f = getattr(g.store, "pred_floor", 0)
                prev = self._floors.get(gid)
                if prev is None:
                    self._floors[gid] = f  # first sight: adopt silently
                elif prev != f:
                    self._floors[gid] = f
                    self._tick += 1
                    self._floor_tick = self._tick
            return self._floor_tick

    def __len__(self) -> int:
        with self._lock:
            return len(self._seen)


class ClusterStore:
    """The engine-facing store: replicated writes, snapshot-stable reads.

    Implements PostingStore's read/write surface (duck-typed — the engine
    and serving layer never isinstance-check)."""

    # per-predicate versions exist for CACHE KEYING only: there is no
    # local mutation journal to stream deltas from, so the serving
    # layer must not attach an IVM delta stream or subscriptions here
    # (serve/server.py gates on this)
    supports_ivm_stream = False

    def __init__(self, svc: ClusterService, remote_ttl: float = 0.1):
        self._svc = svc
        self.uids = _ClusterUids(svc)
        self._dirty: set = set()
        self._snaps: Dict[str, PredicateData] = {}
        self._snap_lock = threading.Lock()
        # cross-server read cache: pred -> [version, PredicateData|None,
        # last-freshness-check monotonic time].  Freshness is checked at
        # most every remote_ttl seconds (bounded staleness, matching the
        # reference's eventually-consistent AnyServer reads).  Guarded by
        # its OWN lock: remote fetches block on the network and must never
        # stall local reads holding _snap_lock.
        self._remote: Dict[str, list] = {}
        self._predlists: Dict[int, list] = {}
        # stale-serving bookkeeping: pred -> [gid, last_success_monotonic,
        # last_stale_serve_monotonic] while the owner is unreachable and
        # the cached copy is being served.  Entries clear on the next
        # successful refresh of the predicate, or expire from
        # degraded_info() once no stale read has been SERVED recently —
        # a pred that is never queried again must not flag the node
        # degraded forever after the owner heals.  Guarded by
        # _remote_lock like the caches it shadows.
        self._degraded: Dict[str, list] = {}
        self._remote_lock = threading.Lock()  # guards the cache DICTS only
        # per-key fetch locks: one unreachable owner must stall only its
        # own key, not the whole cross-server read plane.  Keys are either
        # a predicate name (_remote_peek) or ("__predlist__", gid)
        # (predicates) — tuples can never collide with predicate strings.
        self._fetch_locks: Dict[object, threading.Lock] = {}
        self.remote_ttl = remote_ttl
        # per-predicate cache versions (PR 17): hop/arena caches key on
        # the touched predicate's tick instead of the global sum, so a
        # write to one group no longer invalidates every other group's
        # cached expansions (ivm/versions.py version_for)
        self.pred_versions = _PredVersionClock(self)

    @property
    def dirty(self) -> set:
        """Drains the replicas' dirty marks on every read so consumers that
        watch ``store.dirty`` directly (ArenaManager.refresh) see replica
        applies without a peek() having run first."""
        with self._snap_lock:
            self._drain_dirty()
            return self._dirty

    # remote TTL-cached predicates refresh WITHOUT a version bump, and
    # those refreshes only fire during query execution — a tier-2 result
    # cache hit (which skips execution) would therefore starve the
    # freshness probe and serve the stale copy forever.  Declaring the
    # version non-strict keeps tier 2 off for clustered reads; tier 1
    # stays on (arena identity keys it, and a remote refresh marks the
    # predicate dirty → the arena rebuilds under a new identity).
    strict_snapshot_versions = False

    @property
    def version(self) -> int:
        """Snapshot version for the cohort scheduler's admission
        signature (PostingStore.version analog): local replica applies
        bump it.  Remote TTL-cached predicates refresh without a bump,
        but their staleness window (remote_ttl) dwarfs a cohort's queue
        time anyway — the signature only needs to split cohorts across
        LOCAL mutation boundaries."""
        return sum(
            getattr(g.store, "version", 0)
            for g in self._svc.groups.values()
        )

    @property
    def pred_floor(self) -> int:
        """The version_for floor (non-scopeable changes) on the
        cluster clock's scale — see _PredVersionClock.floor."""
        return self.pred_versions.floor()

    # -- schema (metadata group) -------------------------------------------

    @property
    def schema(self) -> SchemaState:
        return self._svc.groups[METADATA_GROUP].store.schema

    def apply_schema(self, text: str) -> None:
        from dgraph_tpu.models.schema import parse_schema

        want = parse_schema(text, into=SchemaState())  # validate first
        self._svc.propose_records(METADATA_GROUP, [codec.encode_schema(text)])
        # On a follower the proposal is forwarded to the leader and the
        # LOCAL apply can lag its commit; a set block in the same request
        # would then convert values against the stale schema, durably
        # storing wrong-typed values.  Wait until every proposed predicate
        # is visible locally (later schema records for the same predicate
        # in log order simply overwrite, so observing ours is sufficient).
        ok = self._svc._wait_local_apply(
            lambda: all(
                self.schema._preds.get(p.name) == p
                for p in want._preds.values()
            )
        )
        if not ok:
            # the proposal IS durably committed at this point — only the
            # local apply is lagging.  Say so precisely: retrying the whole
            # request is safe (same-text schema records are idempotent
            # overwrites), but the client must know the schema itself did
            # not fail.
            raise TimeoutError(
                "schema change committed but not yet applied on this replica "
                "after 5s; retry the request (idempotent) or query another server"
            )

    # -- reads (snapshot copies of local replicas) --------------------------

    def _owner_gid(self, pred: str) -> int:
        """The group that PLACES this predicate.  Local groups and groups
        some peer serves route truthfully.  A group nobody is KNOWN to
        place: in a placement-aware cluster that's a transient state
        (owners announce via MEMBER records) and must fail loudly — a
        metadata-group fallback would durably commit writes where future
        reads will never look.  Only legacy full-replication clusters
        (no placement info beyond ourselves) keep the old fallback."""
        gid = self._svc.conf.belongs_to(pred)
        if gid in self._svc.groups or self._svc.servers_of_group(gid):
            return gid
        if len(self._svc.peer_groups) > 1:
            raise OSError(
                f"group {gid} has no known server yet (owner not announced); "
                "retry shortly"
            )
        return METADATA_GROUP

    def _remote_peek(self, pred: str, gid: int) -> Optional[PredicateData]:
        """Read a predicate another group owns: versioned snapshot pull
        with a TTL-gated freshness probe.  Serves the cached copy when the
        owner is unreachable (stale reads beat unavailability for the
        read plane; writes still require the owner's quorum), recording
        the degradation so responses carry a ``degraded`` annotation.  A
        reader with NO cached copy raises StaleUnavailableError — the
        serving layer maps it to 503 + Retry-After / gRPC UNAVAILABLE
        instead of a raw 500.  Holds only _remote_lock — the network
        fetch must never stall local reads."""
        import time as _time

        from dgraph_tpu.cluster.replica import bytes_to_pred
        from dgraph_tpu.utils.failpoints import fail
        from dgraph_tpu.utils.metrics import DEGRADED_READS

        with self._remote_lock:
            ent = self._remote.get(pred)
            now = _time.monotonic()
            if ent is not None and now - ent[2] < self.remote_ttl:
                d = self._degraded.get(pred)
                if d is not None:
                    d[2] = now  # this response still serves the stale copy
                return ent[1]
            flock = self._fetch_locks.setdefault(pred, threading.Lock())
        with flock:  # only THIS predicate's readers wait on the network
            with self._remote_lock:
                ent = self._remote.get(pred)
                now = _time.monotonic()
                if ent is not None and now - ent[2] < self.remote_ttl:
                    d = self._degraded.get(pred)
                    if d is not None:
                        d[2] = now
                    return ent[1]  # refreshed while we waited for the lock
            since = ent[0] if ent is not None else -1
            try:
                ver, payload = self._svc.fetch_pred_snapshot(pred, gid, since)
                # a payload that FAILS TO DECODE degrades the same way an
                # unreachable owner does: the cached copy outranks an
                # error (ValueError/IndexError = corrupt frame,
                # http.client.IncompleteRead = owner died mid-response)
                fail.point("service.snapshot_decode")
                pd = ent[1] if payload is None else bytes_to_pred(payload, pred)
            except (
                OSError,
                ValueError,
                IndexError,
                http.client.HTTPException,
            ) as e:
                if not resilience_enabled() and not isinstance(e, OSError):
                    # legacy escape hatch is byte-identical to pre-PR:
                    # only the TRANSPORT class (OSError) fell back to the
                    # cached copy; a corrupt/truncated frame propagated.
                    # Serving stale here would mask corruption with both
                    # the annotation and the counter gated off.
                    raise
                if ent is None:
                    if resilience_enabled():
                        raise StaleUnavailableError(
                            f"predicate {pred!r}: owner group {gid} "
                            "unreachable and no cached snapshot to "
                            "degrade to",
                            retry_after=self._svc.peerclient.breaker_cooldown,
                        ) from e
                    raise
                with self._remote_lock:
                    now = _time.monotonic()
                    ent[2] = now  # unreachable: serve stale
                    if resilience_enabled():
                        self._degraded[pred] = [gid, ent[3], now]
                if resilience_enabled():
                    DEGRADED_READS.add(1)
                return ent[1]
            changed = ent is not None and payload is not None
            now = _time.monotonic()
            with self._remote_lock:
                self._remote[pred] = [ver, pd, now, now]
                self._degraded.pop(pred, None)
        if changed:
            with self._snap_lock:
                self._dirty.add(pred)  # arenas rebuild from the fresh copy
        return pd

    def degraded_info(self, preds=None) -> Optional[dict]:
        """The response annotation for stale-served reads: which owner
        groups are being served from cache, and how old the OLDEST such
        cache is (seconds since its last successful refresh).  None when
        nothing is degraded (the overwhelmingly common case).  An entry
        whose predicate hasn't actually SERVED a stale read recently is
        expired — stale serves stopped (owner healed, or nobody reads
        the pred anymore), so the node must stop advertising an outage.

        ``preds`` (gql.ast.referenced_preds, a set — or a zero-arg
        callable producing one, evaluated only once something IS
        degraded so the healthy path never pays the AST walk) scopes the
        answer to the predicates one query can read, so a query that
        never touches a stale group is not falsely branded degraded;
        None = node-wide view (the /health surface)."""
        if not resilience_enabled():
            return None
        import time as _time

        with self._remote_lock:
            if not self._degraded:
                return None
        if callable(preds):
            # the AST walk runs OUTSIDE _remote_lock: during an outage —
            # exactly when _degraded is non-empty and every response
            # lands here — holding the lock through it would serialize
            # the read plane's TTL fast path behind per-query AST walks
            preds = preds()
        with self._remote_lock:
            if not self._degraded:
                return None
            now = _time.monotonic()
            expire = max(5.0, 4.0 * self.remote_ttl)
            for pred in [
                p for p, e in self._degraded.items() if now - e[2] > expire
            ]:
                del self._degraded[pred]
            ents = [
                e for p, e in self._degraded.items()
                if preds is None or p in preds
            ]
            if not ents:
                return None
            gids = sorted({e[0] for e in ents})
            age = max(now - e[1] for e in ents)
        return {"stale_groups": gids, "age": round(age, 3)}

    def _drain_dirty(self) -> None:
        """Caller holds _snap_lock."""
        for g in self._svc.groups.values():
            with g._lock:
                if g.store.dirty:
                    self._dirty |= g.store.dirty
                    if "*" in g.store.dirty:
                        # full-store replacement (raft snapshot restore):
                        # every cached snapshot is stale
                        self._snaps.clear()
                    else:
                        for p in g.store.dirty:
                            self._snaps.pop(p, None)
                    g.store.dirty.clear()

    def peek(self, name: str) -> Optional[PredicateData]:
        gid = self._owner_gid(name)
        g = self._svc.groups.get(gid)
        if g is None:  # another group's data: cross-server read (own lock)
            return self._remote_peek(name, gid)
        with self._snap_lock:
            self._drain_dirty()
            snap = self._snaps.get(name)
            if snap is None:
                with g._lock:
                    live = g.store.peek(name)
                    if live is None:
                        return None
                    snap = _copy_pred(live)
                self._snaps[name] = snap
            return snap

    def pred(self, name: str) -> PredicateData:
        return self.peek(name) or PredicateData()

    def predicates(self) -> List[str]:
        out: set = set()
        for g in self._svc.groups.values():
            with g._lock:
                out.update(g.store._preds.keys())
        # union in the predicates of groups this server does not place
        # (expand(_all_) / export must see the whole graph)
        import time as _time

        for gid in self._svc.conf.known_groups():
            if gid in self._svc.groups:
                continue
            # same lock rule as _remote_peek: _remote_lock guards the cache
            # dicts only — the network fetch happens outside it, serialized
            # per-gid by a fetch lock so one unreachable group (5s timeout)
            # never stalls _remote_peek readers or other groups' fetches
            with self._remote_lock:
                now = _time.monotonic()
                ent = self._predlists.get(gid)
                if ent is not None and now - ent[1] < self.remote_ttl:
                    out.update(ent[0])
                    continue
                flock = self._fetch_locks.setdefault(
                    ("__predlist__", gid), threading.Lock()
                )
            with flock:
                with self._remote_lock:
                    ent = self._predlists.get(gid)
                    if ent is not None and _time.monotonic() - ent[1] < self.remote_ttl:
                        out.update(ent[0])
                        continue
                names = self._svc.fetch_predlist(gid)
                with self._remote_lock:
                    if names is None:  # owner unreachable: keep stale list
                        names = ent[0] if ent is not None else []
                    self._predlists[gid] = [names, _time.monotonic()]
                    out.update(names)
        return sorted(out)

    def value(self, pred: str, uid: int, lang: str = ""):
        p = self.peek(pred)
        if p is None:
            return None
        v = p.values.get((uid, lang))
        if v is None and lang:
            v = p.values.get((uid, ""))
        return v

    def any_value(self, pred: str, uid: int):
        p = self.peek(pred)
        if p is None:
            return None
        v = p.values.get((uid, ""))
        if v is not None:
            return v
        for (u, _l), val in p.values.items():
            if u == uid:
                return val
        return None

    def neighbors(self, pred: str, uid: int) -> List[int]:
        p = self.peek(pred)
        if p is None:
            return []
        return sorted(p.edges.get(uid, ()))

    def edge_count(self) -> int:
        total = 0
        for g in self._svc.groups.values():
            with g._lock:  # raft applies mutate these dicts concurrently
                total += sum(
                    sum(len(s) for s in p.edges.values()) + len(p.values)
                    for p in g.store._preds.values()
                )
        return total

    # -- writes (raft proposals, partitioned by owning group) --------------

    def apply(self, e: Edge) -> None:
        self.apply_many([e])

    def apply_many(self, edges) -> int:
        by_group: Dict[int, List[bytes]] = {}
        n = 0
        for e in edges:
            by_group.setdefault(self._owner_gid(e.pred), []).append(
                codec.encode_edge(e)
            )
            n += 1
        for gid, records in by_group.items():
            self._svc.propose_records(gid, records)
        return n

    def bulk_set_uid_edges(self, pred: str, src, dst) -> None:
        self._svc.propose_records(
            self._owner_gid(pred), [codec.encode_bulk_edges(pred, src, dst)]
        )

    def bulk_set_values(self, pred: str, items) -> None:
        if not items:
            return
        self._svc.propose_records(
            self._owner_gid(pred), [codec.encode_bulk_values(pred, items)]
        )

    def delete_predicate(self, pred: str) -> None:
        self._svc.propose_records(
            self._owner_gid(pred), [codec.encode_delpred(pred)]
        )

    def set_edge(self, pred: str, src: int, dst: int, facets=None):
        self.apply(Edge(pred=pred, src=src, dst=dst, facets=facets))

    def del_edge(self, pred: str, src: int, dst: int):
        self.apply(Edge(pred=pred, src=src, dst=dst, op="del"))


def _copy_pred(p: PredicateData) -> PredicateData:
    out = PredicateData()
    out.edges = {u: set(s) for u, s in p.edges.items()}
    out.values = dict(p.values)
    out.edge_facets = {k: dict(v) for k, v in p.edge_facets.items()}
    out.value_facets = {k: dict(v) for k, v in p.value_facets.items()}
    return out
