"""Raft message wire codec + HTTP transport between server processes.

Equivalent of the reference's raft gRPC plane (worker/draft.go:437
batchAndSendMessages → grpc RaftMessage:1017): messages are length-framed
binary (the shared varint codec — NOT pickle: raft frames arrive off the
network and must never execute anything), queued per peer and shipped by
a sender thread so the raft event loop never blocks on the network.
Delivery is best-effort; raft tolerates loss and the queue drops when a
peer is down (the reference's conn pool likewise drops on dead conns).
"""

from __future__ import annotations

import queue
import threading
import urllib.request
from typing import Dict, List, Optional

from dgraph_tpu.models import codec
from dgraph_tpu.cluster.raft import (
    TimeoutNow,
    AppendReq,
    AppendResp,
    Entry,
    SnapshotReq,
    SnapshotResp,
    Transport,
    VoteReq,
    VoteResp,
)

(_VOTE_REQ, _VOTE_RESP, _APPEND_REQ, _APPEND_RESP, _SNAP_REQ, _SNAP_RESP,
 _TIMEOUT_NOW) = range(7)

# Header carrying the shared cluster secret on every intra-cluster call.
# The raft/propose/assign endpoints share the public port (the reference
# isolates them on an internal gRPC port); the secret is what stops
# anyone with network reach from injecting forged raft frames.
SECRET_HEADER = "X-Dgraph-Cluster-Secret"


class PeerAuth:
    """Security posture for intra-cluster calls: a shared secret attached
    to every request, and the TLS trust model for https peers —
    ``cafile`` pins a CA (chain verified, hostname check off: cluster
    certs are typically issued to names that don't match peer IPs, the
    reference's tls_helper has the same server-name override escape
    hatch); ``insecure=True`` is the explicit opt-out for throwaway
    self-signed setups; default is full system-store verification."""

    def __init__(self, secret: str = "", cafile: str = "", insecure: bool = False):
        self.secret = secret
        self.cafile = cafile
        self.insecure = insecure
        self._ctx = None

    def ssl_context(self):
        if self._ctx is None:
            import ssl

            if self.cafile:
                ctx = ssl.create_default_context(cafile=self.cafile)
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_REQUIRED
            elif self.insecure:
                ctx = ssl._create_unverified_context()
            else:
                ctx = ssl.create_default_context()
            self._ctx = ctx
        return self._ctx


def urlopen_peer(req, timeout: float, auth: Optional[PeerAuth] = None):
    """urlopen for intra-cluster calls: attaches the cluster secret and
    applies the PeerAuth TLS trust model for https peers."""
    if auth is not None and auth.secret and hasattr(req, "add_header"):
        req.add_header(SECRET_HEADER, auth.secret)
    url = req.full_url if hasattr(req, "full_url") else str(req)
    if url.startswith("https://"):
        ctx = (auth or PeerAuth()).ssl_context()
        return urllib.request.urlopen(req, timeout=timeout, context=ctx)
    return urllib.request.urlopen(req, timeout=timeout)


def _put_bytes(buf: bytearray, b: bytes) -> None:
    codec.put_uvarint(buf, len(b))
    buf.extend(b)


def _get_bytes(b: bytes, pos: int):
    n, pos = codec.uvarint(b, pos)
    return bytes(b[pos : pos + n]), pos + n


def _put_str(buf: bytearray, s: str) -> None:
    _put_bytes(buf, s.encode("utf-8"))


def _get_str(b: bytes, pos: int):
    raw, pos = _get_bytes(b, pos)
    return raw.decode("utf-8"), pos


def encode_msg(msg) -> bytes:
    buf = bytearray()
    if isinstance(msg, VoteReq):
        buf.append(_VOTE_REQ)
        codec.put_uvarint(buf, msg.term)
        _put_str(buf, msg.candidate)
        codec.put_uvarint(buf, msg.last_log_index)
        codec.put_uvarint(buf, msg.last_log_term)
        buf.append(1 if msg.pre else 0)
    elif isinstance(msg, VoteResp):
        buf.append(_VOTE_RESP)
        codec.put_uvarint(buf, msg.term)
        buf.append(1 if msg.granted else 0)
        _put_str(buf, msg.sender)
        buf.append(1 if msg.pre else 0)
    elif isinstance(msg, TimeoutNow):
        buf.append(_TIMEOUT_NOW)
        codec.put_uvarint(buf, msg.term)
        _put_str(buf, msg.leader)
    elif isinstance(msg, AppendReq):
        buf.append(_APPEND_REQ)
        codec.put_uvarint(buf, msg.term)
        _put_str(buf, msg.leader)
        codec.put_uvarint(buf, msg.prev_log_index)
        codec.put_uvarint(buf, msg.prev_log_term)
        codec.put_uvarint(buf, msg.leader_commit)
        codec.put_uvarint(buf, len(msg.entries))
        for e in msg.entries:
            codec.put_uvarint(buf, e.term)
            codec.put_uvarint(buf, e.index)
            _put_bytes(buf, e.data)
    elif isinstance(msg, AppendResp):
        buf.append(_APPEND_RESP)
        codec.put_uvarint(buf, msg.term)
        buf.append(1 if msg.success else 0)
        codec.put_uvarint(buf, msg.match_index)
        _put_str(buf, msg.sender)
    elif isinstance(msg, SnapshotReq):
        buf.append(_SNAP_REQ)
        codec.put_uvarint(buf, msg.term)
        _put_str(buf, msg.leader)
        codec.put_uvarint(buf, msg.last_index)
        codec.put_uvarint(buf, msg.last_term)
        _put_bytes(buf, msg.data)
    elif isinstance(msg, SnapshotResp):
        buf.append(_SNAP_RESP)
        codec.put_uvarint(buf, msg.term)
        _put_str(buf, msg.sender)
        codec.put_uvarint(buf, msg.last_index)
    else:
        raise TypeError(f"unknown raft message {type(msg)!r}")
    return bytes(buf)


def decode_msg(b: bytes):
    tag = b[0]
    pos = 1
    if tag == _VOTE_REQ:
        term, pos = codec.uvarint(b, pos)
        cand, pos = _get_str(b, pos)
        lli, pos = codec.uvarint(b, pos)
        llt, pos = codec.uvarint(b, pos)
        # trailing pre byte absent in pre-round-4 frames: degrade to a
        # plain vote instead of crashing the receive path mid-upgrade
        pre = pos < len(b) and b[pos] == 1
        return VoteReq(term, cand, lli, llt, pre)
    if tag == _VOTE_RESP:
        term, pos = codec.uvarint(b, pos)
        granted = b[pos] == 1
        sender, pos = _get_str(b, pos + 1)
        pre = pos < len(b) and b[pos] == 1
        return VoteResp(term, granted, sender, pre)
    if tag == _TIMEOUT_NOW:
        term, pos = codec.uvarint(b, pos)
        leader, pos = _get_str(b, pos)
        return TimeoutNow(term, leader)
    if tag == _APPEND_REQ:
        term, pos = codec.uvarint(b, pos)
        leader, pos = _get_str(b, pos)
        pli, pos = codec.uvarint(b, pos)
        plt, pos = codec.uvarint(b, pos)
        commit, pos = codec.uvarint(b, pos)
        n, pos = codec.uvarint(b, pos)
        entries: List[Entry] = []
        for _ in range(n):
            et, pos = codec.uvarint(b, pos)
            ei, pos = codec.uvarint(b, pos)
            data, pos = _get_bytes(b, pos)
            entries.append(Entry(et, ei, data))
        return AppendReq(term, leader, pli, plt, entries, commit)
    if tag == _APPEND_RESP:
        term, pos = codec.uvarint(b, pos)
        success = b[pos] == 1
        match, pos = codec.uvarint(b, pos + 1)
        sender, pos = _get_str(b, pos)
        return AppendResp(term, success, match, sender)
    if tag == _SNAP_REQ:
        term, pos = codec.uvarint(b, pos)
        leader, pos = _get_str(b, pos)
        li, pos = codec.uvarint(b, pos)
        lt, pos = codec.uvarint(b, pos)
        data, pos = _get_bytes(b, pos)
        return SnapshotReq(term, leader, li, lt, data)
    if tag == _SNAP_RESP:
        term, pos = codec.uvarint(b, pos)
        sender, pos = _get_str(b, pos)
        li, pos = codec.uvarint(b, pos)
        return SnapshotResp(term, sender, li)
    raise ValueError(f"unknown raft message tag {tag:#x}")


class _QueuedPeerTransport(Transport):
    """Queue-per-peer / drop-don't-block sender discipline shared by the
    raft transports: one bounded queue + daemon sender thread per peer —
    the raft loop enqueues and returns; slow/dead peers drop frames
    instead of applying backpressure to consensus (batchAndSendMessages
    behavior, draft.go:434 'no need to send heartbeats if we can't send
    messages').  Subclasses implement ``_sender``."""

    _thread_prefix = "raft-send"

    def __init__(
        self,
        addr_of: Dict[str, str],
        timeout: float,
        auth: Optional["PeerAuth"] = None,
        peerclient=None,
    ):
        self.addr_of = dict(addr_of)      # node_id -> http(s)://host:port
        self.timeout = timeout
        self.auth = auth
        # all network sends route through the PeerClient funnel
        # (cluster/peerclient.py): bounded retries with backoff for
        # transient errors, and the per-peer breaker turns a dead peer's
        # frames into microsecond sheds instead of per-frame timeouts.
        # ClusterService shares ITS client so breaker knowledge is
        # cluster-wide; standalone transports build their own.  Lazy
        # import: peerclient imports PeerAuth/urlopen_peer from here.
        if peerclient is None:
            from dgraph_tpu.cluster.peerclient import PeerClient

            peerclient = PeerClient(auth=auth)
        self.peerclient = peerclient
        self._queues: Dict[str, "queue.Queue"] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()

    def _queue_for(self, peer: str) -> "queue.Queue":
        with self._lock:
            q = self._queues.get(peer)
            if q is None:
                q = queue.Queue(maxsize=256)
                self._queues[peer] = q
                t = threading.Thread(
                    target=self._sender, args=(peer, q),
                    name=f"{self._thread_prefix}-{peer}", daemon=True,
                )
                t.start()
            return q

    def update_peer(self, nid: str, addr: str) -> None:
        """Runtime membership rewiring (atomic reference swap — sender
        threads re-read addr_of per message)."""
        self.addr_of = {**self.addr_of, nid: addr}

    def send(self, to: str, group: int, msg) -> None:
        if to not in self.addr_of:
            return
        try:
            self._queue_for(to).put_nowait((group, encode_msg(msg)))
        except queue.Full:
            pass  # drop: raft retries via next heartbeat

    def _sender(self, peer: str, q: "queue.Queue") -> None:
        raise NotImplementedError

    def stop(self) -> None:
        self._stop.set()


class HttpRaftTransport(_QueuedPeerTransport):
    """Ships raft frames to peers over HTTP POST /raft/<group>."""

    def __init__(
        self,
        addr_of: Dict[str, str],
        timeout: float = 2.0,
        auth: Optional[PeerAuth] = None,
        peerclient=None,
    ):
        super().__init__(addr_of, timeout, auth=auth, peerclient=peerclient)

    def _sender(self, peer: str, q: "queue.Queue") -> None:
        from dgraph_tpu.utils.metrics import RAFT_DROPPED, note_swallowed

        while not self._stop.is_set():
            try:
                group, body = q.get(timeout=0.5)
            except queue.Empty:
                continue
            url = f"{self.addr_of[peer]}/raft/{group}"
            try:
                req = urllib.request.Request(
                    url, data=body,
                    headers={"Content-Type": "application/octet-stream"},
                )
                # bounded retry (2 attempts) through the shared breaker:
                # a transient blip no longer drops the frame, a dead
                # peer sheds in microseconds once its circuit opens.
                # slice_budget=False: halving the (already short) frame
                # timeout would make a healthy-but-loaded peer answering
                # in (timeout/2, timeout] fail BOTH slices — frames
                # dropped and its breaker charged where the legacy
                # single shot delivered; the first attempt keeps the
                # legacy window, the retry covers fast failures only
                with self.peerclient.urlopen(
                    peer, req, op="raft.send",
                    budget=self.timeout, attempts=2,
                    off_timeout=self.timeout, slice_budget=False,
                ) as resp:
                    resp.read()
            except OSError as e:
                # peer still down after retries: drop (raft re-sends via
                # the next heartbeat) — but COUNTED, never silent
                RAFT_DROPPED.add(peer)
                note_swallowed("transport.http_send", e)
            except Exception as e:  # noqa: BLE001 — ANY other failure
                # (IncompleteRead from a peer killed mid-response, encode
                # surprise) must not kill this peer's only sender thread
                # for the process lifetime; same discipline as the gRPC
                # twin: count under its own site AND print the traceback
                import traceback

                RAFT_DROPPED.add(peer)
                note_swallowed("transport.sender_unexpected", e)
                traceback.print_exc()


def grpc_target_of(http_addr: str, port_offset: int) -> str:
    """Peer address → its gRPC target.  Accepts full http(s)://host:port
    urls AND bare host:port (ClusterService's peers param admits both);
    raises on anything it cannot map rather than emitting a target that
    silently drops every frame."""
    from urllib.parse import urlsplit

    addr = http_addr
    if "://" not in addr:
        addr = "http://" + addr
    u = urlsplit(addr)
    if not u.hostname or not u.port:
        raise ValueError(f"cannot derive a gRPC target from peer address {http_addr!r}")
    return f"{u.hostname}:{u.port + port_offset}"


class GrpcRaftTransport(_QueuedPeerTransport):
    """Ships raft frames over the gRPC Worker plane
    (``/protos.Worker/RaftMessage``, serve/grpc_server.py) — the direct
    analog of the reference's raft gRPC leg (worker/draft.go:1017);
    the cluster secret rides gRPC metadata.

    ``addr_of`` holds peer HTTP addresses (same contract as
    HttpRaftTransport, so runtime membership rewiring via update_peer is
    transport-agnostic); targets derive per message, so a member that
    re-announces on a new address is picked up by the live sender.
    https peers require ``auth.cafile`` — gRPC channels are TLS-verified
    with the pinned CA; there is no silent plaintext downgrade."""

    _thread_prefix = "raft-grpc-send"

    def __init__(
        self,
        addr_of: Dict[str, str],  # node_id -> http(s)://host:port
        timeout: float = 2.0,
        secret: str = "",
        port_offset: int = 1000,
        auth: Optional[PeerAuth] = None,
        peerclient=None,
    ):
        super().__init__(addr_of, timeout, auth=auth, peerclient=peerclient)
        self.secret = secret
        self.port_offset = port_offset
        for a in self.addr_of.values():
            self._check_addr(a)
        self._chans: Dict[str, object] = {}  # target -> channel

    def _check_addr(self, addr: str) -> None:
        grpc_target_of(addr, self.port_offset)  # raises if unmappable
        if addr.startswith("https://") and not (self.auth and self.auth.cafile):
            raise ValueError(
                "https peers over the gRPC raft transport require a pinned "
                "CA (--peer_ca): gRPC has no unverified-TLS mode and "
                "silently downgrading raft frames to plaintext would leak "
                "the cluster secret"
            )

    def update_peer(self, nid: str, addr: str) -> None:
        self._check_addr(addr)
        old = self.addr_of.get(nid)
        super().update_peer(nid, addr)
        if old and old != addr:
            # close the superseded channel unless another peer still maps
            # to the same target — re-addressing members must not leak
            # one open HTTP/2 connection per churn for the process life
            old_t = grpc_target_of(old, self.port_offset)
            live = {
                grpc_target_of(a, self.port_offset)
                for a in self.addr_of.values()
            }
            if old_t not in live:
                with self._lock:
                    ch = self._chans.pop(old_t, None)
                if ch is not None:
                    try:
                        ch.close()
                    except Exception as e:  # noqa: BLE001 — grpc close
                        # failures are unactionable here, but visible
                        from dgraph_tpu.utils.metrics import note_swallowed

                        note_swallowed("transport.channel_close", e)

    def _channel_for(self, addr: str):
        import grpc

        target = grpc_target_of(addr, self.port_offset)
        with self._lock:
            ch = self._chans.get(target)
            if ch is None:
                if addr.startswith("https://"):
                    with open(self.auth.cafile, "rb") as f:
                        creds = grpc.ssl_channel_credentials(f.read())
                    ch = grpc.secure_channel(target, creds)
                else:
                    ch = grpc.insecure_channel(target)
                self._chans[target] = ch
            return ch

    def _sender(self, peer: str, q: "queue.Queue") -> None:
        import grpc

        from dgraph_tpu.serve.grpc_server import (
            _SECRET_MD,
            encode_payload,
            frame_raft,
        )
        from dgraph_tpu.utils.metrics import RAFT_DROPPED, note_swallowed

        md = [(_SECRET_MD, self.secret)] if self.secret else None
        while not self._stop.is_set():
            try:
                group, body = q.get(timeout=0.5)
            except queue.Empty:
                continue
            try:
                # re-resolve per message (like HttpRaftTransport): a
                # member re-announcing on a new address routes the next
                # frame to the new target
                addr = self.addr_of.get(peer)
                if addr is None:
                    continue
                payload = encode_payload(frame_raft(group, body))
                # the channel-RPC itself runs inside PeerClient (its
                # grpc_unary leg): bounded retries, breaker sheds, and
                # the ValueError a closing channel throws mid-call is
                # classified transient there — a ValueError out of
                # encode_payload above still reaches the unexpected
                # handler below, as before
                try:
                    # slice_budget=False for the same reason as the HTTP
                    # twin: a loaded peer answering within the legacy
                    # window must not fail two half-window slices
                    self.peerclient.grpc_unary(
                        peer, "raft.send", self._channel_for(addr),
                        "/protos.Worker/RaftMessage", payload,
                        metadata=md, budget=self.timeout, attempts=2,
                        slice_budget=False,
                    )
                except ValueError as e:
                    # the RESILIENCE=0 off-path returns the raw attempt,
                    # so the closed-channel ValueError arrives HERE
                    # instead of wrapped transient inside peerclient:
                    # same quiet counted drop as any peer-down error,
                    # not a per-frame traceback
                    RAFT_DROPPED.add(peer)
                    note_swallowed("transport.grpc_send", e)
            except (grpc.RpcError, OSError) as e:
                # peer still down after retries: drop (heartbeats will
                # re-send) — counted, never silent
                RAFT_DROPPED.add(peer)
                note_swallowed("transport.grpc_send", e)
            except Exception as e:  # noqa: BLE001 — ANY other failure
                # (encode bug, channel-construction surprise) must not
                # kill this peer's only sender thread for the process
                # lifetime; count under its own site AND print — an
                # unexpected type here is a bug worth a traceback
                import traceback

                note_swallowed("transport.sender_unexpected", e)
                traceback.print_exc()

    def stop(self) -> None:
        super().stop()
        from dgraph_tpu.utils.metrics import note_swallowed

        with self._lock:
            for ch in self._chans.values():
                try:
                    ch.close()
                except Exception as e:  # noqa: BLE001 — best-effort teardown
                    note_swallowed("transport.channel_close", e)
            self._chans.clear()
