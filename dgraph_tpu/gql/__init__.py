"""GraphQL± query language frontend.

Equivalent of the reference's gql/ + lex/ packages: parses query strings
into the AST the engine consumes.  The reference uses a Rob-Pike-style
state-function lexer (lex/lexer.go:113) feeding a hand-written parser
(gql/parser.go:481); here a regex tokenizer feeds a recursive-descent
parser — the language accepted is the same (queries, filters, functions,
variables, facets, fragments, mutations, schema blocks).
"""

from dgraph_tpu.gql.ast import (  # noqa: F401
    FacetsSpec,
    FilterTree,
    Function,
    GraphQuery,
    MathTree,
    Mutation,
    ParsedResult,
    SchemaRequest,
    VarRef,
)
from dgraph_tpu.gql.parser import ParseError, parse  # noqa: F401
