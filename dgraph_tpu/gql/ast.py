"""AST node types for GraphQL±.

Mirrors the reference's gql.GraphQuery (gql/parser.go:41), FilterTree
(parser.go:74), Function (parser.go:56), MathTree (gql/math.go) and
facet parameters — as plain dataclasses.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

UID_VAR = "uid"
VALUE_VAR = "value"


@dataclass
class VarRef:
    """A variable a query needs (NeedsVar), with its kind."""

    name: str
    typ: str  # UID_VAR | VALUE_VAR


@dataclass
class Function:
    """A function application: func name, attribute, args.

    Forms of the first argument (gql/parser.go parseFunction:1362):
    plain attr, attr@lang, val(var), count(attr) — flagged here.
    """

    name: str = ""
    attr: str = ""
    lang: str = ""
    args: List[str] = field(default_factory=list)
    needs_vars: List[VarRef] = field(default_factory=list)
    is_count: bool = False      # gt(count(friends), 10)
    is_val_var: bool = False    # gt(val(a), 10)
    uid_args: List[int] = field(default_factory=list)  # uid(0x1, 0x2)


@dataclass
class FilterTree:
    """Boolean filter tree: op in {"and","or","not",""}; leaf has func."""

    op: str = ""
    children: List["FilterTree"] = field(default_factory=list)
    func: Optional[Function] = None


@dataclass
class FacetsSpec:
    """@facets directive params (keys to fetch / order / var bindings)."""

    all_keys: bool = False
    keys: List[str] = field(default_factory=list)
    aliases: Dict[str, str] = field(default_factory=dict)   # key -> var name
    order_key: str = ""
    order_desc: bool = False


@dataclass
class MathTree:
    """math(...) expression tree (gql/math.go)."""

    fn: str = ""                 # operator/function name; "" for leaf
    var: str = ""                # leaf: value-variable name
    const: Optional[float] = None  # leaf: numeric constant
    children: List["MathTree"] = field(default_factory=list)

    def debug(self) -> str:
        if self.fn:
            return "(" + " ".join([self.fn] + [c.debug() for c in self.children]) + ")"
        if self.var:
            return self.var
        return repr(self.const)


@dataclass
class GraphQuery:
    """One node of the query tree (block root or attribute child)."""

    attr: str = ""
    alias: str = ""
    langs: List[str] = field(default_factory=list)
    func: Optional[Function] = None
    args: Dict[str, str] = field(default_factory=dict)  # first/offset/after/orderasc/...
    filter: Optional[FilterTree] = None
    children: List["GraphQuery"] = field(default_factory=list)
    uid_list: List[int] = field(default_factory=list)   # explicit root uids

    is_count: bool = False          # count(pred)
    is_internal: bool = False       # var-only node (no output)
    is_groupby: bool = False
    expand: str = ""                # "_all_" or a value-var name
    var: str = ""                   # "x as pred" definition
    needs_var: List[VarRef] = field(default_factory=list)
    agg_func: str = ""              # min/max/sum/avg over val(...)
    math_exp: Optional[MathTree] = None
    facets: Optional[FacetsSpec] = None
    facets_filter: Optional[FilterTree] = None
    groupby_attrs: List[Tuple[str, str]] = field(default_factory=list)  # (attr, lang)

    normalize: bool = False
    cascade: bool = False
    ignore_reflex: bool = False

    # shortest-path / recurse args resolved by the engine from ``args``


def referenced_preds(queries: List["GraphQuery"]) -> Optional[set]:
    """The set of predicate names a parsed query can read, or None when
    the set is not statically determinable (``expand()`` and
    ``_predicate_`` blocks read schema-driven predicate lists only known
    at execution time).  Used to scope the ``degraded`` response
    annotation to the owner groups a query actually touches: a reader of
    purely-local predicates must not be told its response may be stale.
    Collection errs on the side of INCLUSION — an extra name that is
    never degraded is harmless, a missed one under-reports staleness."""
    out: set = set()

    def add(name: str) -> None:
        if name:
            # "~pred" reads the same predicate's data through its reverse
            # index; "pred@lang" order args keep the raw form
            out.add(name.lstrip("~").split("@", 1)[0])

    def walk_fn(fn: Optional[Function]) -> None:
        if fn is not None:
            add(fn.attr)

    def walk_filter(ft: Optional[FilterTree]) -> None:
        if ft is None:
            return
        walk_fn(ft.func)
        for c in ft.children:
            walk_filter(c)

    def walk(gq: "GraphQuery") -> bool:
        if gq.expand:
            return False  # schema/var-driven: preds unknown until run time
        if gq.attr == "_predicate_":
            return False  # reads every predicate of the node
        add(gq.attr)
        walk_fn(gq.func)
        walk_filter(gq.filter)
        walk_filter(gq.facets_filter)
        for key in ("orderasc", "orderdesc"):
            v = gq.args.get(key, "")
            if v and not v.startswith("val("):
                add(v)
        for attr, _lang in gq.groupby_attrs:
            add(attr)
        return all(walk(c) for c in gq.children)

    for gq in queries:
        if not walk(gq):
            return None
    return out


@dataclass
class Mutation:
    """Raw mutation bodies; RDF parsing happens in dgraph_tpu.rdf."""

    set_nquads: str = ""
    del_nquads: str = ""
    schema: str = ""


@dataclass
class SchemaRequest:
    predicates: List[str] = field(default_factory=list)
    fields: List[str] = field(default_factory=list)


@dataclass
class ParsedResult:
    queries: List[GraphQuery] = field(default_factory=list)
    mutation: Optional[Mutation] = None
    schema_request: Optional[SchemaRequest] = None
    # per-block (defines, needs) for scheduling (gql checkDependency:605)
    query_vars: List[Tuple[List[str], List[str]]] = field(default_factory=list)
