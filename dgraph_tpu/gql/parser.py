"""Recursive-descent parser for GraphQL±.

Accepts the language of the reference's gql.Parse (gql/parser.go:481):
named/anonymous query blocks, root functions and ``id:`` lists, filters
with AND/OR/NOT, pagination/order args, aliases, language tags, variables
(``x as pred``), value/uid var usage, aggregations, math(), expand(),
count blocks, @facets, @groupby, @normalize/@cascade/@ignorereflex,
GraphQL query variables ($var), fragments, mutation blocks and schema
blocks.  The HTTP JSON wrapper {"query":..., "variables":...} is also
handled here (reference does this under Request.Http).
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Tuple

from dgraph_tpu.gql.ast import (
    FacetsSpec,
    FilterTree,
    Function,
    GraphQuery,
    MathTree,
    Mutation,
    ParsedResult,
    SchemaRequest,
    VarRef,
    UID_VAR,
    VALUE_VAR,
)


class ParseError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Lexer

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>\#[^\n]*)
  | (?P<string>"(?:\\.|[^"\\])*")
  | (?P<iri><[^>\s]+>)
  | (?P<number>0[xX][0-9a-fA-F]+|\d+(?:\.\d+)?(?:[eE][-+]?\d+)?)
  | (?P<name>~?[A-Za-z_][A-Za-z0-9_.]*)
  | (?P<dollar>\$[A-Za-z_][A-Za-z0-9_]*)
  | (?P<spread>\.\.\.)
  | (?P<op><=|>=|==|!=|&&|\|\||=|[-+*/%<>])
  | (?P<punct>[{}()\[\]:,@!.])
    """,
    re.VERBOSE,
)


class Tok:
    __slots__ = ("kind", "text", "pos")

    def __init__(self, kind, text, pos):
        self.kind, self.text, self.pos = kind, text, pos

    def __repr__(self):  # pragma: no cover
        return f"Tok({self.kind},{self.text!r})"


def _lex(s: str) -> List[Tok]:
    out, i = [], 0
    n = len(s)
    while i < n:
        m = _TOKEN_RE.match(s, i)
        if m is None:
            raise ParseError(f"unexpected character {s[i]!r} at offset {i}")
        i = m.end()
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        out.append(Tok(kind, m.group(), m.start()))
    out.append(Tok("eof", "", n))
    return out


def _unquote(s: str) -> str:
    body = s[1:-1]
    return re.sub(
        r"\\(.)",
        lambda m: {"n": "\n", "t": "\t", '"': '"', "\\": "\\", "'": "'"}.get(
            m.group(1), m.group(1)
        ),
        body,
    )


_DIRECTIVES = {
    "filter",
    "facets",
    "groupby",
    "normalize",
    "cascade",
    "ignorereflex",
    "recurse",
}

_AGG_FUNCS = {"min", "max", "sum", "avg"}

_ROOT_ARGS = {
    "first",
    "offset",
    "after",
    "orderasc",
    "orderdesc",
    "depth",
    "from",
    "to",
    "numpaths",
    "minweight",
    "maxweight",
}


class _Parser:
    def __init__(self, toks: List[Tok], gqlvars: Dict[str, str]):
        self.toks = toks
        self.i = 0
        self.vars = gqlvars
        self.fragments: Dict[str, List[GraphQuery]] = {}

    # -- token plumbing ----------------------------------------------------

    def peek(self, ahead: int = 0) -> Tok:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Tok:
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def expect(self, kind: str, text: Optional[str] = None) -> Tok:
        t = self.next()
        if t.kind != kind or (text is not None and t.text != text):
            raise ParseError(
                f"expected {text or kind} at offset {t.pos}, got {t.text!r}"
            )
        return t

    def accept(self, kind: str, text: Optional[str] = None) -> Optional[Tok]:
        t = self.peek()
        if t.kind == kind and (text is None or t.text == text):
            return self.next()
        return None

    def _value_token(self) -> str:
        """One scalar argument value, with $var substitution."""
        t = self.next()
        if t.kind == "op" and t.text in ("-", "+"):
            num = self.expect("number")
            return t.text + num.text
        if t.kind == "string":
            return _unquote(t.text)
        if t.kind == "dollar":
            if t.text not in self.vars:
                raise ParseError(f"undefined query variable {t.text}")
            return self.vars[t.text]
        if t.kind in ("name", "number", "iri"):
            return t.text.strip("<>") if t.kind == "iri" else t.text
        raise ParseError(f"expected value at offset {t.pos}, got {t.text!r}")

    # -- entry -------------------------------------------------------------

    def parse(self) -> ParsedResult:
        res = ParsedResult()
        while True:
            t = self.peek()
            if t.kind == "eof":
                break
            if t.kind == "punct" and t.text == "{":
                self._parse_query_body(res)
            elif t.kind == "name" and t.text == "query":
                self.next()
                if self.peek().text == "(":
                    self._parse_var_decls()
                if self.peek().kind == "name":  # named query: query name(...)
                    self.next()
                    if self.peek().text == "(":
                        self._parse_var_decls()
                self._parse_query_body(res)
            elif t.kind == "name" and t.text == "schema":
                self.next()
                res.schema_request = self._parse_schema_request()
            elif t.kind == "name" and t.text == "fragment":
                self.next()
                name = self.expect("name").text
                self.expect("punct", "{")
                self.fragments[name] = self._parse_children()
            else:
                raise ParseError(f"unexpected {t.text!r} at offset {t.pos}")
        self._expand_fragments_all(res)
        self._collect_query_vars(res)
        return res

    def _parse_var_decls(self):
        """query name($a: int = 3, $b: string!) — fills defaults into vars."""
        self.expect("punct", "(")
        while not self.accept("punct", ")"):
            d = self.expect("dollar").text
            self.expect("punct", ":")
            self.expect("name")  # type
            self.accept("punct", "!")
            if self.accept("op", "="):
                self.vars.setdefault(d, self._value_token())
            self.accept("punct", ",")

    # -- query blocks ------------------------------------------------------

    def _parse_query_body(self, res: ParsedResult):
        self.expect("punct", "{")
        n0 = len(res.queries)
        while not self.accept("punct", "}"):
            self.accept("punct", ",")
            res.queries.append(self._parse_block())
        if len(res.queries) == n0:
            raise ParseError("empty query body")

    def _parse_block(self) -> GraphQuery:
        gq = GraphQuery()
        name_tok = self.expect("name")
        name = name_tok.text
        var_def = ""
        if self.peek().kind == "name" and self.peek().text.lower() == "as":
            # "X as shortest(...)" / var-block named by a variable
            self.next()
            var_def = name
            name = self.expect("name").text
        gq.alias = name
        gq.var = var_def
        if name == "var":
            gq.is_internal = True
        self._parse_root_args(gq)
        self._parse_directives(gq)
        self.expect("punct", "{")
        gq.children = self._parse_children()
        return gq

    def _parse_lang_chain(self) -> List[str]:
        """The lang list after '@': ``ru:en:.`` — names separated by ':',
        where '.' is the forced any-value fallback (gql/parser.go lang
        list semantics, query_test.go TestLangMany*/ForcedFallback)."""
        langs: List[str] = []
        while True:
            if self.accept("punct", "."):
                langs.append(".")
            else:
                langs.append(self.expect("name").text)
            if not self.accept("punct", ":"):
                return langs

    def _parse_root_args(self, gq: GraphQuery):
        if not self.accept("punct", "("):
            return
        while not self.accept("punct", ")"):
            self.accept("punct", ",")
            if self.peek().text == ")":
                continue
            key = self.expect("name").text
            self.expect("punct", ":")
            if key == "func":
                gq.func = self._parse_function()
            elif key == "id":
                self._parse_id_arg(gq)
            elif key in _ROOT_ARGS:
                if (
                    key in ("orderasc", "orderdesc")
                    and self.peek().kind == "name"
                    and self.peek().text == "val"
                    and self.peek(1).text == "("
                ):
                    self.next()
                    self.expect("punct", "(")
                    v = self.expect("name").text
                    self.expect("punct", ")")
                    gq.args[key] = "val:" + v
                    gq.needs_var.append(VarRef(v, VALUE_VAR))
                else:
                    v = self._value_token()
                    if key in ("orderasc", "orderdesc"):
                        while self.accept("punct", "@"):
                            v += "@" + self.expect("name").text
                    elif key in ("first", "offset", "after", "depth", "numpaths"):
                        # integer args validate at parse time (parser.go:360
                        # "Expected an int but got %v"); counts are base 10
                        # to match the reference's strconv semantics
                        # (leading-zero literals parse as decimal, 0x is
                        # rejected) — but `after` is a uid boundary and
                        # keeps accepting hex like uid() does
                        try:
                            int(v, 0 if key == "after" else 10)
                        except ValueError:
                            raise ParseError(
                                f"expected an int for {key}: but got {v!r}"
                            )
                    gq.args[key] = v
            else:
                # unknown args are ignored (reference ignores xid:, etc.)
                self._value_token()

    def _parse_id_arg(self, gq: GraphQuery):
        """id: 0x0a | id: [1, 2, 0x3] — sugar for root uid list."""
        if self.accept("punct", "["):
            while not self.accept("punct", "]"):
                self.accept("punct", ",")
                if self.peek().text == "]":
                    continue
                gq.uid_list.append(_parse_uid(self._value_token()))
        else:
            v = self._value_token()
            gq.uid_list.append(_parse_uid(v))

    # -- functions ---------------------------------------------------------

    def _parse_function(self) -> Function:
        fn = Function()
        fn.name = self.expect("name").text.lower()
        self.expect("punct", "(")
        if fn.name == "uid":
            if self.peek().text == ")":  # uid() — "Empty Argument"
                raise ParseError("uid() needs at least one uid or variable")
            while not self.accept("punct", ")"):
                self.accept("punct", ",")
                if self.peek().text == ")":
                    continue
                t = self.next()
                if t.kind == "number" or (t.kind == "name" and _is_uid(t.text)):
                    fn.uid_args.append(_parse_uid(t.text))
                elif t.kind == "name":
                    fn.needs_vars.append(VarRef(t.text, UID_VAR))
                elif t.kind == "dollar":
                    if t.text not in self.vars:
                        raise ParseError(f"undefined query variable {t.text}")
                    fn.uid_args.append(_parse_uid(self.vars[t.text]))
                else:
                    raise ParseError(f"bad uid() arg {t.text!r}")
            return fn
        # first argument: attr | attr@lang | val(v) | count(attr)
        t = self.next()
        if t.kind == "name" and t.text == "val" and self.peek().text == "(":
            self.expect("punct", "(")
            v = self.expect("name").text
            self.expect("punct", ")")
            fn.is_val_var = True
            fn.attr = v
            fn.needs_vars.append(VarRef(v, VALUE_VAR))
        elif t.kind == "name" and t.text == "count" and self.peek().text == "(":
            self.expect("punct", "(")
            fn.is_count = True
            fn.attr = self.expect("name").text
            self.expect("punct", ")")
        elif t.kind in ("name", "iri"):
            fn.attr = t.text.strip("<>") if t.kind == "iri" else t.text
            if self.accept("punct", "@"):
                fn.lang = ",".join(self._parse_lang_chain())
        else:
            raise ParseError(f"bad function first arg {t.text!r}")
        # remaining args
        while not self.accept("punct", ")"):
            self.accept("punct", ",")
            if self.peek().text == ")":
                continue
            if self.peek().text == "[":
                fn.args.append(self._parse_bracket_list())
            elif (
                self.peek().kind == "name"
                and self.peek().text == "val"
                and self.peek(1).text == "("
            ):
                self.next()
                self.expect("punct", "(")
                v = self.expect("name").text
                self.expect("punct", ")")
                # note: is_val_var stays false — that flag means the FIRST
                # arg is val(var); a val() comparand is carried in args
                fn.needs_vars.append(VarRef(v, VALUE_VAR))
                fn.args.append("val(" + v + ")")
            else:
                fn.args.append(self._value_token())
        return fn

    def _parse_bracket_list(self) -> str:
        """Geo coordinate lists: returned as a JSON string."""

        def rec():
            self.expect("punct", "[")
            out = []
            while not self.accept("punct", "]"):
                self.accept("punct", ",")
                if self.peek().text == "]":
                    continue
                if self.peek().text == "[":
                    out.append(rec())
                else:
                    v = self._value_token()
                    try:
                        out.append(float(v))
                    except ValueError:
                        out.append(v)
            return out

        return json.dumps(rec())

    # -- filters -----------------------------------------------------------

    def _parse_filter(self) -> Optional[FilterTree]:
        self.expect("punct", "(")
        if self.accept("punct", ")"):
            raise ParseError("empty @filter()")  # lex "Empty Argument"
        tree = self._parse_filter_or()
        self.expect("punct", ")")
        return tree

    def _parse_filter_or(self) -> FilterTree:
        left = self._parse_filter_and()
        while self.peek().kind == "name" and self.peek().text.lower() == "or":
            self.next()
            right = self._parse_filter_and()
            if left.op == "or":
                left.children.append(right)
            else:
                left = FilterTree(op="or", children=[left, right])
        return left

    def _parse_filter_and(self) -> FilterTree:
        left = self._parse_filter_not()
        while self.peek().kind == "name" and self.peek().text.lower() == "and":
            self.next()
            right = self._parse_filter_not()
            if left.op == "and":
                left.children.append(right)
            else:
                left = FilterTree(op="and", children=[left, right])
        return left

    def _parse_filter_not(self) -> FilterTree:
        if self.peek().kind == "name" and self.peek().text.lower() == "not":
            self.next()
            return FilterTree(op="not", children=[self._parse_filter_not()])
        if self.accept("punct", "("):
            t = self._parse_filter_or()
            self.expect("punct", ")")
            return t
        return FilterTree(func=self._parse_function())

    # -- directives --------------------------------------------------------

    def _parse_directives(self, gq: GraphQuery):
        while True:
            t = self.peek()
            if not (t.kind == "punct" and t.text == "@"):
                return
            nxt = self.peek(1)
            if nxt.kind != "name":
                return
            d = nxt.text.lower()
            if d not in _DIRECTIVES:
                return
            self.next()
            self.next()
            if d == "filter":
                gq.filter = self._parse_filter()
            elif d == "normalize":
                gq.normalize = True
            elif d == "cascade":
                gq.cascade = True
            elif d == "ignorereflex":
                gq.ignore_reflex = True
            elif d == "groupby":
                gq.is_groupby = True
                self.expect("punct", "(")
                while not self.accept("punct", ")"):
                    self.accept("punct", ",")
                    if self.peek().text == ")":
                        continue
                    attr = self.expect("name").text
                    lang = ""
                    if self.accept("punct", "@"):
                        # full chain, ':'-joined (groupby.py resolves it
                        # element by element, '.' = any_value fallback)
                        lang = ":".join(self._parse_lang_chain())
                    gq.groupby_attrs.append((attr, lang))
            elif d == "facets":
                self._parse_facets(gq)
            elif d == "recurse":
                # modern-style @recurse(depth: n) — also accepted alongside
                # the v0.7 "recurse(func:...)" block-name form
                gq.args["recurse"] = "true"
                if self.accept("punct", "("):
                    while not self.accept("punct", ")"):
                        self.accept("punct", ",")
                        if self.peek().text == ")":
                            continue
                        k = self.expect("name").text
                        self.expect("punct", ":")
                        gq.args[k] = self._value_token()

    def _parse_facets(self, gq: GraphQuery):
        spec = gq.facets or FacetsSpec()
        if not self.accept("punct", "("):
            spec.all_keys = True
            gq.facets = spec
            return
        if self.accept("punct", ")"):
            spec.all_keys = True
            gq.facets = spec
            return
        first = True
        while True:
            if not first:
                if not self.accept("punct", ","):
                    break
                if self.peek().text == ")":
                    raise ParseError("trailing comma in @facets")
            first = False
            t = self.peek()
            if t.kind == "punct" and t.text == "(":
                # parenthesized filter tree: @facets((eq(a,1) or eq(b,2))
                # and ge(c,3)) — the reference's parseFilter admits a
                # leading group the same way
                gq.facets_filter = self._parse_filter_or()
                break
            if t.kind == "name" and t.text in ("orderasc", "orderdesc") and self.peek(1).text == ":":
                self.next()
                self.expect("punct", ":")
                if spec.order_key:
                    raise ParseError("only one facet order allowed")
                spec.order_key = self.expect("name").text
                spec.order_desc = t.text == "orderdesc"
            elif t.kind == "name":
                # facet key, possibly "v as key", possibly a filter tree
                if self.peek(1).kind == "name" and self.peek(1).text.lower() == "as":
                    v = self.next().text
                    self.next()
                    key = self.expect("name").text
                    spec.keys.append(key)
                    spec.aliases[key] = v
                elif self.peek(1).text == "(" or t.text.lower() == "not":
                    # facet filter tree: @facets(eq(close, true)) — the
                    # reference reverts to parseFilter when the content
                    # is not a key list, which also admits leading NOT
                    gq.facets_filter = self._parse_filter_or()
                    break
                else:
                    key = self.next().text
                    if key in spec.keys:
                        raise ParseError(f"duplicate facet key {key}")
                    spec.keys.append(key)
            else:
                raise ParseError(f"bad @facets content at {t.text!r}")
        self.expect("punct", ")")
        if spec.keys or spec.all_keys or spec.order_key or spec.aliases:
            gq.facets = spec  # filter-only @facets(...) fetches nothing

    # -- children ----------------------------------------------------------

    def _parse_children(self) -> List[GraphQuery]:
        out: List[GraphQuery] = []
        while not self.accept("punct", "}"):
            self.accept("punct", ",")
            if self.peek().text == "}":
                continue
            if self.accept("spread"):
                name = self.expect("name").text
                ph = GraphQuery(attr="...fragment", alias=name)
                out.append(ph)
                continue
            out.append(self._parse_child())
        return out

    def _parse_child(self) -> GraphQuery:
        gq = GraphQuery()
        # optional alias prefix: "alias: <anything>", including aliased
        # count()/math()/val() forms ("total: count(friends)")
        if (
            self.peek().kind == "name"
            and self.peek(1).kind == "punct"
            and self.peek(1).text == ":"
            and self.peek(2).kind in ("name", "iri")
        ):
            gq.alias = self.next().text
            self.next()
        t = self.next()
        if t.kind == "iri":
            gq.attr = t.text.strip("<>")
            if self.peek().text == "(":
                self._parse_root_args(gq)
            self._parse_directives(gq)
            if self.accept("punct", "{"):
                gq.children = self._parse_children()
            return gq
        if t.kind != "name":
            raise ParseError(f"expected attribute at offset {t.pos}, got {t.text!r}")
        name = t.text

        # "x as ..." variable definition
        if self.peek().kind == "name" and self.peek().text.lower() == "as":
            self.next()
            gq.var = name
            t = self.expect("name")
            name = t.text

        low = name.lower()
        if low == "count" and self.peek().text == "(":
            self.expect("punct", "(")
            if self.accept("punct", ")"):  # bare count(): count of uids
                gq.attr = ""
                gq.is_count = True
                self._parse_directives(gq)
                return gq
            inner = self.expect("name").text
            if inner == "var" or inner == "val":
                raise ParseError("count(val()) is not allowed")
            gq.attr = inner
            gq.is_count = True
            if self.accept("punct", "@"):
                gq.langs.extend(self._parse_lang_chain())
            self.expect("punct", ")")
        elif low in _AGG_FUNCS and self.peek().text == "(":
            self.expect("punct", "(")
            self.expect("name", "val")
            self.expect("punct", "(")
            v = self.expect("name").text
            self.expect("punct", ")")
            self.expect("punct", ")")
            gq.attr = "val"
            gq.agg_func = low
            gq.needs_var.append(VarRef(v, VALUE_VAR))
        elif low == "val" and self.peek().text == "(":
            self.expect("punct", "(")
            v = self.expect("name").text
            self.expect("punct", ")")
            gq.attr = "val"
            gq.needs_var.append(VarRef(v, VALUE_VAR))
        elif low == "math" and self.peek().text == "(":
            gq.attr = "math"
            gq.math_exp = self._parse_math()
            gq.is_internal = not bool(gq.var) and not bool(gq.alias)
        elif low == "expand" and self.peek().text == "(":
            self.expect("punct", "(")
            inner = self.expect("name").text
            if inner == "_all_":
                gq.expand = "_all_"
            elif inner == "val":
                self.expect("punct", "(")
                v = self.expect("name").text
                self.expect("punct", ")")
                gq.expand = v
                gq.needs_var.append(VarRef(v, VALUE_VAR))
            else:
                raise ParseError(f"bad expand() arg {inner!r}")
            self.expect("punct", ")")
            gq.attr = "expand"
        elif low == "checkpwd" and self.peek().text == "(":
            self.expect("punct", "(")
            gq.attr = self.expect("name").text
            self.accept("punct", ",")
            pwd = self._value_token()
            self.expect("punct", ")")
            f = Function(name="checkpwd", attr=gq.attr, args=[pwd])
            gq.func = f
        else:
            gq.attr = name
            if self.peek().kind == "punct" and self.peek().text == "@":
                nxt = self.peek(1)
                if not (nxt.kind == "name" and nxt.text.lower() in _DIRECTIVES):
                    self.next()
                    gq.langs.extend(self._parse_lang_chain())

        # (args) — pagination/order on the edge
        if self.peek().text == "(":
            self._parse_root_args(gq)
        self._parse_directives(gq)
        if self.accept("punct", "{"):
            gq.children = self._parse_children()
        return gq

    # -- math --------------------------------------------------------------

    _MATH_FUNCS = {
        "exp", "ln", "sqrt", "floor", "ceil", "since", "pow", "logbase",
        "max", "min", "cond",
    }

    def _parse_math(self) -> MathTree:
        self.expect("punct", "(")
        tree = self._math_expr(0)
        self.expect("punct", ")")
        return tree

    # Binary operator precedences — the reference's exact (all-distinct)
    # table (gql/parser.go:156 mathOpPrecedence), which with left
    # associativity reproduces its shunting-yard groupings, e.g.
    # "a + b*c/a + e - l" ⇒ (+ (+ a (* b (/ c a))) (- e l)).
    _BINOPS = {
        "/": 50, "*": 49, "%": 48, "-": 47, "+": 46,
        "<": 10, ">": 9, "<=": 8, ">=": 7, "==": 6, "!=": 5,
        "&&": 3, "and": 3, "||": 2, "or": 2,
    }

    def _math_expr(self, min_prec: int) -> MathTree:
        left = self._math_atom()
        while True:
            t = self.peek()
            op = t.text.lower() if t.kind in ("op", "name") else None
            if op not in self._BINOPS or self._BINOPS[op] < min_prec:
                return left
            self.next()
            right = self._math_expr(self._BINOPS[op] + 1)
            left = MathTree(fn=t.text if t.kind == "op" else op, children=[left, right])

    def _math_atom(self) -> MathTree:
        t = self.peek()
        if t.kind == "punct" and t.text == "(":
            self.next()
            e = self._math_expr(0)
            self.expect("punct", ")")
            return e
        if t.kind == "op" and t.text == "-":
            self.next()
            return MathTree(fn="u-", children=[self._math_atom()])
        if t.kind == "number":
            self.next()
            return MathTree(const=float(t.text))
        if t.kind == "name":
            name = t.text
            if name.lower() in self._MATH_FUNCS and self.peek(1).text == "(":
                self.next()
                self.expect("punct", "(")
                node = MathTree(fn=name.lower())
                node.children.append(self._math_expr(0))
                while self.accept("punct", ","):
                    node.children.append(self._math_expr(0))
                self.expect("punct", ")")
                return node
            self.next()
            return MathTree(var=name)
        raise ParseError(f"bad math expression at {t.text!r}")

    # -- schema request ----------------------------------------------------

    def _parse_schema_request(self) -> SchemaRequest:
        req = SchemaRequest()
        if self.accept("punct", "("):
            self.expect("name", "pred")
            self.expect("punct", ":")
            if self.accept("punct", "["):
                while not self.accept("punct", "]"):
                    self.accept("punct", ",")
                    if self.peek().text == "]":
                        continue
                    req.predicates.append(self._value_token())
            else:
                req.predicates.append(self._value_token())
            self.expect("punct", ")")
        self.expect("punct", "{")
        while not self.accept("punct", "}"):
            self.accept("punct", ",")
            if self.peek().text == "}":
                continue
            req.fields.append(self.expect("name").text)
        return req

    # -- fragments ---------------------------------------------------------

    def _expand_fragments_all(self, res: ParsedResult):
        for q in res.queries:
            self._expand_fragments(q, set())

    def _expand_fragments(self, gq: GraphQuery, seen: frozenset):
        out = []
        for c in gq.children:
            if c.attr == "...fragment":
                name = c.alias
                if name in seen:
                    raise ParseError(f"fragment cycle at {name}")
                body = self.fragments.get(name)
                if body is None:
                    raise ParseError(f"missing fragment {name}")
                import copy

                for item in body:
                    item2 = copy.deepcopy(item)
                    holder = GraphQuery(children=[item2])
                    self._expand_fragments(holder, set(seen) | {name})
                    out.extend(holder.children)
            else:
                self._expand_fragments(c, seen)
                out.append(c)
        gq.children = out

    # -- var dependency collection ------------------------------------------

    def _collect_query_vars(self, res: ParsedResult):
        for q in res.queries:
            defines: List[str] = []
            needs: List[str] = []
            self._walk_vars(q, defines, needs, is_root=True)
            res.query_vars.append((defines, needs))
        # checkDependency (gql/parser.go:605): undefined uses, duplicate
        # definitions, and defined-but-unused vars are all request errors
        flat_defs = [d for ds, _ in res.query_vars for d in ds]
        all_defs = set(flat_defs)
        if len(flat_defs) != len(all_defs):
            raise ParseError("some variables are declared multiple times")
        all_needs = {n for _ds, ns in res.query_vars for n in ns}
        unused = all_defs - all_needs
        if unused:
            raise ParseError(
                f"some variables are defined but not used: {sorted(unused)}"
            )
        for q, (_ds, ns) in zip(res.queries, res.query_vars):
            for n in ns:
                if n not in all_defs:
                    raise ParseError(f"variable {n!r} used but not defined")

    def _walk_vars(self, gq: GraphQuery, defines, needs, is_root=False):
        if gq.var:
            defines.append(gq.var)
        if gq.facets:
            defines.extend(gq.facets.aliases.values())  # "a as facetkey"
        for vr in gq.needs_var:
            needs.append(vr.name)
        if gq.func:
            for vr in gq.func.needs_vars:
                needs.append(vr.name)
        if gq.filter:
            self._walk_filter_vars(gq.filter, needs)
        if gq.math_exp:
            self._walk_math_vars(gq.math_exp, needs)
        for c in gq.children:
            self._walk_vars(c, defines, needs)

    def _walk_filter_vars(self, ft: FilterTree, needs):
        if ft.func:
            for vr in ft.func.needs_vars:
                needs.append(vr.name)
        for c in ft.children:
            self._walk_filter_vars(c, needs)

    def _walk_math_vars(self, mt: MathTree, needs):
        if mt.var:
            needs.append(mt.var)
        for c in mt.children:
            self._walk_math_vars(c, needs)


def _is_uid(s: str) -> bool:
    return bool(re.fullmatch(r"0[xX][0-9a-fA-F]+|\d+", s))


def _parse_uid(s: str) -> int:
    if s.lower().startswith("0x"):
        return int(s, 16)
    if s.isdigit():
        return int(s)
    raise ParseError(f"invalid uid {s!r}")


# Brace matching over big mutation bodies is a bulk-load hot path: any
# scheme that visits every token pays ~3 Python iterations per RDF line
# (two IRIs + a literal).  Braces themselves are RARE — section headers
# plus the odd quoted brace — so the matcher seeks candidate braces with
# C-level str.find and tokenizes ONLY the lines containing them (string
# literals, IRIs and comments never span lines, matching the reference's
# single-line lexer tokens; gql/state.go errors on unclosed strings).
_LINE_TOK_RE = re.compile(
    r'"(?:\\.|[^"\\\n])*(?:"|$)'  # string literal, line-bounded
    r"|<[^>\n]*>"                 # IRI
    r"|#[^\n]*"                   # comment
    r"|[{}]",
    re.MULTILINE,
)


def _match_brace(text: str, open_idx: int) -> int:
    """Index of the '}' matching text[open_idx] == '{' (string/comment/
    IRI aware)."""
    depth = 1
    pos = open_idx + 1
    n = len(text)
    # candidates memoize across iterations (refreshed only once passed):
    # re-finding both per loop would go quadratic on bodies dense in one
    # brace kind, e.g. literals full of '{' with a distant final '}'
    jo = jc = -2
    while pos < n:
        if -1 < jo < pos or jo == -2:
            jo = text.find("{", pos)
        if -1 < jc < pos or jc == -2:
            jc = text.find("}", pos)
        if jc == -1 and jo == -1:
            break
        cand = min(x for x in (jo, jc) if x != -1)
        # tokenize just this candidate's line (from the later of line
        # start / the char after the open brace — both token boundaries)
        ls = text.rfind("\n", 0, cand) + 1
        le = text.find("\n", cand)
        le = n if le == -1 else le
        for m in _LINE_TOK_RE.finditer(text, max(ls, open_idx + 1), le):
            c = text[m.start()]
            if c == "{":
                depth += 1
            elif c == "}":
                depth -= 1
                if depth == 0:
                    return m.start()
        pos = le + 1
    raise ParseError("unbalanced braces")


_REGEXP_ARG_RE = re.compile(
    r"(regexp\s*\(\s*[^,()]+?,\s*)/((?:\\.|[^/\\\n])*)/([a-z]*)"
)


_MUT_TOK_RE = re.compile(
    # string-literal token is LINE-bounded, like _LINE_TOK_RE's: an
    # unterminated quote must swallow at most the rest of its line, or
    # this tokenizer and _match_brace disagree about brace nesting (a
    # multi-line string here would hide real braces — and a genuine
    # top-level `mutation {` — that _match_brace still counts)
    r'"(?:\\.|[^"\\\n])*(?:"|(?=\n)|\Z)|#[^\n]*|[{}]|mutation'
)


def _find_toplevel_mutation(text: str) -> Optional[re.Match]:
    """Find 'mutation {' at brace depth 0, outside strings/comments —
    a regex search alone would match inside string literals or a
    predicate subtree named 'mutation'.  Tokenized like _match_brace
    (per-character walking is too slow for bulk bodies); string and
    comment tokens fall through untouched."""
    depth = 0
    n = len(text)
    for m in _MUT_TOK_RE.finditer(text):
        i = m.start()
        c = text[i]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
        elif c == "m":  # the literal 'mutation'
            if depth == 0 and (
                i == 0 or not (text[i - 1].isalnum() or text[i - 1] in "_.")
            ):
                j = m.end()
                while j < n and text[j].isspace():
                    j += 1
                if j < n and text[j] == "{":
                    return _FakeMatch(i, j)
    return None


class _FakeMatch:
    """Minimal match-like holder: start of keyword + index of '{'."""

    def __init__(self, start: int, brace: int):
        self._start, self.brace = start, brace

    def start(self) -> int:
        return self._start


_SECTION_AT_RE = re.compile(r"(set|delete|del|schema)\s*\{")


def _extract_mutation(text: str) -> Tuple[str, Optional[Mutation]]:
    """Cut the top-level ``mutation { set {...} delete {...} schema {...} }``
    out of the request text before lexing — N-Quad bodies are not lexable
    as query tokens (they contain bare '.', '^^', etc.).

    Single forward pass: each section's body is brace-matched exactly
    once (the earlier outer-then-per-section structure scanned every
    multi-million-quad set body twice), and anything between sections
    that is not whitespace/comment is an unknown operation (the
    reference lexer's "Invalid operation type")."""
    m = _find_toplevel_mutation(text)
    if m is None:
        return text, None
    mu = Mutation()
    n = len(text)
    i = m.brace + 1
    close_idx = None
    while i < n:
        c = text[i]
        if c in " \t\r\n":
            i += 1
            continue
        if c == "#":  # comment between sections
            j = text.find("\n", i + 1)
            i = n if j == -1 else j + 1
            continue
        if c == "}":
            close_idx = i
            break
        sm = _SECTION_AT_RE.match(text, i)
        if sm is None:
            snippet = text[i : i + 30].split("\n")[0]
            raise ParseError(f"unknown mutation section near {snippet!r}")
        o = sm.end() - 1
        c_idx = _match_brace(text, o)
        content = text[o + 1 : c_idx]
        kw = sm.group(1)
        if kw == "set":
            mu.set_nquads = content
        elif kw in ("delete", "del"):
            mu.del_nquads = content
        else:
            mu.schema = content
        i = c_idx + 1
    if close_idx is None:
        raise ParseError("unbalanced braces")
    rest = text[: m.start()] + text[close_idx + 1 :]
    return rest, mu


def parse(text: str, variables: Optional[Dict[str, str]] = None) -> ParsedResult:
    """Parse a GraphQL± request.

    Accepts either a bare query string or the HTTP JSON wrapper
    {"query": "...", "variables": {...}} (gql.Parse with Request.Http).
    """
    stripped = text.lstrip()
    gqlvars: Dict[str, str] = dict(variables or {})
    if stripped.startswith("{") and '"query"' in stripped[:400]:
        try:
            obj = json.loads(stripped)
        except json.JSONDecodeError:
            obj = None
        if isinstance(obj, dict) and "query" in obj:
            text = obj["query"]
            v = obj.get("variables") or {}
            if isinstance(v, str):
                v = json.loads(v) if v else {}
            # keep JSON lexical form: true/false/null, not True/False/None
            gqlvars.update(
                {
                    k: (val if isinstance(val, str) else json.dumps(val))
                    for k, val in v.items()
                }
            )
    text, mutation = _extract_mutation(text)
    # /re/ literals are only legal as regexp() args; quote them before
    # lexing so '/' never collides with the division operator
    text = _REGEXP_ARG_RE.sub(
        lambda m: m.group(1) + json.dumps("/" + m.group(2) + "/" + m.group(3)),
        text,
    )
    toks = _lex(text)
    p = _Parser(toks, gqlvars)
    res = p.parse()
    if mutation is not None:
        res.mutation = mutation
    return res
