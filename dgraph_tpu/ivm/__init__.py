"""dgraph_tpu.ivm — incremental view maintenance.

The write-path half of the serving story.  Before this package, every
derived view in the tree — both query-cache tiers, the arena-derived
layouts, the PR-9 tile store — keyed its freshness on the store's
GLOBAL mutation ``version``: one write anywhere invalidated every
cached hop, every memoized response, and every warm tile block, so the
cache tiers' measured QPS win evaporated exactly at the write rates a
production deployment runs at (ROADMAP item 1).  Continuous Graph
Processing (PAPERS.md) frames the fix as one mechanism with two
customers: a mutation **delta stream** whose deltas both *repair*
derived views in place and *push* re-evaluated results to standing
queries.

Three layers, all gated by ``DGRAPH_TPU_IVM`` (default on; ``0``
restores the global-version keying byte-identically):

- **Per-predicate versions** (models/store.py + :mod:`ivm.versions`) —
  the store tracks, per predicate, the version of the last mutation
  that touched it.  Cache entries key on the MAX version over the
  predicates they actually read (the ``gql.ast.referenced_preds``
  footprint for tier-2 responses, the single hop predicate for tier-1
  entries), so a mutation only invalidates entries that reference its
  predicates.  This module is the ONE sanctioned home of
  ``store.version``-derived cache keys (graftlint:
  ``naked-version-key``).
- **Delta repair** (:mod:`ivm.repair` + models/arena.py +
  ops/spgemm.py) — for the hot head, a small mutation batch is applied
  to cached hop expansions and densified tile blocks IN PLACE instead
  of dropping them (a tile delta is a scatter on one T×T block), behind
  a repair-vs-rebuild cost gate in the PR-10 planner.
- **Live queries** (:mod:`ivm.deltas` + :mod:`ivm.subs`) — the same
  delta stream powers ``POST /subscribe``: registered queries re-run
  when a predicate in their footprint mutates and PUSH the new result
  (SSE / gRPC server-stream), cancellable via PR-11 ``CancelToken``,
  quota-bounded per tenant, traced by the PR-7 flight recorder.

docs/deploy.md "Incremental view maintenance" covers the knobs and the
operator surface.
"""

from dgraph_tpu.ivm.deltas import DeltaStream, attach_stream
from dgraph_tpu.ivm.versions import (
    hop_version,
    ivm_enabled,
    result_version,
    version_for,
)

__all__ = [
    "DeltaStream",
    "attach_stream",
    "hop_version",
    "ivm_enabled",
    "result_version",
    "version_for",
]
