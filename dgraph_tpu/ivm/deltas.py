"""The mutation delta stream: a bounded, versioned ring of store edits.

Published from the store's commit path (models/store.py — every
``apply``/bulk/schema/delete site, which is also where the WAL journals
on durable stores, so "journaled" and "streamed" are the same event),
consumed by the live-query notifier (ivm/subs.py).  Cache and arena
*repair* deliberately does NOT consume this stream: the store's
existing per-predicate journal (``store.delta``) already carries exact
(src, dst, ±1) batches to ``ArenaManager.refresh`` under the
serialization the arenas need, and repair rides that path
(models/arena.py).  The stream's job is the PUSH half: "which
predicates changed, at which version, with which edges" — delivered to
subscribers that cannot sit inside the write lock.

Events are plain tuples ``(seq, version, pred, kind, src, dst, sign)``:

- ``kind="edge"`` — one uid-edge add (+1) or delete (-1); direction is
  the sign, the predicate names the posting list.
- ``kind="pred"`` — a whole-predicate change with no per-edge form
  (value mutations, bulk loads, predicate deletes): subscribers treat
  every view over ``pred`` as dirty.
- ``kind="epoch"`` — a non-scopeable change (schema mutation,
  full-store replacement): everything is dirty.

Bounded by ``DGRAPH_TPU_IVM_STREAM_CAP`` (default 65536 events):
overflow drops the OLDEST events and any reader whose cursor fell off
the tail is told so (``lost=True``) and must treat all predicates as
dirty — exactly the degradation a lagging subscriber can survive.

Thread-safety: publishers (mutations) are serialized by the server's
write lock; the ring's own lock only bridges to readers (notifier
threads), so per-edge publication is one short lock hold + append.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import List, Optional, Tuple

from dgraph_tpu.utils.metrics import IVM_DELTAS, IVM_STREAM_DROPPED

_DEFAULT_CAP = 65536


def _cap() -> int:
    try:
        return max(16, int(os.environ.get("DGRAPH_TPU_IVM_STREAM_CAP",
                                          _DEFAULT_CAP)))
    except ValueError:
        return _DEFAULT_CAP


Event = Tuple[int, int, str, str, int, int, int]


class DeltaStream:
    """One per store.  See the module docstring for the event model."""

    # graftcheck tier 3: publishers (mutation path) and the notifier
    # both advance these under _cond — witnessed when armed
    __race_fields__ = frozenset({"_seq", "_dropped"})

    def __init__(self, cap: Optional[int] = None):
        self._cap = cap if cap is not None else _cap()
        self._ring: "deque[Event]" = deque(maxlen=self._cap)
        self._cond = threading.Condition()
        self._seq = 0          # seq of the NEXT event to be published
        self._dropped = 0      # events lost to ring overflow (monotonic)

    # -- publication (store commit path) ------------------------------------

    def publish_edge(
        self, pred: str, src: int, dst: int, sign: int, version: int
    ) -> None:
        self._publish((pred, "edge", int(src), int(dst), int(sign)), version)

    def publish_pred(self, pred: str, version: int) -> None:
        """Whole-predicate change (value mutation, bulk load, delete)."""
        self._publish((pred, "pred", 0, 0, 0), version)

    def publish_epoch(self, version: int) -> None:
        """Non-scopeable change (schema, full-store replacement)."""
        self._publish(("", "epoch", 0, 0, 0), version)

    def _publish(self, body: tuple, version: int) -> None:
        pred, kind, src, dst, sign = body
        with self._cond:
            if len(self._ring) == self._cap:
                self._dropped += 1
                IVM_STREAM_DROPPED.add(1)
            self._ring.append(
                (self._seq, int(version), pred, kind, src, dst, sign)
            )
            self._seq += 1
            self._cond.notify_all()
        IVM_DELTAS.add(kind)

    # -- consumption (notifier threads) --------------------------------------

    @property
    def seq(self) -> int:
        """Seq the NEXT published event will carry (== count ever
        published)."""
        with self._cond:
            return self._seq

    @property
    def dropped(self) -> int:
        return self._dropped

    def read_since(self, cursor: int) -> Tuple[List[Event], int, bool]:
        """Events with seq >= ``cursor``: (events, next_cursor, lost).
        ``lost=True`` means the cursor fell off the ring's tail — the
        reader missed events and must treat every predicate as dirty."""
        with self._cond:
            if not self._ring:
                return [], self._seq, cursor < self._seq
            first = self._ring[0][0]
            lost = cursor < first
            evs = [e for e in self._ring if e[0] >= cursor]
            return evs, self._seq, lost

    def wait_for(self, cursor: int, timeout: Optional[float] = None) -> bool:
        """Block until an event with seq >= ``cursor`` exists (or
        timeout).  Returns whether one does."""
        with self._cond:
            return self._cond.wait_for(
                lambda: self._seq > cursor, timeout=timeout
            )

    def snapshot(self) -> dict:
        """/debug introspection: cap / live length / seq / dropped."""
        with self._cond:
            return {
                "cap": self._cap,
                "len": len(self._ring),
                "seq": self._seq,
                "dropped": self._dropped,
            }


def attach_stream(store) -> DeltaStream:
    """Attach (or return the existing) DeltaStream on a store.  The
    store publishes to ``store.delta_stream`` when the attribute is set
    — None (the default on every store) costs one attribute read per
    mutation."""
    ds = getattr(store, "delta_stream", None)
    if ds is None:
        ds = DeltaStream()
        store.delta_stream = ds
    return ds
