"""Delta repair of cached hop expansions — the segmented-array math.

A tier-1 hop-cache entry is ``(out_flat, seg_ptr)`` for a frontier
``src``: targets grouped by frontier row, ascending within each group
(cache/hop.py).  A small uid-edge delta against the SAME predicate
changes that value in a purely local way — an added edge ``(s, d)``
inserts ``d`` into the segment of every row whose frontier uid is
``s``; a deleted edge removes it — so the entry can be repaired with
one ``np.delete`` + one ``np.insert`` pass instead of being dropped and
re-expanded on the next hit.  The result is byte-identical to
re-running the expansion over the post-delta arena (pinned by the
repair-equals-rebuild property tests in tests/test_ivm.py): the CSR
flat layout is sorted by (row, dst), which is exactly the order the
insert positions reproduce.

Callers (models/arena.py → cache/hop.py) gate the work with
``query/planner.py::repair_route`` and only hand over deltas the store
journal vouches for: adds did not exist, deletes did.  A delete naming
an absent target means the entry does NOT reflect the pre-delta store —
``None`` tells the caller to drop it rather than guess.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def repair_hop_entry(
    out: np.ndarray,
    seg_ptr: np.ndarray,
    src: np.ndarray,
    adds: np.ndarray,
    dels: np.ndarray,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Apply ``adds``/``dels`` (int64[k, 2] (src, dst) pairs) to one
    cached expansion over frontier ``src``.  Returns the repaired
    ``(out, seg_ptr)`` — fresh arrays, the entry value is shared with
    readers and must never mutate in place — or None when the delta is
    inconsistent with the entry.  Delta edges whose source is not in
    the frontier are no-ops (the expansion never read that row)."""
    n = len(src)
    # uid → frontier rows (duplicates legal: ordered roots may repeat)
    order = np.argsort(src, kind="stable")
    ssrc = src[order]
    ins: list = []      # (original position, value, row)
    del_pos: list = []  # (original position, row)
    for arr, sign in ((dels, -1), (adds, +1)):
        for s, d in arr:
            lo = int(np.searchsorted(ssrc, s, side="left"))
            hi = int(np.searchsorted(ssrc, s, side="right"))
            for i in order[lo:hi]:
                i = int(i)
                a, b = int(seg_ptr[i]), int(seg_ptr[i + 1])
                j = a + int(np.searchsorted(out[a:b], d))
                if sign > 0:
                    ins.append((j, int(d), i))
                else:
                    if j >= b or int(out[j]) != d:
                        return None  # entry predates a state with (s, d)
                    del_pos.append((j, i))
    if not ins and not del_pos:
        return out, seg_ptr
    row_delta = np.zeros(n, dtype=np.int64)
    dp = np.array(sorted(p for p, _i in del_pos), dtype=np.int64)
    out2 = np.delete(out, dp) if len(dp) else np.asarray(out)
    for _p, i in del_pos:
        row_delta[i] -= 1
    if ins:
        # positions were computed against the ORIGINAL array: shift each
        # by the deletions before it, and keep (pos, value) order so
        # same-position inserts land ascending within their segment
        ins.sort(key=lambda t: (t[0], t[1]))
        pos = np.array([p for p, _v, _i in ins], dtype=np.int64)
        vals = np.array([v for _p, v, _i in ins], dtype=out.dtype)
        pos -= np.searchsorted(dp, pos, side="left")
        out2 = np.insert(out2, pos, vals)
        for _p, _v, i in ins:
            row_delta[i] += 1
    seg2 = np.asarray(seg_ptr).copy()
    seg2[1:] += np.cumsum(row_delta)
    return out2.astype(np.int64, copy=False), seg2
