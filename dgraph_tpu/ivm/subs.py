"""Live-query subscriptions: standing queries pushed on affecting writes.

The serving scenario the reference Dgraph never had (Continuous Graph
Processing, PAPERS.md): a client registers a read-only query once and
the server PUSHES re-evaluated results whenever a mutation touches a
predicate in the query's footprint — the same
``gql.ast.referenced_preds`` walk that scopes cache invalidation
decides which subscriptions a delta wakes, so an unrelated-predicate
write costs every subscription nothing.

Shape:

- :class:`SubscriptionRegistry` — one per server.  ``register`` parses
  the query (mutations rejected), computes its predicate footprint,
  enforces quotas (global ``DGRAPH_TPU_SUBS_MAX``, per-tenant from the
  PR-11 QoS table's ``max_subs`` or ``DGRAPH_TPU_SUBS_PER_TENANT``),
  and runs an initial evaluation so the consumer starts from a
  snapshot.
- A single **notifier thread** tails the store's mutation delta stream
  (ivm/deltas.py).  Edge/pred events mark subscriptions whose footprint
  contains the predicate dirty; epoch events (schema, snapshot
  restore) and ring overflow mark ALL dirty.  Dirty subscriptions
  re-evaluate — through the cohort scheduler when one is armed, so
  re-evaluations ride the result cache, QoS admission and singleflight
  like any client read — debounced per subscription
  (``DGRAPH_TPU_SUBS_DEBOUNCE_MS``) so a write burst coalesces into one
  push.
- A push happens only when the re-evaluated response DIFFERS from the
  last pushed one (canonical-JSON digest): that difference is the
  delta a subscriber observes; byte-identical re-evaluations count as
  ``skip`` in ``dgraph_subscription_events_total``.
- Every subscription carries a PR-11 :class:`CancelToken`: unsubscribe,
  server shutdown and per-eval failures flip it, and a mid-flight
  evaluation stops at the engine's next hop-dispatch checkpoint.
- Evaluations head-sample through the PR-7 flight recorder
  (``subs.eval`` root span with the usual engine/cache children); a
  sampled push carries its ``trace_id`` so the delivered event links
  straight into ``/debug/traces``.

Transport is the serving layer's business: serve/server.py exposes
``POST /subscribe`` (register; SSE-streams inline when the client asks
for ``text/event-stream``), ``GET /subscribe?id=`` (attach), ``POST
/subscribe/cancel?id=`` — and serve/grpc_server.py mirrors it as the
``/protos.Dgraph/Subscribe`` server-stream.  Each subscription buffers
at most ``DGRAPH_TPU_SUBS_QUEUE`` undelivered events; a slower consumer
loses the OLDEST (counted ``lagged``) — a live query's contract is
"current result, promptly", never "every intermediate state".
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Dict, List, Optional, Set

from dgraph_tpu import obs
from dgraph_tpu.sched import qos as _qos
from dgraph_tpu.utils.env import env_float as _env_f
from dgraph_tpu.utils.metrics import (
    SUBS_ACTIVE,
    SUBS_EVALS,
    SUBS_EVENTS,
    SUBS_SHED,
    note_swallowed,
)


def subs_enabled() -> bool:
    """DGRAPH_TPU_SUBS gate (default on; the registry additionally
    needs IVM on and a store with a delta stream)."""
    return os.environ.get("DGRAPH_TPU_SUBS", "1") != "0"


def _env_i(name: str, default: int) -> int:
    return int(_env_f(name, default))


class SubQuotaError(RuntimeError):
    """Registration refused: the tenant (or the server) is at its
    subscription cap.  Maps to HTTP 429 / gRPC RESOURCE_EXHAUSTED."""

    def __init__(self, msg: str, tenant: str = "", retry_after: float = 1.0):
        super().__init__(msg)
        self.tenant = tenant
        self.retry_after = retry_after


def _digest(obj) -> bytes:
    return hashlib.blake2b(
        json.dumps(obj, sort_keys=True, default=str).encode(),
        digest_size=16,
    ).digest()


class Subscription:
    """One registered live query.  Single-consumer event queue."""

    def __init__(
        self,
        sid: str,
        text: str,
        variables: Optional[dict],
        parsed,
        footprint: Optional[Set[str]],
        tenant: str,
        queue_cap: int,
    ):
        self.id = sid
        self.text = text
        self.variables = variables
        self.parsed = parsed
        self.footprint = footprint  # None = every predicate affects it
        self.tenant = tenant
        self.token = _qos.CancelToken(None, tenant=tenant or "default")
        self.created = time.monotonic()
        self.seq = 0            # events pushed so far
        self.evals = 0
        self.dropped = 0        # events a slow consumer lost
        self.last_digest: Optional[bytes] = None
        self.last_eval = 0.0    # monotonic time of the last evaluation
        self.pending: Optional[Set[str]] = set()  # dirty preds; None=all
        self._q: List[dict] = []
        self._cap = max(1, queue_cap)
        self._cond = threading.Condition()
        # serializes evaluations of THIS subscription: the register
        # thread's snapshot eval and the notifier's update evals must
        # not interleave their seq/digest bookkeeping (snapshot-first
        # event order is part of the contract)
        self._eval_lock = threading.Lock()

    # -- consumer side -------------------------------------------------------

    def next_event(self, timeout: Optional[float] = None) -> Optional[dict]:
        """Pop the next undelivered event, blocking up to ``timeout``
        (None on timeout — the transport writes a heartbeat and keeps
        waiting).  Returns a terminal ``{"kind": "cancelled"}`` event
        once after the token flips with the queue drained."""
        with self._cond:
            self._cond.wait_for(
                lambda: self._q or self.token.cancelled, timeout=timeout
            )
            if self._q:
                return self._q.pop(0)
            if self.token.cancelled:
                return {
                    "kind": "cancelled",
                    "sub_id": self.id,
                    "reason": self.token.reason,
                }
            return None

    # -- registry side -------------------------------------------------------

    def _push(self, event: dict) -> None:
        with self._cond:
            if len(self._q) >= self._cap:
                self._q.pop(0)
                self.dropped += 1
                SUBS_EVENTS.add("lagged")
            self._q.append(event)
            self._cond.notify_all()

    def _wake(self) -> None:
        with self._cond:
            self._cond.notify_all()

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "tenant": self.tenant,
            "preds": (
                sorted(self.footprint) if self.footprint is not None else None
            ),
            "seq": self.seq,
            "evals": self.evals,
            "dropped": self.dropped,
            "cancelled": self.token.cancelled,
            "queued": len(self._q),
        }


class SubscriptionRegistry:
    """Owns the subscriptions and the delta-stream notifier thread."""

    def __init__(self, server, stream):
        self._server = server
        self._stream = stream
        self._lock = threading.Lock()
        self._subs: Dict[str, Subscription] = {}
        self._by_tenant: Dict[str, int] = {}
        self._stopped = False
        self._seq = 0
        self.max_total = _env_i("DGRAPH_TPU_SUBS_MAX", 256)
        self.per_tenant_default = _env_i("DGRAPH_TPU_SUBS_PER_TENANT", 64)
        self.queue_cap = _env_i("DGRAPH_TPU_SUBS_QUEUE", 64)
        self.debounce_s = _env_f("DGRAPH_TPU_SUBS_DEBOUNCE_MS", 10.0) / 1e3
        self.eval_timeout_s = _env_f("DGRAPH_TPU_SUBS_EVAL_TIMEOUT_S", 10.0)
        self._thread = threading.Thread(
            target=self._notify_loop, name="dgraph-subs", daemon=True
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            subs = list(self._subs.values())
        for sub in subs:
            sub.token.cancel("shutdown")
            sub._wake()
        # wake the notifier out of its stream wait: one epoch-shaped
        # nudge through the ring it is already blocked on.  A server
        # constructed but never start()ed has no thread to join.
        if self._thread.ident is not None:
            self._stream.publish_epoch(-1)
            self._thread.join(timeout=5)

    # -- registration ---------------------------------------------------------

    def _tenant_cap(self, tenant: str) -> int:
        sched = self._server.scheduler
        if sched is not None and sched.qos is not None:
            cap = sched.qos.tenant(tenant).max_subs
            if cap > 0:
                return cap
        return self.per_tenant_default

    def register(
        self, text: str, variables: Optional[dict] = None, tenant: str = ""
    ) -> Subscription:
        """Parse, quota-check, admit, and run the initial snapshot
        evaluation.  Raises ValueError/ParseError on a bad or mutating
        request and SubQuotaError over quota."""
        from dgraph_tpu import gql
        from dgraph_tpu.gql.ast import referenced_preds

        parsed = gql.parse(text, variables)
        if parsed.mutation is not None:
            raise ValueError("subscriptions are read-only; mutation refused")
        if not parsed.queries:
            raise ValueError("subscription has no query block")
        tenant = _qos.resolve_tenant(tenant)
        footprint = referenced_preds(parsed.queries)
        sub = Subscription(
            "", text, variables, parsed, footprint, tenant, self.queue_cap,
        )
        # hold the sub's eval lock ACROSS table insertion: a mutation
        # landing between insert and the snapshot evaluation wakes the
        # notifier, which then BLOCKS here until the snapshot pushed —
        # the first delivered event is always the snapshot, and the
        # post-mutation update that follows legally dedups against it
        sub._eval_lock.acquire()
        try:
            self._admit(sub, tenant)
            self._evaluate_locked(sub, trigger=None, kind="snapshot")
        finally:
            sub._eval_lock.release()
        return sub

    def _admit(self, sub: Subscription, tenant: str) -> None:
        """Quota-check and insert (caller holds the sub's eval lock)."""
        with self._lock:
            if self._stopped:
                raise SubQuotaError("server shutting down", tenant)
            if len(self._subs) >= self.max_total:
                SUBS_SHED.add("cap")
                raise SubQuotaError(
                    f"subscription cap reached ({self.max_total})", tenant
                )
            cap = self._tenant_cap(tenant)
            have = self._by_tenant.get(tenant, 0)
            if cap > 0 and have >= cap:
                SUBS_SHED.add("quota")
                raise SubQuotaError(
                    f"tenant {tenant!r} over subscription quota "
                    f"({have}/{cap})",
                    tenant,
                )
            self._seq += 1
            sub.id = f"sub-{self._seq:x}-{os.getpid():x}"
            self._subs[sub.id] = sub
            self._by_tenant[tenant] = have + 1
            SUBS_ACTIVE.set(len(self._subs))

    def get(self, sid: str) -> Optional[Subscription]:
        with self._lock:
            return self._subs.get(sid)

    def cancel(self, sid: str, reason: str = "unsubscribe") -> bool:
        """Flip the subscription's token and drop it from the table.
        The consumer drains its queue, then sees one terminal
        ``cancelled`` event."""
        with self._lock:
            sub = self._subs.pop(sid, None)
            if sub is not None:
                left = self._by_tenant.get(sub.tenant, 0) - 1
                if left > 0:
                    self._by_tenant[sub.tenant] = left
                else:
                    self._by_tenant.pop(sub.tenant, None)
                SUBS_ACTIVE.set(len(self._subs))
        if sub is None:
            return False
        sub.token.cancel(reason)
        sub._wake()
        return True

    # -- the notifier ---------------------------------------------------------

    def _notify_loop(self) -> None:
        cursor = self._stream.seq
        next_due: Optional[float] = None
        while True:
            if next_due is None:
                self._stream.wait_for(cursor, timeout=1.0)
            else:
                self._stream.wait_for(
                    cursor, timeout=max(1e-3, next_due - time.monotonic())
                )
            if self._stopped:
                return
            events, cursor, lost = self._stream.read_since(cursor)
            dirty: Optional[Set[str]] = set()
            for _seq, _ver, pred, kind, _s, _d, _sg in events:
                if kind == "epoch":
                    dirty = None
                    break
                if dirty is not None:
                    dirty.add(pred)
            if lost:
                dirty = None  # fell off the ring: treat everything dirty
            next_due = self._mark_and_run(dirty)

    def _mark_and_run(self, dirty: Optional[Set[str]]) -> Optional[float]:
        """Fold freshly-dirty predicates into each affected
        subscription's pending set, evaluate the ones past their
        debounce window, and return the earliest debounce deadline
        still pending (None when nothing waits)."""
        with self._lock:
            subs = list(self._subs.values())
        now = time.monotonic()
        next_due = None
        for sub in subs:
            if sub.token.cancelled:
                continue
            # fold this round's triggers into what's pending.  An EMPTY
            # dirty set (idle timeout tick) adds nothing for ANY
            # footprint shape — it only gives carried-over pendings a
            # chance past their debounce window; a footprint-unknown
            # sub (expand()/_predicate_) is affected by every non-empty
            # round, never by silence.
            if dirty is None:
                sub.pending = None
            elif dirty:
                if sub.footprint is None:
                    sub.pending = None
                elif sub.pending is not None:
                    sub.pending |= dirty & sub.footprint
            if sub.pending is not None and not sub.pending:
                continue  # nothing triggered, nothing carried over
            due = sub.last_eval + self.debounce_s
            if now < due:
                next_due = due if next_due is None else min(next_due, due)
                continue
            trigger = sub.pending
            sub.pending = set()
            self._evaluate(sub, trigger=trigger, kind="update")
        return next_due

    # -- evaluation -----------------------------------------------------------

    def _evaluate(self, sub: Subscription, trigger, kind: str) -> None:
        """Re-run one subscription and push iff the result changed.
        Retryable backpressure (scheduler sheds) re-marks the triggers
        and tries again after the debounce window; hard failures cancel
        the subscription (counted, pushed as the terminal event) — a
        standing query that can no longer evaluate must say so, not
        silently go dark."""
        from dgraph_tpu.sched.cohort import (
            SchedDeadlineError,
            SchedOverloadError,
        )

        if sub.token.cancelled:
            return
        with sub._eval_lock:
            self._evaluate_locked(sub, trigger, kind)

    def _evaluate_locked(self, sub: Subscription, trigger, kind: str) -> None:
        """_evaluate's body; the caller holds ``sub._eval_lock``
        (register() holds it ACROSS table insertion so the snapshot
        always lands before any notifier update)."""
        from dgraph_tpu.sched.cohort import (
            SchedDeadlineError,
            SchedOverloadError,
        )

        if sub.token.cancelled:
            return
        sub.last_eval = time.monotonic()
        sub.evals += 1
        SUBS_EVALS.add(1)
        root = obs.start_request("subs.eval")
        tid = None
        try:
            if root is not None:
                tid = root.trace_id
                root.set_attr("sub_id", sub.id)
                root.set_attr("kind", kind)
                if trigger:
                    root.set_attr("preds", sorted(trigger))
                root.__enter__()
            try:
                result = self._run(sub)
            finally:
                if root is not None:
                    root.__exit__(None, None, None)
        except _qos.QueryCancelledError:
            return  # token flipped mid-eval: terminal event follows
        except (SchedOverloadError, SchedDeadlineError) as e:
            # 429/504-class backpressure is RETRYABLE by PR-11's own
            # contract: keep the subscription, restore its triggers,
            # and let the next notifier round (≤1s idle tick +
            # debounce) try again — a load spike must not tear down
            # every standing query that re-evaluated during it
            note_swallowed("subs.eval_deferred", e)
            SUBS_EVENTS.add("deferred")
            if trigger is None:
                sub.pending = None
            elif sub.pending is not None:
                sub.pending |= set(trigger)
            return
        except Exception as e:  # noqa: BLE001 — delivered, counted
            note_swallowed("subs.eval", e)
            SUBS_EVENTS.add("error")
            self.cancel(sub.id, reason=f"eval failed: {e}")
            return
        dg = _digest(result)
        if kind != "snapshot" and dg == sub.last_digest:
            SUBS_EVENTS.add("skip")
            return
        sub.last_digest = dg
        sub.seq += 1
        store_ver = getattr(self._server.store, "version", 0)
        sub._push({
            "kind": kind,
            "sub_id": sub.id,
            "seq": sub.seq,
            "version": store_ver,
            "preds": sorted(trigger) if trigger else None,
            "trace_id": tid,
            "data": result,
        })
        SUBS_EVENTS.add("push")

    def _run(self, sub: Subscription) -> dict:
        """One evaluation over the current store — through the cohort
        scheduler when armed (result cache + QoS + singleflight apply
        to subscription traffic exactly like client reads), else the
        direct read-locked path."""
        srv = self._server
        if srv.scheduler is not None:
            vkey = (
                json.dumps(sub.variables, sort_keys=True)
                if sub.variables else ""
            )
            result, _stats = srv.scheduler.run(
                sub.parsed,
                debug=False,
                timeout_s=self.eval_timeout_s,
                key=(sub.text, vkey, False),
                tenant=sub.tenant if srv.scheduler.qos is not None else "",
                cancel=sub.token,
            )
            return result
        from dgraph_tpu.query.engine import QueryEngine

        with srv._engine_lock.read():
            eng = QueryEngine(srv.store, arenas=srv.engine.arenas)
            eng.chain_threshold = srv.engine.chain_threshold
            eng.cancel = sub.token
            return eng.run_parsed(sub.parsed)

    # -- introspection ---------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            subs = [s.to_dict() for s in self._subs.values()]
        return {
            "active": len(subs),
            "max_total": self.max_total,
            "per_tenant_default": self.per_tenant_default,
            "stream": self._stream.snapshot(),
            "subs": subs,
        }
