"""Predicate-scoped cache versions — the footprint-freshness primitive.

Both cache tiers (cache/core.py) admit and probe entries under a caller
-supplied integer version: an entry recorded under any OLDER version
can never match.  Before IVM that integer was the store's global
``version`` — correct, but maximally pessimistic: one write anywhere
killed every entry.  These helpers substitute the tightest correct
version for a given read:

    version_for(store, preds) = max(pred_floor,
                                    max(pred_versions[p] for p in preds))

where ``pred_versions[p]`` is the version of the last mutation that
touched predicate ``p`` and ``pred_floor`` is the last NON-scopeable
mutation (schema changes, full-store replacement) — reads that touch
none of a mutation's predicates keep their cached version, so their
entries stay hits.

Correctness argument (the same stale-keyed-never-stale-served shape the
tiers already rely on): a response/expansion is a pure function of the
predicates it reads.  If no predicate in the footprint mutated between
fill and probe, the footprint version is unchanged and the cached value
is byte-identical to a re-execution; if any did, its pred version (and
hence the max) advanced past the entry's, and the entry can never be
served again.  Footprints err on the side of INCLUSION
(gql.ast.referenced_preds) and fall back to the global version when the
predicate set is not statically knowable (``expand()``/``_predicate_``)
or the store predates per-pred tracking (duck-typed cluster stores).

This module is the ONE sanctioned home of ``store.version``-derived
cache keys: the graftlint rule ``naked-version-key`` flags new bare
reads in cache//query//sched//serve/ so future tiers land here instead
of quietly regrowing the global-invalidation behavior.

Gate: ``DGRAPH_TPU_IVM`` (default on).  ``0`` restores the bare global
version for every helper — byte-identical keying to the pre-IVM tree.
"""

from __future__ import annotations

import os
from typing import Optional


def ivm_enabled() -> bool:
    """The DGRAPH_TPU_IVM gate (default ON; ``0`` restores global
    ``store.version`` cache keying byte-identically)."""
    return os.environ.get("DGRAPH_TPU_IVM", "1") != "0"


def version_for(store, preds) -> Optional[int]:
    """The cache version scoped to ``preds`` (an iterable of predicate
    names), or the store's global version when ``preds`` is None, the
    store has no per-predicate tracking, or IVM is off.  None when the
    store has no version at all (version-less duck stores never
    cache)."""
    ver = getattr(store, "version", None)
    if ver is None:
        return None
    if not ivm_enabled() or preds is None:
        return ver
    pv = getattr(store, "pred_versions", None)
    if pv is None:
        return ver
    floor = getattr(store, "pred_floor", 0)
    return max(floor, max((pv.get(p, 0) for p in preds), default=0))


def hop_version(store, pred: str) -> Optional[int]:
    """Tier-1 (hop cache) version for one predicate's expansion: the
    reverse direction reads the same predicate's data, so direction
    never enters the version."""
    return version_for(store, (pred,))


def _footprint(parsed):
    """The referenced-predicate footprint of a parsed request, memoized
    on the parsed object (the cached-hit fast path probes per request;
    the AST walk should run once, not once per probe)."""
    fp = getattr(parsed, "_ivm_footprint", False)
    if fp is False:
        from dgraph_tpu.gql.ast import referenced_preds

        fp = referenced_preds(parsed.queries)
        try:
            parsed._ivm_footprint = fp
        except AttributeError:  # slotted/frozen parse results: recompute
            pass
    return fp


def result_version(store, parsed) -> Optional[int]:
    """Tier-2 (result cache) version for a parsed read request: scoped
    to its statically-known predicate footprint, global when that is
    unknowable (expand()/_predicate_ read schema-driven predicate
    lists).  A schema-only request has an EMPTY footprint and keys on
    the floor — apply_schema bumps the floor, so schema responses stay
    exactly as fresh as before."""
    return version_for(store, _footprint(parsed))
