"""Mesh serving plane (PR 17): one server driving the whole TPU mesh.

``parallel/mesh.py`` is the kernel library — shard_map steps, row
sharding, the packed cross-chip reassembly.  This package is the plane
that makes those kernels a first-class serving backend:

- :mod:`dgraph_tpu.mesh.plan` — ``MeshPlan``: predicate→shard placement
  (which chip holds a predicate's shard 0), persisted and
  rebalance-aware, so co-resident predicates don't all pile their
  heaviest row shard on the same chip.
- :mod:`dgraph_tpu.mesh.programs` — the multi-hop mesh program whose
  cross-chip frontier exchange (all_gather/psum of bucketed frontier
  buffers) happens INSIDE the compiled hop program, with the frontier
  carry donated across levels (no host round trip between hops).
- :mod:`dgraph_tpu.mesh.executor` — ``MeshExecutor``: the engine-facing
  entry points (one-hop expand, fused multi-hop) that slot in behind
  ``DeviceExpander``/``chain`` as the planner-priced ``route:mesh``,
  devguard-bracketed under the "mesh" fault domain and ledger-charged
  (per-chip device time + exchange bytes).
- :mod:`dgraph_tpu.mesh.fault` — ``MeshFaultDomain`` (PR 20): the
  elastic fault domain that turns chip loss into a CAPACITY event —
  per-chip devguard sub-domains, epoch-fenced re-sharding onto the
  surviving sub-mesh, drain-and-resume for in-flight segmented
  queries, and warm-then-cutover staged rejoin of healed chips.

``DGRAPH_TPU_MESH`` tri-state (serve/server.py::_auto_mesh): "0"/"off"
never (byte-identical unsharded serving), "1"/"auto"/unset on when >1
device is visible, "force" always.  ``DGRAPH_TPU_MESH_ELASTIC=0``
keeps the mesh but restores the PR 17 whole-plane fault latch.
"""

from dgraph_tpu.mesh.executor import MeshExecutor
from dgraph_tpu.mesh.fault import MeshFaultDomain
from dgraph_tpu.mesh.plan import MeshPlan

__all__ = ["MeshExecutor", "MeshFaultDomain", "MeshPlan"]
