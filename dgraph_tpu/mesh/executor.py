"""MeshExecutor: the engine-facing entry points of the mesh plane.

``parallel/mesh.py`` exposes raw kernels; the executor is what the
serving path actually calls — one-hop sharded expansion behind
``DeviceExpander`` (query/engine.py::_mesh_expand) and the fused
multi-hop scan behind ``chain`` (query/chain.py::_try_mesh_chain).
Both entry points carry the full serving contract the kernels alone
don't:

- **fault domain**: every dispatch runs under the ``"mesh"`` device
  guard (utils/devguard.py) — ``DeviceFaultError`` propagates to the
  caller, which re-plans the level/chain unsharded (the PR 15
  degrade-to-unsharded path the ``device.mesh`` failpoint drives).
- **ledger attribution**: wall time inside mesh programs, the mesh
  width it ran on (per-chip time under SPMD = wall × width), and the
  estimated cross-chip exchange payload land on the request's ledger
  (obs/ledger.py ``mesh_ms``/``mesh_chips``/``exchange_bytes``).
- **placement**: sharded arenas come via ``ArenaManager.sharded_csr``,
  which applies the ``MeshPlan`` roll — the executor never sees an
  unplaced arena.
"""

from __future__ import annotations

import time
from typing import Tuple

import numpy as np

from dgraph_tpu import obs, ops
from dgraph_tpu.obs import ledger as _ledger
from dgraph_tpu.utils import devguard


class MeshExecutor:
    """Serving-path executor over one ArenaManager's mesh.

    Cheap to construct (holds no device state of its own — the sharded
    arenas and compiled steps are the manager's/module caches' assets);
    ArenaManager memoizes one per manager (``mesh_executor()``)."""

    def __init__(self, arenas):
        self.arenas = arenas  # models/arena.py::ArenaManager

    @property
    def mesh(self):
        return self.arenas.mesh

    @property
    def width(self) -> int:
        """Model-axis width — the chips one dispatch spans."""
        m = self.mesh
        return int(m.shape["model"]) if m is not None else 1

    def allowed(self) -> bool:
        """May the mesh domain be dispatched to right now (devguard
        latch + half-open probe)?"""
        return devguard.get("mesh").allowed()

    # -- entry points --------------------------------------------------------

    def expand(
        self, attr: str, reverse: bool, src: np.ndarray, cap: int, stats: dict
    ) -> Tuple[np.ndarray, np.ndarray]:
        """One engine-level sharded expansion (the route:mesh leaf).
        Returns (out, seg_ptr) byte-identical to the single-device
        expand; raises ``devguard.DeviceFaultError`` on a classified
        chip fault / wedged collective (guard enabled) so the caller
        re-plans unsharded."""
        from dgraph_tpu.parallel.mesh import sharded_expand_segments

        sharded = self.arenas.sharded_csr(attr, reverse=reverse)

        def _dispatch():
            with obs.stage(stats, "device_expand_ms"):
                return sharded_expand_segments(self.mesh, sharded, src, cap)

        t0 = time.perf_counter()
        mg = devguard.get("mesh")
        if not devguard.enabled():
            out, seg_ptr = _dispatch()
        else:
            out, seg_ptr = mg.run("mesh.expand", _dispatch)
        self._charge(
            h2d=int(src.nbytes),
            d2h=int(out.nbytes + seg_ptr.nbytes),
            cap=cap,
            hops=1,
            wall_ms=(time.perf_counter() - t0) * 1e3,
        )
        return out, seg_ptr

    def multi_hop(
        self,
        attr: str,
        reverse: bool,
        src: np.ndarray,
        n_hops: int,
        cap: int,
        stats: dict,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """The fused multi-hop chain over the mesh: ONE compiled program
        whose cross-chip frontier exchange happens between scan levels
        on the interconnect (mesh/programs.py), no host round trip per
        hop.  Returns (frontiers int64-convertible int32[n_hops, cap],
        totals int32[n_hops]) — per-level sorted-unique-padded
        frontiers matching the unsharded scan driver (ops.multi_hop
        with track_visited=False) value-for-value.

        Raises ``devguard.DeviceFaultError`` under the guard exactly
        like :meth:`expand`; the chain then declines the fused path and
        the per-level ladder (which re-plans unsharded on the latched
        domain) takes over."""
        from dgraph_tpu.mesh.programs import mesh_multi_hop_step
        from dgraph_tpu.utils.failpoints import fail

        sharded = self.arenas.sharded_csr(attr, reverse=reverse)
        step = mesh_multi_hop_step(self.mesh, cap, int(n_hops))
        import jax.numpy as jnp

        def _dispatch():
            # the chip-loss probe of the PR 15 chaos suite fires on the
            # guard's worker, same as the one-hop kernel path
            fail.point("device.mesh")
            f = jnp.asarray(ops.pad_to(np.asarray(src, dtype=np.int64), cap))
            with obs.stage(stats, "chain_ms"):
                fs, totals, _final = step(
                    sharded.src, sharded.offsets, sharded.dst, f
                )
                return np.asarray(fs), np.asarray(totals)

        # segmented dataflow (PR 18): k hops of the mesh scan per
        # dispatched program, the in-program exchange untouched inside a
        # segment, the ``final`` frontier output threaded (device-
        # resident) between segments with a scheduler yield point at
        # every seam.  mesh_multi_hop_step's lru_cache bounds the
        # segment programs: fixed k compiles the k-hop step and at most
        # one remainder per cap bucket.
        from dgraph_tpu.sched import segments

        seg_k = segments.plan(int(n_hops), cap, "mesh")

        def _dispatch_segment(f, hops):
            fail.point("device.mesh")
            sstep = mesh_multi_hop_step(self.mesh, cap, hops)
            with obs.stage(stats, "chain_ms"):
                sfs, stot, final = sstep(
                    sharded.src, sharded.offsets, sharded.dst, f
                )
                return np.asarray(sfs), np.asarray(stot), final

        def _run_segmented():
            fs_parts, tot_parts = [], []
            f = jnp.asarray(
                ops.pad_to(np.asarray(src, dtype=np.int64), cap)
            )
            done = 0
            while done < int(n_hops):
                if done:
                    segments.seam("mesh")
                hops = min(seg_k, int(n_hops) - done)
                mg2 = devguard.get("mesh")
                if not devguard.enabled():
                    sfs, stot, f = _dispatch_segment(f, hops)
                else:
                    sfs, stot, f = mg2.run(
                        "mesh.multi_hop",
                        lambda f=f, hops=hops: _dispatch_segment(f, hops),
                    )
                fs_parts.append(sfs)
                tot_parts.append(stot)
                done += hops
                if done < int(n_hops) and sfs[-1][0] == ops.SENT:
                    # drained frontier: the remaining hops are all-SENT
                    # rows / zero totals on every chip — synthesize and
                    # stop dispatching
                    segments.early_exit("mesh")
                    r = int(n_hops) - done
                    fs_parts.append(
                        np.full((r, cap), ops.SENT, sfs.dtype)
                    )
                    tot_parts.append(np.zeros((r,), stot.dtype))
                    break
            return np.concatenate(fs_parts), np.concatenate(tot_parts)

        t0 = time.perf_counter()
        mg = devguard.get("mesh")
        if 0 < seg_k < int(n_hops):
            fs, totals = _run_segmented()
        elif not devguard.enabled():
            fs, totals = _dispatch()
        else:
            fs, totals = mg.run("mesh.multi_hop", _dispatch)
        self._charge(
            h2d=cap * 4,
            d2h=int(fs.nbytes + totals.nbytes),
            cap=cap,
            hops=int(n_hops),
            wall_ms=(time.perf_counter() - t0) * 1e3,
        )
        return fs, totals

    # -- attribution ---------------------------------------------------------

    def _charge(
        self, h2d: int, d2h: int, cap: int, hops: int, wall_ms: float
    ) -> None:
        led = _ledger.current()
        if led is None:
            return
        from dgraph_tpu.mesh.programs import exchange_bytes_per_hop

        led.bytes_h2d += h2d
        led.bytes_d2h += d2h
        led.exchange_bytes += exchange_bytes_per_hop(self.mesh, cap) * hops
        led.mesh_ms += wall_ms
        led.mesh_chips = max(led.mesh_chips, self.width)
