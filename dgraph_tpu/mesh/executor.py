"""MeshExecutor: the engine-facing entry points of the mesh plane.

``parallel/mesh.py`` exposes raw kernels; the executor is what the
serving path actually calls — one-hop sharded expansion behind
``DeviceExpander`` (query/engine.py::_mesh_expand) and the fused
multi-hop scan behind ``chain`` (query/chain.py::_try_mesh_chain).
Both entry points carry the full serving contract the kernels alone
don't:

- **fault domain**: every dispatch runs under the ``"mesh"`` device
  guard (utils/devguard.py).  With the elastic fault domain active
  (mesh/fault.py) a CHIP-attributed fault evicts that chip, re-shards
  the plan onto the survivors and the executor RETRIES under the new
  epoch (bounded by ``DGRAPH_TPU_MESH_RESUME_RETRIES``) — the route
  stays mesh on the surviving sub-mesh.  Un-attributed faults keep the
  PR 15 path: ``DeviceFaultError`` propagates and the caller re-plans
  the level/chain unsharded.
- **epoch fence + drain-and-resume**: a segmented multi-hop captures
  the fault domain's fence (epoch, mesh) at plan time and re-checks it
  at every ``segments.seam()``.  On a flip — another query's chip loss,
  or a staged rejoin cutting over — the query's carry is already
  mirrored on the host (each segment's fetched ``fs[-1]`` row IS the
  donated carry's value), so it re-fetches the sharded arena at the new
  width and resumes byte-identically: placement is byte-invisible
  (mesh/plan.py) and every sub-mesh program is pinned value-for-value
  against the unsharded scan driver.  A wedged collective
  (``DeviceHangError``) mid-query latches the plane and the remaining
  hops complete on that same unsharded driver from the host carry.
- **ledger attribution**: wall time inside mesh programs, the mesh
  width it ran on (per-chip time under SPMD = wall × width), and the
  estimated cross-chip exchange payload land on the request's ledger
  (obs/ledger.py ``mesh_ms``/``mesh_chips``/``exchange_bytes``).
- **placement**: sharded arenas come via ``ArenaManager.sharded_csr``,
  which applies the ``MeshPlan`` roll — the executor never sees an
  unplaced arena.
"""

from __future__ import annotations

import time
from typing import Tuple

import numpy as np

from dgraph_tpu import obs, ops
from dgraph_tpu.obs import ledger as _ledger
from dgraph_tpu.utils import devguard


class MeshExecutor:
    """Serving-path executor over one ArenaManager's mesh.

    Cheap to construct (holds no device state of its own — the sharded
    arenas and compiled steps are the manager's/module caches' assets);
    ArenaManager memoizes one per manager (``mesh_executor()``)."""

    def __init__(self, arenas):
        self.arenas = arenas  # models/arena.py::ArenaManager

    @property
    def mesh(self):
        return self.arenas.mesh

    @property
    def width(self) -> int:
        """Model-axis width — the chips one dispatch spans."""
        m = self.mesh
        return int(m.shape["model"]) if m is not None else 1

    def allowed(self) -> bool:
        """May the mesh domain be dispatched to right now (devguard
        latch + half-open probe)?"""
        return self._guard().allowed()

    def _guard(self) -> devguard.DeviceGuard:
        """The plane guard, fault-sink re-attached when the elastic
        domain is live (devguard.reset_for_tests builds fresh guards)."""
        dom = self.arenas.mesh_fault
        if dom is not None:
            return dom.plane_guard()
        return devguard.get("mesh")

    def _retries(self) -> int:
        if self.arenas.mesh_fault is None:
            return 0
        from dgraph_tpu.mesh.fault import resume_retries

        return resume_retries()

    def _chip_retryable(self, e: BaseException) -> bool:
        """A fault the elastic domain already attributed to ONE chip:
        its sink re-sharded the plan synchronously before the raise, so
        a retry dispatches the surviving sub-mesh — the route stays
        mesh.  Hangs/sick-latch are never chip-attributable."""
        if self.arenas.mesh_fault is None:
            return False
        if isinstance(e, (devguard.DeviceHangError, devguard.DeviceSickError)):
            return False
        return devguard.chip_of(e) is not None

    def _note_degraded(self, stats: dict, resumed: int = 0) -> None:
        """Stamp per-request sub-mesh disclosure: results are
        byte-identical, capacity is not (engine.run_parsed lifts this
        into the response's ``degraded.mesh``, the PR 5 discipline)."""
        dom = self.arenas.mesh_fault
        if dom is None:
            return
        info = dom.degraded_info()
        if resumed or info["chips_healthy"] < info["chips_total"]:
            info["resumed"] = resumed + (
                stats.get("mesh_degraded", {}).get("resumed", 0)
            )
            stats["mesh_degraded"] = info

    # -- entry points --------------------------------------------------------

    def expand(
        self, attr: str, reverse: bool, src: np.ndarray, cap: int, stats: dict
    ) -> Tuple[np.ndarray, np.ndarray]:
        """One engine-level sharded expansion (the route:mesh leaf).
        Returns (out, seg_ptr) byte-identical to the single-device
        expand.  A chip-attributed fault re-shards and retries on the
        surviving sub-mesh (reads are idempotent — the dispatch either
        returned or it didn't); anything else raises
        ``devguard.DeviceFaultError`` so the caller re-plans unsharded."""
        from dgraph_tpu.parallel.mesh import _fcap_bucket, sharded_expand_segments
        from dgraph_tpu.sched import segments

        dom = self.arenas.mesh_fault
        retries = self._retries()
        resumed = 0
        t0 = time.perf_counter()
        while True:
            sharded = self.arenas.sharded_csr(attr, reverse=reverse)
            if dom is not None:
                dom.note_shape("expand", cap, _fcap_bucket(len(src)))

            def _dispatch():
                with obs.stage(stats, "device_expand_ms"):
                    return sharded_expand_segments(
                        self.mesh, sharded, src, cap
                    )

            try:
                if not devguard.enabled():
                    out, seg_ptr = _dispatch()
                else:
                    out, seg_ptr = self._guard().run("mesh.expand", _dispatch)
                break
            except devguard.DeviceFaultError as e:
                if retries <= 0 or not self._chip_retryable(e):
                    raise
                retries -= 1
                resumed += 1
                segments.resume("mesh", "loss")
        self._charge(
            h2d=int(src.nbytes),
            d2h=int(out.nbytes + seg_ptr.nbytes),
            cap=cap,
            hops=1,
            wall_ms=(time.perf_counter() - t0) * 1e3,
        )
        self._note_degraded(stats, resumed)
        return out, seg_ptr

    def multi_hop(
        self,
        attr: str,
        reverse: bool,
        src: np.ndarray,
        n_hops: int,
        cap: int,
        stats: dict,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """The fused multi-hop chain over the mesh: ONE compiled program
        whose cross-chip frontier exchange happens between scan levels
        on the interconnect (mesh/programs.py), no host round trip per
        hop.  Returns (frontiers int64-convertible int32[n_hops, cap],
        totals int32[n_hops]) — per-level sorted-unique-padded
        frontiers matching the unsharded scan driver (ops.multi_hop
        with track_visited=False) value-for-value.

        Raises ``devguard.DeviceFaultError`` under the guard exactly
        like :meth:`expand` when the fault cannot be owned by one chip;
        the chain then declines the fused path and the per-level ladder
        (which re-plans unsharded on the latched domain) takes over."""
        from dgraph_tpu.sched import segments

        n_hops = int(n_hops)
        # segmented dataflow (PR 18): k hops of the mesh scan per
        # dispatched program, the in-program exchange untouched inside a
        # segment, the ``final`` frontier output threaded (device-
        # resident) between segments with a scheduler yield point at
        # every seam.  mesh_multi_hop_step's lru_cache bounds the
        # segment programs: fixed k compiles the k-hop step and at most
        # one remainder per cap bucket.
        seg_k = segments.plan(n_hops, cap, "mesh")
        t0 = time.perf_counter()
        resumed = [0]
        if 0 < seg_k < n_hops:
            fs, totals = self._run_segmented(
                attr, reverse, src, n_hops, cap, seg_k, stats, resumed
            )
        else:
            fs, totals = self._run_monolithic(
                attr, reverse, src, n_hops, cap, stats, resumed
            )
        self._charge(
            h2d=cap * 4,
            d2h=int(fs.nbytes + totals.nbytes),
            cap=cap,
            hops=n_hops,
            wall_ms=(time.perf_counter() - t0) * 1e3,
        )
        self._note_degraded(stats, resumed[0])
        return fs, totals

    # -- dispatch strategies --------------------------------------------------

    def _run_monolithic(
        self, attr, reverse, src, n_hops, cap, stats, resumed
    ):
        from dgraph_tpu.mesh.programs import mesh_multi_hop_step
        from dgraph_tpu.sched import segments
        from dgraph_tpu.utils.failpoints import fail

        import jax.numpy as jnp

        dom = self.arenas.mesh_fault
        retries = self._retries()
        while True:
            sharded = self.arenas.sharded_csr(attr, reverse=reverse)
            step = mesh_multi_hop_step(self.mesh, cap, n_hops)
            if dom is not None:
                dom.note_shape("hop", cap, n_hops)

            def _dispatch():
                # the chip-loss probe of the PR 15 chaos suite fires on
                # the guard's worker, same as the one-hop kernel path
                fail.point("device.mesh")
                f = jnp.asarray(
                    ops.pad_to(np.asarray(src, dtype=np.int64), cap)
                )
                with obs.stage(stats, "chain_ms"):
                    fs, totals, _final = step(
                        sharded.src, sharded.offsets, sharded.dst, f
                    )
                    return np.asarray(fs), np.asarray(totals)

            try:
                if not devguard.enabled():
                    return _dispatch()
                return self._guard().run("mesh.multi_hop", _dispatch)
            except devguard.DeviceFaultError as e:
                if retries <= 0 or not self._chip_retryable(e):
                    raise
                # the sink already evicted the chip and re-sharded: loop
                # re-fetches the arena at the new width and re-dispatches
                # the whole (idempotent) read on the surviving sub-mesh
                retries -= 1
                resumed[0] += 1
                segments.resume("mesh", "loss")

    def _run_segmented(
        self, attr, reverse, src, n_hops, cap, seg_k, stats, resumed
    ):
        from dgraph_tpu.mesh.programs import mesh_multi_hop_step
        from dgraph_tpu.sched import segments
        from dgraph_tpu.utils.failpoints import fail

        import jax.numpy as jnp

        dom = self.arenas.mesh_fault
        retries = self._retries()
        sharded = self.arenas.sharded_csr(attr, reverse=reverse)
        fence = dom.fence() if dom is not None else None
        # the host mirror of the donated carry: the padded seed before
        # the first segment, then each fetched segment's fs[-1] row
        # (== the donated final frontier, value-for-value) — so a drain
        # never fetches the donated device buffer at all
        f_host = ops.pad_to(np.asarray(src, dtype=np.int64), cap)
        f = jnp.asarray(f_host)
        fs_parts, tot_parts = [], []
        done = 0
        while done < n_hops:
            if done:
                segments.seam("mesh")
                if dom is not None and dom.fence() != fence:
                    # epoch flipped between segments (another query's
                    # chip loss, or a staged rejoin cutting over): drain
                    # — the carry already lives in f_host — and resume
                    # under the new sub-mesh's plan
                    sharded, fence, f = self._replan(
                        attr, reverse, f_host, dom
                    )
                    resumed[0] += 1
                    segments.resume("mesh", "epoch")
            hops = min(seg_k, n_hops - done)
            if dom is not None:
                dom.note_shape("hop", cap, hops)

            def _dispatch_segment(f=f, hops=hops, sharded=sharded):
                fail.point("device.mesh")
                sstep = mesh_multi_hop_step(self.mesh, cap, hops)
                with obs.stage(stats, "chain_ms"):
                    sfs, stot, final = sstep(
                        sharded.src, sharded.offsets, sharded.dst, f
                    )
                    return np.asarray(sfs), np.asarray(stot), final

            try:
                if not devguard.enabled():
                    sfs, stot, f = _dispatch_segment()
                else:
                    sfs, stot, f = self._guard().run(
                        "mesh.multi_hop", _dispatch_segment
                    )
            except devguard.DeviceFaultError as e:
                if (
                    dom is not None
                    and isinstance(
                        e,
                        (devguard.DeviceHangError, devguard.DeviceSickError),
                    )
                ):
                    # wedged collective / plane latched mid-query: no
                    # chip to blame, the mesh is gone for now — finish
                    # the remaining hops on the unsharded scan driver
                    # from the host carry (its byte-parity twin) and
                    # disclose the failover
                    sfs, stot = self._finish_unsharded(
                        attr, reverse, f_host, n_hops - done, cap, stats
                    )
                    fs_parts.append(sfs)
                    tot_parts.append(stot)
                    resumed[0] += 1
                    segments.resume("mesh", "hang")
                    devguard.count_failover("unsharded", stats, "mesh")
                    break
                if retries <= 0 or not self._chip_retryable(e):
                    raise
                retries -= 1
                sharded, fence, f = self._replan(
                    attr, reverse, f_host, dom
                )
                resumed[0] += 1
                segments.resume("mesh", "loss")
                continue  # retry THIS segment on the surviving sub-mesh
            fs_parts.append(sfs)
            tot_parts.append(stot)
            done += hops
            f_host = np.asarray(sfs[-1])
            if done < n_hops and sfs[-1][0] == ops.SENT:
                # drained frontier: the remaining hops are all-SENT
                # rows / zero totals on every chip — synthesize and
                # stop dispatching
                segments.early_exit("mesh")
                r = n_hops - done
                fs_parts.append(np.full((r, cap), ops.SENT, sfs.dtype))
                tot_parts.append(np.zeros((r,), stot.dtype))
                break
        return np.concatenate(fs_parts), np.concatenate(tot_parts)

    def _replan(self, attr, reverse, f_host, dom):
        """Drain-and-resume bookkeeping: re-fetch the sharded arena
        under the new epoch's plan (new width ⇒ sharded_csr rebuilds —
        the survivor re-seed path) and rebuild the device carry from
        its host mirror."""
        import jax.numpy as jnp

        dom.note_drain(1)
        try:
            sharded = self.arenas.sharded_csr(attr, reverse=reverse)
            fence = dom.fence()
            f = jnp.asarray(f_host)
        finally:
            dom.note_drain(-1)
        return sharded, fence, f

    def _finish_unsharded(self, attr, reverse, f_host, hops, cap, stats):
        """Complete a drained query's remaining hops on the unsharded
        lax.scan driver — ``ops.multi_hop`` is the exact driver the mesh
        program is pinned byte-identical against, fed the same
        sorted-unique-padded carry, so the stitched result is
        indistinguishable from an all-mesh run.  Same universe
        convention as the chain scan path (max src uid)."""
        import jax.numpy as jnp

        a = self.arenas.reverse(attr) if reverse else self.arenas.data(attr)
        a.ensure_device()
        universe = int(a.h_src[-1]) if a.n_rows else 0
        lut = a.lut(universe)
        f = jnp.asarray(np.asarray(f_host, dtype=np.int32))
        vis = jnp.full((cap,), ops.SENT, dtype=jnp.int32)
        with obs.stage(stats, "chain_ms"):
            fs, totals, _vis = ops.multi_hop(
                a.offsets, a.dst, f, vis, hops, cap, lut=lut
            )
        return np.asarray(fs), np.asarray(totals)

    # -- attribution ---------------------------------------------------------

    def _charge(
        self, h2d: int, d2h: int, cap: int, hops: int, wall_ms: float
    ) -> None:
        led = _ledger.current()
        if led is None:
            return
        from dgraph_tpu.mesh.programs import exchange_bytes_per_hop

        led.bytes_h2d += h2d
        led.bytes_d2h += d2h
        led.exchange_bytes += exchange_bytes_per_hop(self.mesh, cap) * hops
        led.mesh_ms += wall_ms
        led.mesh_chips = max(led.mesh_chips, self.width)
