"""Elastic mesh fault domain: chip loss as a CAPACITY event.

Before this module a single sick chip collapsed the whole mesh route:
devguard's monolithic "mesh" domain latched on any classified fault
and every eligible expansion re-planned unsharded — N−1 healthy chips'
capacity forfeited to one failure (the exact failure mode that ate TPU
bench rounds 4–5).  The fault domain here splits the plane:

- **Per-chip sub-domains** — each mesh chip gets its own
  :class:`~dgraph_tpu.utils.devguard.DeviceGuard` (``mesh.chip<i>``,
  ``sick_after=1``: one attributed fault evicts).  The plane guard's
  ``fault_sink`` consults :func:`devguard.chip_of` — a fault whose
  exception text names a chip (real XLA device errors, or the
  ``chip=`` failpoint selector) charges THAT chip's guard and leaves
  the plane guard untouched; un-attributed faults keep the PR 15/17
  whole-plane path byte-identically.

- **Epoch-fenced re-shard** — evicting a chip re-targets the
  :class:`~dgraph_tpu.mesh.plan.MeshPlan` at the surviving sub-mesh
  (``rebalance(n_shards=k)``, N−1 … down to 1 chip), drops the stale
  sharded views (survivors re-seed lazily under the existing HBM
  budget/LRU), and publishes a new epoch — the plan version the new
  sub-mesh was sharded under.  Every dispatched mesh program carries
  the fence it was planned under (:meth:`fence`); an in-flight
  segmented query observing a flip at a ``segments.seam()`` drains its
  carry to host and resumes under the new plan (mesh/executor.py).

- **Staged rejoin (warm-then-cutover)** — a healed chip re-enters
  behind its guard's half-open probe via ``on_readmit``: the candidate
  sub-mesh is built, sharded views are re-built at the candidate width
  and the recently-served program shapes are compiled and run against
  them BEFORE the epoch flips (``fail.point("mesh.warm")`` is the
  chaos hook).  A warm failure re-latches the chip sick without
  touching live traffic — a flapping chip can never bounce the serving
  plan — and a clean warm cuts over atomically, adopting the staged
  shards.

Gate: ``DGRAPH_TPU_MESH_ELASTIC`` (default on).  ``0`` restores the
PR 17 behavior exactly — one "mesh" domain, chip loss degrades to
unsharded.  Observability: ``dgraph_mesh_epoch``,
``dgraph_mesh_chips_healthy``, ``dgraph_mesh_reshard_total{reason}``,
``dgraph_mesh_reshard_seconds``, the ``mesh.reshard`` span, the
``/health?detail=1`` ``mesh`` section, and the ``degraded.mesh`` /
``dgraph-mesh-epoch`` response annotations.  Runbook:
docs/deploy.md "Mesh fault domain".
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from dgraph_tpu.utils import devguard
from dgraph_tpu.utils.failpoints import fail
from dgraph_tpu.utils.metrics import (
    MESH_CHIPS_HEALTHY,
    MESH_EPOCH,
    MESH_RESHARD,
    MESH_RESHARD_SECONDS,
)


def elastic_enabled() -> bool:
    """The DGRAPH_TPU_MESH_ELASTIC gate (default ON); ``0`` restores
    the PR 17 monolithic mesh domain — chip loss degrades the route to
    unsharded instead of re-sharding onto survivors."""
    return os.environ.get("DGRAPH_TPU_MESH_ELASTIC", "1") != "0"


def resume_retries() -> int:
    """How many times one in-flight query may re-plan-and-resume before
    surrendering the mesh route to the caller's unsharded fallback
    (bounded retry budget — a re-shard storm must degrade, not loop)."""
    return int(os.environ.get("DGRAPH_TPU_MESH_RESUME_RETRIES", "2"))


# at most this many (shape × staged arena) warm dispatches per rejoin:
# the warm exists to pre-pay compiles for the shapes live traffic is
# actually using, not to enumerate the program space
_WARM_CAP = 16


class StagedShards:
    """Sharded views pre-built at a rejoin CANDIDATE width, before the
    epoch flips.  ``views`` holds ArenaManager ``_sharded``-shaped
    entries — ``(source arena, ShardedArena, offset)`` keyed by
    ``(pred, reverse)`` — built under the plan's PREVIEWED candidate
    placement; the cutover adopts them only if the survivor set decided
    at cutover still matches ``width`` (a loss racing the warm just
    discards the stage)."""

    __slots__ = ("width", "views")

    def __init__(self, width: int):
        self.width = int(width)
        self.views: Dict[tuple, tuple] = {}


class MeshFaultDomain:
    """Per-chip health + epoch-fenced sub-mesh re-sharding for one
    ArenaManager's mesh.  Created by the manager at boot (elastic gate
    permitting); the executor reads :meth:`fence`/:attr:`mesh` on every
    dispatch and the per-chip guards own eviction/rejoin."""

    # graftcheck tier 3: callers (the plane guard's fault_sink runs on
    # query threads), the chip guards' probe loops (rejoin), and
    # /health readers all touch the serving plan — every write below
    # holds self._lock; _fence is published as ONE tuple swap so
    # readers never see a torn (epoch, mesh) pair.
    __race_fields__ = frozenset({
        "epoch", "reshards", "drains", "_healthy", "_mesh", "_fence",
    })

    def __init__(self, arenas, mesh):
        self.arenas = arenas          # models/arena.py::ArenaManager
        self.boot_mesh = mesh
        # model-axis device order of the boot mesh — chip i everywhere
        # in this module means THIS index (failpoint chip=, guard
        # domain names, /health chips)
        self.devices = list(np.asarray(mesh.devices).reshape(-1))
        self.n_chips = len(self.devices)
        self._lock = threading.RLock()
        self._healthy = frozenset(range(self.n_chips))
        # healthy-set → Mesh, memoized so a rejoin back to a previously
        # served set reuses the SAME Mesh object: the compiled program
        # caches (mesh/programs.py, parallel/mesh.py lru_caches) key on
        # it, so flip-back adds zero program shapes
        self._meshes: Dict[frozenset, object] = {
            self._healthy: mesh
        }
        self._mesh = mesh
        self.epoch = self.plan.version if self.plan is not None else 0
        # the dispatch fence: ONE tuple, swapped atomically at re-shard
        # — executors capture it at plan time and compare identity at
        # every segment seam
        self._fence: Tuple[int, object] = (self.epoch, mesh)
        self.reshards = 0
        self.drains = 0               # in-flight drain-and-resumes
        # program shapes live traffic used — what a rejoin warms.
        # dict as an ordered bounded set: kind → ("hop", cap, hops) or
        # ("expand", cap, fcap)
        self._shapes: Dict[tuple, None] = {}
        self._chip_guards: Dict[int, devguard.DeviceGuard] = {}
        self.attach()
        MESH_EPOCH.set(self.epoch)
        MESH_CHIPS_HEALTHY.set(self.n_chips)

    # -- wiring ---------------------------------------------------------------

    @property
    def plan(self):
        return self.arenas.mesh_plan

    @property
    def mesh(self):
        """The CURRENT serving sub-mesh (the boot mesh until a chip is
        evicted)."""
        return self._mesh

    @property
    def width(self) -> int:
        return int(self._mesh.shape["model"])

    def attach(self) -> None:
        """(Re-)attach the fault sink to the plane guard — devguard's
        ``reset_for_tests`` builds fresh guards, so the executor
        re-checks on each dispatch via :meth:`plane_guard`."""
        devguard.get("mesh").fault_sink = self._sink

    def plane_guard(self) -> devguard.DeviceGuard:
        g = devguard.get("mesh")
        if g.fault_sink is not self._sink:
            g.fault_sink = self._sink
        return g

    def fence(self) -> Tuple[int, object]:
        """The (epoch, mesh) pair a dispatch is planned under.  Compare
        pairs: an epoch bump with the same mesh never happens (the
        epoch only moves at re-shard), and placement-only plan-version
        bumps between re-shards are byte-invisible by the MeshPlan
        correctness argument, so they need no fence at all."""
        return self._fence

    def chip_guard(self, chip: int) -> devguard.DeviceGuard:
        # resolved through the registry EVERY call (not a held
        # reference): devguard.reset_for_tests rebuilds guards, and a
        # stale object here would split the domain's view of chip
        # health from the registry's
        g = devguard.ensure(
            f"mesh.chip{chip}",
            sick_after=1,
            probe_fn=lambda c=chip: self._chip_probe(c),
            on_readmit=lambda c=chip: self._chip_rejoin(c),
        )
        with self._lock:
            self._chip_guards[chip] = g
        return g

    def note_shape(self, kind: str, *dims: int) -> None:
        """Record a program shape live traffic dispatched (the rejoin
        warm set).  Bounded FIFO — shapes are bucketed caps, so the set
        is small by construction."""
        key = (kind, *dims)
        with self._lock:
            self._shapes[key] = None
            while len(self._shapes) > _WARM_CAP:
                self._shapes.pop(next(iter(self._shapes)))

    # -- fault attribution ----------------------------------------------------

    def _sink(self, kind: str, op: str, exc: BaseException) -> bool:
        """The plane guard's fault_sink: True = one chip owns this
        fault (guard charged, plan re-sharded, plane untouched)."""
        if not elastic_enabled():
            return False
        if kind == "hang":
            # a watchdog overrun has no exception to attribute — the
            # plane latches sick (PR 15) and in-flight segmented
            # queries finish their remaining hops unsharded
            return False
        chip = devguard.chip_of(exc)
        if chip is None or not (0 <= chip < self.n_chips):
            return False
        g = self.chip_guard(chip)
        g.note_fault(kind, op, exc)
        with self._lock:
            lost = chip in self._healthy
        if lost:
            self.reshard("loss")
        return True

    # -- re-shard -------------------------------------------------------------

    def _survivors(self, admit: Optional[int] = None) -> frozenset:
        """The healthy chip set, derived from guard states — eviction
        is one-way except through ``admit`` (the staged-rejoin cutover
        names the chip it just warmed; a merely-probed chip whose warm
        has not passed can never slip back in via someone else's
        re-shard)."""
        # caller holds self._lock
        alive = {
            i for i in self._healthy
            if i not in self._chip_guards or self._chip_guards[i].allowed()
        }
        if admit is not None and 0 <= admit < self.n_chips:
            g = self._chip_guards.get(admit)
            if g is None or g.allowed():
                alive.add(admit)
        return frozenset(alive)

    def _submesh(self, chips: frozenset):
        # caller holds self._lock
        m = self._meshes.get(chips)
        if m is None:
            from jax.sharding import Mesh

            devs = [self.devices[i] for i in sorted(chips)]
            m = Mesh(
                np.array(devs).reshape(1, len(devs)),
                axis_names=("data", "model"),
            )
            self._meshes[chips] = m
        return m

    def reshard(
        self, reason: str, admit: Optional[int] = None, staged=None
    ) -> bool:
        """Re-target the serving plan at the current survivor set.
        Returns whether the plan changed.  ``reason`` ∈ loss / rejoin /
        manual (the metric label); ``staged`` is a rejoin's pre-built
        sharded views, adopted only when their width still matches the
        survivor set decided HERE (a loss racing the warm simply
        discards the stage — correctness never depends on it)."""
        t0 = time.perf_counter()
        from dgraph_tpu import obs

        with self._lock:
            chips = self._survivors(admit)
            if not chips:
                # nothing to serve on: leave the plan alone and let the
                # plane guard's ordinary latch degrade the route
                return False
            if chips == self._healthy:
                return False
            mesh = self._submesh(chips)
            if self.plan is not None:
                self.plan.rebalance(n_shards=len(chips))
                self.epoch = self.plan.version
            else:
                self.epoch += 1
            self._healthy = chips
            self._mesh = mesh
            self._fence = (self.epoch, mesh)
            self.reshards += 1
            epoch, width = self.epoch, len(chips)
        # cache surgery outside the domain lock (it takes the arena
        # cache lock; the build path takes them in the other order)
        self.arenas.drop_sharded()
        if staged is not None and width == staged.width:
            self.arenas.adopt_sharded(staged)
        MESH_RESHARD.add(reason)
        MESH_EPOCH.set(epoch)
        MESH_CHIPS_HEALTHY.set(width)
        dt = time.perf_counter() - t0
        MESH_RESHARD_SECONDS.observe(dt)
        with obs.child("mesh.reshard") as rs:
            rs.set_attr("reason", reason)
            rs.set_attr("epoch", epoch)
            rs.set_attr("chips", width)
        print(
            f"# mesh fault domain re-sharded ({reason}): epoch {epoch}, "
            f"{width}/{self.n_chips} chips healthy "
            f"({dt * 1e3:.1f}ms drain window)",
            file=sys.stderr,
        )
        return True

    # -- drain accounting -----------------------------------------------------

    def note_drain(self, delta: int) -> None:
        with self._lock:
            self.drains += delta

    # -- staged rejoin --------------------------------------------------------

    def _chip_probe(self, chip: int) -> None:
        """The half-open probe for one chip: a trivial dispatch that
        must round-trip THAT device (the plane's default probe only
        proves the default device answers)."""
        fail.point("mesh.chip.probe")
        import jax
        import jax.numpy as jnp

        x = jax.device_put(
            jnp.arange(8, dtype=jnp.int32), self.devices[chip]
        )
        jax.block_until_ready(x.sum())

    def _chip_rejoin(self, chip: int) -> None:
        """on_readmit for one chip guard: warm-then-cutover.  Runs on
        the guard's probe loop thread — live traffic keeps serving the
        surviving sub-mesh until the cutover flips the epoch, and a
        warm failure re-latches the chip without any epoch churn."""
        if not elastic_enabled():
            return
        with self._lock:
            if chip in self._healthy:
                return
            candidate = self._survivors(admit=chip)
            if chip not in candidate:
                return
            cand_mesh = self._submesh(candidate)
            shapes = list(self._shapes)
        try:
            fail.point("mesh.warm")
            staged = self.arenas.warm_sharded(cand_mesh)
            self._warm_programs(cand_mesh, staged, shapes)
        except Exception as e:  # noqa: BLE001 — ANY warm failure means
            # the candidate plan is unproven: re-latch the chip (its
            # probe loop restarts) and keep serving the current plan —
            # the flapping-chip contract
            self.chip_guard(chip).note_fault(
                "transient", "mesh.warm", e
            )
            print(
                f"# mesh chip {chip} rejoin warm failed "
                f"({type(e).__name__}: {e}); chip re-latched sick, "
                "serving plan unchanged",
                file=sys.stderr,
            )
            return
        self.reshard("rejoin", admit=chip, staged=staged)

    def _warm_programs(self, mesh, staged, shapes) -> None:
        """Compile-and-run the recently-served program shapes on the
        candidate mesh BEFORE cutover, against the staged shards, so
        post-rejoin traffic re-enters warm (the compile-count guard:
        repeat-shape queries after the flip add zero programs)."""
        import jax
        import jax.numpy as jnp

        from dgraph_tpu.mesh.programs import mesh_multi_hop_step
        from dgraph_tpu.ops.sets import SENT
        from dgraph_tpu.parallel.mesh import (
            seg_expand_packed_step,
            shard_arena_rows,
        )

        views = list(staged.views.values())
        if not views:
            # nothing sharded yet: prove the collective plane itself
            # with a minimal synthetic arena
            views = [(
                None,
                shard_arena_rows(
                    np.array([1], dtype=np.int64),
                    np.array([0, 0], dtype=np.int64),
                    np.empty(0, dtype=np.int64),
                    int(mesh.shape["model"]),
                ),
                0,
            )]
        budget = _WARM_CAP
        for _a, sa, _off in views:
            for shape in shapes or [("hop", 256, 1)]:
                if budget <= 0:
                    return
                budget -= 1
                if shape[0] == "hop":
                    _kind, cap, hops = shape
                    step = mesh_multi_hop_step(mesh, cap, hops)
                    f = jnp.full((cap,), SENT, dtype=jnp.int32)
                    out = step(sa.src, sa.offsets, sa.dst, f)
                else:
                    _kind, cap, fcap = shape
                    step, _slots = seg_expand_packed_step(
                        mesh, cap, fcap
                    )
                    f = jnp.full((fcap,), SENT, dtype=jnp.int32)
                    out = step(sa.src, sa.offsets, sa.dst, f)
                jax.block_until_ready(out)

    # -- surfaces -------------------------------------------------------------

    def degraded_info(self) -> dict:
        """The response annotation for sub-mesh serving (the PR 5
        degraded-read disclosure, mesh flavored): results are
        byte-identical, capacity is not."""
        with self._lock:
            return {
                "epoch": self.epoch,
                "chips_healthy": len(self._healthy),
                "chips_total": self.n_chips,
            }

    def status(self) -> dict:
        """The /health?detail=1 ``mesh`` section."""
        with self._lock:
            healthy = self._healthy
            epoch = self.epoch
            reshards = self.reshards
            drains = self.drains
            guards = dict(self._chip_guards)
        chips = {}
        for i in range(self.n_chips):
            g = guards.get(i)
            chips[str(i)] = (
                "healthy" if g is None
                else g.state + ("" if i in healthy else " (evicted)")
            )
        plan = self.plan
        placement = None
        if plan is not None:
            with plan._lock:
                placement = {
                    "n_shards": plan.n_shards,
                    "predicates": len(plan.placement),
                    "version": plan.version,
                }
        return {
            "elastic": elastic_enabled(),
            "epoch": epoch,
            "chips_total": self.n_chips,
            "chips_healthy": len(healthy),
            "chips": chips,
            "reshards": reshards,
            "drains_in_flight": drains,
            "placement": placement,
        }
