"""MeshPlan: predicate→shard placement for the mesh serving plane.

``shard_arena_rows`` (parallel/mesh.py) always puts a predicate's
first uid-range shard at model-axis position 0.  Left alone, EVERY
predicate's densest region (low uids are the oldest, usually hottest
rows) lands on chip 0 — the mesh-wide analog of the reference's group
hot-spotting (group/conf.go's fingerprint-mod placement exists for the
same reason).  A ``MeshPlan`` assigns each predicate a START OFFSET on
the model axis; the sharded arrays are rolled by that offset before
upload, so different predicates' shard 0 lands on different chips.

Correctness: the roll permutes WHICH device owns WHICH uid-range
slice, nothing else.  Every cross-shard combine in the mesh kernels is
position-independent — ``rows_of`` resolves a uid only on its owner
wherever it sits, the packed reassembly combines via ``psum``/``pmin``
(commutative), and the gather-merge path re-sorts — so placement is
byte-invisible to results (tests/test_mesh_serving.py pins this).

Placement is greedy least-loaded: a predicate's shard 0 goes to the
chip with the least placed bytes so far.  ``rebalance()`` re-runs the
assignment over everything seen (big predicates first), for operators
reshaping a skewed mesh; the plan version bumps so cached sharded
arenas rebuild under the new offsets.

Persistence: ``DGRAPH_TPU_MESH_PLAN`` names a JSON file; the plan
loads on boot and every placement change writes back atomically
(tmp + rename, the models/durability.py discipline).  Unset = in-memory
only (tests, embedded engines).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional


def plan_path() -> str:
    """The DGRAPH_TPU_MESH_PLAN knob ("" = in-memory plan)."""
    return os.environ.get("DGRAPH_TPU_MESH_PLAN", "")


def _greedy_pack(order, n_shards: int):
    """The one greedy bin-pack (biggest predicate first, least-loaded
    chip): ``rebalance`` commits its result, ``preview`` only looks.
    Shared so the two can never disagree — the staged rejoin warms
    shards under preview's offsets and relies on the cutover rebalance
    reproducing them exactly."""
    load = [0] * n_shards
    placement: Dict[str, int] = {}
    for pred, nb in order:
        off = min(range(n_shards), key=lambda i: load[i])
        placement[pred] = off
        load[off] += nb
    return placement, load


class MeshPlan:
    """Predicate→start-shard placement over an ``n_shards``-wide model
    axis.  Thread-safe: the serving layer places from concurrent read
    shells (ArenaManager.sharded_csr builds under arena locks)."""

    def __init__(self, n_shards: int, path: str = ""):
        self.n_shards = max(1, int(n_shards))
        self.path = path
        self.version = 0
        # pred -> model-axis offset of the predicate's shard 0
        self.placement: Dict[str, int] = {}
        # pred -> device bytes at placement time (the rebalance input)
        self._bytes: Dict[str, int] = {}
        self._load = [0] * self.n_shards  # placed bytes per chip
        self._lock = threading.Lock()

    # -- placement -----------------------------------------------------------

    def offset_for(self, pred: str, device_bytes: int = 0) -> int:
        """This predicate's start offset, assigning (least-loaded chip)
        and persisting on first sight."""
        with self._lock:
            off = self.placement.get(pred)
            if off is not None:
                return off
            off = min(range(self.n_shards), key=lambda i: self._load[i])
            self.placement[pred] = off
            self._bytes[pred] = int(device_bytes)
            self._load[off] += int(device_bytes)
            self.version += 1
            self._save_locked()
            return off

    def placed(self, pred: str, sharded):
        """Apply this predicate's placement to a freshly built
        ``ShardedArena``: roll the shard axis so shard 0 lands on the
        assigned chip.  Offset 0 (and a 1-wide mesh) returns the input
        untouched — the staged arrays never copy for the common case."""
        off = self.offset_for(pred, sharded.device_bytes()) % self.n_shards
        return self.rolled(sharded, off)

    @staticmethod
    def rolled(sharded, off: int):
        """Apply one start offset to a freshly built ``ShardedArena``
        (shared with the staged-rejoin warm path, which rolls under a
        PREVIEWED placement before the plan itself re-targets)."""
        if off == 0:
            return sharded
        import jax.numpy as jnp

        from dgraph_tpu.parallel.mesh import ShardedArena

        return ShardedArena(
            src=jnp.roll(sharded.src, off, axis=0),
            offsets=jnp.roll(sharded.offsets, off, axis=0),
            dst=jnp.roll(sharded.dst, off, axis=0),
            n_shards=sharded.n_shards,
        )

    def preview(self, n_shards: int) -> Dict[str, int]:
        """The placement ``rebalance(n_shards=n)`` WOULD commit, without
        touching the plan: the staged rejoin (mesh/fault.py) warms
        sharded views under the candidate width's offsets so the
        post-cutover rebalance finds them already valid.  Greedy is
        deterministic — same recorded bytes + same width ⇒ same
        offsets — which is the whole contract here."""
        with self._lock:
            order = sorted(self._bytes.items(), key=lambda kv: -kv[1])
        placement, _load = _greedy_pack(order, max(1, int(n_shards)))
        return placement

    def rebalance(self, n_shards: Optional[int] = None) -> Dict[str, int]:
        """Re-place everything seen so far, biggest predicate first
        (greedy bin-pack by recorded device bytes).  Returns the new
        placement; the version bump invalidates cached sharded arenas
        (ArenaManager keys the cache on it).

        ``n_shards`` re-targets the plan at a DIFFERENT model-axis
        width — the elastic mesh fault domain's re-shard (mesh/fault.py):
        chip loss packs everything onto the N−1 … 1 surviving chips,
        staged rejoin widens back.  The version bump is the mesh EPOCH
        FENCE — every dispatched mesh program carries the version it was
        planned under, and an in-flight query observing a bump at a
        segment seam re-plans its remaining hops under the new width."""
        with self._lock:
            if n_shards is not None:
                self.n_shards = max(1, int(n_shards))
            order = sorted(
                self._bytes.items(), key=lambda kv: -kv[1]
            )
            self.placement, self._load = _greedy_pack(
                order, self.n_shards
            )
            self.version += 1
            self._save_locked()
            return dict(self.placement)

    # -- persistence ---------------------------------------------------------

    def _save_locked(self) -> None:
        if not self.path:
            return
        from dgraph_tpu.utils.atomicio import atomic_write_file

        try:
            atomic_write_file(
                self.path,
                json.dumps(
                    self.to_dict(), indent=1, sort_keys=True
                ).encode(),
            )
        except OSError:
            # read-only scratch: the in-memory plan still serves; the
            # next boot just re-derives placement
            pass

    def to_dict(self) -> dict:
        return {
            "n_shards": self.n_shards,
            "version": self.version,
            "placement": dict(self.placement),
            "bytes": dict(self._bytes),
        }

    @classmethod
    def load(cls, n_shards: int, path: Optional[str] = None) -> "MeshPlan":
        """Boot-time constructor: adopt a persisted plan when its shard
        width still matches the live mesh (a resized mesh re-derives —
        stale offsets beyond the new width would wrap arbitrarily)."""
        p = plan_path() if path is None else path
        plan = cls(n_shards, path=p)
        if not p:
            return plan
        try:
            with open(p) as f:
                d = json.load(f)
        except (OSError, ValueError):
            return plan
        if int(d.get("n_shards", 0)) != plan.n_shards:
            return plan
        plan.version = int(d.get("version", 0))
        plan.placement = {
            str(k): int(v) % plan.n_shards
            for k, v in d.get("placement", {}).items()
        }
        plan._bytes = {
            str(k): int(v) for k, v in d.get("bytes", {}).items()
        }
        plan._load = [0] * plan.n_shards
        for pred, off in plan.placement.items():
            plan._load[off] += plan._bytes.get(pred, 0)
        return plan
