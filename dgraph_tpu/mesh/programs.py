"""Compiled mesh programs: multi-hop traversal with IN-PROGRAM exchange.

The PR 13/16 serving path dispatches the mesh ONCE PER HOP
(parallel/mesh.py::sharded_expand_segments): each level pays a host
round trip to slice the packed buffer, rebuild the frontier, and
dispatch again — exactly the per-level staging the single-device chain
scan (ops/batch.py::multi_hop) already deleted.  The program here is
the mesh twin of that scan: ``lax.scan`` over hops INSIDE one
``shard_map``, so the cross-chip frontier exchange (``all_gather`` of
each shard's bucketed expansion, ``psum`` of the edge counts) happens
between scan iterations on the ICI, never through the host.  The
frontier carry is donated — XLA threads one [cap] buffer across every
level instead of allocating per hop.

Byte-parity contract: each hop's merged frontier is
``sort_unique(all_gather(per-shard expand_csr))[:cap]`` — the same
sorted-unique-padded set the unsharded ``multi_hop`` driver produces
(its per-hop ``sort_unique(expand_ascending(...))``), because the
shards partition the rows and the re-sort erases gather order.
tests/test_mesh_serving.py pins chain results sharded == unsharded.

Memoized per (mesh, cap, n_hops) like every step in parallel/mesh.py:
jax.jit caches on function identity, and caps ride ops.bucket so the
program family stays bounded (analysis/budgets.json entries cap the
compile count in CI).

Elastic fault domain (PR 20): a ``Mesh`` hashes by its device set +
axis names, so programs built here key cleanly per mesh EPOCH — an
eviction re-shards onto a sub-mesh and compiles its own bounded
family, and the staged rejoin's flip back to the memoized boot mesh
hash-hits the original cache (zero recompiles; mesh/fault.py warms
the candidate mesh's shapes BEFORE the cutover either way).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from dgraph_tpu import ops


@lru_cache(maxsize=64)
def mesh_multi_hop_step(mesh: Mesh, cap: int, n_hops: int):
    """Build the jitted fused multi-hop mesh program.

    Signature: ``fn(src, offsets, dst, frontier)`` where src/offsets/
    dst are a ShardedArena's [n_model, ...] arrays and frontier is the
    replicated [cap] sorted-unique-padded seed (int32 on device).
    Returns ``(frontiers int32[n_hops, cap], totals int32[n_hops],
    final int32[cap])`` — per-level post-dedup frontiers and global
    edge counts, plus the final frontier (the output the donated seed
    buffer aliases).

    Every hop shares one capacity (lax.scan needs a uniform carry
    shape), so callers plan ``cap`` from the worst level, exactly like
    the unsharded scan driver (query/chain.py::_try_chain_scan)."""

    def local(src, offsets, dst, frontier):
        src, offsets, dst = src[0], offsets[0], dst[0]

        def body(f, _):
            # local expansion of the rows this shard owns (rows_of
            # resolves a uid only on its owner — off-shard uids expand
            # to nothing here and to their targets on the owner chip)
            rows = ops.rows_of(src, f)
            out, _seg, t = ops.expand_csr(offsets, dst, rows, cap)
            # the cross-chip frontier exchange, INSIDE the program:
            # every shard contributes its bucketed [cap] expansion over
            # the ICI, the count reduction rides psum, and the re-sort
            # erases gather order so placement can't leak into results
            gathered = jax.lax.all_gather(out, "model")  # [n_model, cap]
            nxt = ops.sort_unique(gathered.reshape(-1))[:cap]
            total = jax.lax.psum(t, "model")
            return nxt, (nxt, total)

        final, (fs, totals) = jax.lax.scan(
            body, frontier, None, length=n_hops
        )
        return fs, totals, final

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P("model", None), P("model", None), P("model", None), P()),
        out_specs=(P(), P(), P()),
        check_rep=False,
    )
    # the [cap] final-frontier output exists exactly so the donated
    # seed buffer has something to alias — the scan's internal carry
    # then reuses it across every level (the batch.multi_hop donation
    # discipline, contract-checked in analysis/programs.py)
    return jax.jit(fn, donate_argnums=(3,))


def exchange_bytes_per_hop(mesh: Mesh, cap: int) -> int:
    """The cross-chip payload one hop of the fused program moves: each
    of the n_model chips all_gathers the other shards' [cap] int32
    expansions ((n-1)/n of the gathered buffer crosses the ICI) plus
    the psum'd count lane.  An ESTIMATE for ledger attribution — the
    collective's wire format is XLA's business — but a monotone,
    shape-accurate one, which is what capacity dashboards need."""
    n = int(mesh.shape["model"])
    per_chip = (n - 1) * cap * 4 + (n - 1) * 4
    return n * per_chip
