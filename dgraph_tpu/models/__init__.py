"""Data model: value types, uid dictionary, schema state, the host posting
store with mutation semantics, and the device-resident CSR arenas.

Equivalent of the reference's posting/ + schema/ + types/ layers
(SURVEY.md §2), re-designed so the query-time representation is a set of
immutable, device-resident tensors ("arenas") rebuilt incrementally from
the mutable host store — the TPU analog of posting list cache + badger.
"""

from dgraph_tpu.models.types import TypeID, TypedValue  # noqa: F401
from dgraph_tpu.models.uids import UidMap  # noqa: F401
from dgraph_tpu.models.schema import SchemaState, parse_schema  # noqa: F401
from dgraph_tpu.models.store import PostingStore  # noqa: F401
from dgraph_tpu.models.arena import ArenaManager  # noqa: F401
