"""Device-resident posting-list arenas.

The query-time representation of the graph: per predicate, immutable CSR
tensors on device —

- **data arena**: sorted source uids + offsets + packed sorted target uids
  (uid predicates) — replaces the reference's per-key badger lookups +
  posting-list iteration (posting/list.go PIterator, worker/task.go:287).
- **reverse arena**: the inverted edge set (@reverse, posting/index.go:152).
- **index arenas**: one per tokenizer — host-side sorted token table +
  device CSR token-row → uid list (posting/index.go addIndexMutation:108).
  Inequalities become contiguous token-row ranges (sortable tokenizers).
- **value arena**: sorted uids + float32 numerics for device order-by /
  aggregation / math; exact typed values stay on the host store.
- count queries need no extra arena: degree = offsets diff (the reference
  maintains a separate count index, x/keys.go:101 — dense CSR gives it
  for free).

Arenas are rebuilt per dirty predicate from the host store (the analog of
the gentle-commit + lcache refresh cycle, posting/lists.go:109-215).
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from dgraph_tpu import ops
from dgraph_tpu.obs import ledger as _ledger
from dgraph_tpu.utils.metrics import ARENA_EVICTIONS
from dgraph_tpu.ops.sets import SENT
from dgraph_tpu import tok as tokmod
from dgraph_tpu.models.store import PostingStore
from dgraph_tpu.models.types import TypeID, TypedValue, numeric


# Shared lock for lazy per-arena derived-structure builds (ensure_device,
# chunked, lut).  Struck once per build, never on warm reads — the warm
# paths double-check their cached field before locking.  A single module
# lock (vs per-arena) keeps CSRArena a plain dataclass; contention is
# limited to cold-cache bursts.
_BUILD_LOCK = threading.RLock()


@dataclass
class CSRArena:
    """One CSR posting structure on device, with host mirrors for planning."""

    src: Optional[jnp.ndarray]      # int32[Sb] sorted row-key uids; None if rows are implicit
    offsets: jnp.ndarray            # int32[Sb+1]; padded rows have degree 0
    dst: jnp.ndarray                # int32[Eb], SENT-padded
    h_src: np.ndarray               # int64[S] (exact, unpadded)
    h_offsets: np.ndarray           # int64[S+1]
    n_rows: int
    n_edges: int
    _chunked: Optional[tuple] = None  # lazy (meta8, chunk_dst)

    def degree_of_rows(self, rows: np.ndarray) -> np.ndarray:
        """Host-side degree lookup for capacity planning."""
        rows = np.asarray(rows)
        ok = rows >= 0
        r = np.where(ok, rows, 0)
        return np.where(ok, self.h_offsets[r + 1] - self.h_offsets[r], 0)

    @property
    def avg_degree(self) -> float:
        """Mean out-degree — the O(1) fan-out estimate the cohort hop
        merger uses to predict device routing before paying for exact
        per-row degrees (query/engine.py DeviceExpander.expand)."""
        return self.n_edges / max(1, self.n_rows)

    _h_dst: Optional[np.ndarray] = None
    _n_distinct_dst: Optional[int] = None

    def host_dst(self) -> np.ndarray:
        """Host mirror of the packed dst column (lazy, cached; one device
        fetch).  Serves the small-expansion numpy fast path and chunked()."""
        if self._h_dst is None:
            self._h_dst = np.asarray(self.dst)[: self.n_edges]
        return self._h_dst

    def n_distinct_dst(self) -> int:
        """Number of distinct target uids (lazy).  Bounds the unique
        frontier any expansion over this arena can produce — unlike the
        source-uid universe, which says nothing about row-less leaves."""
        if self._n_distinct_dst is None:
            self._n_distinct_dst = (
                int(len(np.unique(self.host_dst()))) if self.n_edges else 0
            )
        return self._n_distinct_dst

    def expand_host(self, rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Vectorized numpy CSR expansion over the host mirror: returns
        (out, seg_ptr) in the engine's layout — out grouped by input row
        (ascending within each group), seg_ptr[i]:seg_ptr[i+1] slicing row
        i's targets.  Rows < 0 skip (degree 0).  The single host gather
        shared by the engine's and the resolver's small-expansion paths."""
        rows = np.asarray(rows)
        n = len(rows)
        ok = rows >= 0
        r = np.where(ok, rows, 0)
        degs = np.where(ok, self.h_offsets[r + 1] - self.h_offsets[r], 0)
        total = int(degs.sum())
        seg_ptr = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(degs, out=seg_ptr[1:])
        if total == 0:
            return np.empty(0, dtype=np.int64), seg_ptr
        starts = np.where(ok, self.h_offsets[r], 0)
        within = np.arange(total) - np.repeat(seg_ptr[:-1], degs)
        out = self.host_dst()[np.repeat(starts, degs) + within].astype(np.int64)
        return out, seg_ptr

    def chunked(self) -> tuple:
        """Chunk-packed layout for ops.expand_chunked, built lazily.

        Returns (meta8, chunk_dst): int32[Sb, 8] per-row
        (chunk_start, chunk_count, degree) and int32[NCb, CHUNK]
        chunk-packed dst with SENT pad lanes.  Rebuilt with the arena on
        dirty refresh (the tuple dies with the CSRArena object); host
        capacity planning uses chunk_degree_of_rows.
        """
        if self._chunked is not None:
            return self._chunked
        with _BUILD_LOCK:
            return self._chunked_locked()

    def _chunked_locked(self) -> tuple:
        if self._chunked is not None:  # lost the build race: reuse
            return self._chunked
        C = ops.CHUNK
        S = self.n_rows
        E = self.n_edges
        deg = self.h_offsets[1:] - self.h_offsets[:-1]
        cdeg = (deg + C - 1) // C
        coff = np.zeros(S + 1, dtype=np.int64)
        np.cumsum(cdeg, out=coff[1:])
        NC = int(coff[-1])
        NCb = ops.bucket(max(1, NC))
        chunk = np.full((NCb, C), SENT, dtype=np.int32)
        if E:
            h_dst = self.host_dst()
            rowid = np.repeat(np.arange(S, dtype=np.int64), deg)
            within = np.arange(E, dtype=np.int64) - np.repeat(
                self.h_offsets[:-1], deg
            )
            chunk[coff[rowid] + within // C, within % C] = h_dst
        # size from HOST state, not the device offsets tensor: after
        # apply_delta the device tensors are stale until ensure_device(),
        # but chunked() must serve fused chains immediately (a new source
        # row crossing the power-of-two row bucket would otherwise break
        # the meta[:S] broadcast below)
        Sb = ops.bucket(max(1, self.n_rows))
        meta = np.zeros((Sb, 8), dtype=np.int32)
        meta[:S, 0] = coff[:-1]
        meta[:S, 1] = cdeg
        meta[:S, 2] = deg
        self._chunked = (jnp.asarray(meta), jnp.asarray(chunk))
        return self._chunked

    def chunk_degree_of_rows(self, rows: np.ndarray) -> np.ndarray:
        """Host chunk-count lookup (ceil(degree/CHUNK)) for planning."""
        C = ops.CHUNK
        return (self.degree_of_rows(rows) + C - 1) // C

    def device_bytes(self) -> int:
        """HBM footprint of this arena's device tensors (incl. built lazy
        layouts) — the residency manager's accounting unit."""
        n = 0
        for t in (self.src, self.offsets, self.dst, self._lut):
            if t is not None:
                n += t.size * t.dtype.itemsize
        for pair in (self._chunked, self._inline, self._inline_grouped):
            if pair is not None:
                n += sum(t.size * t.dtype.itemsize for t in pair)
        if self._tiles is not None:
            # MXU join tier (ops/spgemm.py): densified adjacency blocks
            # ride the same HBM budget/eviction as every other layout
            n += self._tiles.device_bytes()
        if self._resident is not None:
            # resident Pallas tier: live epoch buffers AND the shadow
            # (previous epoch, pinned through the flip window) — each
            # counted exactly once (ResidentArena.device_bytes)
            n += self._resident.device_bytes()
        return n

    _inline: Optional[tuple] = None  # lazy (metap, ov_chunks)

    def inline_layout(self) -> tuple:
        """Inline-head layout for ops.expand_inline, built lazily.

        Returns (metap, ov_chunks): int32[Sb, 8] per-row rows with
        lane0 = overflow chunk start, lane1 = degree, lanes 2..7 = the
        first INLINE targets (SENT pad); int32[NCov, 8] overflow chunks
        (targets INLINE.. of each row), UNPADDED row count.  One row
        gather serves metadata AND short posting lists — the gather-index
        halving that lifted the 2-hop bench past the chunked layout
        (docs/ROOFLINE.md round 4)."""
        if self._inline is not None:
            return self._inline
        with _BUILD_LOCK:
            if self._inline is not None:
                return self._inline
            INL = ops.INLINE
            S = self.n_rows
            deg = self.h_offsets[1:] - self.h_offsets[:-1]
            ovdeg = np.maximum(deg - INL, 0)
            cdeg = (ovdeg + 7) >> 3
            coff = np.zeros(S + 1, dtype=np.int64)
            np.cumsum(cdeg, out=coff[1:])
            NCov = int(coff[-1])
            Sb = ops.bucket(max(1, S))
            metap = np.full((Sb, 8), SENT, dtype=np.int32)
            metap[:, :2] = 0
            metap[:S, 0] = coff[:-1]
            metap[:S, 1] = deg
            h_dst = self.host_dst() if self.n_edges else np.zeros(0, np.int32)
            starts = self.h_offsets[:-1]
            for j in range(INL):
                sel = deg > j
                metap[:S][sel, 2 + j] = h_dst[starts[sel] + j]
            ov = np.full((max(1, NCov), 8), SENT, dtype=np.int32)
            rows = np.nonzero(deg > INL)[0]
            if len(rows):
                # vectorized tail-edge index set (no per-row arange loop):
                # within = 0..ovdeg-1 per row via the repeat/cumsum trick
                od = ovdeg[rows]
                rowid = np.repeat(rows, od)
                ends = np.cumsum(od)
                within = np.arange(int(ends[-1]), dtype=np.int64) - np.repeat(
                    ends - od, od
                )
                e = starts[rowid] + INL + within
                ov[coff[rowid] + (within >> 3), within & 7] = h_dst[e]
            self._inline = (jnp.asarray(metap), jnp.asarray(ov))
            return self._inline

    def ov_chunk_degree_of_rows(self, rows: np.ndarray) -> np.ndarray:
        """Host overflow-chunk-count lookup for inline_layout planning."""
        d = np.maximum(self.degree_of_rows(rows) - ops.INLINE, 0)
        return (d + 7) >> 3

    _inline_grouped: Optional[tuple] = None

    def inline_layout_grouped(self) -> tuple:
        """inline_layout with skey-coded target lanes (ops.skey_encode):
        stored targets carry the no-overflow group bit, so sorting an
        expansion's output groups overflow-bearing rows into an ascending
        prefix and ops.expand_inline_grouped can run its slot-map on that
        prefix alone.  Dense arenas only (row i == uid i) with uids below
        2^GROUP_BIT — raises ValueError beyond that; callers must catch
        it and use inline_layout() (bench.py does)."""
        if self._inline_grouped is not None:
            return self._inline_grouped
        from dgraph_tpu.ops.sets import GROUP_BIT, skey_encode

        max_uid = self.n_rows
        if self.n_edges:
            max_uid = max(max_uid, int(self.host_dst().max()) + 1)
        if max_uid >= (1 << GROUP_BIT):
            raise ValueError(
                f"uid space too large for grouped inline layout "
                f"({max_uid} >= 2^{GROUP_BIT}); use inline_layout()"
            )
        with _BUILD_LOCK:
            if self._inline_grouped is not None:
                return self._inline_grouped
            metap_j, ov_j = self.inline_layout()
            metap = np.asarray(metap_j).copy()
            ov = np.asarray(ov_j).copy()
            S = self.n_rows
            deg = self.h_offsets[1:] - self.h_offsets[:-1]
            # overflow bit by TARGET uid; uids without a row have no edges,
            # hence no overflow
            has_ov_of_uid = np.zeros(max_uid + 1, bool)
            has_ov_of_uid[:S] = deg > ops.INLINE
            for tab in (metap[:, 2:], ov):
                valid = tab != SENT
                u = tab[valid]
                tab[valid] = skey_encode(u, has_ov_of_uid[u])
            self._inline_grouped = (jnp.asarray(metap), jnp.asarray(ov))
            return self._inline_grouped

    # -- MXU join tier (ops/spgemm.py) --------------------------------------

    _tiles: Optional[object] = None  # lazy PredTiles (blocked adjacency)

    def tile_blocks(self) -> Tuple[int, int]:
        """(non-empty adjacency block count, universe) at the current
        tile size — the join planner's byte estimate, computable WITHOUT
        building the tiles (one O(E) unique pass, cached; invalidated
        with the other derived layouts on apply_delta)."""
        from dgraph_tpu.ops import spgemm

        t = spgemm.tile_size()
        cached = getattr(self, "_tile_blocks", None)
        if cached is not None and cached[0] == t:
            return cached[1], cached[2]
        if self.n_edges == 0:
            k, uni = 0, 0
        else:
            k, uni = spgemm.count_tile_blocks(
                self.h_src, self.h_offsets, self.host_dst(), t
            )
        self._tile_blocks = (t, k, uni)
        return k, uni

    def tiles(self):
        """Blocked boolean adjacency tiles for the MXU join tier, built
        lazily from the CSR host mirrors and cached on the arena (they
        die with it, like every derived layout; device_bytes() accounts
        them, so the ArenaManager HBM budget governs their residency).
        Returns None — without caching a negative — when the estimated
        footprint exceeds DGRAPH_TPU_TILE_BUDGET or the arena is
        edgeless; the planner then stays on the gather tier."""
        from dgraph_tpu.ops import spgemm
        from dgraph_tpu.utils.metrics import JOIN_TILE_BUILDS, JOIN_TILE_BYTES

        pt = self._tiles
        t = spgemm.tile_size()
        if pt is not None and pt.t == t:
            return pt
        if self.n_edges == 0:
            return None
        k, _uni = self.tile_blocks()
        if spgemm.est_tile_bytes(k, t) > spgemm.tile_budget():
            return None
        with _BUILD_LOCK:
            pt = self._tiles
            if pt is not None and pt.t == t:
                return pt
            pt = spgemm.build_tiles(
                self.h_src, self.h_offsets, self.host_dst(), t=t
            )
            if pt is not None:
                self._tiles = pt
                JOIN_TILE_BUILDS.add()
                JOIN_TILE_BYTES.add(pt.device_bytes())
            return pt

    def degree_histogram(self) -> np.ndarray:
        """Log2-bucketed out-degree histogram: slot c counts rows with
        ⌈log2(degree)⌉ == c (degree ≥ 1; slot 0 holds degree-1 rows).
        Cached; the join planner reads it to spot heavy-tailed
        predicates, where the dense-tile pass is immune to the skew
        that serializes gather capacity planning."""
        h = getattr(self, "_deg_hist", None)
        if h is None:
            deg = (self.h_offsets[1:] - self.h_offsets[:-1]).astype(np.int64)
            deg = deg[deg > 0]
            if len(deg):
                c = np.ceil(np.log2(deg, where=deg > 1, out=np.zeros(len(deg))))
                h = np.bincount(c.astype(np.int64), minlength=32)
            else:
                h = np.zeros(32, dtype=np.int64)
            self._deg_hist = h
        return h

    _lut: Optional[jnp.ndarray] = None

    def lut(self, universe: int) -> jnp.ndarray:
        """Dense uid→row lookup table on device: int32[bucket(universe+1)],
        -1 where the uid has no row.  One elementwise gather replaces a
        device binary search (searchsorted costs log(S) gather rounds —
        measured ~20× slower at engine scales).  ~4 bytes/uid of HBM."""
        need = ops.bucket(max(1, universe + 1))
        if self._lut is not None and self._lut.shape[0] >= need:
            return self._lut
        with _BUILD_LOCK:
            cur = self._lut
            if cur is not None and cur.shape[0] >= need:
                return cur
            t = np.full(need, -1, dtype=np.int32)
            if self.n_rows:
                keys = self.h_src[self.h_src <= universe]
                t[keys] = np.arange(len(keys), dtype=np.int32)
            self._lut = jnp.asarray(t)
            return self._lut

    def rows_for_uids_host(self, uids: np.ndarray) -> np.ndarray:
        pos = np.searchsorted(self.h_src, uids)
        pos = np.clip(pos, 0, max(0, self.n_rows - 1))
        if self.n_rows == 0:
            return np.full(len(uids), -1, dtype=np.int64)
        hit = self.h_src[pos] == uids
        return np.where(hit, pos, -1)

    # -- device-resident tier (PR 16: ops/pallas_gather.py) -----------------

    _resident: Optional[object] = None  # lazy ResidentArena
    epoch: int = 0  # bumped once per applied delta; hop-cache key element
    #                 (cache/hop.py key_for index 3): a pre-delta entry
    #                 can never match a post-delta probe by key equality

    def resident(self) -> "ResidentArena":
        """Device-pinned CSR view for the Pallas gather tier, built
        lazily from the host mirrors and kept fresh by ``apply_delta``
        (device-side merge, or a reseed on structural change) — never by
        per-query re-staging: after the first seed, mutations cross the
        host→device boundary as delta pairs only.  Counted in
        ``device_bytes()``, so the ArenaManager HBM budget/LRU governs
        its residency like every other derived layout."""
        ra = self._resident
        if ra is not None:
            return ra
        with _BUILD_LOCK:
            if self._resident is None:
                self._resident = ResidentArena.seed(
                    self.h_offsets, self.host_dst(), self.n_rows,
                    self.n_edges,
                )
            return self._resident

    # -- incremental refresh (gentle-commit analog) -------------------------

    _device_stale: bool = False

    def apply_delta(self, adds: np.ndarray, dels: np.ndarray) -> None:
        """Apply a small mutation batch to the HOST mirrors in place of a
        full rebuild: O(E) memcpy via np.insert/np.delete instead of the
        O(E log E) lexsort + dict flatten of csr_from_edges — the
        incremental counterpart of the reference's mutation layer merge
        (posting/list.go:321-410).  Device tensors go stale and re-upload
        lazily on the next device-path use (ensure_device) — host-routed
        queries after a point mutation never touch the device at all.

        adds/dels: int64[n, 2] (src, dst) arrays; adds must not already
        exist, dels must exist (the store journal guarantees both).

        Runs under _BUILD_LOCK: in clustered mode refresh() applies
        deltas while readers run (ClusterStore drains dirty marks inside
        peek), so mirror mutation must be mutually exclusive with the
        lazy derived-layout builds (inline_layout/chunked also take this
        lock) — otherwise a build that sampled the mirrors pre-delta
        could cache a torn layout AFTER the invalidation below.
        """
        with _BUILD_LOCK:
            self._apply_delta_locked(adds, dels)

    def _apply_delta_locked(self, adds: np.ndarray, dels: np.ndarray) -> None:
        # degree-histogram repair (IVM satellite): capture the affected
        # rows' PRE-delta degrees so the log2 buckets can be adjusted
        # instead of dropped — the planner's skew inputs (joinplan's
        # heavy-tail pad) otherwise cold-start on every point write
        pre_rows = self.n_rows  # resident reseed probe: new source rows
        #                         shift every row index (see tail below)
        hist = getattr(self, "_deg_hist", None)
        touched = None
        if hist is not None:
            touched = np.unique(np.concatenate([
                np.asarray(a[:, 0], dtype=np.int64)
                for a in (adds, dels) if len(a)
            ])) if (len(adds) or len(dels)) else np.empty(0, np.int64)
            old_degs = self._degrees_of_uids(touched)
        h_dst = self.host_dst().astype(np.int64, copy=False)
        # absolute edge positions via the composite (row, dst) key — the
        # CSR flat dst IS sorted by it
        for arr, sign in ((dels, -1), (adds, +1)):
            if not len(arr):
                continue
            srcs = arr[:, 0]
            dsts = arr[:, 1]
            if sign > 0:
                # new source rows first (degree 0), keeping h_src sorted
                newsrc = np.setdiff1d(srcs, self.h_src)
                if len(newsrc):
                    at = np.searchsorted(self.h_src, newsrc)
                    self.h_src = np.insert(self.h_src, at, newsrc)
                    self.h_offsets = np.insert(
                        self.h_offsets, at + 1, self.h_offsets[at]
                    )
                    self.n_rows = len(self.h_src)
            rows = np.searchsorted(self.h_src, srcs)
            keys = (rows.astype(np.int64) << 32) | dsts
            edge_rows = np.repeat(
                np.arange(self.n_rows, dtype=np.int64),
                np.diff(self.h_offsets),
            )
            edge_keys = (edge_rows << 32) | h_dst
            order = np.argsort(keys, kind="stable")
            keys, rows, dsts = keys[order], rows[order], dsts[order]
            pos = np.searchsorted(edge_keys, keys)
            if sign > 0:
                h_dst = np.insert(h_dst, pos, dsts)
            else:
                h_dst = np.delete(h_dst, pos)
            cnt = np.bincount(rows, minlength=self.n_rows)
            self.h_offsets = self.h_offsets.copy()
            self.h_offsets[1:] += sign * np.cumsum(cnt)
        self._h_dst = h_dst.astype(np.int32)
        self.n_edges = len(h_dst)
        # derived device structures are stale until next device use
        self._chunked = None
        self._inline = None
        self._inline_grouped = None
        self._lut = None
        self._n_distinct_dst = None
        for attr in (
            "_topm_cdeg", "_topm_ovdeg", "_topm_deg", "_classed",
            "_tile_blocks",
        ):
            if hasattr(self, attr):
                delattr(self, attr)
        if hist is not None and touched is not None:
            # move each affected row between its old and new log2 bucket
            new_degs = self._degrees_of_uids(touched)
            for od, nd in zip(old_degs.tolist(), new_degs.tolist()):
                if od != nd:
                    self._hist_move(od, nd)
        # MXU tile repair (dgraph_tpu/ivm/): a small delta scatters onto
        # the stored T×T blocks instead of dropping the densified layout
        # wholesale — structurally-impossible repairs (new block, grown
        # universe) and disabled modes fall back to the drop
        pt = self._tiles
        if pt is not None:
            repaired = None
            if len(adds) + len(dels) > 0 and _ivm_repair_gate(
                len(adds) + len(dels), self.n_edges
            ):
                from dgraph_tpu.ops import spgemm as _spgemm
                from dgraph_tpu.utils.metrics import (
                    IVM_REPAIR_EDGES,
                    IVM_REPAIRS,
                )

                repaired = _spgemm.apply_tile_delta(pt, adds, dels)
                IVM_REPAIRS.add(
                    ("tile", "repaired" if repaired is not None
                     else "rebuild")
                )
                if repaired is not None:
                    IVM_REPAIR_EDGES.add(len(adds) + len(dels))
                    led = _ledger.current()
                    if led is not None:
                        led.repairs += 1
            self._tiles = repaired
        if len(adds) or len(dels):
            # arena EPOCH flip: probes formed after this point can never
            # match entries filled before it (cache/hop.py key_for)
            self.epoch += 1
            ra = self._resident
            if ra is not None:
                if self.n_rows != pre_rows or self.n_edges + 128 > ra.ecap:
                    # structural change (new source rows renumber every
                    # row) or the gather kernel's 128-lane slack tile
                    # would be breached: fresh upload becomes the next
                    # epoch, old buffers become the shadow (honest h2d
                    # charge inside seed)
                    nra = ResidentArena.seed(
                        self.h_offsets, self._h_dst, self.n_rows,
                        self.n_edges,
                    )
                    nra._prev = (ra.off, ra.dst)
                    self._resident = nra
                else:
                    # device-side delta application: only the (row, dst)
                    # delta pairs cross host→device; the merge program
                    # produces the next epoch's buffers off the current
                    # ones, and the reference flip inside apply_delta is
                    # the atomic epoch swap
                    def _pack(arr):
                        rows = np.searchsorted(self.h_src, arr[:, 0])
                        b = ops.bucket(max(1, len(arr)))
                        return (
                            jnp.asarray(
                                ops.pad_to(rows.astype(np.int32), b)
                            ),
                            jnp.asarray(
                                ops.pad_to(arr[:, 1].astype(np.int32), b)
                            ),
                        )

                    ar, ad = _pack(adds)
                    dr, dd = _pack(dels)
                    ra.apply_delta(ar, ad, dr, dd, self.n_edges)
        self._device_stale = True

    def _degrees_of_uids(self, uids: np.ndarray) -> np.ndarray:
        """Out-degree per ROW-KEY uid (0 where the uid has no row) —
        the histogram repair's before/after probe."""
        if not len(uids):
            return np.zeros(0, dtype=np.int64)
        pos = np.searchsorted(self.h_src, uids)
        pos = np.clip(pos, 0, max(0, self.n_rows - 1))
        if self.n_rows == 0:
            return np.zeros(len(uids), dtype=np.int64)
        hit = self.h_src[pos] == uids
        deg = self.h_offsets[pos + 1] - self.h_offsets[pos]
        return np.where(hit, deg, 0).astype(np.int64)

    def _hist_move(self, old_deg: int, new_deg: int) -> None:
        """Shift one row between log2 degree buckets (bucket definition
        mirrors degree_histogram: slot ⌈log2(deg)⌉, degree-1 rows in
        slot 0; degree-0 rows are uncounted)."""
        h = self._deg_hist
        for deg, step in ((old_deg, -1), (new_deg, +1)):
            if deg <= 0:
                continue
            b = (int(deg) - 1).bit_length()
            if b >= len(h):
                h = self._deg_hist = np.concatenate(
                    [h, np.zeros(b + 1 - len(h), dtype=h.dtype)]
                )
            h[b] += step

    def ensure_device(self) -> None:
        """Re-upload device tensors from the host mirrors if a delta made
        them stale (one upload amortizes a burst of point mutations).

        Thread-safe under concurrent readers: the rebuild updates several
        fields, so it runs under the shared build lock with a re-check;
        the staleness flag clears LAST, so lock-free fast-path readers
        only skip once every field is fresh (mutations themselves are
        excluded by the server's write lock — see utils/rwlock.py)."""
        if not self._device_stale:
            return
        with _BUILD_LOCK:
            if not self._device_stale:
                return
            fresh = _csr_from_arrays(self.h_src, self.h_offsets, self._h_dst)
            self.src = fresh.src
            self.offsets = fresh.offsets
            self.dst = fresh.dst
            self._device_stale = False
            led = _ledger.current()
            if led is not None:
                # the re-upload is this request's staging cost: the CSR
                # triple just crossed host→device on its behalf
                led.bytes_h2d += int(
                    self.src.nbytes + self.offsets.nbytes + self.dst.nbytes
                )


def _ivm_repair_gate(n_delta: int, entry_edges: float) -> bool:
    """The repair-vs-rebuild decision for one derived view (IVM): off
    when the IVM gate is, else the planner's cost call
    (query/planner.py::repair_route — recorded like every other route
    decision, visible at /debug/planner)."""
    from dgraph_tpu.ivm import ivm_enabled

    if not ivm_enabled():
        return False
    from dgraph_tpu.query import planner

    ok, dec = planner.repair_route(n_delta, entry_edges)
    if dec is not None:
        planner.record(None, dec)
    return ok


def _resident_cap(n_edges: int) -> int:
    """Capacity of the resident dst buffer: live edges plus growth
    headroom (~1/8th, floor 1024) so point-mutation bursts merge on
    device instead of reseeding, rounded to the gather kernel's 128-lane
    granule PLUS one slack tile — the layout contract of
    ops/pallas_gather.py (a row's tail tile may read up to 127 lanes
    past its span without bounds checks)."""
    head = max(n_edges // 8, 1024)
    return ((n_edges + head + 127) // 128) * 128 + 128


@jax.jit
def _resident_merge(off, dst, add_r, add_d, del_r, del_d):
    """Jitted segment-scatter: produce the NEXT epoch's (offsets, dst)
    from the live buffers plus padded (row, dst) delta pairs — the
    device-side twin of ``CSRArena._apply_delta_locked``'s host merge,
    with sorts in place of np.insert/np.delete (no int64 composite keys:
    x64 is disabled, so the (row, dst, tag) triple rides ``lexsort``).

    Correctness leans on the store-journal contract the host merge
    already relies on: adds must not already exist, dels must exist, and
    ``_try_apply_delta`` nets the journal so no key is both — hence a
    del's (row, dst) twin is exactly one live edge, and with ``tag`` as
    the last sort key it lands IMMEDIATELY after that twin.  Delta pads
    carry (SENT, SENT) and sort past every live row.  Registered as
    "resident.merge" in the device-program contract registry."""
    sb1 = off.shape[0]              # Sb + 1 (static)
    big = jnp.int32(sb1)            # > any live row index
    ecap = dst.shape[0]
    idx = jnp.arange(ecap, dtype=jnp.int32)
    # row of each packed edge slot; off[-1] == E by the pad contract
    er = jnp.searchsorted(off[1:], idx, side="right").astype(jnp.int32)
    live = idx < off[-1]
    rows0 = jnp.where(live, er, big)
    dst0 = jnp.where(live, dst, SENT)
    rows_c = jnp.concatenate([rows0, add_r, del_r])
    dst_c = jnp.concatenate([dst0, add_d, del_d])
    tag = jnp.concatenate([
        jnp.zeros(ecap + add_r.shape[0], jnp.int32),
        jnp.ones(del_r.shape[0], jnp.int32),
    ])
    o = jnp.lexsort((tag, dst_c, rows_c))
    r_s, d_s, t_s = rows_c[o], dst_c[o], tag[o]
    nxt_del = jnp.concatenate([t_s[1:] == 1, jnp.zeros(1, bool)])
    same = jnp.concatenate([
        (r_s[1:] == r_s[:-1]) & (d_s[1:] == d_s[:-1]),
        jnp.zeros(1, bool),
    ])
    remove = (t_s == 1) | (nxt_del & same)
    r_f = jnp.where(remove, big, r_s)
    d_f = jnp.where(remove, SENT, d_s)
    o2 = jnp.lexsort((d_f, r_f))
    r_f = r_f[o2][:ecap]
    d_f = d_f[o2][:ecap]
    # new offsets by rank: matches _csr_from_arrays pad semantics
    # (off[r] == E' for every padding row r > S, dst SENT-padded)
    new_off = jnp.searchsorted(
        r_f, jnp.arange(sb1, dtype=jnp.int32), side="left"
    ).astype(jnp.int32)
    return new_off, d_f


class ResidentArena:
    """Device-pinned CSR (offsets + packed dst) for the Pallas gather
    tier: the buffers ``ops.gather_pallas`` walks directly in HBM — the
    "store format IS the kernel format" endpoint (PAPERS.md RedisGraph/
    GraphBLAS line).  Unlike ``CSRArena.ensure_device`` — which re-stages
    the full CSR triple after every mutation — a resident arena absorbs
    deltas ON DEVICE (``_resident_merge``) under double-buffered epochs:
    the merge produces the next epoch's buffers, the reference flip in
    ``apply_delta`` is the atomic swap, and the previous epoch's buffers
    stay pinned as the shadow so in-flight expansions holding them read
    a consistent snapshot.  ``device_bytes()`` counts live AND shadow,
    each exactly once — the constant-across-flips total the ArenaManager
    budget accountant sees (no transient double-count in the flip
    window)."""

    def __init__(self, off: jnp.ndarray, dst: jnp.ndarray, n_edges: int):
        self.off = off              # int32[Sb+1], live epoch
        self.dst = dst              # int32[Ecap], SENT slack-padded
        self.n_edges = int(n_edges)
        self._prev: Optional[tuple] = None  # shadow: previous epoch

    @property
    def ecap(self) -> int:
        return int(self.dst.shape[0])

    @classmethod
    def seed(cls, h_offsets, h_dst, n_rows: int, n_edges: int):
        """Initial (or reseed) upload from the host mirrors — the ONE
        sanctioned full staging of a resident arena, charged h2d."""
        Sb = ops.bucket(max(1, n_rows))
        E = int(n_edges)
        off = np.full(Sb + 1, E, dtype=np.int32)
        off[: n_rows + 1] = h_offsets.astype(np.int32)
        dstp = np.full(_resident_cap(E), SENT, dtype=np.int32)
        if E:
            dstp[:E] = np.asarray(h_dst[:E], dtype=np.int32)
        ra = cls(jnp.asarray(off), jnp.asarray(dstp), E)
        led = _ledger.current()
        if led is not None:
            led.bytes_h2d += int(ra.off.nbytes + ra.dst.nbytes)
        return ra

    def apply_delta(self, add_r, add_d, del_r, del_d, n_edges: int) -> None:
        """Merge padded device delta pairs into the NEXT epoch's buffers
        and flip.  Only the delta pairs cross the boundary (charged h2d);
        the merge inputs and outputs never leave the device."""
        new_off, new_dst = _resident_merge(
            self.off, self.dst, add_r, add_d, del_r, del_d
        )
        led = _ledger.current()
        if led is not None:
            led.bytes_h2d += int(
                add_r.nbytes + add_d.nbytes + del_r.nbytes + del_d.nbytes
            )
        # the flip: previous epoch's buffers become the shadow (readers
        # holding them stay consistent; the NEXT flip releases them)
        self._prev = (self.off, self.dst)
        self.off = new_off
        self.dst = new_dst
        self.n_edges = int(n_edges)

    def expand_packed(
        self, rows: jnp.ndarray, cap: int, interpret: bool = False
    ) -> jnp.ndarray:
        """Packed frontier expansion against the LIVE epoch buffers:
        device-in, device-out, concat([out, seg]) like the engine's
        ``_packed_expand_csr`` — the transfer-free hop core (the engine
        fetches the result and charges the ledger itself)."""
        return ops.gather_pallas_packed(
            self.off, self.dst, rows, cap, interpret=interpret
        )

    def device_bytes(self) -> int:
        n = int(self.off.nbytes + self.dst.nbytes)
        if self._prev is not None:
            n += int(sum(t.nbytes for t in self._prev))
        return n


def _build_csr(rows_to_dsts: Dict[int, np.ndarray]) -> CSRArena:
    """Build a CSR arena from {row_key: array-of-dst} (host)."""
    keys = np.array(sorted(rows_to_dsts.keys()), dtype=np.int64)
    S = len(keys)
    degs = np.array([len(rows_to_dsts[k]) for k in keys], dtype=np.int64)
    offsets = np.zeros(S + 1, dtype=np.int64)
    np.cumsum(degs, out=offsets[1:])
    E = int(offsets[-1])
    dst = np.empty(E, dtype=np.int32)
    for i, k in enumerate(keys):
        d = np.sort(np.asarray(list(rows_to_dsts[k]), dtype=np.int32))
        dst[offsets[i] : offsets[i + 1]] = d
    return _csr_from_arrays(keys, offsets, dst)


def _edges_columnar(edges: Dict[int, set]) -> Tuple[np.ndarray, np.ndarray]:
    """Flatten a dict-of-sets edge map into parallel (src, dst) arrays in
    ONE pass — per-row work is two C-speed slice assignments, so the
    million-row predicates of a 21M-quad graph extract in seconds (the
    per-row _build_csr path took a python sort per row)."""
    n = sum(len(s) for s in edges.values())
    src = np.empty(n, dtype=np.int64)
    dst = np.empty(n, dtype=np.int64)
    i = 0
    for u, s in edges.items():
        k = len(s)
        src[i : i + k] = u
        dst[i : i + k] = list(s)
        i += k
    return src, dst


def _sorted_unique_edges(src: np.ndarray, dst: np.ndarray):
    """Sort edge pairs by (src, dst) and drop duplicates (vectorized)."""
    src = np.asarray(src, dtype=np.int64)
    dst = np.asarray(dst, dtype=np.int64)
    order = np.lexsort((dst, src))
    s, d = src[order], dst[order]
    if len(s):
        keep = np.ones(len(s), dtype=bool)
        keep[1:] = (s[1:] != s[:-1]) | (d[1:] != d[:-1])
        s, d = s[keep], d[keep]
    return s, d


def csr_from_edges(
    src: np.ndarray, dst: np.ndarray, row_universe: Optional[np.ndarray] = None
) -> CSRArena:
    """Vectorized bulk CSR construction from parallel edge arrays — no
    per-row python loops (one global lexsort).  ``row_universe`` adds
    degree-0 rows for uids beyond the edge sources (the has()/_predicate_
    arena needs rows for uids that only carry values)."""
    s, d = _sorted_unique_edges(src, dst)
    ekeys, counts = np.unique(s, return_counts=True)
    if row_universe is not None and len(row_universe):
        keys = np.union1d(ekeys, np.asarray(row_universe, dtype=np.int64))
        full = np.zeros(len(keys), dtype=np.int64)
        full[np.searchsorted(keys, ekeys)] = counts
        counts = full
    else:
        keys = ekeys
    offsets = np.zeros(len(keys) + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    return _csr_from_arrays(keys, offsets, d.astype(np.int32))


def csr_dense_from_edges(src: np.ndarray, dst: np.ndarray, n_nodes: int) -> CSRArena:
    """Dense CSR: one row per uid in [0, n_nodes] (degree 0 where absent),
    so frontier uids ARE row indices — no searchsorted on the query path.
    The layout of choice for whole-graph predicates at bench scale."""
    s, d = _sorted_unique_edges(src, dst)
    counts = np.bincount(s, minlength=n_nodes + 1)
    offsets = np.zeros(n_nodes + 2, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    keys = np.arange(n_nodes + 1, dtype=np.int64)
    return _csr_from_arrays(keys, offsets, d.astype(np.int32))


def _csr_from_arrays(keys: np.ndarray, offsets: np.ndarray, dst: np.ndarray) -> CSRArena:
    S, E = len(keys), len(dst)
    Sb = ops.bucket(max(1, S))
    Eb = ops.bucket(max(1, E))
    src_pad = np.full(Sb, SENT, dtype=np.int32)
    src_pad[:S] = keys.astype(np.int32)
    off_pad = np.full(Sb + 1, offsets[-1] if S else 0, dtype=np.int32)
    off_pad[: S + 1] = offsets.astype(np.int32)
    dst_pad = np.full(Eb, SENT, dtype=np.int32)
    dst_pad[:E] = dst
    return CSRArena(
        src=jnp.asarray(src_pad),
        offsets=jnp.asarray(off_pad),
        dst=jnp.asarray(dst_pad),
        h_src=keys,
        h_offsets=offsets,
        n_rows=S,
        n_edges=E,
    )


@dataclass
class IndexArena:
    """Secondary index: host token table + device token-row → uids CSR."""

    tokenizer: str
    tokens: list                    # sorted token keys (host)
    csr: CSRArena                   # rows aligned with ``tokens``
    lossy: bool

    def row_of(self, token) -> int:
        i = bisect.bisect_left(self.tokens, token)
        if i < len(self.tokens) and self.tokens[i] == token:
            return i
        return -1

    def device_bytes(self) -> int:
        return self.csr.device_bytes()

    def row_range(self, lo=None, hi=None, lo_open=False, hi_open=False) -> Tuple[int, int]:
        """Token rows t with lo <=(<) t <=(<) hi, as [start, end)."""
        start = 0
        end = len(self.tokens)
        if lo is not None:
            start = (
                bisect.bisect_right(self.tokens, lo)
                if lo_open
                else bisect.bisect_left(self.tokens, lo)
            )
        if hi is not None:
            end = (
                bisect.bisect_left(self.tokens, hi)
                if hi_open
                else bisect.bisect_right(self.tokens, hi)
            )
        return start, max(start, end)


@dataclass
class ValueArena:
    """Numeric values on device for order-by/aggregation/math."""

    src: jnp.ndarray                # int32[Sb] sorted uids, SENT-padded
    vals: jnp.ndarray               # float32[Sb]; padding slots hold NaN
    ranks: jnp.ndarray              # int32[Sb] dense rank of the EXACT
                                    # float64 value (device ordering by
                                    # rank is exact; float32 vals are not);
                                    # padding slots hold -1
    h_src: np.ndarray               # int64[S]
    h_vals: np.ndarray              # float64[S]
    h_ranks: np.ndarray             # int32[S] host mirror of ranks (exact)
    n: int
    langless: bool = True           # no lang-tagged values existed for the
                                    # predicate — untagged host lookup and
                                    # this arena agree uid-for-uid

    def device_bytes(self) -> int:
        return sum(
            t.size * t.dtype.itemsize for t in (self.src, self.vals, self.ranks)
        )


def _cache_locked(fn):
    """Run an ArenaManager accessor under its cache lock (see __init__)."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, *a, **k):
        with self._cache_lock:
            return fn(self, *a, **k)

    return wrapper


class ArenaManager:
    """Builds and caches arenas; invalidates on store dirty marks.

    The analog of posting's lcache + periodicCommit (posting/lists.go):
    arenas for clean predicates stay resident on device between queries.
    Accessors are thread-safe for concurrent read queries: the cache lock
    guards dict lookups and dirty-refresh only; heavy builds run outside
    it under per-key build locks (_get_or_build), so a cold predicate
    stalls only readers of that same predicate.
    """

    # graftcheck tier 3: the LRU accounting and the full-store-clear
    # generation are bumped from every query thread — the witness holds
    # them to the _cache_lock discipline the docstring above promises.
    # expand_device_min is deliberately NOT listed: it is a GIL-atomic
    # planner knob (engine setter rebinds an int; readers take either
    # value and both are valid plans).
    __race_fields__ = frozenset({"_lru_total", "_inval_gen_star"})

    def __init__(
        self,
        store: PostingStore,
        mesh=None,
        shard_threshold: int = 4096,
        budget_bytes: Optional[int] = None,
    ):
        self.store = store
        # device mesh for uid-range row sharding of big predicates (the
        # intra-predicate sharding the reference lacks, SURVEY.md §5);
        # None = single-device execution.  ``self.mesh`` is a property:
        # with the elastic fault domain active it reads the CURRENT
        # surviving sub-mesh, so every consumer (sharded_csr width,
        # executor dispatch, scheduler concurrency) follows a re-shard
        # through one swap.
        self._mesh = mesh
        self.shard_threshold = shard_threshold
        # mesh serving plane (PR 17): predicate→shard placement so
        # co-resident predicates don't all pile shard 0 (their densest
        # uid range) on the same chip, plus the memoized serving-path
        # executor the engine/chain dispatch through
        self.mesh_plan = None
        self._mesh_exec = None
        # elastic mesh fault domain (mesh/fault.py): per-chip health +
        # epoch-fenced sub-mesh re-sharding.  Only meaningful when there
        # is more than one chip to lose; DGRAPH_TPU_MESH_ELASTIC=0
        # restores the PR 17 monolithic plane exactly.
        self.mesh_fault = None
        if mesh is not None:
            from dgraph_tpu.mesh.plan import MeshPlan

            self.mesh_plan = MeshPlan.load(int(mesh.shape["model"]))
            if int(mesh.shape["model"]) > 1:
                from dgraph_tpu.mesh import fault as _mesh_fault

                if _mesh_fault.elastic_enabled():
                    self.mesh_fault = _mesh_fault.MeshFaultDomain(
                        self, mesh
                    )
        # single source of truth for host-vs-device expansion routing
        # (engine and FuncResolver both read it; engine may retune at
        # runtime) — see QueryEngine.__init__ for the rationale.  While
        # it sits at the planconfig default, the adaptive planner
        # (query/planner.py) substitutes its calibrated break-even;
        # assigning it (or pinning the env knob) restores the static gate
        from dgraph_tpu.utils import planconfig as _planconfig

        self.expand_device_min = _planconfig.expand_device_min()
        self._data: Dict[str, CSRArena] = {}
        self._reverse: Dict[str, CSRArena] = {}
        self._index: Dict[Tuple[str, str], IndexArena] = {}
        self._values: Dict[str, ValueArena] = {}
        self._sharded: Dict[Tuple[str, bool], tuple] = {}
        # protects the cache dicts + refresh bookkeeping ONLY — heavy
        # arena builds run outside it under per-key build locks
        # (_get_or_build), so one cold predicate never stalls readers of
        # warm ones.  RLock because accessors nest (has_rows → data).
        self._cache_lock = threading.RLock()
        self._build_locks: Dict[tuple, threading.Lock] = {}
        # journal-consumption generations: refresh() bumps a predicate's
        # counter whenever it consumes that predicate's journal window
        # (delta applied in place OR caches dropped for rebuild).  A
        # build snapshots the counter before peeking the store and
        # retries if it moved — otherwise a writer's refresh can pop the
        # journal while a cold build holds a pre-write peek, and the
        # build then caches an arena the consumed delta never reaches
        # (the write is lost with no dirty mark left to repair it).
        self._inval_gen: Dict[str, int] = {}
        self._inval_gen_star = 0  # bumped by the "*" full-store clear
        # HBM residency budget (bytes): the analog of the reference's
        # memory-watermark-sized posting LRU (posting/lru.go:57,
        # posting/lists.go:191).  0 = unlimited.  Cold arenas evict
        # WHOLLY from the cache (host store keeps the truth; the next
        # access rebuilds), touched arenas move to the LRU tail.
        from collections import OrderedDict as _OD

        import os as _os

        self.budget_bytes = int(
            budget_bytes
            if budget_bytes is not None
            else _os.environ.get("DGRAPH_TPU_ARENA_BUDGET", 0)
        )
        self._lru: "_OD[tuple, int]" = _OD()  # (cache id, key) -> bytes
        self._lru_total = 0  # running sum of _lru values (O(1) touches)
        # tier-1 hop-expansion cache (dgraph_tpu/cache/hop.py): expansion
        # results are arena-snapshot state, so the cache lives and dies
        # with this manager and must hear about arena evictions below
        # (id-keyed entries may never outlive the arena object).  None
        # when DGRAPH_TPU_CACHE=0 — the expander then skips every probe.
        from dgraph_tpu.cache import HopCache, cache_enabled

        self.hop_cache = HopCache() if cache_enabled() else None
        self._caches_by_id = {
            id(self._data): self._data,
            id(self._reverse): self._reverse,
            id(self._index): self._index,
            id(self._values): self._values,
            id(self._sharded): self._sharded,
        }
        self.evictions = 0

    def _get_or_build(self, cache, key, build, valid=None, gen_key=None):
        """cache[key], building OUTSIDE the cache lock under a per-key
        build lock: concurrent readers of other keys proceed; concurrent
        readers of the same key wait for one build instead of duplicating
        it (the pattern of ClusterStore._remote_peek's fetch locks).
        ``valid`` optionally rejects a cached entry (sharded_csr checks
        its source-arena identity).  The build-lock entry is dropped even
        when the build raises, so a failed build can't wedge the key.

        ``gen_key`` (the predicate the build peeks) closes the
        build-vs-journal race: refresh() consuming a journal window
        between our peek and our cache commit means the consumed delta
        can neither reach the arena we are building (it isn't cached
        yet) nor survive for a later refresh — so the build must retry
        on a fresh peek.  The commit and the generation check share the
        cache lock with refresh, so a window consumed after the check
        necessarily sees (and repairs) the entry we just cached."""
        lkey = (id(cache), key)
        with self._cache_lock:
            a = cache.get(key)
            if a is not None and (valid is None or valid(a)):
                self._touch(lkey, a)
                return a
            bl = self._build_locks.setdefault(lkey, threading.Lock())
        with bl:
            with self._cache_lock:
                a = cache.get(key)
                if a is not None and (valid is None or valid(a)):
                    self._touch(lkey, a)
                    return a
            try:
                while True:
                    with self._cache_lock:
                        g0 = (
                            self._inval_gen.get(gen_key, 0),
                            self._inval_gen_star,
                        )
                    a = build()
                    with self._cache_lock:
                        if gen_key is not None and g0 != (
                            self._inval_gen.get(gen_key, 0),
                            self._inval_gen_star,
                        ):
                            continue  # journal consumed mid-build: re-peek
                        cache[key] = a
                        self._touch(lkey, a)
                        self._evict_over_budget(protect=lkey)
                        return a
            finally:
                with self._cache_lock:
                    self._build_locks.pop(lkey, None)

    def _touch(self, lkey: tuple, obj) -> None:
        """LRU bookkeeping under _cache_lock: refresh recency + size (lazy
        device layouts — lut/chunked/inline — built after caching grow the
        footprint, so warm touches also re-check the budget)."""
        if lkey[0] == id(self._sharded):
            obj = obj[1]  # (_sharded caches (source arena, ShardedArena))
        db = getattr(obj, "device_bytes", None)
        if db is None:
            return
        new = db()
        self._lru_total += new - self._lru.get(lkey, 0)
        self._lru[lkey] = new
        self._lru.move_to_end(lkey)
        self._evict_over_budget(protect=lkey)

    def _lru_drop(self, cache, key) -> None:
        """Remove a cache entry's budget accounting (refresh invalidation
        path) — phantom bytes would otherwise shrink the budget forever."""
        b = self._lru.pop((id(cache), key), None)
        if b is not None:
            self._lru_total -= b

    def _evict_over_budget(self, protect: tuple) -> None:
        """Drop least-recently-used arenas until within budget (never the
        entry just touched).  Evicting a data/reverse arena also drops its
        mesh-sharded view — the view holds a reference that would pin the
        arena's HBM alive.  Concurrent readers holding a popped arena keep
        using their reference safely — the object only leaves the cache,
        and the momentary overshoot ends with their request."""
        if not self.budget_bytes:
            return
        while self._lru_total > self.budget_bytes and len(self._lru) > 1:
            if not self._pop_lru_victim(protect):
                break

    def _pop_lru_victim(self, protect: Optional[tuple] = None) -> bool:
        """Evict the least-recently-used entry (never ``protect``);
        returns whether one was dropped.  Caller holds _cache_lock."""
        if not self._lru:
            return False
        victim, vbytes = next(iter(self._lru.items()))
        if victim == protect:
            return False
        self._lru.pop(victim)
        self._lru_total -= vbytes
        cache = self._caches_by_id.get(victim[0])
        gone = cache.pop(victim[1], None) if cache is not None else None
        if gone is not None and self.hop_cache is not None:
            # tier-1 entries are keyed by id(arena): drop them NOW,
            # while the object is still alive, or a later allocation
            # recycling the id could alias a dead entry's key
            self.hop_cache.drop_arena(id(gone))
        if cache is self._data or cache is self._reverse:
            skey = (victim[1], cache is self._reverse)
            if skey in self._sharded:
                self._sharded.pop(skey, None)
                self._lru_drop(self._sharded, skey)
        self.evictions += 1
        ARENA_EVICTIONS.add(1)
        return True

    def evict_for_oom(self, n: int = 2) -> int:
        """HBM-pressure valve (utils/devguard.py): a device dispatch
        just failed RESOURCE_EXHAUSTED, so drop up to ``n`` LRU entries
        REGARDLESS of the configured budget (the budget is an estimate;
        the allocator's verdict is ground truth) to give the one retry
        headroom.  Returns how many entries were dropped — zero means
        there is nothing left to free and the caller should fall
        straight to the host route.  In-flight expansions holding a
        dropped arena keep using their reference safely, exactly like
        budget eviction; the device copy is freed when the last
        reference dies."""
        with self._cache_lock:
            dropped = 0
            while dropped < n and len(self._lru) > 1:
                if not self._pop_lru_victim():
                    break
                dropped += 1
            return dropped

    def residency(self) -> dict:
        """HBM-residency + program-cache snapshot (obs/device.py's data
        source).  ``resident_bytes`` is the budget accountant's running
        total — the same number eviction decisions are made on — so the
        telemetry can never disagree with the enforcement.  Program
        counts walk the cached data/reverse arenas' lazily-attached
        expanders/tile sets; the walk is O(cached predicates), debug-
        endpoint cost, never hot-path."""
        with self._cache_lock:
            resident = self._lru_total
            entries = len(self._lru)
            evictions = self.evictions
            arenas = list(self._data.values()) + list(
                self._reverse.values()
            )
        tile_bytes = 0
        tile_sets = 0
        classed = 0
        classed_programs = 0
        for a in arenas:
            pt = getattr(a, "_tiles", None)
            if pt is not None:
                tile_bytes += pt.device_bytes()
                tile_sets += 1
            ce = getattr(a, "_classed", None)
            if ce is not None:
                classed += 1
                classed_programs += len(ce._programs)
        return {
            "resident_bytes": resident,
            "budget_bytes": self.budget_bytes,
            "headroom_bytes": (
                max(0, self.budget_bytes - resident)
                if self.budget_bytes else None
            ),
            "entries": entries,
            "evictions": evictions,
            "tile_bytes": tile_bytes,
            "program_caches": {
                "classed_expanders": classed,
                "classed_programs": classed_programs,
                "tile_sets": tile_sets,
            },
        }

    @_cache_locked
    def refresh(self):
        """Drop or incrementally update cached arenas for predicates
        mutated since last refresh.  Small uid-edge deltas (the store's
        bounded journal) update cached data/reverse arenas in place —
        the gentle-commit amortization (posting/lists.go:109-215) — while
        value mutations, bulk loads and journal overflow fall back to the
        full rebuild."""
        dirty = self.store.dirty
        if not dirty:
            return
        # Never blanket-clear the dirty set: concurrent readers (admitted
        # by the server's RW lock) may add marks between our snapshot and
        # the clear (ClusterStore._drain_dirty runs inside peek); only
        # remove marks we actually processed, so a racing mark survives
        # for the next refresh.
        if "*" in dirty:  # full-store replacement (snapshot restore)
            self._inval_gen_star += 1  # in-flight builds must re-peek
            if self.hop_cache is not None:
                self.hop_cache.clear()
            self._data.clear()
            self._reverse.clear()
            self._values.clear()
            self._index.clear()
            self._sharded.clear()
            self._lru.clear()
            self._lru_total = 0
            dirty.discard("*")
            # remaining per-predicate marks fall through to the loop:
            # their caches are already gone, so it just consumes deltas
        deltas = getattr(self.store, "delta", {})
        bases = getattr(self.store, "delta_base", {})
        for p in list(dirty):
            delta = deltas.pop(p, None)
            # the journal window's repair base (models/store.py) is
            # consumed WITH the journal — a stale base must never
            # re-key a later window's entries
            base = bases.pop(p, None)
            # consuming this window invalidates any build mid-peek for
            # the predicate: the delta can't reach an arena that isn't
            # cached yet, so the builder must re-peek (_get_or_build)
            self._inval_gen[p] = self._inval_gen.get(p, 0) + 1
            if delta is not None and self._try_apply_delta(p, delta, base):
                dirty.discard(p)
                continue
            for key in [k for k in self._data if k == p or k.startswith(p + "\x00")]:
                gone = self._data.pop(key, None)
                if gone is not None and self.hop_cache is not None:
                    self.hop_cache.drop_arena(id(gone))
                self._lru_drop(self._data, key)
            gone = self._reverse.pop(p, None)
            if gone is not None and self.hop_cache is not None:
                self.hop_cache.drop_arena(id(gone))
            self._lru_drop(self._reverse, p)
            self._values.pop(p, None)
            self._lru_drop(self._values, p)
            for sk in ((p, False), (p, True)):
                self._sharded.pop(sk, None)
                self._lru_drop(self._sharded, sk)
            for key in [k for k in self._index if k[0] == p]:
                self._index.pop(key, None)
                self._lru_drop(self._index, key)
            dirty.discard(p)

    def _try_apply_delta(self, pred: str, delta: list, base=None) -> bool:
        """Incrementally update the cached data (and reverse) arena for
        ``pred``.  Returns False when no cached arena exists (nothing to
        update — the next access builds fresh anyway) or a has-rows
        variant is cached (its row universe can shift: full rebuild).

        IVM (dgraph_tpu/ivm/): after the arena mirrors absorb the
        delta, the predicate's cached hop expansions absorb it too —
        repaired IN PLACE and re-keyed from ``base`` (the pred version
        every live entry carries, recorded when the journal window
        opened) to the predicate's post-mutation version, behind the
        planner's repair-vs-rebuild gate.  Entries a repair cannot fix
        simply stay stale-keyed and die by sweep, exactly as before."""
        a = self._data.get(pred)
        if a is None or (pred + "\x00has") in self._data:
            return False
        if (pred, False) in self._sharded or (pred, True) in self._sharded:
            return False  # mesh-sharded copies rebuild wholesale
        _E = np.zeros((0, 2), dtype=np.int64)
        if not delta:
            # facet-only touches: arenas unaffected, and the cached
            # expansions are still EXACT — a zero-delta repair merely
            # re-keys them to the new pred version (facet edits live in
            # the host store, never in (out, seg_ptr))
            self._repair_hop_entries(pred, a, _E, _E, base, gate=True)
            return True
        # row-garbage bound: repeated delete churn leaves degree-0 rows
        # that only a full rebuild reclaims; rebuild once they dominate
        zero_rows = int(np.count_nonzero(np.diff(a.h_offsets) == 0))
        if zero_rows > max(4096, a.n_rows // 4):
            return False
        net: Dict[Tuple[int, int], int] = {}
        for s, d, sign in delta:
            net[(s, d)] = net.get((s, d), 0) + sign
        adds = np.array(
            [k for k, v in net.items() if v > 0], dtype=np.int64
        ).reshape(-1, 2)
        dels = np.array(
            [k for k, v in net.items() if v < 0], dtype=np.int64
        ).reshape(-1, 2)
        a.apply_delta(adds, dels)
        r = self._reverse.get(pred)
        if r is not None:
            r.apply_delta(adds[:, ::-1], dels[:, ::-1])
        n_delta = len(adds) + len(dels)
        self._repair_hop_entries(
            pred, a, adds, dels, base,
            # the cost prior prices a typical warm entry as a ~32-row
            # frontier at this arena's mean fan-out (the tiers cap huge
            # entries anyway, so the prior errs small → errs toward
            # rebuild, the safe side)
            gate=(n_delta > 0 and _ivm_repair_gate(
                n_delta, max(1.0, a.avg_degree) * 32.0
            )),
        )
        # post-delta epoch sweep (the delta-driven twin of the PR 15
        # eviction race): entries the repair pass did not carry to the
        # new epoch describe a snapshot that no longer exists — drop
        # them now rather than letting them squat until their sweep
        if self.hop_cache is not None and n_delta > 0:
            self.hop_cache.drop_stale_epoch(id(a), a.epoch)
            if r is not None:
                self.hop_cache.drop_stale_epoch(id(r), r.epoch)
        return True

    def _repair_hop_entries(
        self, pred: str, a: CSRArena, adds, dels, base, gate: bool
    ) -> None:
        """Repair (or zero-delta re-key) the tier-1 entries for ``pred``
        on both directions' arenas.  Skips entirely when: the gate said
        rebuild, IVM is off (entries are keyed on the global version —
        nothing here could re-key them safely), the journal window
        carried no base, or a non-scopeable change (floor) landed
        inside the window (a repaired entry must never claim freshness
        across a schema epoch)."""
        if self.hop_cache is None or not gate or base is None:
            return
        from dgraph_tpu import ivm
        from dgraph_tpu.utils.metrics import IVM_REPAIR_EDGES, IVM_REPAIRS

        if not ivm.ivm_enabled():
            return
        pv = getattr(self.store, "pred_versions", None)
        if pv is None:
            return
        new_v = pv.get(pred, 0)
        floor = getattr(self.store, "pred_floor", 0)
        if new_v <= base or floor > base:
            return
        from dgraph_tpu import obs

        repaired = dropped = 0
        with obs.child("ivm.repair") as sp:
            for arena, rev, ad, dl in (
                (a, False, adds, dels),
                (self._reverse.get(pred), True,
                 adds[:, ::-1], dels[:, ::-1]),
            ):
                if arena is None:
                    continue
                # the delta that drives this repair bumped the arena
                # epoch exactly once (zero-delta re-keys bump nothing)
                ne = getattr(arena, "epoch", 0)
                oe = ne - 1 if (len(adds) or len(dels)) else ne
                rep, drop = self.hop_cache.repair_pred(
                    id(arena), pred, rev, ad, dl, base, new_v,
                    old_epoch=oe, new_epoch=ne,
                )
                repaired += rep
                dropped += drop
            sp.set_attr("pred", pred)
            sp.set_attr("delta", len(adds) + len(dels))
            sp.set_attr("repaired", repaired)
            sp.set_attr("dropped", dropped)
        if repaired:
            IVM_REPAIRS.add(("hop", "repaired"))
            IVM_REPAIR_EDGES.add((len(adds) + len(dels)) * repaired)
            led = _ledger.current()
            if led is not None:
                # attributed to the request whose refresh drove the
                # repair (usually the mutation; sometimes the first
                # reader after it — same attribution rule as spans)
                led.repairs += repaired
        if dropped:
            IVM_REPAIRS.add(("hop", "rebuild"))

    # -- mesh sharding -------------------------------------------------------

    @property
    def mesh(self):
        """The CURRENT serving mesh: the boot mesh, or — when the
        elastic fault domain has evicted a chip — the surviving
        sub-mesh it re-sharded onto.  None = unsharded execution."""
        if self.mesh_fault is not None:
            return self.mesh_fault.mesh
        return self._mesh

    @mesh.setter
    def mesh(self, m):
        self._mesh = m

    def sharded_csr(self, pred: str, reverse: bool = False):
        """Row-sharded view of a predicate's CSR over the mesh's 'model'
        axis, cached against the source arena's identity (rebuilds follow
        the same dirty invalidation as the arena itself) AND the
        MeshPlan offset it was placed under — a ``rebalance()`` moves a
        predicate's offset, so its next read rebuilds under the new
        placement instead of serving the old roll."""
        from dgraph_tpu.parallel.mesh import shard_arena_rows

        a = self.reverse(pred) if reverse else self.data(pred)
        pkey = ("~" + pred) if reverse else pred

        def build():
            n_model = self.mesh.shape["model"]
            sa = shard_arena_rows(
                a.h_src, a.h_offsets, a.host_dst(), n_model
            )
            off = 0
            if self.mesh_plan is not None:
                sa = self.mesh_plan.placed(pkey, sa)
                off = self.mesh_plan.placement.get(pkey, 0)
            return (a, sa, off)

        def valid(e):
            if e[0] is not a:
                return False
            # an elastic re-shard changed the model-axis width: the old
            # width's rolls are unservable on the new sub-mesh
            if e[1].n_shards != int(self.mesh.shape["model"]):
                return False
            if self.mesh_plan is None:
                return True
            return self.mesh_plan.placement.get(pkey, 0) == e[2]

        return self._get_or_build(
            self._sharded, (pred, reverse), build, valid=valid,
            gen_key=pred,
        )[1]

    def mesh_executor(self):
        """The memoized serving-path executor (dgraph_tpu/mesh) over
        this manager's mesh — None when unsharded."""
        if self.mesh is None:
            return None
        if self._mesh_exec is None:
            from dgraph_tpu.mesh.executor import MeshExecutor

            self._mesh_exec = MeshExecutor(self)
        return self._mesh_exec

    def use_mesh_for(self, arena: CSRArena) -> bool:
        """Route this arena's expansions through the row-sharded mesh?

        Two policies (``shard_policy`` attr, default "rows"):
          "rows"  — shard at/above shard_threshold rows (explicit operator
                    knob; the mode every virtual-mesh test pins).
          "model" — consult the ICI crossover cost model
                    (parallel/crossover.py): shard when the model predicts
                    sharded wins for a typical query against this arena's
                    physical size, or when the arena cannot fit one
                    chip's HBM at all.  The threshold still floors it.
        """
        if self.mesh is None or arena.n_rows < self.shard_threshold:
            return False
        if getattr(self, "shard_policy", "rows") == "model":
            from dgraph_tpu.parallel.crossover import should_shard

            n_model = self.mesh.shape["model"]
            arena_bytes = 32 * arena.n_rows + 4 * arena.n_edges
            avg_deg = arena.n_edges / max(1, arena.n_rows)
            return should_shard(arena_bytes, arena.n_rows, avg_deg, n_model)
        return True

    def drop_sharded(self) -> None:
        """Drop every mesh-sharded view — the elastic re-shard's cache
        surgery: the evicted width's rolls are dead weight on the new
        sub-mesh, and survivors re-seed lazily through sharded_csr
        under the same HBM budget/LRU (this IS the re-seeding
        mechanism; no bulk re-upload)."""
        with self._cache_lock:
            for key in list(self._sharded):
                self._sharded.pop(key, None)
                self._lru_drop(self._sharded, key)

    def warm_sharded(self, mesh):
        """Pre-build sharded views at a rejoin CANDIDATE mesh's width —
        the warm half of warm-then-cutover, run on the fault domain's
        probe thread while live traffic keeps serving the current
        sub-mesh.  Offsets come from the plan's ``preview`` of the
        candidate width so the post-cutover ``rebalance`` finds the
        adopted entries already valid.  Build failures propagate: an
        unprovable warm means no cutover (the chip re-latches)."""
        from dgraph_tpu.mesh.fault import StagedShards
        from dgraph_tpu.mesh.plan import MeshPlan
        from dgraph_tpu.parallel.mesh import shard_arena_rows

        n_model = int(mesh.shape["model"])
        staged = StagedShards(n_model)
        with self._cache_lock:
            keys = list(self._sharded)
        preview = (
            self.mesh_plan.preview(n_model)
            if self.mesh_plan is not None
            else {}
        )
        for pred, reverse in keys:
            a = self.reverse(pred) if reverse else self.data(pred)
            pkey = ("~" + pred) if reverse else pred
            sa = shard_arena_rows(
                a.h_src, a.h_offsets, a.host_dst(), n_model
            )
            off = preview.get(pkey, 0) % n_model
            staged.views[(pred, reverse)] = (
                a, MeshPlan.rolled(sa, off), off,
            )
        return staged

    def adopt_sharded(self, staged) -> None:
        """Cutover half of warm-then-cutover: install the staged views
        built by :meth:`warm_sharded`, with LRU/budget accounting as if
        each had just been built (a stage whose width no longer matches
        the live mesh is the caller's to discard)."""
        if self.mesh is None or int(self.mesh.shape["model"]) != staged.width:
            return
        with self._cache_lock:
            for key, entry in staged.views.items():
                self._sharded[key] = entry
                self._touch((id(self._sharded), key), entry)

    # -- data / reverse ----------------------------------------------------

    def data(self, pred: str) -> CSRArena:
        self.refresh()
        return self._get_or_build(
            self._data, pred, lambda: self._build_data(pred), gen_key=pred
        )

    def _build_data(self, pred: str) -> CSRArena:
        pd = self.store.peek(pred)
        if pd is not None and pd.edges:
            return csr_from_edges(*_edges_columnar(pd.edges))
        return _build_csr({})

    def has_rows(self, pred: str) -> CSRArena:
        """Arena whose rows are every uid with *any* posting (edge or value)
        for the predicate — serves has(pred) and _predicate_ expansion.
        Realized as the data arena for uid preds; for value preds a CSR of
        degree-0 rows whose row set is what matters."""
        self.refresh()
        pd = self.store.peek(pred)
        if pd is None or not pd.values:
            return self.data(pred)
        return self._get_or_build(
            self._data, pred + "\x00has", lambda: self._build_has(pred),
            gen_key=pred,
        )

    def _build_has(self, pred: str) -> CSRArena:
        pd = self.store.peek(pred)
        universe = np.fromiter(pd.uids_with_data(), dtype=np.int64)
        src, dst = _edges_columnar(pd.edges)
        return csr_from_edges(src, dst, row_universe=universe)

    def reverse(self, pred: str) -> CSRArena:
        self.refresh()
        return self._get_or_build(
            self._reverse, pred, lambda: self._build_reverse(pred),
            gen_key=pred,
        )

    def _build_reverse(self, pred: str) -> CSRArena:
        pd = self.store.peek(pred)
        if pd is not None and pd.edges:
            src, dst = _edges_columnar(pd.edges)
            return csr_from_edges(dst, src)  # inverted: one lexsort, no
            # per-target python append loop (posting/index.go:152)
        return _build_csr({})

    # -- secondary indexes ---------------------------------------------------

    def index(self, pred: str, tokenizer: str) -> IndexArena:
        self.refresh()
        return self._get_or_build(
            self._index,
            (pred, tokenizer),
            lambda: self._build_index(pred, tokenizer),
            gen_key=pred,
        )

    def _build_index(self, pred: str, tokenizer: str) -> IndexArena:
        tk = tokmod.get_tokenizer(tokenizer)
        pd = self.store.peek(pred)
        buckets: Dict[object, set] = {}
        if pd is not None:
            for (uid, _lang), val in pd.values.items():
                try:
                    # fulltext analyzes under the VALUE's language tag
                    # (per-language stemmer+stopwords, tok/fts.go:46-142)
                    toks = tokmod.tokens_for_value_lang(tk.name, val, _lang)
                except (ValueError, TypeError, OverflowError):
                    continue  # unindexable value (wrong type, inf, ...)
                for t in toks:
                    buckets.setdefault(t, set()).add(uid)
        tokens = sorted(buckets.keys())
        rows = {
            i: np.fromiter(buckets[t], dtype=np.int64, count=len(buckets[t]))
            for i, t in enumerate(tokens)
        }
        csr = _build_csr(rows)
        # implicit rows: row i of the CSR == tokens[i]
        csr2 = CSRArena(
            src=None,
            offsets=csr.offsets,
            dst=csr.dst,
            h_src=csr.h_src,
            h_offsets=csr.h_offsets,
            n_rows=csr.n_rows,
            n_edges=csr.n_edges,
        )
        return IndexArena(tokenizer=tokenizer, tokens=tokens, csr=csr2, lossy=tk.lossy)

    # -- numeric values ------------------------------------------------------

    def values(self, pred: str) -> ValueArena:
        self.refresh()
        return self._get_or_build(
            self._values, pred, lambda: self._build_values(pred),
            gen_key=pred,
        )

    def _build_values(self, pred: str) -> ValueArena:
        pd = self.store.peek(pred)
        pairs: Dict[int, float] = {}
        langless = True
        if pd is not None:
            # Deterministic lang choice: untagged value wins, else the
            # lexicographically first language (stable across ingest
            # order, unlike dict iteration).
            for (uid, lang) in sorted(pd.values.keys(), key=lambda k: (k[0], k[1] != "", k[1])):
                if lang:
                    langless = False
                if uid in pairs:
                    continue
                x = numeric(pd.values[(uid, lang)])
                if x is not None:
                    pairs[uid] = x
        uids = np.array(sorted(pairs.keys()), dtype=np.int64)
        vals = np.array([pairs[u] for u in uids], dtype=np.float64)
        S = len(uids)
        Sb = ops.bucket(max(1, S))
        su = np.full(Sb, SENT, dtype=np.int32)
        su[:S] = uids.astype(np.int32)
        vv = np.full(Sb, np.nan, dtype=np.float32)
        vv[:S] = vals.astype(np.float32)
        # dense rank of the exact float64 value: device order-by sorts
        # by rank, immune to float32 rounding collisions
        rk = np.full(Sb, -1, dtype=np.int32)
        if S:
            rk[:S] = np.searchsorted(np.unique(vals), vals).astype(np.int32)
        a = ValueArena(
            src=jnp.asarray(su),
            vals=jnp.asarray(vv),
            ranks=jnp.asarray(rk),
            h_src=uids,
            h_vals=vals,
            h_ranks=rk[:S].copy(),
            n=S,
            langless=langless,
        )
        return a
