"""Binary codec for durable records: varints + typed values + edges.

The reference serializes postings/WAL entries as protobuf into Badger
(posting/list.go SyncIfDirty, raftwal/wal.go).  Here the equivalent wire
format is a hand-rolled varint codec shared by the WAL, snapshots and the
bulk loader; the layout is deliberately language-neutral so the C++
fast-path (native/) encodes/decodes the same bytes.

Record payloads (first byte = record tag):

  0x01 EDGE    flags pred src [dst | value] [lang] [facets]
  0x02 SCHEMA  utf8 schema-language text
  0x03 XID     xid-string uid
  0x04 LEASE   next-uid
  0x05 DELPRED pred

Typed values: type byte (TypeID) + payload — zigzag varint for INT,
8-byte LE double for FLOAT, raw byte for BOOL, length-prefixed utf8 for
string-ish types, isoformat string for datetimes, GeoJSON string for GEO.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, Iterator, Optional, Tuple

from dgraph_tpu.models.types import TypeID, TypedValue, parse_datetime

EDGE = 0x01
SCHEMA = 0x02
XID = 0x03
LEASE = 0x04
DELPRED = 0x05
BULKEDGES = 0x06
MEMBER = 0x07   # cluster membership: node_id + serving address
BULKVALS = 0x08  # one record for a predicate group of plain value edges

_F_DEL = 1
_F_VALUE = 2
_F_FACETS = 4
_F_LANG = 8


# -- varints ----------------------------------------------------------------

def put_uvarint(buf: bytearray, x: int) -> None:
    while x >= 0x80:
        buf.append((x & 0x7F) | 0x80)
        x >>= 7
    buf.append(x)


def uvarint(b: bytes, pos: int) -> Tuple[int, int]:
    x = 0
    shift = 0
    while True:
        c = b[pos]
        pos += 1
        x |= (c & 0x7F) << shift
        if c < 0x80:
            return x, pos
        shift += 7


def put_varint(buf: bytearray, x: int) -> None:
    put_uvarint(buf, (x << 1) ^ (x >> 63) if x < 0 else x << 1)


def varint(b: bytes, pos: int) -> Tuple[int, int]:
    u, pos = uvarint(b, pos)
    return (u >> 1) ^ -(u & 1), pos


def put_str(buf: bytearray, s: str) -> None:
    raw = s.encode("utf-8")
    put_uvarint(buf, len(raw))
    buf.extend(raw)


def get_str(b: bytes, pos: int) -> Tuple[str, int]:
    n, pos = uvarint(b, pos)
    return b[pos : pos + n].decode("utf-8"), pos + n


# -- typed values -----------------------------------------------------------

def put_value(buf: bytearray, v: TypedValue) -> None:
    buf.append(int(v.tid))
    t, val = v.tid, v.value
    if t == TypeID.INT:
        put_varint(buf, int(val))
    elif t == TypeID.FLOAT:
        buf.extend(struct.pack("<d", float(val)))
    elif t == TypeID.BOOL:
        buf.append(1 if val else 0)
    elif t in (TypeID.DATETIME, TypeID.DATE):
        put_str(buf, val.isoformat())
    elif t == TypeID.GEO:
        put_str(buf, json.dumps(val.to_geojson(), separators=(",", ":")))
    elif t == TypeID.BINARY:
        raw = bytes(val)
        put_uvarint(buf, len(raw))
        buf.extend(raw)
    else:  # STRING / DEFAULT / PASSWORD / UID-as-str
        put_str(buf, str(val))


def get_value(b: bytes, pos: int) -> Tuple[TypedValue, int]:
    t = TypeID(b[pos])
    pos += 1
    if t == TypeID.INT:
        x, pos = varint(b, pos)
        return TypedValue(t, x), pos
    if t == TypeID.FLOAT:
        (x,) = struct.unpack_from("<d", b, pos)
        return TypedValue(t, x), pos + 8
    if t == TypeID.BOOL:
        return TypedValue(t, b[pos] != 0), pos + 1
    if t in (TypeID.DATETIME, TypeID.DATE):
        s, pos = get_str(b, pos)
        return TypedValue(t, parse_datetime(s)), pos
    if t == TypeID.GEO:
        s, pos = get_str(b, pos)
        from dgraph_tpu.models.geo import parse_geojson

        return TypedValue(t, parse_geojson(s)), pos
    if t == TypeID.BINARY:
        n, pos = uvarint(b, pos)
        return TypedValue(t, bytes(b[pos : pos + n])), pos + n
    s, pos = get_str(b, pos)
    return TypedValue(t, s), pos


def put_facets(buf: bytearray, facets: Dict[str, TypedValue]) -> None:
    put_uvarint(buf, len(facets))
    for k in sorted(facets):
        put_str(buf, k)
        put_value(buf, facets[k])


def get_facets(b: bytes, pos: int) -> Tuple[Dict[str, TypedValue], int]:
    n, pos = uvarint(b, pos)
    out = {}
    for _ in range(n):
        k, pos = get_str(b, pos)
        v, pos = get_value(b, pos)
        out[k] = v
    return out, pos


# -- records ----------------------------------------------------------------

def encode_edge(e) -> bytes:
    """Edge (models/store.py) → EDGE record payload."""
    buf = bytearray([EDGE])
    flags = 0
    if e.op == "del":
        flags |= _F_DEL
    if e.value is not None:
        flags |= _F_VALUE
    if e.facets:
        flags |= _F_FACETS
    if e.lang:
        flags |= _F_LANG
    buf.append(flags)
    put_str(buf, e.pred)
    put_uvarint(buf, e.src)
    if e.value is not None:
        put_value(buf, e.value)
    else:
        put_uvarint(buf, e.dst)
    if e.lang:
        put_str(buf, e.lang)
    if e.facets:
        put_facets(buf, e.facets)
    return bytes(buf)


def decode_edge(b: bytes):
    from dgraph_tpu.models.store import Edge

    assert b[0] == EDGE
    flags = b[1]
    pos = 2
    pred, pos = get_str(b, pos)
    src, pos = uvarint(b, pos)
    value = None
    dst = 0
    if flags & _F_VALUE:
        value, pos = get_value(b, pos)
    else:
        dst, pos = uvarint(b, pos)
    lang = ""
    if flags & _F_LANG:
        lang, pos = get_str(b, pos)
    facets = None
    if flags & _F_FACETS:
        facets, pos = get_facets(b, pos)
    return Edge(
        pred=pred,
        src=src,
        dst=dst,
        value=value,
        lang=lang,
        facets=facets,
        op="del" if flags & _F_DEL else "set",
    )


def encode_bulk_edges(pred: str, src, dst) -> bytes:
    """One record for a whole group of plain uid edges (the native bulk
    ingest journals per predicate-group, not per edge)."""
    import numpy as np

    buf = bytearray([BULKEDGES])
    put_str(buf, pred)
    src = np.ascontiguousarray(src, dtype="<i8")
    dst = np.ascontiguousarray(dst, dtype="<i8")
    put_uvarint(buf, len(src))
    buf += src.tobytes()
    buf += dst.tobytes()
    return bytes(buf)


def decode_bulk_edges(b: bytes):
    import numpy as np

    assert b[0] == BULKEDGES
    pred, pos = get_str(b, 1)
    n, pos = uvarint(b, pos)
    src = np.frombuffer(b, dtype="<i8", count=n, offset=pos)
    dst = np.frombuffer(b, dtype="<i8", count=n, offset=pos + 8 * n)
    return pred, src, dst


def encode_bulk_values(pred: str, items) -> bytes:
    """One record for a predicate group of plain (facet-less) value
    edges; ``items`` = [(src, lang, TypedValue)] in INPUT ORDER (repeated
    writes of one (src, lang) are last-write-wins, so order is part of
    the record's meaning)."""
    buf = bytearray([BULKVALS])
    put_str(buf, pred)
    put_uvarint(buf, len(items))
    for src, lang, v in items:
        put_uvarint(buf, src)
        put_str(buf, lang)
        put_value(buf, v)
    return bytes(buf)


def decode_bulk_values(b: bytes):
    assert b[0] == BULKVALS
    pred, pos = get_str(b, 1)
    n, pos = uvarint(b, pos)
    items = []
    for _ in range(n):
        src, pos = uvarint(b, pos)
        lang, pos = get_str(b, pos)
        v, pos = get_value(b, pos)
        items.append((src, lang, v))
    return pred, items


def encode_schema(text: str) -> bytes:
    buf = bytearray([SCHEMA])
    put_str(buf, text)
    return bytes(buf)


def encode_xid(xid: str, uid: int) -> bytes:
    buf = bytearray([XID])
    put_str(buf, xid)
    put_uvarint(buf, uid)
    return bytes(buf)


def encode_lease(next_uid: int) -> bytes:
    buf = bytearray([LEASE])
    put_uvarint(buf, next_uid)
    return bytes(buf)


def encode_delpred(pred: str) -> bytes:
    buf = bytearray([DELPRED])
    put_str(buf, pred)
    return bytes(buf)


def encode_member(node_id: str, addr: str, groups=()) -> bytes:
    """Runtime membership record (worker/groups.go applyMembershipUpdate
    analog): replicated through the metadata group so every server —
    including restarts replaying the log — learns the peer.  ``groups``
    lists the raft groups the member serves; empty = all (legacy)."""
    buf = bytearray([MEMBER])
    put_str(buf, node_id)
    put_str(buf, addr)
    put_uvarint(buf, len(groups))
    for g in groups:
        put_uvarint(buf, g)
    return bytes(buf)


def decode_member(payload: bytes):
    nid, pos = get_str(payload, 1)
    addr, pos = get_str(payload, pos)
    groups = []
    if pos < len(payload):
        n, pos = uvarint(payload, pos)
        for _ in range(n):
            g, pos = uvarint(payload, pos)
            groups.append(g)
    return nid, addr, groups
