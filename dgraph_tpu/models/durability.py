"""Storage-plane resilience: disk-fault read-only mode + the background
snapshotter that keeps the WAL bounded.

Two small state machines that PR 5's network-plane vocabulary (degraded
annotations, 503 + Retry-After, failpoint-injectable everything) extends
to disks:

- :class:`StorageHealth` — the moment a WAL append/flush/fsync raises
  ``OSError`` (ENOSPC, EIO, or an injected ``FailpointError``), the node
  flips READ-ONLY: mutations shed with 503 + Retry-After (HTTP) /
  UNAVAILABLE (gRPC) while reads keep serving from the in-memory store.
  A background probe (``DGRAPH_TPU_STORAGE_PROBE_S``, default 2s)
  re-proves the directory accepts durable writes and re-arms the write
  path — the storage analog of a circuit breaker's half-open probe.

- :class:`Snapshotter` — the serving path's missing caller of
  ``DurableStore.snapshot()``: watches WAL bytes/records against
  ``DGRAPH_TPU_SNAPSHOT_WAL_MB`` / ``DGRAPH_TPU_SNAPSHOT_WAL_RECORDS``,
  seals the active log into a segment under the serving write lock
  (microseconds), then compacts OFF the write path (models/wal.py
  ``compact``), so under sustained writes the WAL stays bounded and
  restart replay stays O(recent writes), the reference's draft.go:849
  calculateSnapshot loop.  ``/admin/snapshot`` triggers it on demand.
"""

from __future__ import annotations

import sys
import threading
from typing import Callable, Optional

from dgraph_tpu.utils.env import env_float, env_int
from dgraph_tpu.utils.health import CooldownProbeLoop
from dgraph_tpu.utils.metrics import (
    SNAPSHOT_AGE,
    STORAGE_ERRORS,
    STORAGE_READONLY,
    WAL_BYTES,
)


class StorageFaultError(OSError):
    """A durability operation failed against the underlying disk.  The
    serving layer maps this to HTTP 503 + Retry-After / gRPC UNAVAILABLE
    — the write was NOT acknowledged and may not survive a restart."""

    def __init__(self, msg: str, retry_after: float = 2.0):
        self.retry_after = retry_after
        super().__init__(msg)


class ReadOnlyError(StorageFaultError):
    """Mutation rejected at admission: the node is in storage read-only
    mode (a previous disk fault; the re-arm probe has not cleared yet)."""


class SnapshotCorruptError(RuntimeError):
    """Boot refused: ``snapshot.bin`` failed strict replay.  Never an
    OSError — retrying cannot help, and booting from the WAL alone would
    silently lose every snapshotted record."""

    def __init__(self, path: str, quarantine: str, detail: str):
        self.path = path
        self.quarantine = quarantine
        super().__init__(
            f"snapshot {path} is corrupt ({detail}); quarantined to "
            f"{quarantine}.  Refusing to boot from the WAL alone — that "
            "would silently drop every snapshotted record.  Restore the "
            "snapshot from a replica or backup (move it back over "
            f"{path}), or accept the loss explicitly by deleting the "
            "quarantined file AND the store directory's WAL files to "
            "start empty."
        )


class StorageHealth:
    """Read-only latch + re-arm probe for one store directory.

    ``probe_fn`` must raise ``OSError`` while the storage is still bad
    and return cleanly once durable writes work again (DurableStore
    passes a write+fsync probe that also reopens the WAL past any torn
    tail)."""

    def __init__(
        self,
        probe_fn: Callable[[], None],
        probe_interval_s: Optional[float] = None,
    ):
        self._probe_fn = probe_fn
        self.probe_interval_s = (
            probe_interval_s
            if probe_interval_s is not None
            else env_float("DGRAPH_TPU_STORAGE_PROBE_S", 2.0)
        )
        self._lock = threading.Lock()
        self._readonly = False
        self._stopped = False
        # cooldown-FIRST re-arm loop: the shared discipline
        # (utils/health.py CooldownProbeLoop — the peer breaker and the
        # device guard probe the same way): the fault just happened,
        # and re-proving the disk in the same microsecond mostly proves
        # nothing (a failpoint-injected or transient fault would re-arm
        # instantly and flap) — give the condition one interval to clear
        self._probe_loop = CooldownProbeLoop(
            self.probe_now,
            self.probe_interval_s,
            self._probing_active,
            name="dgraph-storage-probe",
        )
        self.errors = 0
        self.rearms = 0
        self.last_error = ""
        self.last_site = ""

    def readonly(self) -> bool:
        return self._readonly

    def note_error(self, site: str, exc: BaseException) -> None:
        """Record a storage fault and latch read-only mode; idempotent
        under a storm of concurrent faults (one probe thread only)."""
        STORAGE_ERRORS.add(site)
        with self._lock:
            self.errors += 1
            self.last_error = f"{type(exc).__name__}: {exc}"
            self.last_site = site
            if not self._readonly:
                self._readonly = True
                STORAGE_READONLY.set(1)
                print(
                    f"# storage fault at {site}: {self.last_error}; "
                    "entering READ-ONLY mode (mutations shed 503, reads "
                    "keep serving; re-arm probe every "
                    f"{self.probe_interval_s:g}s)",
                    file=sys.stderr,
                )
            stopped = self._stopped
        if not stopped:
            # idempotent under a storm of concurrent faults — the loop
            # spawns at most one prober thread
            self._probe_loop.start()

    def note_ok(self) -> None:
        with self._lock:
            if self._readonly:
                self._readonly = False
                self.rearms += 1
                STORAGE_READONLY.set(0)
                print(
                    "# storage probe succeeded; write path RE-ARMED",
                    file=sys.stderr,
                )

    def probe_now(self) -> bool:
        """One synchronous probe (tests; the loop calls this too)."""
        try:
            self._probe_fn()
        except OSError:
            return False
        self.note_ok()
        return True

    def _probing_active(self) -> bool:
        with self._lock:
            return not self._stopped and self._readonly

    def stop(self) -> None:
        with self._lock:
            self._stopped = True

    def status(self) -> dict:
        with self._lock:
            return {
                "readonly": self._readonly,
                "errors": self.errors,
                "rearms": self.rearms,
                "last_error": self.last_error,
                "last_site": self.last_site,
            }


class Snapshotter:
    """Background snapshot/compaction driver for one DurableStore.

    ``exclusive`` is a zero-arg callable returning a context manager
    granting WRITE exclusivity over the store (DgraphServer passes its
    engine write lock) — held only for the seal (rename + reopen, no
    serialization); ``None`` means the caller guarantees no concurrent
    writers (tests).  Compaction then runs off the write path entirely:
    it replays snapshot + sealed segments into a scratch store, so reads
    AND writes proceed while the new snapshot is built (memory cost: one
    extra copy of the snapshotted state, the price of zero write-path
    stalls)."""

    def __init__(
        self,
        store,
        exclusive: Optional[Callable[[], object]] = None,
        wal_mb: Optional[float] = None,
        wal_records: Optional[int] = None,
        interval_s: float = 1.0,
    ):
        self._store = store
        self._exclusive = exclusive
        self.wal_bytes = int(
            (wal_mb if wal_mb is not None
             else env_float("DGRAPH_TPU_SNAPSHOT_WAL_MB", 64.0)) * (1 << 20)
        )
        self.wal_records = (
            wal_records
            if wal_records is not None
            else env_int("DGRAPH_TPU_SNAPSHOT_WAL_RECORDS", 200_000)
        )
        self.interval_s = interval_s
        self._cond = threading.Condition()
        self._stopped = False
        self._req = 0         # explicit trigger requests issued
        self._served = 0      # highest request a COMPLETED round observed
        #                       BEFORE its seal — a waiter is only
        #                       satisfied by a round whose seal covers
        #                       every record journaled before its request
        self._last_ok = True  # did the latest round actually snapshot?
        self._last_error = ""
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="dgraph-snapshotter", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def trigger(self, wait: bool = False, timeout: float = 60.0) -> bool:
        """Request a snapshot now (``/admin/snapshot``).  With ``wait``,
        block until a round that STARTED after this request completed
        (False on timeout) — a round already mid-compaction when the
        request lands sealed too early to cover it and does not count."""
        with self._cond:
            if self._stopped:
                return False
            self._req += 1
            my = self._req
            self._cond.notify_all()
            if not wait:
                return True
            ok = self._cond.wait_for(
                lambda: self._served >= my or self._stopped, timeout=timeout
            )
            return bool(ok) and self._served >= my and self._last_ok

    def due(self) -> bool:
        import os

        store = self._store
        try:
            size = os.path.getsize(store.wal_path)
        except OSError:
            size = 0
        WAL_BYTES.set(size)
        return size >= self.wal_bytes or store.wal.count >= self.wal_records

    def snapshot_once(self) -> bool:
        """One seal+compact round; False (and a counted storage error)
        when the disk refused.  Runs on the loop thread or inline from
        tests."""
        store = self._store
        if getattr(store, "storage_readonly", lambda: False)():
            return False  # a faulted disk cannot take a snapshot either
        try:
            if self._exclusive is not None:
                with self._exclusive():
                    store.seal_segment()
            else:
                store.seal_segment()
            store.compact()
        except OSError as e:
            # seal/compact faults latch read-only via the store's own
            # guards; anything that slipped past still must not kill
            # the snapshotter thread
            # graftlint: shared[_last_error] GIL-atomic string store read only by stats(); last-error-wins is the intended semantics when the loop thread and an inline test caller both fail
            self._last_error = f"{type(e).__name__}: {e}"
            return False
        except ValueError as e:
            # strict replay of the existing snapshot failed during
            # compaction: disk rot after a clean boot.  Keep serving
            # (reads are from memory) but say so loudly.
            self._last_error = f"{type(e).__name__}: {e}"
            STORAGE_ERRORS.add("wal.compact")
            print(
                f"# snapshot compaction failed: {e}; WAL keeps growing "
                "until the snapshot file is repaired",
                file=sys.stderr,
            )
            return False
        self._refresh_age()
        return True

    def _refresh_age(self) -> None:
        # one implementation of snapshot age, owned by the store
        # (models/wal.py _snapshot_age)
        age_fn = getattr(self._store, "_snapshot_age", None)
        age = age_fn() if age_fn is not None else None
        if age is not None:
            SNAPSHOT_AGE.set(age)

    def _loop(self) -> None:
        while True:
            with self._cond:
                if self._stopped:
                    return
                if self._req == self._served:
                    # idle: poll the thresholds each interval; a trigger
                    # notify cuts the wait short
                    self._cond.wait(timeout=self.interval_s)
                if self._stopped:
                    return
                # every request issued BEFORE this read is covered by
                # this round's seal (the seal happens after, under the
                # caller's exclusivity)
                serving = self._req
            explicit = serving > self._served
            ran = explicit or self.due()
            fired = self.snapshot_once() if ran else False
            self._refresh_age()
            with self._cond:
                if ran:
                    # an explicit trigger round completes even when the
                    # disk refused — the waiter gets its answer either
                    # way, with _last_ok telling success apart
                    self._last_ok = fired
                self._served = serving
                self._cond.notify_all()
