"""Geo values and grid cell indexing.

The reference indexes geometries with S2 cell coverings at levels 5-16
(types/s2index.go:42, types/earth.go) and exact-filters candidates
(types/geofilter.go).  We use a hierarchical lat/lng quadtree grid — the
same candidates-then-exact-filter contract, with integer cell tokens whose
containment is prefix arithmetic (TPU/host friendly, no S2 dependency).

A cell id at level L encodes the quadtree path from the root; parents are
obtained by shifting.  index_cells emits the covering cell at each level
in [MIN_LEVEL, MAX_LEVEL] for points; polygons contribute every cell their
bounding box intersects at a level chosen to bound the cell count
(analog of maxCells=18 in types/s2index.go).

Boundary cases of the planar approximation (vs the reference's spherical
S2 cells — VERDICT r3 missing #6, documented rather than papered over):

- **Antimeridian.** A polygon or near() circle crossing ±180° longitude
  produces a bounding box spanning nearly the whole grid, so its
  covering degrades to coarse cells: correctness holds (the exact
  post-filter still runs; geofilter.go's contract), but candidate sets
  are large — queries near the antimeridian are slower, never wrong.
- **Poles.** lat/lng cells shrink in physical width toward the poles
  (S2's cube projection keeps cell area near-uniform).  Coverings above
  ~±85° over-select candidates by the cos(lat) factor; again exact
  filtering preserves correctness.  near() uses true haversine distance
  in the exact phase, so polar distance semantics are right.
- **Great-circle edges.** Long polygon edges are treated as straight in
  lat/lng space during covering; a geodesic bulges away from that line
  by up to ~0.3% of edge length at mid-latitudes.  The exact phase uses
  the same planar point-in-polygon as the covering, so results are
  consistently planar — matching GeoJSON's own planar-ring semantics
  (RFC 7946 §3.1.6) though not S2's geodesic edges for continent-scale
  polygons.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

MIN_LEVEL = 5
MAX_LEVEL = 16
MAX_CELLS = 18
EARTH_RADIUS_M = 6_371_000.0


@dataclass(frozen=True)
class Geom:
    """Parsed geometry: a point or a polygon (lng/lat degrees, GeoJSON order)."""

    kind: str  # "Point" | "Polygon"
    coords: Tuple  # Point: (lng, lat); Polygon: tuple of (lng, lat) ring

    def to_geojson(self) -> dict:
        if self.kind == "Point":
            return {"type": "Point", "coordinates": list(self.coords)}
        return {"type": "Polygon", "coordinates": [[list(c) for c in self.coords]]}


def parse_geojson(s) -> Geom:
    obj = json.loads(s) if isinstance(s, str) else s
    t = obj.get("type")
    if t == "Point":
        lng, lat = obj["coordinates"][:2]
        return Geom("Point", (float(lng), float(lat)))
    if t == "Polygon":
        ring = tuple((float(c[0]), float(c[1])) for c in obj["coordinates"][0])
        return Geom("Polygon", ring)
    raise ValueError(f"unsupported geometry type {t!r}")


def _cell(lng: float, lat: float, level: int) -> int:
    """Quadtree cell id: level tag + interleaved row/col bits."""
    n = 1 << level
    x = min(n - 1, max(0, int((lng + 180.0) / 360.0 * n)))
    y = min(n - 1, max(0, int((lat + 90.0) / 180.0 * n)))
    return (level << 56) | (y << 28) | x


def cell_parent(cell: int, level: int) -> int:
    l = cell >> 56
    if level > l:
        raise ValueError("parent level above cell level")
    shift = l - level
    y = ((cell >> 28) & ((1 << 28) - 1)) >> shift
    x = (cell & ((1 << 28) - 1)) >> shift
    return (level << 56) | (y << 28) | x


def point_cells(lng: float, lat: float) -> List[int]:
    """All ancestor cells for a point — one per level (s2index.go
    IndexGeoTokens indexes cover + ancestors so 'contains' queries hit)."""
    return [_cell(lng, lat, lv) for lv in range(MIN_LEVEL, MAX_LEVEL + 1)]


def _bbox(ring: Sequence[Tuple[float, float]]):
    lngs = [c[0] for c in ring]
    lats = [c[1] for c in ring]
    return min(lngs), min(lats), max(lngs), max(lats)


def polygon_cells(ring: Sequence[Tuple[float, float]]) -> List[int]:
    """Covering of a polygon's bbox with at most ~MAX_CELLS cells, plus the
    ancestors of each covering cell."""
    lo_lng, lo_lat, hi_lng, hi_lat = _bbox(ring)
    for level in range(MAX_LEVEL, MIN_LEVEL - 1, -1):
        n = 1 << level
        x0 = int((lo_lng + 180.0) / 360.0 * n)
        x1 = int((hi_lng + 180.0) / 360.0 * n)
        y0 = int((lo_lat + 90.0) / 180.0 * n)
        y1 = int((hi_lat + 90.0) / 180.0 * n)
        # At MIN_LEVEL accept the covering regardless of size so huge
        # polygons still get indexed (the reference likewise falls back to
        # its coarsest covering rather than dropping the geometry).
        if (x1 - x0 + 1) * (y1 - y0 + 1) <= MAX_CELLS or level == MIN_LEVEL:
            cover = [
                (level << 56) | (y << 28) | x
                for y in range(max(0, y0), min(y1, n - 1) + 1)
                for x in range(max(0, x0), min(x1, n - 1) + 1)
            ]
            out = set(cover)
            for c in cover:  # ancestors
                for lv in range(MIN_LEVEL, level):
                    out.add(cell_parent(c, lv))
            return sorted(out)
    return []


def index_cells(g: Geom) -> List[int]:
    if g.kind == "Point":
        return point_cells(*g.coords)
    return polygon_cells(g.coords)


def query_cells(g: Geom, within: bool = False) -> List[int]:
    """Cells to look up for a geo query (geofilter.go GetGeoTokens:71):
    for a point query — its ancestors; for a region — its covering plus
    ancestors (handled by polygon_cells)."""
    return index_cells(g)


def haversine_m(a: Tuple[float, float], b: Tuple[float, float]) -> float:
    lng1, lat1, lng2, lat2 = map(math.radians, (*a, *b))
    dlat, dlng = lat2 - lat1, lng2 - lng1
    h = math.sin(dlat / 2) ** 2 + math.cos(lat1) * math.cos(lat2) * math.sin(dlng / 2) ** 2
    return 2 * EARTH_RADIUS_M * math.asin(math.sqrt(h))


def haversine_m_vec(q: Tuple[float, float], lngs, lats):
    """Vectorized haversine: distance (meters) from ``q`` to every
    (lngs[i], lats[i]) pair — the near() exact post-filter runs over the
    whole candidate column in one numpy pass (functions.py)."""
    import numpy as np

    lng1, lat1 = map(math.radians, q)
    lng2 = np.radians(np.asarray(lngs, dtype=np.float64))
    lat2 = np.radians(np.asarray(lats, dtype=np.float64))
    dlat, dlng = lat2 - lat1, lng2 - lng1
    h = (
        np.sin(dlat / 2) ** 2
        + math.cos(lat1) * np.cos(lat2) * np.sin(dlng / 2) ** 2
    )
    return 2 * EARTH_RADIUS_M * np.arcsin(np.sqrt(h))


def point_in_polygon(pt: Tuple[float, float], ring: Sequence[Tuple[float, float]]) -> bool:
    """Ray casting, for the exact post-filter (geofilter.go MatchesFilter)."""
    x, y = pt
    inside = False
    n = len(ring)
    for i in range(n):
        x1, y1 = ring[i]
        x2, y2 = ring[(i + 1) % n]
        if (y1 > y) != (y2 > y):
            xin = (x2 - x1) * (y - y1) / (y2 - y1) + x1
            if x < xin:
                inside = not inside
    return inside


def _segs_cross(a1, a2, b1, b2) -> bool:
    """Proper segment intersection via orientation tests."""

    def orient(p, q, r):
        v = (q[0] - p[0]) * (r[1] - p[1]) - (q[1] - p[1]) * (r[0] - p[0])
        return 0 if v == 0 else (1 if v > 0 else -1)

    o1, o2 = orient(a1, a2, b1), orient(a1, a2, b2)
    o3, o4 = orient(b1, b2, a1), orient(b1, b2, a2)
    if o1 != o2 and o3 != o4:
        return True

    def on_seg(p, q, r):
        return (
            orient(p, q, r) == 0
            and min(p[0], q[0]) <= r[0] <= max(p[0], q[0])
            and min(p[1], q[1]) <= r[1] <= max(p[1], q[1])
        )

    return on_seg(a1, a2, b1) or on_seg(a1, a2, b2) or on_seg(b1, b2, a1) or on_seg(b1, b2, a2)


def _rings_cross(r1, r2) -> bool:
    n1, n2 = len(r1), len(r2)
    for i in range(n1):
        for j in range(n2):
            if _segs_cross(r1[i], r1[(i + 1) % n1], r2[j], r2[(j + 1) % n2]):
                return True
    return False


def matches_filter(kind: str, query: Geom, target: Geom, max_m: Optional[float] = None) -> bool:
    """Exact geo predicate evaluation for near/within/contains/intersects."""
    if kind == "near":
        if target.kind != "Point" or query.kind != "Point":
            return False
        return haversine_m(query.coords, target.coords) <= (max_m or 0.0)
    if kind == "within":  # target within query polygon
        if query.kind != "Polygon":
            return False
        if target.kind == "Point":
            return point_in_polygon(target.coords, query.coords)
        return all(point_in_polygon(c, query.coords) for c in target.coords)
    if kind == "contains":  # target polygon contains query point
        if target.kind != "Polygon":
            return False
        if query.kind == "Point":
            return point_in_polygon(query.coords, target.coords)
        return all(point_in_polygon(c, target.coords) for c in query.coords)
    if kind == "intersects":
        if target.kind == "Point" and query.kind == "Point":
            return target.coords == query.coords
        if target.kind == "Point":
            return point_in_polygon(target.coords, query.coords)
        if query.kind == "Point":
            return point_in_polygon(query.coords, target.coords)
        return (
            any(point_in_polygon(c, query.coords) for c in target.coords)
            or any(point_in_polygon(c, target.coords) for c in query.coords)
            or _rings_cross(query.coords, target.coords)
        )
    raise ValueError(f"unknown geo filter {kind!r}")
