"""Password hashing for the password type and checkpwd().

The reference uses bcrypt (types/password.go:29,42).  bcrypt isn't in
this image; we use salted PBKDF2-HMAC-SHA256 from the stdlib — same
contract (one-way hash at mutation time, verify at query time).
"""

from __future__ import annotations

import hashlib
import hmac
import os

_ROUNDS = 10_000
_PREFIX = "pbkdf2$"


def hash_password(plain: str) -> str:
    salt = os.urandom(8)
    dk = hashlib.pbkdf2_hmac("sha256", plain.encode(), salt, _ROUNDS)
    return _PREFIX + salt.hex() + "$" + dk.hex()


def verify_password(plain: str, stored: str) -> bool:
    if not stored.startswith(_PREFIX):
        # unhashed legacy value: constant-time direct compare (bytes —
        # compare_digest rejects non-ASCII str operands)
        return hmac.compare_digest(plain.encode(), stored.encode())
    try:
        salt_hex, dk_hex = stored[len(_PREFIX):].split("$", 1)
        salt = bytes.fromhex(salt_hex)
    except ValueError:
        return False
    dk = hashlib.pbkdf2_hmac("sha256", plain.encode(), salt, _ROUNDS)
    return hmac.compare_digest(dk.hex(), dk_hex)
