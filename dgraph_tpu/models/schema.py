"""Schema state and schema-language parser.

Equivalent of the reference's schema/ package: per-predicate type +
directives (@index(tokenizers), @reverse, @count) parsed from the schema
language (schema/parse.go:94-265), held in a mutable state object
(schema/schema.go:91).  The TPU engine additionally derives from it which
arenas (data/reverse/index/value) each predicate materializes on device.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dgraph_tpu.models.types import TypeID, type_from_name
from dgraph_tpu import tok


@dataclass
class PredicateSchema:
    name: str
    tid: TypeID = TypeID.DEFAULT
    tokenizers: List[str] = field(default_factory=list)  # @index(...)
    reverse: bool = False                                # @reverse
    count: bool = False                                  # @count

    @property
    def indexed(self) -> bool:
        return bool(self.tokenizers)


class SchemaState:
    """Mutable predicate → schema map (schema.State() analog)."""

    def __init__(self):
        self._preds: Dict[str, PredicateSchema] = {}

    def get(self, pred: str) -> PredicateSchema:
        s = self._preds.get(pred)
        if s is None:
            s = PredicateSchema(name=pred)
            self._preds[pred] = s
        return s

    def peek(self, pred: str) -> Optional[PredicateSchema]:
        return self._preds.get(pred)

    def set(self, s: PredicateSchema):
        self._preds[s.name] = s

    def predicates(self) -> List[str]:
        return sorted(self._preds)

    def type_of(self, pred: str) -> TypeID:
        s = self._preds.get(pred)
        return s.tid if s else TypeID.DEFAULT

    def tokenizers(self, pred: str) -> List[str]:
        s = self._preds.get(pred)
        return s.tokenizers if s else []

    def has_reverse(self, pred: str) -> bool:
        s = self._preds.get(pred)
        return bool(s and s.reverse)

    def has_count(self, pred: str) -> bool:
        s = self._preds.get(pred)
        return bool(s and s.count)

    def is_sortable(self, pred: str) -> bool:
        return any(
            tok.get_tokenizer(t).sortable for t in self.tokenizers(pred)
        )

    def sortable_tokenizer(self, pred: str) -> Optional[str]:
        for t in self.tokenizers(pred):
            if tok.get_tokenizer(t).sortable:
                return t
        return None

    def to_text(self) -> str:
        """Render in schema-language form (worker/export.go toSchema analog)."""
        out = []
        for name in self.predicates():
            s = self._preds[name]
            line = f"{name}: {s.tid.name.lower()}"
            if s.tokenizers:
                line += f" @index({', '.join(s.tokenizers)})"
            if s.reverse:
                line += " @reverse"
            if s.count:
                line += " @count"
            out.append(line + " .")
        return "\n".join(out) + ("\n" if out else "")


_DEFAULT_TOKENIZER = {
    TypeID.INT: "int",
    TypeID.FLOAT: "float",
    TypeID.BOOL: "bool",
    TypeID.DATETIME: "year",
    TypeID.DATE: "year",
    TypeID.STRING: "term",
    TypeID.DEFAULT: "term",
    TypeID.GEO: "geo",
}

_LINE_RE = re.compile(
    r"""^\s*
    (?P<name>[^\s:]+)\s*:\s*
    (?P<type>\[?\s*[\w:]+\s*\]?)
    (?P<directives>(?:\s*@\w+(?:\([^)]*\))?)*)
    \s*\.?\s*$""",
    re.VERBOSE,
)
_DIRECTIVE_RE = re.compile(r"@(\w+)(?:\(([^)]*)\))?")


def split_entries(text: str) -> List[str]:
    """Split schema text into '.'-terminated entries (several may share a
    line); a standalone '.' token ends an entry — dots inside predicate
    names don't split."""
    stripped = "\n".join(l.split("#", 1)[0] for l in text.splitlines())
    return [e.strip() for e in re.split(r"(?<=[\s)])\.(?=\s|$)", stripped) if e.strip()]


def parse_schema(text: str, into: Optional[SchemaState] = None) -> SchemaState:
    """Parse schema-language text (schema/parse.go:265).

    Syntax per entry: ``pred: type [@index(tok1, tok2)] [@reverse] [@count] .``
    ``@index`` with no argument selects the default tokenizer for the type
    (schema/parse.go resolveTokenizers:216).
    """
    state = into if into is not None else SchemaState()
    for lineno, line in enumerate(split_entries(text), 1):
        m = _LINE_RE.match(line)
        if not m:
            raise ValueError(f"schema entry {lineno}: cannot parse {line!r}")
        name = m.group("name")
        tname = m.group("type").strip().strip("[]").strip()
        tid = type_from_name(tname)
        s = PredicateSchema(name=name, tid=tid)
        for dm in _DIRECTIVE_RE.finditer(m.group("directives") or ""):
            d, args = dm.group(1), dm.group(2)
            if d == "index":
                if args and args.strip():
                    toks = [t.strip() for t in args.split(",") if t.strip()]
                else:
                    toks = [_DEFAULT_TOKENIZER.get(tid, "term")]
                for t in toks:
                    tk = tok.get_tokenizer(t)  # validates name
                    if tk.typ != tid and not (
                        tk.typ == TypeID.STRING and tid == TypeID.DEFAULT
                    ):
                        raise ValueError(
                            f"schema line {lineno}: tokenizer {t!r} is for "
                            f"{tk.typ.name}, predicate is {tid.name}"
                        )
                s.tokenizers = toks
            elif d == "reverse":
                if tid != TypeID.UID:
                    raise ValueError(
                        f"schema line {lineno}: @reverse needs uid type"
                    )
                s.reverse = True
            elif d == "count":
                s.count = True
            else:
                raise ValueError(f"schema line {lineno}: unknown directive @{d}")
        state.set(s)
    return state
