"""Host-side posting store with Set/Del mutation semantics.

Equivalent of the reference's posting/ package (list.go mutation layer +
lists.go store): the mutable source of truth that the immutable device
arenas are built from.  The reference overlays a sorted mutation layer on
an immutable protobuf layer per list (posting/list.go:321-410); here the
host store is a straightforward per-predicate edge/value map with dirty
tracking, and "commit" = rebuilding the affected predicate's arena
(models/arena.py) — the analog of SyncIfDirty + lcache refresh.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from dgraph_tpu.models.types import TypeID, TypedValue
from dgraph_tpu.models.schema import SchemaState
from dgraph_tpu.models.uids import UidMap


@dataclass
class Edge:
    """A directed edge mutation (protos DirectedEdge, task.proto:103)."""

    pred: str
    src: int
    dst: int = 0                      # uid edges
    value: Optional[TypedValue] = None  # value edges
    lang: str = ""
    facets: Optional[Dict[str, TypedValue]] = None
    op: str = "set"                   # "set" | "del"


class PredicateData:
    """All postings for one predicate: uid edges and/or values."""

    __slots__ = ("edges", "values", "edge_facets", "value_facets",
                 "_has_langs",  # lazy lang-presence flag (functions.py)
                 "_untagged",   # lazy vectorized value mirror (below)
                 "_efmirror",   # lazy vectorized edge-facet mirror
                 "_wdmirror")   # lazy sorted uids-with-data mirror

    def __init__(self):
        # src uid -> set of dst uids
        self.edges: Dict[int, Set[int]] = {}
        # (src uid, lang) -> TypedValue ; lang "" is the default value
        self.values: Dict[Tuple[int, str], TypedValue] = {}
        # (src, dst) -> facets
        self.edge_facets: Dict[Tuple[int, int], Dict[str, TypedValue]] = {}
        # src -> facets (on value edges)
        self.value_facets: Dict[int, Dict[str, TypedValue]] = {}
        self._untagged = None
        self._efmirror = None
        self._wdmirror = None

    def untagged_mirror(self):
        """Vectorized mirror of the untagged values: (sorted int64 uid
        array, aligned object array of TypedValues).  The engine's
        value-leaf fetch probes this with ONE searchsorted instead of a
        Python dict probe per uid (VERDICT r3 weak #6: at 21M-corpus
        fan-outs the per-uid loop becomes the bottleneck once expansion
        is fast).  Invalidated on every value mutation (apply/apply_many
        clear the slot)."""
        m = self._untagged
        if m is None:
            import numpy as _np

            uids = sorted(u for (u, l) in self.values.keys() if l == "")
            arr = _np.fromiter(uids, dtype=_np.int64, count=len(uids))
            vals = _np.empty(len(uids), dtype=object)
            for i, u in enumerate(uids):
                vals[i] = self.values[(u, "")]
            m = self._untagged = (arr, vals)
        return m

    def untagged_lookup(self, uids):
        """Vectorized untagged-value probe: (hit_mask, positions) into the
        mirror's value array for ``uids`` (int64 ndarray).  Shared by the
        engine's value-leaf fetch and groupby."""
        import numpy as _np

        mu, mv = self.untagged_mirror()
        if not len(mu):
            return _np.zeros(len(uids), bool), _np.zeros(len(uids), _np.int64), mv
        pos = _np.clip(_np.searchsorted(mu, uids), 0, len(mu) - 1)
        return mu[pos] == uids, pos, mv

    def edge_facets_lookup(self, srcs, dsts):
        """Vectorized edge-facet probe: for parallel src/dst arrays return
        (hit_mask, positions, facet_dict_array) — one searchsorted over a
        sorted (src<<32|dst) mirror instead of a Python dict probe per
        edge (VERDICT r3 weak #6).  Mirror invalidated on facet writes."""
        import numpy as _np

        m = self._efmirror
        if m is None:
            keys = _np.fromiter(
                ((s << 32) | d for (s, d) in self.edge_facets.keys()),
                dtype=_np.int64,
                count=len(self.edge_facets),
            )
            order = _np.argsort(keys)
            keys = keys[order]
            vals = _np.empty(len(keys), dtype=object)
            items = list(self.edge_facets.values())
            for i, oi in enumerate(order):
                vals[i] = items[oi]
            m = self._efmirror = (keys, vals)
        mk, mv = m
        if not len(mk):
            return _np.zeros(len(srcs), bool), _np.zeros(len(srcs), _np.int64), mv
        q = (_np.asarray(srcs, _np.int64) << 32) | _np.asarray(dsts, _np.int64)
        pos = _np.clip(_np.searchsorted(mk, q), 0, len(mk) - 1)
        return mk[pos] == q, pos, mv

    def uids_with_data(self) -> Set[int]:
        out = set(self.edges.keys())
        out.update(u for (u, _l) in self.values.keys())
        return out

    def uids_with_data_sorted(self):
        """Sorted int64 array of uids_with_data, cached until the next
        mutation (apply() clears the slot unconditionally).  The engine's
        ``_predicate_`` probe runs ONE searchsorted per predicate over
        this instead of a Python set probe per uid × per predicate."""
        m = self._wdmirror
        if m is None:
            import numpy as _np

            s = self.uids_with_data()
            m = _np.fromiter(s, dtype=_np.int64, count=len(s))
            m.sort()
            self._wdmirror = m
        return m


class PostingStore:
    """The mutable graph: schema + uid dictionary + per-predicate postings."""

    # per-predicate mutation journal cap: deltas beyond this fall back to
    # a full arena rebuild (bulk loads overflow immediately, point
    # mutations stay incremental — the gentle-commit amortization analog,
    # posting/lists.go:109-215)
    DELTA_MAX = 65536

    # version covers EVERY observable change: anything readable through
    # this store changes only via a version bump.  The tier-2 result
    # cache (cache/result.py) requires this — a hit short-circuits
    # execution entirely, so any freshness mechanism that piggybacks on
    # execution (ClusterStore's remote-TTL pulls) would starve behind a
    # warm cache.  Stores with such eventually-consistent side channels
    # must override this to False (ClusterStore does); tier 1 stays safe
    # there regardless because arena identity is part of its key and
    # remote refreshes rebuild arenas.
    strict_snapshot_versions = True

    def __init__(self, schema: Optional[SchemaState] = None):
        self.schema = schema if schema is not None else SchemaState()
        self.uids = UidMap()
        self._preds: Dict[str, PredicateData] = {}
        self.dirty: Set[str] = set()
        # monotonic snapshot version: bumps on every mutation batch so
        # readers can tell "same immutable arena snapshot" apart without
        # hashing store state.  Consumers: the cohort scheduler's
        # admission signature (sched/cohort.py) and BOTH query-cache
        # tiers (dgraph_tpu/cache/ — every entry is keyed under the
        # version it was computed at, so a bump is a global O(1)
        # invalidation; see cache/core.py).  Anything that changes query
        # results MUST bump it — apply/apply_many, the bulk setters,
        # apply_schema and delete_predicate all do.
        self.version = 0
        # pred -> [(src, dst, +1|-1), ...] since the last arena refresh;
        # None = overflowed (full rebuild required).  Only uid-edge ops
        # journal here; value mutations always force a full refresh of
        # the value/index arenas (cheap: those arenas are value-sized).
        self.delta: Dict[str, Optional[List[Tuple[int, int, int]]]] = {}
        # IVM (dgraph_tpu/ivm/): per-predicate freshness.  pred_versions
        # maps each predicate to the version of the LAST mutation that
        # touched it; pred_floor is the version of the last change that
        # cannot be scoped to predicates (schema mutation, full-store
        # replacement).  Cache tiers key entries on
        # max(floor, max(pred_versions[footprint])) via ivm/versions.py
        # instead of the global version above, so a mutation only
        # invalidates entries that reference its predicates.  delta_base
        # records, per journaled predicate, the pred version BEFORE the
        # journal's first delta — the version every live cache entry for
        # that predicate carries, which the delta-repair path
        # (models/arena.py) needs to re-key repaired entries safely.
        self.pred_versions: Dict[str, int] = {}
        self.pred_floor = 0
        self.delta_base: Dict[str, int] = {}
        # mutation delta stream (ivm/deltas.py), attached by the serving
        # layer for live-query subscriptions; None costs one attribute
        # read per mutation
        self.delta_stream = None
        # runtime cluster membership (MEMBER records) — only meaningful
        # on the metadata group's replica store; member_hook fires on
        # apply so the cluster service can rewire transports live
        self.members: Dict[str, str] = {}
        self.member_hook = None

    # -- access ------------------------------------------------------------

    def predicates(self) -> List[str]:
        return sorted(self._preds)

    def pred(self, name: str) -> PredicateData:
        p = self._preds.get(name)
        if p is None:
            p = PredicateData()
            self._preds[name] = p
        return p

    def peek(self, name: str) -> Optional[PredicateData]:
        return self._preds.get(name)

    def value(self, pred: str, uid: int, lang: str = "") -> Optional[TypedValue]:
        """Exact-language lookup: a tagged request does NOT fall back to
        the untagged value — matching the reference's v0.7 semantics
        (query_test.go TestLangSingleFallback: name@cn with no @cn value
        yields nothing).  Fallback is explicit: the '.' element of a lang
        chain maps to any_value()."""
        p = self._preds.get(pred)
        if p is None:
            return None
        return p.values.get((uid, lang))

    def any_value(self, pred: str, uid: int) -> Optional[TypedValue]:
        """The untagged value, else any language's value (list.go:835)."""
        p = self._preds.get(pred)
        if p is None:
            return None
        v = p.values.get((uid, ""))
        if v is not None:
            return v
        for (u, _l), val in p.values.items():
            if u == uid:
                return val
        return None

    def neighbors(self, pred: str, uid: int) -> List[int]:
        p = self._preds.get(pred)
        if p is None:
            return []
        return sorted(p.edges.get(uid, ()))

    # -- mutation ----------------------------------------------------------

    def _journal_delta(self, pred: str, src: int, dst: int, sign: int) -> None:
        d = self.delta.get(pred, [])
        if d is None:
            return  # already overflowed
        if pred not in self.delta:
            # fresh journal window: remember the pred version its views
            # were built at (repair re-keys entries FROM this version)
            self.delta_base[pred] = self.pred_versions.get(pred, 0)
        if len(d) >= self.DELTA_MAX:
            self.delta[pred] = None
            return
        d.append((src, dst, sign))
        self.delta[pred] = d

    def _journal_touch(self, pred: str) -> None:
        """Journal a no-op/facet-only touch: arenas are unaffected, so
        an EMPTY entry lets refresh skip the rebuild (setdefault
        preserves an overflow None) — but the window still needs its
        repair base recorded (see _journal_delta)."""
        if pred not in self.delta:
            self.delta_base[pred] = self.pred_versions.get(pred, 0)
            self.delta[pred] = []

    def _delta_overflow(self, pred: str) -> None:
        self.delta[pred] = None

    def _note_pred_mutation(self, pred: str, stream_kind: str = "",
                            src: int = 0, dst: int = 0, sign: int = 0) -> None:
        """Per-predicate freshness + delta-stream publication for ONE
        mutation (the version was already bumped).  ``stream_kind``:
        "edge" publishes the exact edge delta, "pred" a whole-predicate
        change, "" nothing (callers that publish separately)."""
        self.pred_versions[pred] = self.version
        ds = self.delta_stream
        if ds is None or not stream_kind:
            return
        if stream_kind == "edge":
            ds.publish_edge(pred, src, dst, sign, self.version)
        else:
            ds.publish_pred(pred, self.version)

    def apply(self, e: Edge) -> None:
        """Apply one edge mutation (AddMutationWithIndex analog,
        posting/index.go:273 — index derivation happens at arena build)."""
        p = self.pred(e.pred)
        self.dirty.add(e.pred)
        self.version += 1
        p._wdmirror = None  # any mutation can change uids-with-data
        # IVM stream shape of this mutation: an exact edge delta when
        # one exists, else a whole-predicate change (value/facet edits
        # have no per-edge form the repair path could apply)
        kind, sign = "pred", 0
        if e.op == "set":
            if e.value is not None:
                p.values[(e.src, e.lang)] = e.value
                if not e.lang:  # the mirror indexes untagged values only
                    p._untagged = None
                self._delta_overflow(e.pred)  # value/index arenas rebuild
                if e.lang:
                    # invalidate the lazy lang-presence flag (functions.py
                    # caches it on this live object)
                    try:
                        del p._has_langs
                    except AttributeError:
                        pass
                if e.facets:
                    p.value_facets[e.src] = dict(e.facets)
            else:
                tgt = p.edges.setdefault(e.src, set())
                if e.dst not in tgt:
                    tgt.add(e.dst)
                    self._journal_delta(e.pred, e.src, e.dst, +1)
                    kind, sign = "edge", +1
                else:
                    # facet-only / no-op touch: arenas unaffected — keep
                    # an (empty) journal entry so refresh skips the
                    # rebuild (an overflow None is preserved)
                    self._journal_touch(e.pred)
                if e.facets:
                    p.edge_facets[(e.src, e.dst)] = dict(e.facets)
                    p._efmirror = None
        elif e.op == "del":
            if e.value is not None or e.dst == 0:
                p.values.pop((e.src, e.lang), None)
                if not e.lang:
                    p._untagged = None
                p.value_facets.pop(e.src, None)
                self._delta_overflow(e.pred)
                if e.lang:
                    try:
                        del p._has_langs
                    except AttributeError:
                        pass
            else:
                s = p.edges.get(e.src)
                if s is not None and e.dst in s:
                    s.discard(e.dst)
                    if not s:
                        del p.edges[e.src]
                    self._journal_delta(e.pred, e.src, e.dst, -1)
                    kind, sign = "edge", -1
                else:
                    self._journal_touch(e.pred)  # no-op delete
                if p.edge_facets.pop((e.src, e.dst), None) is not None:
                    p._efmirror = None
        else:
            raise ValueError(f"unknown mutation op {e.op!r}")
        self._note_pred_mutation(e.pred, kind, e.src, e.dst, sign)

    def apply_many(self, edges: Iterable[Edge]) -> int:
        n = 0
        for e in edges:
            self.apply(e)
            n += 1
        return n

    # bulk_set_uid_edges batches at or under this size journal per-edge
    # deltas like apply() instead of overflowing: the serving path's
    # fast mutation scanner (serve/bulk.py) routes EVERY set mutation
    # here — including the single-edge point writes whose cached views
    # the IVM layer repairs in place — and an unconditional overflow
    # forced a full arena rebuild (and killed every repairable entry)
    # per point write.  Genuine bulk loads sail past it into the
    # rebuild-is-cheaper path unchanged.
    BULK_JOURNAL_MAX = 256

    def bulk_set_uid_edges(self, pred: str, src, dst) -> None:
        """Vectorized ingest of plain uid edges (no facets): group-by-src
        with one sort instead of a dict/set round trip per edge.  The
        native bulk path (serve/bulk.py) feeds whole predicate groups
        here; semantics identical to apply(set) per edge."""
        import numpy as np

        src = np.asarray(src, dtype=np.int64)
        dst = np.asarray(dst, dtype=np.int64)
        if len(src) == 0:
            return
        p = self.pred(pred)
        self.dirty.add(pred)
        self.version += 1
        p._wdmirror = None  # uids-with-data changes under bulk adds too
        if len(src) <= self.BULK_JOURNAL_MAX:
            # point-write shape: per-edge journal entries (new edges
            # +1, duplicates an empty touch) so arena delta refresh and
            # IVM view repair keep working through the serving path
            edges = p.edges
            for s, d in zip(src.tolist(), dst.tolist()):
                tgt = edges.setdefault(s, set())
                if d not in tgt:
                    tgt.add(d)
                    self._journal_delta(pred, s, d, +1)
                else:
                    self._journal_touch(pred)
            self._note_pred_mutation(pred, "pred")
            return
        self._delta_overflow(pred)  # bulk volume: full rebuild is cheaper
        order = np.argsort(src, kind="stable")
        s = src[order]
        d = dst[order]
        bounds = np.flatnonzero(np.concatenate(([True], s[1:] != s[:-1])))
        ends = np.append(bounds[1:], len(s))
        edges = p.edges
        for b0, b1 in zip(bounds.tolist(), ends.tolist()):
            u = int(s[b0])
            tgt = edges.get(u)
            if tgt is None:
                edges[u] = set(d[b0:b1].tolist())
            else:
                tgt.update(d[b0:b1].tolist())
        self._note_pred_mutation(pred, "pred")  # bulk: no per-edge stream

    def bulk_set_values(self, pred: str, items) -> None:
        """Vectorized ingest of plain (facet-less) value edges: ONE dict
        update pass per predicate group instead of an Edge object +
        apply() dispatch per value.  ``items`` = [(src, lang, TypedValue)]
        in input order — last-write-wins per (src, lang) is preserved by
        insertion order.  Semantics identical to apply(set) per edge."""
        if not items:
            return
        p = self.pred(pred)
        self.dirty.add(pred)
        self.version += 1
        p._wdmirror = None
        self._delta_overflow(pred)  # value/index arenas rebuild
        vals = p.values
        any_untagged = any_lang = False
        for src, lang, v in items:
            vals[(src, lang)] = v
            if lang:
                any_lang = True
            else:
                any_untagged = True
        if any_untagged:
            p._untagged = None
        if any_lang:
            try:
                del p._has_langs
            except AttributeError:
                pass
        self._note_pred_mutation(pred, "pred")

    def apply_schema(self, text: str) -> None:
        """Parse schema text into this store's schema state; journaled
        subclasses override (schema mutations, worker/mutation.go:94)."""
        from dgraph_tpu.models.schema import parse_schema

        parse_schema(text, into=self.schema)
        self.version += 1
        # schema changes (type/index/reverse semantics) are not scoped
        # to a predicate's POSTINGS: bump the IVM floor so every
        # footprint-keyed cache entry goes stale, exactly like the
        # global version did
        self.note_global_change()

    def delete_predicate(self, pred: str) -> None:
        """posting.DeletePredicate analog (posting/index.go:666)."""
        self._preds.pop(pred, None)
        self.dirty.add(pred)
        self.version += 1
        self._delta_overflow(pred)
        self._note_pred_mutation(pred, "pred")

    def note_global_change(self) -> None:
        """Record a change that cannot be scoped to predicates (schema
        mutation, full-store replacement): the IVM floor advances to the
        current version, so EVERY footprint-keyed cache entry goes
        stale — predicate scoping degrades to the global behavior for
        exactly these events."""
        self.pred_floor = self.version
        ds = self.delta_stream
        if ds is not None:
            ds.publish_epoch(self.version)

    def set_edge(self, pred: str, src: int, dst: int, facets=None):
        self.apply(Edge(pred=pred, src=src, dst=dst, facets=facets))

    def del_edge(self, pred: str, src: int, dst: int):
        self.apply(Edge(pred=pred, src=src, dst=dst, op="del"))

    def set_value(self, pred: str, uid: int, value: TypedValue, lang: str = "", facets=None):
        self.apply(Edge(pred=pred, src=uid, value=value, lang=lang, facets=facets))

    def del_value(self, pred: str, uid: int, lang: str = ""):
        self.apply(
            Edge(pred=pred, src=uid, value=TypedValue(TypeID.DEFAULT, ""), lang=lang, op="del")
        )

    # -- stats -------------------------------------------------------------

    def edge_count(self) -> int:
        return sum(
            sum(len(s) for s in p.edges.values()) + len(p.values)
            for p in self._preds.values()
        )
