"""Value type system.

Equivalent of the reference's types/ package: the TypeID enum mirrors
Posting_ValType (types/scalar_types.go:60 in /root/reference), and
``convert`` implements the useful part of the conversion matrix
(types/conversion.go:36) for the types the engine supports.
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Any, Dict, Optional


def _ts(d: _dt.datetime) -> float:
    """Timestamp treating naive datetimes as UTC (all internal datetimes
    are naive-UTC; .timestamp() alone would apply the host timezone)."""
    if d.tzinfo is None:
        d = d.replace(tzinfo=_dt.timezone.utc)
    return d.timestamp()


class TypeID(IntEnum):
    DEFAULT = 0
    BINARY = 1
    INT = 2
    FLOAT = 3
    BOOL = 4
    DATETIME = 5
    GEO = 6
    UID = 7
    PASSWORD = 8
    STRING = 9
    DATE = 10


_NAME_TO_TYPE = {
    "default": TypeID.DEFAULT,
    "binary": TypeID.BINARY,
    "int": TypeID.INT,
    "float": TypeID.FLOAT,
    "bool": TypeID.BOOL,
    "datetime": TypeID.DATETIME,
    "geo": TypeID.GEO,
    "uid": TypeID.UID,
    "password": TypeID.PASSWORD,
    "string": TypeID.STRING,
    "date": TypeID.DATE,
    # xsd names accepted in RDF typed literals (rdf/parse.go typeMap)
    "xs:string": TypeID.STRING,
    "xs:int": TypeID.INT,
    "xs:integer": TypeID.INT,
    "xs:boolean": TypeID.BOOL,
    "xs:double": TypeID.FLOAT,
    "xs:float": TypeID.FLOAT,
    "xs:date": TypeID.DATE,
    "xs:dateTime": TypeID.DATETIME,
    "http://www.w3.org/2001/XMLSchema#string": TypeID.STRING,
    "http://www.w3.org/2001/XMLSchema#int": TypeID.INT,
    "http://www.w3.org/2001/XMLSchema#integer": TypeID.INT,
    "http://www.w3.org/2001/XMLSchema#boolean": TypeID.BOOL,
    "http://www.w3.org/2001/XMLSchema#double": TypeID.FLOAT,
    "http://www.w3.org/2001/XMLSchema#float": TypeID.FLOAT,
    "http://www.w3.org/2001/XMLSchema#date": TypeID.DATE,
    "http://www.w3.org/2001/XMLSchema#dateTime": TypeID.DATETIME,
    "http://www.w3.org/2001/XMLSchema#gYear": TypeID.DATETIME,
}


def type_from_name(name: str) -> TypeID:
    t = _NAME_TO_TYPE.get(name)
    if t is None:
        raise ValueError(f"unknown type name: {name!r}")
    return t


def type_name(t: TypeID) -> str:
    return t.name.lower()


@dataclass(frozen=True)
class TypedValue:
    """A typed scalar value, the analog of types.Val."""

    tid: TypeID
    value: Any

    def __repr__(self):  # pragma: no cover
        return f"TypedValue({self.tid.name}, {self.value!r})"


def parse_datetime(s: str) -> _dt.datetime:
    """Parse the RFC3339-ish formats the reference accepts
    (types/conversion.go ParseTime): full datetime, date, or bare year."""
    s = s.strip()
    # fast paths for the dominant shapes: bulk loads hit this once per
    # dated quad, and strptime costs ~18µs/value in locale machinery —
    # direct slicing is ~20× cheaper and bit-identical for these forms
    n = len(s)
    try:
        if (
            n == 10
            and s[4] == "-"
            and s[7] == "-"
            and s[:4].isdigit()
            and s[5:7].isdigit()
            and s[8:10].isdigit()
        ):
            return _dt.datetime(int(s[:4]), int(s[5:7]), int(s[8:10]))
        if (
            n == 19
            and s[4] == "-"
            and s[7] == "-"
            and s[10] == "T"
            and s[13] == ":"
            and s[16] == ":"
            and s[:4].isdigit()
            and s[5:7].isdigit()
            and s[8:10].isdigit()
            and s[11:13].isdigit()
            and s[14:16].isdigit()
            and s[17:19].isdigit()
        ):
            return _dt.datetime(
                int(s[:4]), int(s[5:7]), int(s[8:10]),
                int(s[11:13]), int(s[14:16]), int(s[17:19]),
            )
        if n == 4 and s.isdigit():
            return _dt.datetime(int(s), 1, 1)
    except ValueError:
        pass  # e.g. month 13: fall through to the full chain's error
    for fmt in ("%Y-%m-%dT%H:%M:%S%z", "%Y-%m-%dT%H:%M:%S", "%Y-%m-%d", "%Y"):
        try:
            return _dt.datetime.strptime(s, fmt)
        except ValueError:
            continue
    # fromisoformat handles fractional seconds and offsets
    try:
        return _dt.datetime.fromisoformat(s.replace("Z", "+00:00"))
    except ValueError:
        raise ValueError(f"cannot parse datetime: {s!r}")


def convert(v: TypedValue, to: TypeID) -> TypedValue:
    """Convert a value between types (subset of types/conversion.go:36)."""
    if v.tid == to:
        return v
    src, val = v.tid, v.value
    if src in (TypeID.DEFAULT, TypeID.STRING, TypeID.BINARY):
        s = val if isinstance(val, str) else bytes(val).decode("utf-8")
        if to in (TypeID.STRING, TypeID.DEFAULT):
            return TypedValue(to, s)
        if to == TypeID.INT:
            return TypedValue(to, int(s))
        if to == TypeID.FLOAT:
            return TypedValue(to, float(s))
        if to == TypeID.BOOL:
            if s in ("true", "1", "T", "True"):
                return TypedValue(to, True)
            if s in ("false", "0", "F", "False"):
                return TypedValue(to, False)
            raise ValueError(f"cannot convert {s!r} to bool")
        if to in (TypeID.DATETIME, TypeID.DATE):
            return TypedValue(to, parse_datetime(s))
        if to == TypeID.PASSWORD:
            return TypedValue(to, s)
        if to == TypeID.GEO:
            from dgraph_tpu.models import geo as _geo

            return TypedValue(to, _geo.parse_geojson(s))
    if src == TypeID.INT:
        if to == TypeID.FLOAT:
            return TypedValue(to, float(val))
        if to == TypeID.BOOL:
            return TypedValue(to, val != 0)
        if to in (TypeID.STRING, TypeID.DEFAULT):
            return TypedValue(to, str(val))
        if to in (TypeID.DATETIME, TypeID.DATE):
            return TypedValue(to, _dt.datetime.utcfromtimestamp(val))
    if src == TypeID.FLOAT:
        if to == TypeID.INT:
            return TypedValue(to, int(val))
        if to == TypeID.BOOL:
            return TypedValue(to, val != 0.0)
        if to in (TypeID.STRING, TypeID.DEFAULT):
            return TypedValue(to, str(val))
    if src == TypeID.BOOL:
        if to == TypeID.INT:
            return TypedValue(to, int(val))
        if to == TypeID.FLOAT:
            return TypedValue(to, float(val))
        if to in (TypeID.STRING, TypeID.DEFAULT):
            return TypedValue(to, "true" if val else "false")
    if src in (TypeID.DATETIME, TypeID.DATE):
        if to in (TypeID.DATETIME, TypeID.DATE):
            return TypedValue(to, val)
        if to in (TypeID.STRING, TypeID.DEFAULT):
            return TypedValue(to, val.isoformat())
        if to == TypeID.INT:
            return TypedValue(to, int(_ts(val)))
        if to == TypeID.FLOAT:
            return TypedValue(to, _ts(val))
    raise ValueError(f"cannot convert {src.name} -> {to.name}")


def compare_vals(op: str, a: TypedValue, b: TypedValue) -> bool:
    """types.CompareVals (types/compare.go:23): numeric promotion, then
    python comparison."""
    av, bv = a.value, b.value
    if {a.tid, b.tid} <= {TypeID.INT, TypeID.FLOAT}:
        av, bv = float(av), float(bv)
    elif a.tid != b.tid:
        try:
            bv = convert(b, a.tid).value
        except ValueError:
            return False
    if op == "eq":
        return av == bv
    if op == "lt":
        return av < bv
    if op == "le":
        return av <= bv
    if op == "gt":
        return av > bv
    if op == "ge":
        return av >= bv
    raise ValueError(f"unknown comparison op {op!r}")


def sort_key(v: TypedValue):
    """A python sort key for host-side value sorting (types/sort.go:92)."""
    if v.tid in (TypeID.INT, TypeID.FLOAT):
        return (0, float(v.value))
    if v.tid in (TypeID.DATETIME, TypeID.DATE):
        return (1, _ts(v.value) if hasattr(v.value, "timestamp") else 0)
    if v.tid == TypeID.BOOL:
        return (2, bool(v.value))
    return (3, str(v.value))


def numeric(v: TypedValue) -> Optional[float]:
    """Float view for device value arenas (order-by / aggregation / math)."""
    if v.tid in (TypeID.INT, TypeID.FLOAT):
        return float(v.value)
    if v.tid == TypeID.BOOL:
        return 1.0 if v.value else 0.0
    if v.tid in (TypeID.DATETIME, TypeID.DATE):
        try:
            return _ts(v.value)
        except (OSError, OverflowError, ValueError):
            return None
    return None
