"""Uid dictionary: external ids ↔ dense internal int32 uids.

The reference leases sparse uint64 uids from a Raft-replicated counter
(worker/assign.go, worker/lease.go).  On TPU, 64-bit ints are emulated and
sparse ids waste gather bandwidth, so we instead assign *dense* int32 uids
at ingest: uid N is row N of every dense per-predicate value table.  The
external representation (client-visible `_uid_`, RDF `<0x...>` subjects)
remains hex of the internal id; string xids (`<name>`, `_:blank`) resolve
through this dictionary exactly like the reference's client-side allocator
(client/mutations.go:125).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

# Hard ceiling of the dense id space: uids are int32 row indexes into
# device arenas (ops/sets.py uses int32 throughout; SENT = 2^31-1 is
# reserved as the padding sentinel).  Beyond it the design requires
# sharding the uid space across groups — see docs/design.md "uid-space
# ceiling".  We fail LOUDLY well before silent int32 wraparound.
UID_CEILING = (1 << 31) - 2  # last assignable uid (SENT is reserved)
# start warning when within 1/64 of the ceiling (~33M uids of headroom)
_WARN_MARGIN = UID_CEILING >> 6


class UidSpaceExhausted(RuntimeError):
    """The dense int32 uid space is exhausted for this group.

    Remedies: split predicates across more groups (each group owns its
    own dense space), or re-shard the uid range (docs/design.md)."""


class UidMap:
    """Monotonic allocator: xid string → dense uid, starting at 1."""

    def __init__(self):
        self._xid_to_uid: Dict[str, int] = {}
        self._next = 1
        self._warned = False

    def __len__(self) -> int:
        return self._next - 1

    @property
    def max_uid(self) -> int:
        return self._next - 1

    def _check_ceiling(self, top: int) -> None:
        if top > UID_CEILING:
            raise UidSpaceExhausted(
                f"dense uid space exhausted: next uid {top} exceeds the "
                f"int32 ceiling {UID_CEILING}; shard the uid space across "
                "groups (docs/design.md: uid-space ceiling)"
            )
        if not self._warned and top > UID_CEILING - _WARN_MARGIN:
            self._warned = True
            import logging

            logging.getLogger("dgraph_tpu.uids").warning(
                "uid space at %d of %d (%.1f%%): approaching the int32 "
                "ceiling — plan a group split (docs/design.md)",
                top, UID_CEILING, 100.0 * top / UID_CEILING,
            )

    def assign(self, xid: str) -> int:
        """Get or allocate the uid for an external id."""
        uid = self._xid_to_uid.get(xid)
        if uid is None:
            self._check_ceiling(self._next)
            uid = self._next
            self._next += 1
            self._xid_to_uid[xid] = uid
        return uid

    def assign_many(self, xids: Iterable[str]) -> List[int]:
        return [self.assign(x) for x in xids]

    def lookup(self, xid: str) -> Optional[int]:
        return self._xid_to_uid.get(xid)

    def fresh(self, n: int = 1) -> List[int]:
        """Allocate n anonymous uids (blank nodes without reuse)."""
        self._check_ceiling(self._next + n - 1)
        out = list(range(self._next, self._next + n))
        self._next += n
        return out

    def reserve_through(self, uid: int) -> None:
        """Ensure explicit numeric uids (RDF `<0x5>`) stay allocatable."""
        if uid >= self._next:
            self._check_ceiling(uid)
            self._next = uid + 1

    def snapshot(self) -> Dict[str, int]:
        return dict(self._xid_to_uid)
