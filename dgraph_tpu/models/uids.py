"""Uid dictionary: external ids ↔ dense internal int32 uids.

The reference leases sparse uint64 uids from a Raft-replicated counter
(worker/assign.go, worker/lease.go).  On TPU, 64-bit ints are emulated and
sparse ids waste gather bandwidth, so we instead assign *dense* int32 uids
at ingest: uid N is row N of every dense per-predicate value table.  The
external representation (client-visible `_uid_`, RDF `<0x...>` subjects)
remains hex of the internal id; string xids (`<name>`, `_:blank`) resolve
through this dictionary exactly like the reference's client-side allocator
(client/mutations.go:125).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional


class UidMap:
    """Monotonic allocator: xid string → dense uid, starting at 1."""

    def __init__(self):
        self._xid_to_uid: Dict[str, int] = {}
        self._next = 1

    def __len__(self) -> int:
        return self._next - 1

    @property
    def max_uid(self) -> int:
        return self._next - 1

    def assign(self, xid: str) -> int:
        """Get or allocate the uid for an external id."""
        uid = self._xid_to_uid.get(xid)
        if uid is None:
            uid = self._next
            self._next += 1
            self._xid_to_uid[xid] = uid
        return uid

    def assign_many(self, xids: Iterable[str]) -> List[int]:
        return [self.assign(x) for x in xids]

    def lookup(self, xid: str) -> Optional[int]:
        return self._xid_to_uid.get(xid)

    def fresh(self, n: int = 1) -> List[int]:
        """Allocate n anonymous uids (blank nodes without reuse)."""
        out = list(range(self._next, self._next + n))
        self._next += n
        return out

    def reserve_through(self, uid: int) -> None:
        """Ensure explicit numeric uids (RDF `<0x5>`) stay allocatable."""
        if uid >= self._next:
            self._next = uid + 1

    def snapshot(self) -> Dict[str, int]:
        return dict(self._xid_to_uid)
