"""Write-ahead log + snapshots: the durability layer.

Equivalent of the reference's raftwal/wal.go (entry log in Badger) plus
posting's dirty-sync contract (posting/lists.go:47-58: snapshots only up
to the synced watermark).  Design: every mutation is appended to an
append-only CRC-framed log *before* it is applied to the in-memory
store; a snapshot is the compacted log — the full state re-encoded as
the same record stream — written atomically, after which the covered
log files are deleted.  Recovery = replay snapshot records, then sealed
segments, then the active WAL; a torn tail (crash mid-append) is
detected by CRC/length and truncated, like Badger's value-log replay.

File layout in the store directory:
  snapshot.bin      magic "DGTPSNP1" + record stream
  wal-<n>.seg       sealed (fully fsynced) log segments awaiting compaction
  wal.log           the active record stream
Record framing: u32 payload-length | u32 crc32(payload) | payload.

Snapshotting is a two-phase seal/compact (draft.go:849 snapshot + wal
truncation analog, made safe against concurrent writers): ``seal``
durably renames the active log to a segment and reopens fresh — the
only step needing write exclusivity, microseconds; ``compact`` then
replays snapshot+segments into a scratch store OFF the write path and
atomically installs the new snapshot before deleting the segments it
folded.  A crash between install and delete merely replays the
segments twice — every record type is last-writer-wins per key or an
idempotent union, so re-applying an already-folded prefix is a fixpoint.

Durability modes: ``sync_writes`` fsyncs before acknowledging, as
before; :meth:`DurableStore.enable_group_commit` lets a serving layer
move that fsync OUT of its exclusive section into a shared
:meth:`DurableStore.sync_barrier` so concurrent writers amortize one
fsync (leader/follower group commit — the reference's gentle-commit
batching applied to fsyncs).

Disk faults (ENOSPC/EIO/injected) latch the store read-only via
:class:`~dgraph_tpu.models.durability.StorageHealth`: mutations raise
:class:`~dgraph_tpu.models.durability.StorageFaultError` (503 at the
serving layer), reads keep working, and a background probe re-arms the
write path — reopening the WAL past any torn tail first, so post-fault
appends can never land after garbage and become unreachable to replay.
"""

from __future__ import annotations

import os
import re
import struct
import sys
import threading
import time
import zlib
from typing import Callable, Dict, Iterator, List, Optional

from dgraph_tpu import obs
from dgraph_tpu.models import codec
from dgraph_tpu.models.durability import (
    SnapshotCorruptError,
    StorageFaultError,
    StorageHealth,
)
from dgraph_tpu.models.schema import SchemaState, parse_schema
from dgraph_tpu.models.store import Edge, PostingStore
from dgraph_tpu.models.types import TypedValue
from dgraph_tpu.models.uids import UidMap
from dgraph_tpu.utils.atomicio import atomic_write_file, fsync_dir
from dgraph_tpu.utils.failpoints import fail
from dgraph_tpu.utils.metrics import (
    GROUP_COMMIT_SYNCS,
    GROUP_COMMIT_WRITES,
    RECOVERY_RECORDS,
    RECOVERY_SECONDS,
    RECOVERY_TORN_BYTES,
    SNAPSHOT_AGE,
    SNAPSHOTS,
    WAL_SEGMENTS,
)

_MAGIC = b"DGTPSNP1"
_HDR = struct.Struct("<II")
_SEG_RE = re.compile(r"^wal-(\d+)\.seg$")


class Wal:
    """Append-only CRC-framed record log (raftwal analog).

    Appends must be serialized by the caller (the engine write lock, the
    raft loop thread, or a batch context) — appends are NOT internally
    locked.  :meth:`sync_upto` is safe from any thread."""

    def __init__(self, path: str, sync: bool = False):
        self.path = path
        self.sync = sync
        # group-commit mode (DurableStore.enable_group_commit): flush()
        # stops fsyncing; callers ack only after sync_upto()
        self.group_commit = False
        self._f = open(path, "ab")
        self.count = 0  # records appended this session
        self._seq = 0          # appends issued (caller-serialized)
        self._flushed_seq = 0  # pushed to the OS through
        self._synced_seq = 0   # fsynced through
        # leader/follower fsync: the first barrier in holds the lock
        # through ONE fsync; followers blocked on the lock find their
        # seq already covered when they get in and return without I/O
        self._sync_lock = threading.Lock()

    def append(self, payload: bytes) -> None:
        fail.point("wal.append")
        # the frame is built in ONE buffer and written with ONE call: an
        # exception mid-append (or a future concurrent writer) can never
        # leave a header in the file with a foreign/absent payload
        self._f.write(_HDR.pack(len(payload), zlib.crc32(payload)) + payload)
        self.count += 1
        self._seq += 1

    def flush(self) -> None:
        fail.point("wal.flush")
        seq = self._seq
        self._f.flush()
        if seq > self._flushed_seq:
            self._flushed_seq = seq
        if self.sync and not self.group_commit:
            with self._sync_lock:
                os.fsync(self._f.fileno())
                fail.point("wal.post_flush")
                if seq > self._synced_seq:
                    self._synced_seq = seq

    def sync_upto(self, seq: Optional[int] = None) -> None:
        """Group-commit barrier: make every record appended+flushed
        through ``seq`` (default: all so far) durable, sharing fsyncs —
        barriers that queue behind a leader's fsync covering their seq
        return without touching the disk.

        Sampled mutations record the barrier as a span
        (``wal.group_commit``): its duration is the ack's durability
        cost, and the ``fsync`` attr says whether THIS writer led the
        fsync or rode a leader's — the per-trace view of the
        writes/syncs amortization ratio."""
        if not self.sync:
            return
        if seq is None:
            seq = self._seq
        sp = obs.current_span()
        if sp is None:
            # discard _sync_upto's bool: sync_upto returns None on EVERY
            # path, sampled or not — a caller must never see a return
            # shape that depends on whether tracing happened to be on
            self._sync_upto(seq)
            return
        with sp.child("wal.group_commit") as bs:
            bs.set_attr("seq", seq)
            led = self._sync_upto(seq)
            bs.set_attr("fsync", led)

    def _sync_upto(self, seq: int) -> bool:
        """The barrier proper; True when this caller LED an fsync."""
        GROUP_COMMIT_WRITES.add(1)
        with self._sync_lock:
            if self._synced_seq >= seq:
                return False  # a leader's fsync already covered us
            target = self._flushed_seq
            os.fsync(self._f.fileno())
            fail.point("wal.post_flush")
            GROUP_COMMIT_SYNCS.add(1)
            if target > self._synced_seq:
                self._synced_seq = target
            return True

    def close(self) -> None:
        self.flush()
        if self.sync and self.group_commit:
            # flush() skipped the fsync in group-commit mode; a clean
            # close must still leave everything durable
            os.fsync(self._f.fileno())
        self._f.close()

    def reset(self) -> None:
        """Truncate in place (raft log rewrite after a raft snapshot;
        the store WAL compacts via seal/compact instead)."""
        with self._sync_lock:
            self._f.close()
            self._f = open(self.path, "wb")
            self.count = 0
        self.flush()

    def seal(self, seg_path: str) -> None:
        """Durably rename the active log to ``seg_path`` and reopen
        fresh.  Caller must hold append exclusivity; the segment is
        fully fsynced BEFORE the rename, so a sealed file never has a
        torn tail."""
        self.flush()
        with self._sync_lock:
            os.fsync(self._f.fileno())
            self._synced_seq = self._flushed_seq
            fail.point("wal.seal")
            self._f.close()
            # rename of a fully-synced file: atomic without a tmp hop
            os.replace(self.path, seg_path)  # graftlint: ignore[naked-atomic-write]
            fsync_dir(os.path.dirname(os.path.abspath(self.path)))
            self._f = open(self.path, "ab")
            self.count = 0

    def rearm(self) -> None:
        """Recover the handle after a storage fault: drop any half-
        written tail (a failed append/flush can leave a torn frame) so
        post-fault appends never land after garbage and vanish from
        replay, then reopen.  Callers guarantee no append is in flight
        (mutations are shed while the store is read-only)."""
        with self._sync_lock:
            try:
                self._f.close()
            except OSError:
                pass
            for _ in replay_records(self.path, truncate_torn=True):
                pass
            self._f = open(self.path, "ab")


def replay_records(
    path: str,
    truncate_torn: bool = True,
    strict: bool = False,
    stats: Optional[dict] = None,
) -> Iterator[bytes]:
    """Yield record payloads; stop at (and optionally cut) a torn tail.
    ``strict`` raises instead — for atomically-written files (snapshots)
    where a bad record is corruption, not a crash artifact, and loading
    a partial state would silently lose data.

    Frames are streamed with a bounded buffer (one chunk + the largest
    in-flight record), so recovering a multi-GB WAL does not double
    resident memory.  ``stats`` (optional dict) receives ``records``,
    ``bytes`` and ``torn_bytes`` when the iterator is exhausted."""
    if stats is not None:
        stats.setdefault("records", 0)
        stats.setdefault("bytes", 0)
        stats.setdefault("torn_bytes", 0)
    if not os.path.exists(path):
        return
    chunk_size = 1 << 20
    buf = bytearray()
    base = 0          # file offset of buf[0]
    good_end = 0      # file offset after the last valid record
    size = 0
    bad = False       # CRC/garbage hit: stop yielding, keep sizing
    with open(path, "rb") as f:
        head = f.read(len(_MAGIC))
        size += len(head)
        if head == _MAGIC:
            base = good_end = len(_MAGIC)
        else:
            buf.extend(head)
        while True:
            pos = 0  # parse offset within buf
            n = len(buf)
            while not bad and pos + _HDR.size <= n:
                length, crc = _HDR.unpack_from(buf, pos)
                start = pos + _HDR.size
                end = start + length
                if end > n:
                    break  # need more bytes (or it's the torn tail)
                payload = bytes(buf[start:end])
                if zlib.crc32(payload) != crc:
                    if strict:
                        raise ValueError(
                            f"{path}: CRC mismatch at offset {base + pos}"
                        )
                    bad = True
                    break
                yield payload
                if stats is not None:
                    stats["records"] += 1
                pos = end
                good_end = base + end
            if pos:
                del buf[:pos]
                base += pos
            chunk = f.read(chunk_size)
            if not chunk:
                break
            size += len(chunk)
            if not bad:
                buf.extend(chunk)
    # whatever remains past good_end is a torn tail / trailing garbage
    torn = size - good_end
    if strict and torn:
        # distinguish the messages the old reader produced: a header
        # promising more bytes than exist is a truncated record; bytes
        # shorter than a header are trailing garbage
        if len(buf) >= _HDR.size and not bad:
            raise ValueError(f"{path}: truncated record at offset {good_end}")
        raise ValueError(f"{path}: trailing garbage at offset {good_end}")
    if stats is not None:
        stats["bytes"] = size
        stats["torn_bytes"] = torn
    if truncate_torn and torn:
        with open(path, "r+b") as f:
            f.truncate(good_end)


def apply_record(store: PostingStore, payload: bytes):
    """Apply one record to a store WITHOUT journaling — used for WAL/
    snapshot replay, Raft committed-entry application, and replica
    catch-up (the processMutation → posting apply path, draft.go:514).
    Returns the touched predicate name (or None for non-predicate
    records) so replicas can version predicates individually."""
    tag = payload[0]
    if tag == codec.EDGE:
        e = codec.decode_edge(payload)
        PostingStore.apply(store, e)
        return e.pred
    elif tag == codec.SCHEMA:
        text, _ = codec.get_str(payload, 1)
        parse_schema(text, into=store.schema)
        # schema semantics (index/reverse/type) change how EVERY
        # predicate reads: bump the version and the IVM floor exactly
        # like a live apply_schema, so replica-backed caches (and the
        # cluster version clock's floor) observe the change
        store.version += 1
        note = getattr(store, "note_global_change", None)
        if note is not None:
            note()
    elif tag == codec.XID:
        xid, pos = codec.get_str(payload, 1)
        uid, _ = codec.uvarint(payload, pos)
        # first write wins: concurrent assigns of one xid race their XID
        # records through the metadata group; applying in log order with
        # setdefault makes every replica agree on the winner
        store.uids._xid_to_uid.setdefault(xid, uid)
        store.uids.reserve_through(uid)
    elif tag == codec.LEASE:
        nxt, _ = codec.uvarint(payload, 1)
        store.uids.reserve_through(nxt - 1)
    elif tag == codec.BULKEDGES:
        pred, src, dst = codec.decode_bulk_edges(payload)
        PostingStore.bulk_set_uid_edges(store, pred, src, dst)
        return pred
    elif tag == codec.BULKVALS:
        pred, items = codec.decode_bulk_values(payload)
        PostingStore.bulk_set_values(store, pred, items)
        return pred
    elif tag == codec.DELPRED:
        pred, _ = codec.get_str(payload, 1)
        PostingStore.delete_predicate(store, pred)
        return pred
    elif tag == codec.MEMBER:
        nid, addr, groups = codec.decode_member(payload)
        store.members[nid] = (addr, tuple(groups))
        hook = getattr(store, "member_hook", None)
        if hook is not None:
            hook(nid, addr, groups)
    else:
        raise ValueError(f"unknown WAL record tag {tag:#x}")
    return None


def iter_state_records(store: PostingStore):
    """Encode a store's full state as a record stream (compacted log).
    Used for snapshots, replica catch-up (worker/predicate.go
    populateShard analog) and binary export."""
    text = store.schema.to_text()
    if text:
        yield codec.encode_schema(text)
    for nid, (addr, groups) in sorted(store.members.items()):
        yield codec.encode_member(nid, addr, groups)
    for xid, uid in sorted(store.uids.snapshot().items(), key=lambda kv: kv[1]):
        yield codec.encode_xid(xid, uid)
    yield codec.encode_lease(store.uids._next)
    for pred in store.predicates():
        pd = store.pred(pred)
        for src in sorted(pd.edges):
            for dst in sorted(pd.edges[src]):
                yield codec.encode_edge(
                    Edge(pred=pred, src=src, dst=dst,
                         facets=pd.edge_facets.get((src, dst)))
                )
        for (src, lang) in sorted(pd.values):
            yield codec.encode_edge(
                Edge(pred=pred, src=src, value=pd.values[(src, lang)],
                     lang=lang, facets=pd.value_facets.get(src))
            )


class _JournaledUidMap(UidMap):
    """UidMap that journals new xid assignments and lease movement."""

    def __init__(self, journal: Callable[[bytes], None]):
        super().__init__()
        self._journal: Optional[Callable[[bytes], None]] = journal

    def assign(self, xid: str) -> int:
        known = xid in self._xid_to_uid
        uid = super().assign(xid)
        if not known and self._journal is not None:
            self._journal(codec.encode_xid(xid, uid))
        return uid

    def fresh(self, n: int = 1) -> List[int]:
        out = super().fresh(n)
        if self._journal is not None:
            self._journal(codec.encode_lease(self._next))
        return out

    def reserve_through(self, uid: int) -> None:
        moved = uid >= self._next
        super().reserve_through(uid)
        if moved and self._journal is not None:
            self._journal(codec.encode_lease(self._next))


class DurableStore(PostingStore):
    """PostingStore journaled to a WAL with atomic snapshots.

    The write path mirrors the reference's raft-then-apply order
    (worker/draft.go:514 processMutation → posting apply): journal
    first, apply second, so recovery can always re-apply.
    """

    def __init__(self, directory: str, sync_writes: bool = False):
        super().__init__()
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.snapshot_path = os.path.join(directory, "snapshot.bin")
        self.wal_path = os.path.join(directory, "wal.log")
        self._replaying = True
        self._in_batch = False
        self._group_commit = False
        self.applied_index = 0  # records applied (watermark analog)
        self._compact_lock = threading.Lock()
        # guards the _segments LIST only (compact holds _compact_lock for
        # its whole replay+write; a seal on the write path must never
        # queue behind that — it only needs the list for a microsecond)
        self._seg_lock = threading.Lock()
        # boot hygiene: a crash mid-compaction leaves a half-written tmp
        for name in os.listdir(directory):
            if name.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(directory, name))
                except OSError:
                    pass
        # recover: snapshot stream, then sealed segments, then wal stream
        t0 = time.monotonic()
        snap_stats: dict = {}
        seg_stats: dict = {}
        wal_stats: dict = {}
        try:
            for payload in replay_records(
                self.snapshot_path, truncate_torn=False, strict=True,
                stats=snap_stats,
            ):
                apply_record(self, payload)
                self.applied_index += 1
        except ValueError as e:
            # quarantine the bad file and refuse to boot with an
            # actionable message — silently replaying WAL-only would
            # lose every snapshotted record (models/durability.py)
            corrupt = self.snapshot_path + ".corrupt"
            # preserving evidence, not writing durable state: plain rename
            os.replace(self.snapshot_path, corrupt)  # graftlint: ignore[naked-atomic-write]
            fsync_dir(directory)
            raise SnapshotCorruptError(
                self.snapshot_path, corrupt, str(e)
            ) from e
        self._segments = self._list_segments()
        self._seal_counter = 0
        for seg in self._segments:
            # sealed segments were fully fsynced before their rename, so
            # a torn tail here is disk damage, not a crash artifact —
            # still replay the good prefix (lenient), but never truncate
            # a sealed file in place
            for payload in replay_records(
                seg, truncate_torn=False, stats=seg_stats
            ):
                apply_record(self, payload)
                self.applied_index += 1
        for payload in replay_records(self.wal_path, stats=wal_stats):
            apply_record(self, payload)
            self.applied_index += 1
        self._replaying = False
        self.wal = Wal(self.wal_path, sync=sync_writes)
        self.uids = self._rebind_uids()
        self.health = StorageHealth(self._storage_probe)
        self._record_recovery(t0, snap_stats, seg_stats, wal_stats)

    # -- recovery observability ---------------------------------------------

    def _record_recovery(self, t0, snap_stats, seg_stats, wal_stats) -> None:
        dur = time.monotonic() - t0
        torn = wal_stats.get("torn_bytes", 0) + seg_stats.get("torn_bytes", 0)
        total = (
            snap_stats.get("records", 0)
            + seg_stats.get("records", 0)
            + wal_stats.get("records", 0)
        )
        age = self._snapshot_age()
        self.recovery = {
            "snapshot_records": snap_stats.get("records", 0),
            "segment_records": seg_stats.get("records", 0),
            "wal_records": wal_stats.get("records", 0),
            "segments": len(self._segments),
            "torn_bytes": torn,
            "duration_s": round(dur, 4),
            "snapshot_age_s": None if age is None else round(age, 1),
        }
        RECOVERY_RECORDS.set(total)
        RECOVERY_TORN_BYTES.set(torn)
        RECOVERY_SECONDS.set(dur)
        if age is not None:
            SNAPSHOT_AGE.set(age)
        if total or torn:
            r = self.recovery
            print(
                f"# recovery {self.dir}: "
                f"snapshot_records={r['snapshot_records']} "
                f"segments={r['segments']} "
                f"segment_records={r['segment_records']} "
                f"wal_records={r['wal_records']} "
                f"torn_bytes={r['torn_bytes']} "
                f"duration={r['duration_s']}s "
                f"snapshot_age={r['snapshot_age_s']}s",
                file=sys.stderr,
            )

    def _snapshot_age(self) -> Optional[float]:
        try:
            mtime = os.path.getmtime(self.snapshot_path)
        except OSError:
            return None
        # wall-clock minus file mtime: mtimes ARE wall clock
        return max(0.0, time.time() - mtime)  # graftlint: ignore[wallclock-duration]

    def _list_segments(self) -> List[str]:
        segs = []
        for name in os.listdir(self.dir):
            m = _SEG_RE.match(name)
            if m:
                segs.append((int(m.group(1)), os.path.join(self.dir, name)))
        return [p for _n, p in sorted(segs)]

    # -- journaling hooks ---------------------------------------------------

    def _rebind_uids(self) -> UidMap:
        jm = _JournaledUidMap(self._journal_durable)
        jm._xid_to_uid = self.uids._xid_to_uid
        jm._next = self.uids._next
        return jm

    def _storage_fault(self, site: str, exc: OSError) -> None:
        """Latch read-only mode and surface the fault as the serving
        layer's retriable class."""
        self.health.note_error(site, exc)
        raise StorageFaultError(
            f"storage fault at {site}: {exc}",
            retry_after=self.health.probe_interval_s,
        ) from exc

    def _append_guarded(self, payload: bytes) -> None:
        try:
            self.wal.append(payload)
        except StorageFaultError:
            raise
        except OSError as e:
            self._storage_fault("wal.append", e)

    def _flush_guarded(self) -> None:
        try:
            self.wal.flush()
        except StorageFaultError:
            raise
        except OSError as e:
            self._storage_fault("wal.flush", e)

    def _journal(self, payload: bytes) -> None:
        if not self._replaying:
            self._append_guarded(payload)

    def _journal_durable(self, payload: bytes) -> None:
        """Journal + flush: uid handouts must be durable before the uid is
        visible to a client, or a crash re-issues it and a new entity
        aliases the old one's postings (lease.py's contract).  Under
        group commit "visible to a client" means after the serving
        layer's sync_barrier, which covers this append too."""
        if not self._replaying:
            self._append_guarded(payload)
            if not self._in_batch:
                self._flush_guarded()

    # -- group commit --------------------------------------------------------

    def enable_group_commit(self) -> None:
        """Serving-layer opt-in (DGRAPH_TPU_GROUP_COMMIT, default on with
        --sync): apply() stops fsyncing inside the caller's exclusive
        section; the caller PROMISES to run :meth:`sync_barrier` after
        each mutation BEFORE acknowledging it, outside that section, so
        concurrent writers share one fsync.  Library users who never
        opt in keep the fsync-per-acknowledged-write contract."""
        if self.wal.sync:
            self._group_commit = True
            self.wal.group_commit = True

    def sync_barrier(self) -> None:
        """Block until everything journaled so far is fsynced (one
        shared fsync per convoy of concurrent writers).  No-op unless
        group commit is enabled."""
        if not self._group_commit:
            return
        try:
            self.wal.sync_upto()
        except StorageFaultError:
            raise
        except OSError as e:
            self._storage_fault("wal.sync", e)

    # -- storage health ------------------------------------------------------

    def storage_readonly(self) -> bool:
        return self.health.readonly()

    def _storage_probe(self) -> None:
        """Re-arm probe: prove the directory takes durable writes, then
        reopen the WAL past any torn tail.  Raises OSError while bad."""
        probe = os.path.join(self.dir, ".probe")
        with open(probe, "wb") as f:
            f.write(b"ok")
            f.flush()
            os.fsync(f.fileno())
        os.unlink(probe)
        self.wal.rearm()

    def storage_status(self) -> dict:
        st = self.health.status()
        try:
            wal_bytes = os.path.getsize(self.wal_path)
        except OSError:
            wal_bytes = 0
        age = self._snapshot_age()
        st.update(
            wal_bytes=wal_bytes,
            wal_records=self.wal.count,
            sealed_segments=len(self._segments),
            snapshot_age_s=None if age is None else round(age, 1),
            last_recovery=self.recovery,
            sync=self.wal.sync,
            group_commit=self._group_commit,
        )
        return st

    # -- the write path -----------------------------------------------------

    def batch(self):
        """Context manager deferring WAL flushes to the end of a multi-
        record operation (gentle-commit batching, posting/lists.go:109)."""
        import contextlib

        @contextlib.contextmanager
        def _cm():
            self._in_batch = True
            try:
                yield self
            finally:
                self._in_batch = False
                self._flush_guarded()

        return _cm()

    def apply(self, e: Edge) -> None:
        if e.op not in ("set", "del"):  # validate BEFORE journaling: a
            # rejected mutation must not resurface from the WAL on restart
            raise ValueError(f"unknown mutation op {e.op!r}")
        self._journal(codec.encode_edge(e))
        super().apply(e)
        self.applied_index += 1
        # an acknowledged single write must survive a process crash; batch
        # paths flush once at the end (gentleCommit analog)
        if not self._replaying and not self._in_batch:
            self._flush_guarded()

    def apply_many(self, edges, flush: bool = True) -> int:
        self._in_batch = True
        try:
            n = super().apply_many(edges)
        finally:
            self._in_batch = False
        if flush and not self._replaying:
            self._flush_guarded()
        return n

    def bulk_set_uid_edges(self, pred: str, src, dst) -> None:
        # one WAL record for the whole predicate group
        self._journal(codec.encode_bulk_edges(pred, src, dst))
        super().bulk_set_uid_edges(pred, src, dst)
        self.applied_index += 1
        if not self._replaying and not self._in_batch:
            self._flush_guarded()

    def bulk_set_values(self, pred: str, items) -> None:
        if not items:
            return
        self._journal(codec.encode_bulk_values(pred, items))
        super().bulk_set_values(pred, items)
        self.applied_index += 1
        if not self._replaying and not self._in_batch:
            self._flush_guarded()

    def apply_schema(self, text: str) -> None:
        parse_schema(text, into=self.schema)  # validate before journaling
        self._journal(codec.encode_schema(text))
        self.applied_index += 1
        if not self._replaying:
            self._flush_guarded()

    def delete_predicate(self, pred: str) -> None:
        self._journal(codec.encode_delpred(pred))
        super().delete_predicate(pred)
        self.applied_index += 1
        if not self._replaying:
            self._flush_guarded()

    # -- snapshots ----------------------------------------------------------

    def iter_state_records(self) -> Iterator[bytes]:
        return iter_state_records(self)

    def seal_segment(self) -> Optional[str]:
        """Phase 1 (needs write exclusivity, microseconds): durably move
        the active WAL aside as a sealed segment and reopen fresh.
        Returns the segment path, or None when there is nothing to seal."""
        try:
            size = os.path.getsize(self.wal_path)
        except OSError:
            size = 0
        if size == 0 and self.wal.count == 0:
            return None
        with self._seg_lock:
            nxt = self._seal_counter
            if self._segments:
                m = _SEG_RE.match(os.path.basename(self._segments[-1]))
                if m:
                    nxt = max(nxt, int(m.group(1)) + 1)
            self._seal_counter = nxt + 1
        seg = os.path.join(self.dir, f"wal-{nxt:016d}.seg")
        try:
            self.wal.seal(seg)
        except StorageFaultError:
            raise
        except OSError as e:
            self._storage_fault("wal.seal", e)
        with self._seg_lock:
            self._segments.append(seg)
            WAL_SEGMENTS.set(len(self._segments))
        return seg

    def compact(self) -> None:
        """Phase 2 (no locks, off the write path): fold snapshot +
        sealed segments into a new snapshot installed atomically, then
        delete the folded segments.  State is rebuilt by REPLAY into a
        scratch store, never read from the live dicts — concurrent
        readers and writers proceed untouched (memory cost: one scratch
        copy of the snapshotted state).  Crash windows are all safe:
        before install the old snapshot + segments still recover; after
        install but before the deletes, the segments replay twice, which
        is a fixpoint (every record type is last-writer-wins per key or
        an idempotent union)."""
        with self._compact_lock:
            with self._seg_lock:
                segs = [s for s in self._segments if os.path.exists(s)]
            scratch = PostingStore()
            for payload in replay_records(
                self.snapshot_path, truncate_torn=False, strict=True
            ):
                apply_record(scratch, payload)
            for seg in segs:
                for payload in replay_records(seg, truncate_torn=False):
                    apply_record(scratch, payload)

            def chunks():
                yield _MAGIC
                for payload in iter_state_records(scratch):
                    yield _HDR.pack(
                        len(payload), zlib.crc32(payload)
                    ) + payload

            try:
                atomic_write_file(
                    self.snapshot_path, chunks(), site="wal.snapshot"
                )
                fail.point("wal.snapshot.installed")
                for seg in segs:
                    os.unlink(seg)
            except StorageFaultError:
                raise
            except OSError as e:
                self._storage_fault("wal.snapshot", e)
            with self._seg_lock:
                self._segments = [
                    s for s in self._segments if s not in segs
                ]
                WAL_SEGMENTS.set(len(self._segments))
            SNAPSHOTS.add(1)
            SNAPSHOT_AGE.set(0)

    def snapshot(self) -> None:
        """Synchronous seal + compact (draft.go:849 snapshot + wal
        truncation analog).  Callers guarantee no concurrent appends
        during the seal, as before; the background Snapshotter
        (models/durability.py) takes the seal under the serving write
        lock instead and compacts off it."""
        self.seal_segment()
        self.compact()

    def close(self) -> None:
        self.health.stop()
        self.wal.close()
